# fishnet-tpu container image (reference: Dockerfile:1-10 — builder + slim
# runtime; here the "build" step compiles the native chesscore library and
# pre-trains/verifies assets instead of compiling engines).
FROM python:3.12-slim AS builder
RUN apt-get update && apt-get install -y --no-install-recommends g++ && \
    rm -rf /var/lib/apt/lists/*
WORKDIR /app
COPY fishnet_tpu ./fishnet_tpu
COPY bench.py __graft_entry__.py ./
RUN pip install --no-cache-dir "jax[cpu]" flax optax numpy && \
    g++ -O2 -std=c++17 -shared -fPIC fishnet_tpu/cc/chesscore.cpp \
        -o fishnet_tpu/cc/libchesscore.so

FROM python:3.12-slim
RUN useradd --create-home fishnet
WORKDIR /app
COPY --from=builder /usr/local/lib/python3.12/site-packages /usr/local/lib/python3.12/site-packages
COPY --from=builder /app /app
COPY docker-entrypoint.sh /docker-entrypoint.sh
RUN chmod +x /docker-entrypoint.sh
USER fishnet
ENV PYTHONPATH=/app
ENTRYPOINT ["/docker-entrypoint.sh"]
