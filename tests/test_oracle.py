"""Device search vs host oracle: exact score equality, with and without TT.

The reference's search correctness is carried by Stockfish itself
(reference: src/stockfish.rs drives it and trusts its output); the device
search needs an explicit oracle instead. ops/oracle.py mirrors the device
state machine move-for-move, so scores must agree EXACTLY — any drift is
a search bug, not noise.

Two tiers: the default (fast) tier proves exactness on 16 mixed positions
at depth 1 plus the budget-truncation rule — a per-commit signal that runs
in minutes on a single-core box. The `slow` tier widens to 50 positions
and depths 2-3 (the host oracle recursion, not the device, is what's
expensive: it dispatches jitted evals per visited node).

All device dispatches share ONE shape (B=16 lanes, max_ply=4) so the fast
tier pays a single XLA compile per feature set.
"""
import random

import jax
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops import tt
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.oracle import oracle_search
from fishnet_tpu.ops.search import search_batch_jit

B = 16
MAX_PLY = 4


@pytest.fixture(scope="module", params=["board768", "halfkav2_hm"])
def params(request):
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set=request.param
    )


def _mixed_fens(n: int, seed: int = 7) -> list[str]:
    """n positions sampled from seeded random games: openings through
    endgames, captures, checks, promotions — whatever random play visits."""
    rng = random.Random(seed)
    fens = []
    while len(fens) < n:
        pos = Position.initial()
        for ply in range(rng.randrange(2, 70)):
            moves = pos.legal_moves()
            if not moves:
                break
            pos = pos.push(rng.choice(moves))
        fens.append(pos.to_fen())
    return fens


FENS = _mixed_fens(50)


def _device(params, fens, depth, budget, table=None):
    """One fixed-shape dispatch: fens cycled up to B lanes; per-lane depth
    from the (possibly shorter) depth list."""
    roots = stack_boards(
        [from_position(Position.from_fen(fens[i % len(fens)])) for i in range(B)]
    )
    depth_arr = np.full(B, depth, np.int32)
    out = search_batch_jit(
        params, roots, depth_arr, np.full(B, budget, np.int32),
        max_ply=MAX_PLY, tt=table,
    )
    return {k: np.asarray(v) for k, v in out.items() if k != "tt"}


def _device_many(params, fens, depth, budget, table=None):
    """len(fens) > B: dispatch in B-sized slices, same compiled shape."""
    outs = [
        _device(params, fens[i:i + B], depth, budget, table)
        for i in range(0, len(fens), B)
    ]
    n_last = len(fens) - (len(outs) - 1) * B
    return {
        k: np.concatenate(
            [o[k][:B] for o in outs[:-1]] + [outs[-1][k][:n_last]]
        )
        for k in ("score", "nodes")
    }


def _assert_matches(params, out, fens, depth, budget, idxs):
    for i in idxs:
        exp = oracle_search(
            params, from_position(Position.from_fen(fens[i])), depth,
            budget, MAX_PLY,
        )
        assert int(out["score"][i]) == exp["score"], (fens[i], depth)
        assert int(out["nodes"][i]) == exp["nodes"], (fens[i], depth)


def test_matches_oracle_depth1(params):
    out = _device(params, FENS[:B], 1, 100_000)
    _assert_matches(params, out, FENS[:B], 1, 100_000, range(B))


@pytest.mark.slow
def test_matches_oracle_depth1_full(params):
    out = _device_many(params, FENS, 1, 100_000)
    _assert_matches(params, out, FENS, 1, 100_000, range(len(FENS)))


@pytest.mark.slow
def test_matches_oracle_depth2(params):
    n = 16 if nnue.is_board768(params) else 8
    out = _device(params, FENS[:n], 2, 100_000)
    _assert_matches(params, out, FENS[:n], 2, 100_000, range(n))


@pytest.mark.slow
def test_matches_oracle_depth3(params):
    n = 6 if nnue.is_board768(params) else 3
    out = _device(params, FENS[:n], 3, 100_000)
    _assert_matches(params, out, FENS[:n], 3, 100_000, range(n))


@pytest.mark.slow
def test_matches_oracle_depth4_deeper_stack(params):
    """Beyond toy shapes: depth 4 with MAX_PLY 6 exercises deeper QS
    interplay and longer PV propagation than the depth<=3 tier (the
    round-2 verdict's 'no oracle witness past depth 3')."""
    if not nnue.is_board768(params):
        pytest.skip("one feature set is enough for the deep witness")
    n = 2
    roots = stack_boards(
        [from_position(Position.from_fen(FENS[i % n])) for i in range(B)]
    )
    out = search_batch_jit(
        params, roots, np.full(B, 4, np.int32), np.full(B, 100_000, np.int32),
        max_ply=6,
    )
    out = {k: np.asarray(v) for k, v in out.items() if k != "tt"}
    for i in range(n):
        exp = oracle_search(
            params, from_position(Position.from_fen(FENS[i])), 4, 100_000, 6
        )
        assert int(out["score"][i]) == exp["score"], (FENS[i],)
        assert int(out["nodes"][i]) == exp["nodes"], (FENS[i],)


def test_budget_truncation_matches_oracle(params):
    """The node-budget leaf rule is part of the semantics: a tiny budget
    truncates the oracle and the device at the same node."""
    n = 6
    out = _device(params, FENS[:n], 3, 40)
    _assert_matches(params, out, FENS[:n], 3, 40, range(n))


@pytest.mark.slow
def test_tt_scores_bit_identical(params):
    """With exact-depth probe matching, the shared TT must not change any
    score — only node counts (reference analog: analysis output must not
    depend on what else the worker happened to search). At depth ≤3 a
    repetition needs more reversible plies than the search has, so the
    known graph-history interaction cannot bite here."""
    plain = _device(params, FENS[:B], 3, 1_000_000)
    shared = _device(params, FENS[:B], 3, 1_000_000, table=tt.make_table(18))
    np.testing.assert_array_equal(plain["score"], shared["score"])
    assert int(shared["nodes"].sum()) <= int(plain["nodes"].sum())
