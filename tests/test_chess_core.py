"""Perft and rules tests for the host chess core.

Perft reference values are the well-known published counts for the standard
test positions (startpos, Kiwipete, and the CPW positions 3-6).
"""
import pytest

from fishnet_tpu.chess import (
    Move,
    Position,
    Chess960Position,
    STARTING_FEN,
    perft,
)

PERFT_CASES = [
    (STARTING_FEN, [20, 400, 8902, 197281]),
    # Kiwipete
    ("r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
     [48, 2039, 97862]),
    # CPW position 3 (en passant pins)
    ("8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1", [14, 191, 2812, 43238]),
    # CPW position 4 (promotions, castling-rights edge cases)
    ("r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1",
     [6, 264, 9467]),
    # CPW position 5
    ("rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
     [44, 1486, 62379]),
    # CPW position 6
    ("r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10",
     [46, 2079, 89890]),
]


@pytest.mark.parametrize("fen,counts", PERFT_CASES, ids=lambda v: v[:20] if isinstance(v, str) else "")
def test_perft(fen, counts):
    pos = Position.from_fen(fen)
    for depth, expected in enumerate(counts, start=1):
        if expected > 150_000:
            continue  # keep the suite fast; deep counts covered in slow marker below
        assert perft(pos, depth) == expected, f"perft({depth}) of {fen}"


@pytest.mark.slow
def test_perft_deep_startpos():
    assert perft(Position.initial(), 4) == 197281


CHESS960_CASES = [
    # from the published Chess960 perft suite
    ("bqnb1rkr/pp3ppp/3ppn2/2p5/5P2/P2P4/NPP1P1PP/BQ1BNRKR w HFhf - 2 9",
     [21, 528, 12189]),
    # depth-1 counts hand-verified move by move; deeper values are pinned
    # regression values from this engine (cross-checked for consistency)
    ("2nnrbkr/p1qppppp/8/1ppb4/6PP/3PP3/PPP2P2/BQNNRBKR w HEhe - 1 9",
     [21, 807, 18002]),
    ("b1q1rrkb/pppppppp/3nn3/8/P7/1PPP4/4PPPP/BQNNRKRB w GE - 1 9",
     [20, 479, 10471]),
]


@pytest.mark.parametrize("fen,counts", CHESS960_CASES, ids=lambda v: v[:16] if isinstance(v, str) else "")
def test_perft_chess960(fen, counts):
    pos = Chess960Position.from_fen(fen)
    for depth, expected in enumerate(counts, start=1):
        assert perft(pos, depth) == expected, f"perft({depth}) of {fen}"


def test_fen_roundtrip():
    for fen, _ in PERFT_CASES:
        assert Position.from_fen(fen).to_fen() == fen


def test_uci_castling_both_notations():
    pos = Position.from_fen("r3k2r/8/8/8/8/8/8/R3K2R w KQkq - 0 1")
    # standard notation e1g1 and 960 notation e1h1 must both castle kingside
    a = pos.push_uci("e1g1")
    b = pos.push_uci("e1h1")
    assert a.to_fen() == b.to_fen()
    assert a.piece_at(6) is not None and a.piece_at(6)[1] == 5  # king on g1
    assert a.piece_at(5) is not None and a.piece_at(5)[1] == 3  # rook on f1


def test_en_passant():
    pos = Position.initial().push_uci("e2e4").push_uci("a7a6").push_uci("e4e5").push_uci("d7d5")
    assert pos.ep_square is not None
    child = pos.push_uci("e5d6")
    assert child.piece_at(35) is None  # d5 pawn gone


def test_promotion():
    pos = Position.from_fen("8/P6k/8/8/8/8/8/K7 w - - 0 1")
    child = pos.push_uci("a7a8q")
    assert child.piece_at(56) == (0, 4)


def test_checkmate_outcome():
    pos = Position.from_fen("rnbqkbnr/pppp1ppp/8/4p3/6P1/5P2/PPPPP2P/RNBQKBNR b KQkq - 0 2")
    pos = pos.push_uci("d8h4")
    out = pos.outcome()
    assert out == (1, "checkmate")  # black wins


def test_stalemate_outcome():
    pos = Position.from_fen("7k/5Q2/6K1/8/8/8/8/8 b - - 0 1")
    assert pos.outcome() == (None, "stalemate")


def test_illegal_move_rejected():
    pos = Position.initial()
    with pytest.raises(Exception):
        pos.push_uci("e2e5")
