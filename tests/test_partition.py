"""Partition-rule registry contracts (fishnet_tpu/parallel/partition.py).

The registry is the ONE place sharding layout lives: these tests pin
(1) total coverage — every leaf of the real search-side pytrees is won
by exactly one rule, and every rule fires (no dead regexes); (2) the
loud-failure contract — an unregistered field raises UnmatchedLeafError
naming the path, instead of sailing through under a default layout;
(3) literal equivalence — the derived segment/merge specs are exactly
the hand-built P-literals parallel/mesh.py used before the registry, so
the refactor cannot have moved a single element; (4) axis renaming and
the batch/replicated helpers behind shard_batch/replicate.

The sharded-vs-serial bit-identity of actual RESULTS under the
registry-derived specs is pinned by tests/test_mesh_refill.py (the
`mesh` marker suite) — here we pin the specs themselves, which needs no
device work and stays in the fast tier.
"""
from __future__ import annotations

import jax
import pytest
from jax.sharding import PartitionSpec as P

from fishnet_tpu.parallel import partition as PT

# ---------------------------------------------------------------- coverage


def test_every_search_leaf_matched_by_exactly_one_rule():
    proto = PT.search_proto()
    for path, leaf in PT.iter_paths(proto):
        hits = PT.matching_rules(path, PT.SEARCH_RULES)
        assert len(hits) == 1, (
            f"leaf {path!r} matched by {len(hits)} rules — the registry "
            "must name exactly one layout per leaf"
        )


def test_validate_rules_counts_cover_the_whole_prototype():
    proto = PT.search_proto()
    counts = PT.validate_rules(proto)
    assert sum(counts.values()) == len(PT.iter_paths(proto))
    # the layout in one screen: 9 state fields, 1 TT shard array,
    # 8 NNUE tensors, 5 boundary values
    assert counts[PT.STATE_RULES[0][0]] == 9
    assert counts[PT.TT_RULES[0][0]] == 1
    assert counts[PT.PARAM_RULES[0][0]] == 8


def test_param_rules_tp_cover_params_exactly():
    counts = PT.validate_rules(PT.param_proto(), PT.PARAM_RULES_TP)
    assert counts[r"(^|/)ft_w$"] == 1
    assert counts[r"(^|/)ft_b$"] == 1
    assert sum(counts.values()) == 8


def test_dead_rule_raises():
    with pytest.raises(ValueError, match="never fire"):
        PT.validate_rules(
            PT.param_proto(),
            PT.PARAM_RULES + ((r"(^|/)renamed_field$", P("dp")),),
        )


# ------------------------------------------------------------ loud failure


def test_unregistered_leaf_fails_loudly_with_path_named():
    tree = {"state": PT.state_proto(), "mystery_field": "mystery_field"}
    with pytest.raises(PT.UnmatchedLeafError) as ei:
        PT.match_partition_rules(tree)
    assert "mystery_field" in str(ei.value)
    assert "partition.py" in str(ei.value)  # says where to register


def test_scalar_leaves_short_circuit_to_replicated():
    import numpy as np

    tree = {"no_rule_matches_me": np.int32(7)}
    specs = PT.match_partition_rules(tree)
    assert specs["no_rule_matches_me"] == P()


# -------------------------------------------------- literal equivalence
#
# Pre-registry, parallel/mesh.py hand-built these exact specs:
#   segment: in  (P(), P(axis), P(axis)|P(), P(), P(axis))
#            out (P(axis), P(axis)|P(), P(axis), P(axis, None, None))
#   merge:   in  (P(axis), P(axis), P(axis)) → out P(axis)
# The registry derives per-leaf trees; every leaf must equal the literal
# that used to broadcast over its subtree.


def _leaves(spec_tree):
    return jax.tree_util.tree_leaves(
        spec_tree, is_leaf=lambda x: isinstance(x, P))


@pytest.mark.parametrize("axis", ["dp", "x"])
@pytest.mark.parametrize("has_tt", [True, False])
def test_segment_specs_equal_old_hand_built_literals(axis, has_tt):
    in_specs, out_specs = PT.segment_specs(has_tt, axis)
    p_params, p_state, p_tt, p_steps, p_gen = in_specs
    assert all(s == P() for s in _leaves(p_params))
    assert all(s == P(axis) for s in _leaves(p_state))
    assert all(s == (P(axis) if has_tt else P()) for s in _leaves(p_tt))
    assert p_steps == P()
    assert p_gen == P(axis)
    o_state, o_tt, o_steps, o_summ = out_specs
    assert all(s == P(axis) for s in _leaves(o_state))
    assert all(s == (P(axis) if has_tt else P()) for s in _leaves(o_tt))
    assert o_steps == P(axis)
    assert o_summ == P(axis, None, None)


@pytest.mark.parametrize("axis", ["dp", "x"])
def test_merge_specs_equal_old_hand_built_literals(axis):
    in_specs, out_specs = PT.merge_specs(axis)
    st, fresh, mask = in_specs
    assert all(s == P(axis) for s in _leaves(st))
    assert all(s == P(axis) for s in _leaves(fresh))
    assert mask == P(axis)
    assert all(s == P(axis) for s in _leaves(out_specs))


def test_training_param_specs_shard_feature_transform_over_tp():
    specs = PT.param_specs(tp=True)
    assert specs.ft_w == P(None, "tp")
    assert specs.ft_b == P("tp")
    assert specs.l1_w == P()
    assert specs.out_b == P()


# ------------------------------------------------------------- helpers


def test_rename_axes_substitutes_only_named_axes():
    assert PT.rename_axes(P("dp", None, "tp"), {"dp": "x"}) \
        == P("x", None, "tp")
    assert PT.rename_axes(P(), {"dp": "x"}) == P()


def test_batch_and_replicated_specs():
    assert PT.batch_spec(1) == P("dp")
    assert PT.batch_spec(3) == P("dp", None, None)
    assert PT.batch_spec(1, "x") == P("x")
    assert PT.batch_spec(0) == P("dp")  # scalar floor: rank >= 1
    assert PT.replicated_spec() == P()


def test_default_topology_names_the_fingerprint_fields():
    topo = PT.default_topology()
    assert set(topo) == {"mesh_shape", "mesh_axes", "process_count"}
    assert topo["mesh_axes"] == "dp"
    # conftest forces 8 virtual CPU devices for every test process
    assert topo["mesh_shape"] == "8"
    assert topo["process_count"] == 1


# --------------------------------------------------- sharded bit-identity


@pytest.mark.mesh
@pytest.mark.slow
def test_registry_derived_sharding_bit_identical_to_serial():
    """ISSUE acceptance: the registry-derived specs produce bit-for-bit
    the results of the plain single-device search on the forced-8-device
    mesh (scores, moves, nodes) — the full-size stream parity lives in
    tests/test_mesh_refill.py; this is the minimal direct pin."""
    import numpy as np

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards
    from fishnet_tpu.ops.search import search_batch_resumable
    from fishnet_tpu.parallel.mesh import make_mesh, sharded_search

    params = nnue.init_params(jax.random.PRNGKey(0), l1=32,
                              feature_set="board768")
    start = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    game = ["e2e4", "c7c5", "g1f3", "d7d6", "d2d4", "c5d4", "f3d4"]
    boards, p = [], Position.from_fen(start)
    for uci in [None] + game:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    roots = stack_boards(boards)
    depth = np.full(8, 2, np.int32)
    budget = np.full(8, 4_000, np.int32)
    serial = search_batch_resumable(params, roots, depth, budget,
                                    max_ply=6)
    sharded = sharded_search(params, roots, depth, budget, max_ply=6,
                             mesh=make_mesh(8))
    for key in ("score", "move", "nodes"):
        np.testing.assert_array_equal(
            np.asarray(serial[key]), np.asarray(sharded[key]), err_msg=key)
