"""TPU engine tests: chunk in, protocol-complete responses out."""
import asyncio
import time

import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import (
    AnalysisWork,
    EngineFlavor,
    MoveWork,
    NodeLimit,
    SkillLevel,
)
from fishnet_tpu.engine.tpu import TpuEngine

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
GAME = ["e2e4", "c7c5", "g1f3", "d7d6"]


@pytest.fixture(scope="module")
def engine():
    return TpuEngine(max_depth=3)


def make_chunk(work, n_positions=3, moves=GAME, variant="standard"):
    positions = [
        WorkPosition(
            work=work, position_index=i, url=None, skip=False,
            root_fen=START, moves=moves[:i],
        )
        for i in range(n_positions)
    ]
    return Chunk(
        work=work, deadline=time.monotonic() + 120, variant=variant,
        flavor=EngineFlavor.TPU, positions=positions,
    )


def analysis_work(depth=3, multipv=None):
    return AnalysisWork(
        id="tpujob01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=multipv,
    )


def run(engine, chunk):
    return asyncio.run(engine.go_multiple(chunk))


def test_analysis_chunk(engine):
    responses = run(engine, make_chunk(analysis_work(depth=3)))
    assert len(responses) == 3
    for i, res in enumerate(responses):
        assert res.position_index == i
        assert res.depth == 3
        assert res.nodes > 0
        best_score = res.scores.best()
        assert best_score is not None and best_score.kind in ("cp", "mate")
        # per-depth rows populated for depths 1..3
        assert res.scores.matrix[0][1] is not None
        assert res.scores.matrix[0][3] is not None
        # pv must be a legal line from the position
        pos = Position.from_fen(START)
        for uci in GAME[:i]:
            pos = pos.push(pos.parse_uci(uci))
        pv = res.pvs.best()
        assert pv, "empty pv"
        for uci in pv:
            pos = pos.push(pos.parse_uci(uci))
        assert res.best_move == pv[0]


def test_multipv_lane_ceiling_splits_dispatches():
    """docs/tpu-hang.md round 5: ~1024 lanes is the v5e ceiling. With a
    tiny ceiling, a multipv chunk whose root moves exceed it must be
    split into sequential dispatch groups — with a warning — and still
    produce complete responses for every position. The device program is
    stubbed: the partitioning is host-side logic and must be testable
    without a dispatch."""
    import numpy as np

    class WarnCatcher:
        def __init__(self):
            self.messages = []

        def warn(self, msg):
            self.messages.append(msg)

    sparse = "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1"  # 6 legal moves
    logger = WarnCatcher()
    engine = TpuEngine(max_depth=2, max_lanes=16, logger=logger)
    dispatches = []

    def fake_search(roots, depth_arr, budget_arr, deadline=None, **kw):
        B = len(depth_arr)
        dispatches.append(B)
        return {
            "done": np.ones(B, bool),
            "score": np.full(B, 20, np.int32),
            "pv": np.full((B, 4), -1, np.int32),
            "pv_len": np.zeros(B, np.int32),
            "nodes": np.ones(B, np.int32),
        }

    engine._search = fake_search
    work = analysis_work(depth=1, multipv=2)
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=sparse, moves=[])
        for i in range(3)  # 18 lanes total: 16-lane ceiling forces a split
    ]
    chunk = Chunk(
        work=work, deadline=time.monotonic() + 120, variant="standard",
        flavor=EngineFlavor.TPU, positions=positions,
    )
    responses = run(engine, chunk)
    assert len(responses) == 3
    for res in responses:
        assert res.depth == 1
        assert res.best_move is not None
        assert res.scores.best() is not None
        assert len(res.scores.matrix) == 2  # multipv rows intact
    # two dispatch groups (12 + 6 lanes), one depth iteration each
    assert len(dispatches) == 2
    assert any("lanes" in m and "splitting" in m for m in logger.messages)


def test_multipv_chunk(engine):
    responses = run(engine, make_chunk(analysis_work(depth=2, multipv=3), n_positions=2))
    for res in responses:
        assert len(res.scores.matrix) == 3  # three ranked rows
        # rank 1 must be >= rank 2 >= rank 3 at the final depth
        def val(rank):
            s = res.scores.matrix[rank][-1]
            return (1000000 - s.value) if s.kind == "mate" and s.value > 0 else (
                -1000000 - s.value if s.kind == "mate" else s.value
            )
        assert val(0) >= val(1) >= val(2)


def test_terminal_position(engine):
    # fool's mate final position: mate 0 at depth 0
    moves = ["f2f3", "e7e5", "g2g4", "d8h4"]
    work = analysis_work(depth=3)
    positions = [
        WorkPosition(work=work, position_index=0, url=None, skip=False,
                     root_fen=START, moves=moves)
    ]
    chunk = Chunk(work=work, deadline=time.monotonic() + 60,
                  variant="standard", flavor=EngineFlavor.TPU, positions=positions)
    (res,) = run(engine, chunk)
    assert res.depth == 0
    assert res.scores.best().kind == "mate" and res.scores.best().value == 0
    assert res.best_move is None


def test_mate_in_one_found(engine):
    work = analysis_work(depth=2)
    positions = [
        WorkPosition(work=work, position_index=0, url=None, skip=False,
                     root_fen="6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1", moves=[])
    ]
    chunk = Chunk(work=work, deadline=time.monotonic() + 60,
                  variant="standard", flavor=EngineFlavor.TPU, positions=positions)
    (res,) = run(engine, chunk)
    assert res.best_move == "e1e8"
    assert res.scores.best().kind == "mate" and res.scores.best().value == 1


def test_time_apportionment():
    """Per-position time is the chunk's shared wall-clock split by node
    share (round-3 advisor flag: a uniform elapsed/len split misstates
    per-position nps on lichess's display). Sums to the chunk elapsed;
    implied nps is uniform across positions of one dispatch."""
    times = TpuEngine._apportion_time(2.0, [100, 300, 0])
    assert times == [0.5, 1.5, 0.0]
    assert abs(sum(times) - 2.0) < 1e-9
    # degenerate: no nodes anywhere → uniform split, still sums
    assert TpuEngine._apportion_time(1.2, [0, 0]) == [0.6, 0.6]


def test_skill_pick_weakens():
    """skill_pick at full strength always takes the top move; at low
    skill it samples weaker near-best moves (the engine's lichess skill
    analog — validated at game level by tools/strength_ab.py --skill)."""
    import random

    from fishnet_tpu.engine.tpu import skill_pick

    ranked = [(50, 0), (40, 1), (-20, 2), (-500, 3)]
    assert skill_pick(ranked, 20, random.Random(1)) == (50, 0)
    picks = {
        skill_pick(ranked, -9, random.Random(s))[1] for s in range(200)
    }
    assert len(picks) > 1, "low skill never deviated from the top move"
    # the hopeless move stays outside the 3×weakness acceptance window
    assert 3 not in picks


def test_move_job(engine):
    work = MoveWork(id="tpumv001", level=SkillLevel(8))
    positions = [
        WorkPosition(work=work, position_index=0, url=None, skip=False,
                     root_fen=START, moves=["e2e4", "e7e5"])
    ]
    chunk = Chunk(work=work, deadline=time.monotonic() + 60,
                  variant="standard", flavor=EngineFlavor.TPU, positions=positions)
    (res,) = run(engine, chunk)
    pos = Position.from_fen(START).push_uci("e2e4").push_uci("e7e5")
    legal = {m.uci() for m in pos.legal_moves()}
    assert res.best_move in legal
