"""Full-application smoke test: `python -m fishnet_tpu` as a subprocess
against the fake lichess server, graceful SIGINT shutdown."""
import os
import signal
import subprocess
import sys
import time

import pytest

from fake_server import FakeLichess

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server():
    s = FakeLichess().start()
    yield s
    s.stop()


@pytest.mark.subproc
def test_app_end_to_end(server, tmp_path):
    # generous per-ply timeout: the chunk deadline is timeout × plies and
    # the pure-python engine needs ~15 s for 3 plies on a busy CI box —
    # 5000 ms/ply put the deadline right at the edge (flaky under load)
    server.add_analysis_job("app00001", START, ["e2e4", "e7e5"], timeout_ms=40000)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "fishnet_tpu", "run",
            "--no-conf", "--endpoint", server.url, "--key", "testkey",
            "--backend", "python", "--cores", "1", "--no-stats-file",
        ],
        cwd=str(tmp_path),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 180  # generous: shared CI boxes jitter a lot
        while time.time() < deadline and "app00001" not in server.analyses:
            time.sleep(0.1)
            if proc.poll() is not None:
                out = proc.stdout.read()
                pytest.fail(f"client exited early ({proc.returncode}):\n{out}")
        if "app00001" not in server.analyses:
            proc.kill()
            out, _ = proc.communicate()
            pytest.fail(f"no analysis submitted; client output:\n{out[-4000:]}")
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=30)
        out = proc.stdout.read()
        assert "><> " in out  # headline present
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    final = server.analyses["app00001"][-1]
    assert len(final["analysis"]) == 3
