"""Native C++ chesscore vs the perft-validated Python library."""
import pytest

from fishnet_tpu.chess import Position, perft as py_perft
from fishnet_tpu.chess.native import (
    NativeError,
    legal_moves,
    native,
    perft,
    replay_game,
)

pytestmark = pytest.mark.skipif(native() is None, reason="no C++ toolchain")

PERFT_CASES = [
    ("rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", 4, 197281),
    ("r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1", 3, 97862),
    ("8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1", 4, 43238),
    ("r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1", 3, 9467),
    ("rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8", 3, 62379),
    ("bqnb1rkr/pp3ppp/3ppn2/2p5/5P2/P2P4/NPP1P1PP/BQ1BNRKR w HFhf - 2 9", 3, 12189),
]


@pytest.mark.parametrize("fen,depth,expected", PERFT_CASES,
                         ids=[f[:16] for f, _, _ in PERFT_CASES])
def test_native_perft(fen, depth, expected):
    assert perft(fen, depth) == expected


def test_legal_moves_match_python():
    for fen, _, _ in PERFT_CASES:
        pos = Position.from_fen(fen)
        py = sorted(m.uci() for m in pos.legal_moves())
        cc = sorted(legal_moves(fen))
        assert cc == py, fen


def test_replay_game_normalizes_castling():
    fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    moves = "e2e4 e7e5 g1f3 b8c6 f1c4 g8f6 e1g1".split()
    final_fen, norm = replay_game(fen, moves)
    assert norm[-1] == "e1h1"  # chess960-normalized
    # matches the python library's replay
    pos = Position.from_fen(fen)
    for uci in moves:
        pos = pos.push(pos.parse_uci(uci))
    assert final_fen == pos.to_fen()


def test_replay_rejects_illegal():
    fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    with pytest.raises(NativeError):
        replay_game(fen, ["e2e5"])
    with pytest.raises(NativeError):
        replay_game("not a fen", [])
