"""Config system tests: durations, cores, backlog, ini merge, systemd units,
update XML parsing, backoff."""
import os

import pytest

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.configure import (
    Config,
    build_parser,
    merge,
    parse_backlog,
    parse_cores,
    parse_duration,
    read_ini,
    validate_key,
    write_ini,
)
from fishnet_tpu.client.systemd import exec_start, system_unit, user_unit
from fishnet_tpu.client.update import latest_release, parse_bucket_listing


def test_parse_duration():
    assert parse_duration("30s") == 30
    assert parse_duration("2m") == 120
    assert parse_duration("1h") == 3600
    assert parse_duration("1d") == 86400
    assert parse_duration("500ms") == 0.5
    assert parse_duration("45") == 45
    with pytest.raises(ValueError):
        parse_duration("abc")


def test_parse_cores():
    n = os.cpu_count() or 1
    assert parse_cores(None) == max(n - 1, 1)
    assert parse_cores("auto") == max(n - 1, 1)
    assert parse_cores("all") == n
    assert parse_cores("1") == 1
    with pytest.raises(ValueError):
        parse_cores("0")


def test_parse_backlog():
    assert parse_backlog(None) is None
    assert parse_backlog("short") == 30.0
    assert parse_backlog("long") == 3600.0
    assert parse_backlog("90s") == 90.0


def test_validate_key():
    assert validate_key("abcDEF123") == "abcDEF123"
    with pytest.raises(ValueError):
        validate_key("bad key!")


def test_ini_roundtrip(tmp_path):
    path = tmp_path / "fishnet.ini"
    write_ini(path, {"key": "abc123", "cores": 4, "endpoint": "http://x/fishnet"})
    ini = read_ini(path)
    assert ini["key"] == "abc123"
    assert ini["cores"] == "4"


def test_ini_without_section_header(tmp_path):
    path = tmp_path / "fishnet.ini"
    path.write_text("key = abc123\ncores = 2\n")
    ini = read_ini(path)
    assert ini["key"] == "abc123"


def test_merge_cli_over_ini():
    args = build_parser().parse_args(["run", "--cores", "2", "--key", "clikey"])
    ini = {"cores": "4", "key": "inikey", "endpoint": "http://ini/fishnet"}
    cfg = merge(args, ini)
    assert cfg.cores == min(2, os.cpu_count() or 1)  # CLI wins (clamped to host)
    assert cfg.key == "clikey"
    assert cfg.endpoint == "http://ini/fishnet"  # ini fills the gap


def test_systemd_units():
    cfg = Config(key="abc123", cores=4, user_backlog=30.0)
    unit = system_unit(cfg)
    assert "ExecStart=" in unit and "--key abc123" in unit
    assert "ProtectSystem=strict" in unit
    assert "Restart=on-failure" in unit
    line = exec_start(cfg)
    assert "--cores 4" in line and "--user-backlog 30s" in line
    assert "WantedBy=default.target" in user_unit(cfg)


S3_XML = """<?xml version="1.0" encoding="UTF-8"?>
<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">
  <Name>fishnet-releases</Name>
  <Contents><Key>v2.9.1/fishnet-tpu-linux-x86_64-v2.9.1.pyz</Key></Contents>
  <Contents><Key>v2.9.3/fishnet-tpu-linux-x86_64-v2.9.3.pyz</Key></Contents>
  <Contents><Key>v2.9.2/fishnet-tpu-darwin-arm64-v2.9.2.pyz</Key></Contents>
</ListBucketResult>
"""


def test_update_bucket_parsing():
    releases = parse_bucket_listing(S3_XML, "linux-x86_64")
    assert len(releases) == 2
    best = latest_release(S3_XML, "linux-x86_64")
    assert best is not None and best.version == (2, 9, 3)
    assert latest_release(S3_XML, "windows-amd64") is None


def test_backoff_growth_and_cap():
    b = RandomizedBackoff(max_s=5.0)
    first = b.next()
    assert 0.1 <= first <= 0.4
    for _ in range(20):
        delay = b.next()
    assert delay <= 5.0
    b.reset()
    assert 0.1 <= b.next() <= 0.4


def test_autoscale_flags_merge():
    # unset anywhere: tri-state None defers to FISHNET_TPU_AUTOSCALE
    cfg = merge(build_parser().parse_args(["serve"]), {})
    assert cfg.autoscale is None
    assert cfg.autoscale_min is None and cfg.autoscale_max is None

    args = build_parser().parse_args(
        ["serve", "--autoscale", "--autoscale-min", "2",
         "--autoscale-max", "6"])
    cfg = merge(args, {})
    assert cfg.autoscale is True
    assert cfg.autoscale_min == 2 and cfg.autoscale_max == 6

    # --no-autoscale beats an ini that turns it on
    args = build_parser().parse_args(["serve", "--no-autoscale"])
    cfg = merge(args, {"autoscale": "1", "autoscale_min": "3"})
    assert cfg.autoscale is False
    assert cfg.autoscale_min == 3  # clamp still threads through

    # ini alone can enable or disable
    assert merge(build_parser().parse_args(["serve"]),
                 {"autoscale": "1"}).autoscale is True
    assert merge(build_parser().parse_args(["serve"]),
                 {"autoscale": "off"}).autoscale is False


def test_fleet_ctl_json_flag():
    cfg = merge(build_parser().parse_args(["fleet-ctl", "list"]), {})
    assert cfg.json_output is False
    cfg = merge(
        build_parser().parse_args(["fleet-ctl", "list", "--json"]), {})
    assert cfg.json_output is True
