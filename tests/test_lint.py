"""fishnet-lint self-tests: fixture projects per rule family, the
suppression/baseline mechanics, and the real-repo gate.

The mutation tests are the teeth of the suite: they copy real source
into a fixture tree, break an invariant the way a careless edit would
(read an env var off-registry, drop a serde key), and assert the lint
catches it. If a rule rots into always-green, these fail.
"""
import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from fishnet_tpu.lint import Project, dump_baseline, run_lint

REPO_ROOT = Path(__file__).resolve().parents[1]


def make_project(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    return Project.load(tmp_path)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ------------------------------------------------------------------- trace


TRACED_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np


def kernel(x):
    y = jnp.sum(x)
    if y > 0:                    # trace-py-branch
        y = y + 1
    v = float(y)                 # trace-host-cast
    w = y.item()                 # trace-host-item
    z = np.sum(y)                # trace-np-mix
    idx = jnp.arange(8)          # trace-int-dtype
    return v + w + z + idx


run = jax.jit(kernel)
'''


def test_trace_rules_fire_in_jit_wrapped_function(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/ops/bad.py": TRACED_BAD}
    )
    result = run_lint(project, only_families={"trace"})
    assert rules_of(result.findings) == [
        "trace-host-cast", "trace-host-item", "trace-int-dtype",
        "trace-np-mix", "trace-py-branch",
    ]


def test_host_side_code_not_flagged(tmp_path):
    # same calls, but nothing marks the function as traced: host drivers
    # in kernel files legitimately call int()/.item()
    host = TRACED_BAD.replace("run = jax.jit(kernel)", "run = kernel")
    project = make_project(tmp_path, {"fishnet_tpu/ops/host.py": host})
    result = run_lint(project, only_families={"trace"})
    # file-scoped rules still apply; function-scoped ones must not
    assert rules_of(result.findings) == ["trace-int-dtype"]


def test_trace_propagates_through_call_graph(tmp_path):
    src = '''
import jax


def helper(x):
    return x.item()


def kernel(x):
    return helper(x)


run = jax.jit(kernel)
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/prop.py": src})
    result = run_lint(project, only_families={"trace"})
    assert rules_of(result.findings) == ["trace-host-item"]


def test_lax_hof_argument_is_traced(tmp_path):
    src = '''
from jax import lax


def body(carry):
    return carry.item()


def cond(carry):
    return carry < 4


def drive(x):
    return lax.while_loop(cond, body, x)
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/hof.py": src})
    result = run_lint(project, only_families={"trace"})
    assert rules_of(result.findings) == ["trace-host-item"]


def test_trace_sync_flagged_and_suppressible(tmp_path):
    src = '''
import jax.numpy as jnp


def bench(x):
    x.block_until_ready()
    # fishnet-lint: disable=trace-sync
    x.block_until_ready()
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/sync.py": src})
    result = run_lint(project, only_families={"trace"})
    assert len(by_rule(result.findings, "trace-sync")) == 1


def test_scope_excludes_non_kernel_files(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/client/notkernel.py": TRACED_BAD}
    )
    result = run_lint(project, only_families={"trace"})
    assert result.findings == []


# ------------------------------------------------------------------ config

MINI_SETTINGS = '''
from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class Setting:
    name: str
    kind: str
    default: str
    doc: str
    engine: bool = False


SETTINGS: Tuple[Setting, ...] = (
    Setting(name="FISHNET_TPU_MAX_PLY", kind="int", default="32",
            doc="depth", engine=True),
)
'''


def test_direct_env_read_flagged(tmp_path):
    src = '''
import os

ply = os.environ.get("FISHNET_TPU_MAX_PLY", "32")
foo = os.environ["FISHNET_TPU_FOO"]
'''
    project = make_project(tmp_path, {
        "fishnet_tpu/utils/settings.py": MINI_SETTINGS,
        "fishnet_tpu/engine/cfg.py": src,
    })
    result = run_lint(project, only_families={"config"})
    assert len(by_rule(result.findings, "config-env-read")) == 2
    # FISHNET_TPU_FOO additionally has no registry entry
    unreg = by_rule(result.findings, "config-env-unregistered")
    assert len(unreg) == 1 and "FISHNET_TPU_FOO" in unreg[0].message


def test_registry_accessor_is_clean(tmp_path):
    src = '''
from ..utils import settings

ply = settings.get_int("FISHNET_TPU_MAX_PLY")
'''
    project = make_project(tmp_path, {
        "fishnet_tpu/utils/settings.py": MINI_SETTINGS,
        "fishnet_tpu/engine/cfg.py": src,
    })
    result = run_lint(project, only_families={"config"})
    assert by_rule(result.findings, "config-env-read") == []
    assert by_rule(result.findings, "config-env-unregistered") == []


def test_accessor_with_unregistered_name_flagged(tmp_path):
    src = 'from ..utils import settings\n' \
          'x = settings.get_bool("FISHNET_TPU_NOT_REGISTERED")\n'
    project = make_project(tmp_path, {
        "fishnet_tpu/utils/settings.py": MINI_SETTINGS,
        "fishnet_tpu/engine/cfg.py": src,
    })
    result = run_lint(project, only_families={"config"})
    assert len(by_rule(result.findings, "config-env-unregistered")) == 1


def test_env_write_allowed_in_tests_not_in_package(tmp_path):
    write = 'import os\nos.environ.setdefault("FISHNET_TPU_MAX_PLY", "8")\n'
    project = make_project(tmp_path, {
        "fishnet_tpu/utils/settings.py": MINI_SETTINGS,
        "tests/conftest.py": write,
        "fishnet_tpu/engine/cfg.py": write,
    })
    result = run_lint(project, only_families={"config"})
    writes = by_rule(result.findings, "config-env-write")
    assert len(writes) == 1
    assert writes[0].path == "fishnet_tpu/engine/cfg.py"


def test_doc_staleness(tmp_path):
    from fishnet_tpu.utils.settings import render_rows

    files = {"fishnet_tpu/utils/settings.py": MINI_SETTINGS}
    project = make_project(tmp_path, files)
    result = run_lint(project, only_families={"config"})
    assert len(by_rule(result.findings, "config-doc-stale")) == 1  # missing

    good = render_rows([("FISHNET_TPU_MAX_PLY", "int", "32", "depth", True)])
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "config.md").write_text(good, encoding="utf-8")
    result = run_lint(Project.load(tmp_path), only_families={"config"})
    assert by_rule(result.findings, "config-doc-stale") == []

    (tmp_path / "docs" / "config.md").write_text(good + "edited\n",
                                                 encoding="utf-8")
    result = run_lint(Project.load(tmp_path), only_families={"config"})
    assert len(by_rule(result.findings, "config-doc-stale")) == 1


def test_non_literal_registry_flagged(tmp_path):
    bad = MINI_SETTINGS.replace('default="32"', 'default=str(32)')
    project = make_project(
        tmp_path, {"fishnet_tpu/utils/settings.py": bad}
    )
    result = run_lint(project, only_families={"config"})
    assert len(by_rule(result.findings, "config-registry-literal")) == 1


def test_supervisor_must_wire_engine_env(tmp_path):
    project = make_project(tmp_path, {
        "fishnet_tpu/utils/settings.py": MINI_SETTINGS,
        "fishnet_tpu/engine/supervisor.py":
            "import os\n\n\ndef spawn():\n    return dict(os.environ)\n",
    })
    result = run_lint(project, only_families={"config"})
    assert len(by_rule(result.findings, "config-engine-wire")) == 1

    project = make_project(tmp_path, {
        "fishnet_tpu/engine/supervisor.py":
            "from ..utils import settings\n\n\ndef spawn():\n"
            "    env = {}\n    env.update(settings.engine_env())\n"
            "    return env\n",
    })
    result = run_lint(project, only_families={"config"})
    assert by_rule(result.findings, "config-engine-wire") == []


# -------------------------------------------------------------------- wire


def _wire_fixture(tmp_path, mutate=None):
    text = (REPO_ROOT / "fishnet_tpu/client/wire.py").read_text(
        encoding="utf-8")
    if mutate:
        mutated = mutate(text)
        assert mutated != text, "mutation did not apply"
        text = mutated
    return make_project(
        tmp_path, {"fishnet_tpu/client/wire.py": text}
    )


def test_wire_clean_on_pristine_copy(tmp_path):
    result = run_lint(_wire_fixture(tmp_path), only_families={"wire"})
    assert result.findings == []


def test_dropped_consumed_key_is_caught(tmp_path):
    # a careless edit stops work_from_json reading "depth": the to-side
    # still emits it → key asymmetry
    def mutate(text):
        return text.replace(
            'depth=int(obj["depth"]) if obj.get("depth") is not None'
            " else None,\n", "")

    result = run_lint(_wire_fixture(tmp_path, mutate),
                      only_families={"wire"})
    asym = by_rule(result.findings, "wire-key-asymmetry")
    assert len(asym) == 1 and "'depth'" in asym[0].message


def test_new_field_without_serialization_is_caught(tmp_path):
    def mutate(text):
        return text.replace(
            "    sf16: int\n",
            "    sf16: int\n    flavor_hint: int = 0\n")

    result = run_lint(_wire_fixture(tmp_path, mutate),
                      only_families={"wire"})
    missing = by_rule(result.findings, "wire-field-missing")
    assert len(missing) == 1 and "flavor_hint" in missing[0].message


def test_unknown_ctor_kwarg_is_caught(tmp_path):
    def mutate(text):
        return text.replace(
            "wtime_centis=int(obj[\"wtime\"]),",
            "wtime=int(obj[\"wtime\"]),")

    result = run_lint(_wire_fixture(tmp_path, mutate),
                      only_families={"wire"})
    ctor = by_rule(result.findings, "wire-ctor-field-mismatch")
    # 'wtime' is not a field, and required 'wtime_centis' is now missing
    assert len(ctor) == 2


def test_ipc_pairs_clean_on_real_repo():
    project = Project.load(REPO_ROOT)
    result = run_lint(project, only_families={"wire"})
    assert result.findings == []


# ------------------------------------------------------------- concurrency


def test_no_timeout_rules(tmp_path):
    src = '''
import asyncio


async def drain(q, proc, d):
    a = q.get()                                    # flagged
    b = q.get(timeout=1.0)                         # has timeout
    c = d.get("key")                               # dict access
    e = await asyncio.wait_for(proc.wait(), 5.0)   # wrapped
    f = proc.wait()                                # flagged
    return a, b, c, e, f
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/client/queue.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    flagged = by_rule(result.findings, "conc-no-timeout")
    assert [f.line for f in flagged] == [6, 10]


def test_blocking_call_in_lock(tmp_path):
    src = '''
import time


def step(lock, q, out):
    with lock:
        time.sleep(0.1)
    with lock:
        out.append(1)
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/host.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert len(by_rule(result.findings, "conc-block-in-lock")) == 1


def test_sock_in_loop_rule(tmp_path):
    src = '''
import asyncio
import socket
import time


async def handler(sock, reader, writer):
    time.sleep(0.5)                     # flagged
    data = sock.recv(4096)              # flagged
    await asyncio.sleep(0.5)            # asyncio.sleep: fine
    line = await reader.readline()      # asyncio streams: fine
    writer.write(line)
    await writer.drain()

    def blocking_helper():              # sync helper -> to_thread: fine
        return sock.recv(1)

    return data, await asyncio.to_thread(blocking_helper)


def sync_path(sock):
    return sock.recv(1)                 # not in an async def: fine
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/serve/server.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    flagged = by_rule(result.findings, "conc-sock-in-loop")
    assert sorted(f.line for f in flagged) == [8, 9]


def test_sock_in_loop_out_of_scope(tmp_path):
    # the same code outside fishnet_tpu/serve/ must not fire
    src = '''
import time


async def handler():
    time.sleep(0.5)
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/obs/push.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert by_rule(result.findings, "conc-sock-in-loop") == []


RETRY_BAD = '''
import asyncio


async def dial(host):
    while True:
        try:
            r, w = await asyncio.open_connection(host, 80)
            return r, w
        except OSError:
            await asyncio.sleep(0.1)
'''


def _retry_findings(tmp_path, rel, src):
    project = make_project(tmp_path, {rel: src})
    result = run_lint(project, only_families={"concurrency"})
    return by_rule(result.findings, "conc-unbounded-retry")


def test_unbounded_retry_flagged(tmp_path):
    flagged = _retry_findings(
        tmp_path, "fishnet_tpu/fleet/remote.py", RETRY_BAD)
    assert len(flagged) == 1


def test_unbounded_retry_out_of_scope(tmp_path):
    # same shape outside fleet/serve/client: not this rule's business
    assert _retry_findings(
        tmp_path, "fishnet_tpu/obs/push.py", RETRY_BAD) == []


def test_retry_attempt_cap_is_clean(tmp_path):
    src = '''
import asyncio


async def dial(host, retry_max):
    for attempt in range(retry_max):
        try:
            return await asyncio.open_connection(host, 80)
        except OSError:
            await asyncio.sleep(0.1)
    raise ConnectionError("out of attempts")
'''
    assert _retry_findings(
        tmp_path, "fishnet_tpu/fleet/remote.py", src) == []


def test_retry_deadline_guard_is_clean(tmp_path):
    src = '''
import asyncio
import time


async def dial(host, deadline):
    while True:
        if time.monotonic() >= deadline:
            raise ConnectionError("deadline exhausted")
        try:
            return await asyncio.open_connection(host, 80)
        except OSError:
            await asyncio.sleep(0.1)
'''
    assert _retry_findings(
        tmp_path, "fishnet_tpu/fleet/remote.py", src) == []


def test_retry_reraising_handler_is_clean(tmp_path):
    # the handler ends the loop: no second lap, no retry
    src = '''
import asyncio


async def dial(host):
    while True:
        try:
            return await asyncio.open_connection(host, 80)
        except OSError as e:
            raise ConnectionError("no retry") from e
'''
    assert _retry_findings(
        tmp_path, "fishnet_tpu/fleet/remote.py", src) == []


def test_retry_application_error_loop_is_clean(tmp_path):
    # the work queue's long-poll shape: protocol-flow exception, and
    # the awaited call is not in the network-tail set
    src = '''
class ApiError(Exception):
    pass


async def pull(api):
    while True:
        try:
            return await api.acquire(slow=True)
        except ApiError:
            continue
'''
    assert _retry_findings(
        tmp_path, "fishnet_tpu/client/queue.py", src) == []


def test_retry_for_over_count_flagged(tmp_path):
    src = '''
import asyncio
import itertools


async def dial(host):
    for _ in itertools.count():
        try:
            return await asyncio.open_connection(host, 80)
        except ConnectionError:
            await asyncio.sleep(0.1)
'''
    flagged = _retry_findings(
        tmp_path, "fishnet_tpu/serve/server.py", src)
    assert len(flagged) == 1


def test_except_rules(tmp_path):
    src = '''
def f(log):
    try:
        work()
    except:                      # conc-bare-except (+ silent)
        pass
    try:
        work()
    except BaseException:        # conc-swallow-base (no re-raise)
        cleanup()
    try:
        work()
    except Exception:            # conc-silent-except
        pass
    try:
        work()
    except Exception as e:       # logs: clean
        log.warn(f"failed: {e}")
    try:
        work()
    except OSError:              # narrow: clean
        pass
    try:
        work()
    except BaseException:        # re-raises: clean
        cleanup()
        raise
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/client/helpers.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert len(by_rule(result.findings, "conc-bare-except")) == 1
    assert len(by_rule(result.findings, "conc-swallow-base")) == 1
    assert len(by_rule(result.findings, "conc-silent-except")) == 2


SCHED_LOOP_BAD = '''
import numpy as np
import jax


def drive(params, state, tt, seg, stats):
    state, tt, n, summ = _run_segment_jit(params, state, tt, seg)
    while True:
        state, tt, n, summ = _run_segment_jit(params, state, tt, seg)
        steps = int(n)                       # conc-host-sync
        row = np.asarray(summ)               # conc-host-sync
        state.block_until_ready()            # conc-host-sync
        host = jax.device_get(summ)          # conc-host-sync
        if steps == 0:
            break
    return state, tt
'''


SCHED_LOOP_CLEAN = '''
def drive(params, state, tt, seg, stats):
    while True:
        state, tt, n, summ = _run_segment_jit(params, state, tt, seg)
        steps = int(stats.fetch(n, "steps"))   # fetch is the sanctioned sink
        summ = stats.fetch(summ, "summary")
        row = int(summ[0])                     # fetched: host value now
        if steps == 0:
            break
    return state, tt
'''


def test_host_sync_in_scheduler_loop_flagged(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/tpu.py": SCHED_LOOP_BAD}
    )
    result = run_lint(project, only_families={"concurrency"})
    flagged = by_rule(result.findings, "conc-host-sync")
    assert [f.line for f in flagged] == [10, 11, 12, 13]
    # the pre-loop dispatch is not inside the while: never flagged
    assert all("'n'" in f.message or "'summ'" in f.message or
               "'state'" in f.message for f in flagged)


def test_host_sync_via_fetch_is_clean(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/tpu.py": SCHED_LOOP_CLEAN}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert by_rule(result.findings, "conc-host-sync") == []


def test_host_sync_tracks_tuple_unpack_and_subscript(tmp_path):
    src = '''
def drive(params, state, tt, seg, stats):
    pend = dispatch(state, tt, seg)
    while pend is not None:
        p_state, p_tt, pn, p_summ = pend
        tt = pend[1]
        bad = int(pn)                        # conc-host-sync
        pend = dispatch(p_state, tt, seg)
    return tt
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/tpu.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    flagged = by_rule(result.findings, "conc-host-sync")
    assert len(flagged) == 1 and "'pn'" in flagged[0].message


def test_host_sync_scope_is_scheduler_module_only(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/other.py": SCHED_LOOP_BAD}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert by_rule(result.findings, "conc-host-sync") == []


JOURNAL_BAD = '''
class SupervisedEngine:
    def __init__(self):
        self._journal = {}
        self._journal_expect = set()

    def _journal_record(self, fp, wire):
        self._journal[fp] = wire

    def _read_loop(self, msg):
        self._journal[msg["fp"]] = msg["response"]   # item write
        self._journal = {}                           # rebind
        self._journal.pop(msg["fp"], None)           # mutating method
        self._journal_expect.add(msg["fp"])          # set mutator
        del self._journal[msg["fp"]]                 # delete

    def _harvest(self, fp):
        return self._journal.get(fp)                 # read: fine
'''

JOURNAL_CLEAN = '''
class SupervisedEngine:
    def __init__(self):
        self._journal = {}
        self._journal_expect = set()

    def _journal_reset(self, expect=()):
        self._journal = {}
        self._journal_expect = set(expect)

    def _journal_record(self, fp, wire):
        if fp in self._journal:
            return
        self._journal[fp] = wire

    def _harvest(self, fp):
        wire = self._journal.get(fp)
        return wire if fp in self._journal_expect else None
'''


def test_journal_mutation_outside_delivery_path_flagged(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/supervisor.py": JOURNAL_BAD}
    )
    result = run_lint(project, only_families={"concurrency"})
    flagged = by_rule(result.findings, "conc-journal-writer")
    assert [f.line for f in flagged] == [11, 12, 13, 14, 15]


def test_journal_single_writer_path_is_clean(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/supervisor.py": JOURNAL_CLEAN}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert by_rule(result.findings, "conc-journal-writer") == []


def test_journal_rule_scope_is_supervisor_only(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/other.py": JOURNAL_BAD}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert by_rule(result.findings, "conc-journal-writer") == []


# ------------------------------------------------------------------- obs


OBS_BAD = '''
import time
import time as clock
from time import time as wall


def durations():
    t0 = time.time()           # obs-wall-clock
    t1 = clock.time()          # obs-wall-clock (aliased module)
    t2 = wall()                # obs-wall-clock (from-import alias)
    return t0, t1, t2
'''

OBS_CLEAN = '''
import time


def durations():
    t0 = time.monotonic()
    t1 = time.perf_counter()
    stamp = time.strftime("%Y%m%dT%H%M%S")
    return t0, t1, stamp
'''


def test_wall_clock_flagged_through_every_import_form(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/utils/bad.py": OBS_BAD}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-wall-clock")
    assert len(found) == 3
    assert all("monotonic" in f.message for f in found)


def test_monotonic_and_strftime_are_clean(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/utils/ok.py": OBS_CLEAN}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-wall-clock") == []


def test_wall_clock_scope_is_package_only(tmp_path):
    # report timestamps in tools/ and tests/ are out of scope — only the
    # package's timelines carry the clock-discipline contract
    project = make_project(tmp_path, {
        "tools/report.py": OBS_BAD,
        "tests/test_x.py": OBS_BAD,
    })
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-wall-clock") == []


def test_wall_clock_suppressible_for_report_timestamps(tmp_path):
    src = '''
import time


def report_row():
    # correlates with external dashboards, sanctioned wall-clock read
    ts = int(time.time())  # fishnet-lint: disable=obs-wall-clock
    return ts
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/client/sink.py": src}
    )
    result = run_lint(project, only_families={"obs"})
    assert result.findings == []


def test_mutated_heartbeat_is_caught(tmp_path):
    """Mutation test: regress the real heartbeat module back to wall
    clock (the exact careless edit the rule exists for) and assert the
    lint catches it."""
    real = (REPO_ROOT / "fishnet_tpu/utils/heartbeat.py").read_text()
    assert "time.monotonic()" in real  # the fixed form ships
    broken = real.replace("time.monotonic()", "time.time()")
    project = make_project(
        tmp_path, {"fishnet_tpu/utils/heartbeat.py": broken}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-wall-clock")


ORPHAN_BAD = '''
def emit_partial(send, wp, res):
    send({"t": "partial", "id": 1, "fp": "x", "response": res})  # no ctx


def dispatch(send, chunk):
    send({"t": "go", "id": 1, "chunk": {"positions": chunk}})  # raw dict


def to_request(positions):
    return ServeRequest(kind="analysis", positions=positions)  # no ctx
'''

ORPHAN_CLEAN = '''
def emit_partial(send, wp, res):
    frame = {"t": "partial", "id": 1, "fp": "x", "response": res}
    if wp.ctx:
        frame["ctx"] = wp.ctx
    send(frame)


def dispatch(send, chunk):
    send({"t": "go", "id": 1, "chunk": chunk_to_wire(chunk)})


def to_request(positions, ctxs):
    return ServeRequest(kind="analysis", positions=positions,
                        position_ctx=ctxs)
'''


def test_orphan_span_flags_every_dropped_hop(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/hop.py": ORPHAN_BAD}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-orphan-span")
    assert len(found) == 3
    assert [f.line for f in found] == [3, 7, 11]


def test_orphan_span_propagating_hops_are_clean(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/hop.py": ORPHAN_CLEAN}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-orphan-span") == []


def test_orphan_span_scope_is_package_only(tmp_path):
    # the scriptable fixtures in tools/ and tests/ build frames on
    # purpose — only the package's dispatch sites carry the contract
    project = make_project(tmp_path, {
        "tools/hop_hack.py": ORPHAN_BAD,
        "tests/test_hop.py": ORPHAN_BAD,
    })
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-orphan-span") == []


def test_orphan_span_ignores_positionless_frames(tmp_path):
    # hb/log/ok/err frames and a chunkless go echo carry no positions —
    # nothing to orphan
    src = '''
def ticker(send):
    send({"t": "hb", "seq": 1})
    send({"t": "log", "msg": "x"})
    send({"t": "ok", "id": 1, "responses": []})
    send({"t": "go", "positions": 3})
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/hop.py": src}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-orphan-span") == []


def test_mutated_partial_frame_is_caught(tmp_path):
    """Mutation test: strip the ctx forward from the real host's partial
    frame (the exact careless edit the rule exists for) and assert the
    lint flags the orphaned hop."""
    real = (REPO_ROOT / "fishnet_tpu/engine/host.py").read_text()
    assert 'frame["ctx"] = wp.ctx' in real  # the propagating form ships
    broken = real.replace(
        "            if wp.ctx:\n"
        '                frame["ctx"] = wp.ctx\n', "")
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/host.py": broken}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-orphan-span")
    assert found and all("partial" in f.message for f in found)


def test_mutated_serve_dispatch_is_caught(tmp_path):
    """Mutation test: drop position_ctx from the real fleet dispatch
    body builder and assert both ServeRequest sites are flagged."""
    real = (REPO_ROOT / "fishnet_tpu/fleet/remote.py").read_text()
    assert real.count("position_ctx=position_ctx,") == 2
    broken = real.replace("            position_ctx=position_ctx,\n", "")
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/remote.py": broken}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-orphan-span")
    assert len(found) == 2
    assert all("position_ctx" in f.message for f in found)


# --------------------------------------------------------------------- aot


AOT_BAD = '''
import jax
import jax.export
from jax.experimental import serialize_executable
from jax.experimental.serialize_executable import serialize, deserialize_and_load


def snapshot(compiled):
    blob = serialize(compiled)                        # aot-unkeyed-export
    blob2 = serialize_executable.serialize(compiled)  # aot-unkeyed-export
    exp = jax.export.export(jax.jit(sum))             # aot-unkeyed-export
    fn = deserialize_and_load(*blob)                  # aot-unkeyed-export
    return blob2, exp, fn
'''


def test_unkeyed_export_flagged_through_every_import_form(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/snapshots.py": AOT_BAD}
    )
    result = run_lint(project, only_families={"aot"})
    found = by_rule(result.findings, "aot-unkeyed-export")
    assert len(found) == 4
    assert all("registry" in f.message for f in found)


def test_registry_module_is_sanctioned(tmp_path):
    # the identical calls inside the one keyed-store module are the point
    project = make_project(
        tmp_path, {"fishnet_tpu/aot/registry.py": AOT_BAD}
    )
    result = run_lint(project, only_families={"aot"})
    assert by_rule(result.findings, "aot-unkeyed-export") == []


def test_unkeyed_export_scope_covers_tools_not_tests(tmp_path):
    project = make_project(tmp_path, {
        "tools/export_hack.py": AOT_BAD,
        "tests/test_roundtrip.py": AOT_BAD,
    })
    result = run_lint(project, only_families={"aot"})
    found = by_rule(result.findings, "aot-unkeyed-export")
    assert {f.path for f in found} == {"tools/export_hack.py"}


def test_relocated_registry_code_is_caught(tmp_path):
    """Mutation test: lift the real registry's serialize path into
    another module (the exact drift the rule exists for) and assert the
    lint flags it there while the in-place copy stays clean."""
    real = (REPO_ROOT / "fishnet_tpu/aot/registry.py").read_text()
    assert "_serialize_executable.serialize(" in real
    project = make_project(tmp_path, {
        "fishnet_tpu/aot/registry.py": real,
        "fishnet_tpu/engine/warmstore.py": real,
    })
    result = run_lint(project, only_families={"aot"})
    found = by_rule(result.findings, "aot-unkeyed-export")
    assert found and all(
        f.path == "fishnet_tpu/engine/warmstore.py" for f in found
    )


# ------------------------------------------------------------------- cache


CACHE_BAD = '''
import fishnet_tpu.cache.keys as ck
from fishnet_tpu import cache
from fishnet_tpu.cache.keys import CacheKey


def sneak(fp, net):
    a = CacheKey(fp, "analysis", "standard", -1, 1000, 0, net)
    b = ck.CacheKey(fp, "analysis", "standard", -1, 1000, 0, net)
    c = cache.CacheKey(fp, "analysis", "standard", -1, 1000, 0, net)
    d = fishnet_tpu.cache.keys.CacheKey(fp, "a", "s", -1, 1, 0, net)
    return a, b, c, d
'''


def test_unkeyed_cachekey_flagged_through_every_import_form(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/serve/shortcut.py": CACHE_BAD}
    )
    result = run_lint(project, only_families={"cache"})
    found = by_rule(result.findings, "cache-unkeyed-store")
    assert len(found) == 4
    assert all("key_for_chunk_position" in f.message for f in found)


def test_cache_key_builders_are_sanctioned(tmp_path):
    # the identical constructions inside the builder module and the
    # store (which rebuilds keys from its persisted index) are the point
    project = make_project(tmp_path, {
        "fishnet_tpu/cache/keys.py": CACHE_BAD,
        "fishnet_tpu/cache/store.py": CACHE_BAD,
    })
    result = run_lint(project, only_families={"cache"})
    assert by_rule(result.findings, "cache-unkeyed-store") == []


def test_unkeyed_cachekey_scope_covers_tools_not_tests(tmp_path):
    project = make_project(tmp_path, {
        "tools/cache_hack.py": CACHE_BAD,
        "tests/test_keys.py": CACHE_BAD,
    })
    result = run_lint(project, only_families={"cache"})
    found = by_rule(result.findings, "cache-unkeyed-store")
    assert {f.path for f in found} == {"tools/cache_hack.py"}


def test_careless_coordinator_key_edit_is_caught(tmp_path):
    """Mutation test: replace the coordinator's call to the canonical
    key builder with an inline CacheKey (the exact drift that would
    de-sync serve and fleet keys) and assert the lint flags it, while
    the unmodified copy stays clean."""
    real = (REPO_ROOT / "fishnet_tpu/fleet/coordinator.py").read_text()
    target = "from ..cache.keys import key_for_chunk_position"
    assert target in real
    broken = real.replace(
        target,
        "from ..cache.keys import CacheKey, key_for_chunk_position",
    ).replace(
        "key, depth = key_for_chunk_position(chunk, wp, self.cache.net)",
        'key, depth = CacheKey(wp.root_fen, "analysis", chunk.variant, '
        "-1, -1, 0, self.cache.net), chunk.work.depth",
        1,
    )
    assert broken != real
    project = make_project(
        tmp_path / "broken", {"fishnet_tpu/fleet/coordinator.py": broken}
    )
    result = run_lint(project, only_families={"cache"})
    found = by_rule(result.findings, "cache-unkeyed-store")
    assert len(found) == 1
    assert found[0].path == "fishnet_tpu/fleet/coordinator.py"

    clean = make_project(
        tmp_path / "clean", {"fishnet_tpu/fleet/coordinator.py": real}
    )
    assert by_rule(
        run_lint(clean, only_families={"cache"}).findings,
        "cache-unkeyed-store",
    ) == []


# ------------------------------------------- suppressions, baseline, CLI


def test_suppression_same_line_and_line_above(tmp_path):
    src = '''
def f(q):
    a = q.get()  # fishnet-lint: disable=conc-no-timeout
    # fishnet-lint: disable=conc-no-timeout
    b = q.get()
    c = q.get()
    return a, b, c
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/client/queue.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert [f.line for f in by_rule(result.findings, "conc-no-timeout")] == [6]


def test_baseline_absolves_and_goes_stale(tmp_path):
    src = "def f(q):\n    return q.get()\n"
    project = make_project(
        tmp_path, {"fishnet_tpu/client/queue.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    assert result.failed
    baseline = [f.fingerprint() for f in result.findings]

    result = run_lint(project, baseline=baseline,
                      only_families={"concurrency"})
    assert not result.failed
    assert all(f.baselined for f in result.findings)

    # fix the finding: the baseline entry is now stale
    (tmp_path / "fishnet_tpu/client/queue.py").write_text(
        "def f(q):\n    return q.get(timeout=1.0)\n", encoding="utf-8")
    result = run_lint(Project.load(tmp_path), baseline=baseline,
                      only_families={"concurrency"})
    assert result.findings == [] and result.stale_baseline == baseline


def test_dump_baseline_round_trips(tmp_path):
    src = "def f(q):\n    return q.get()\n"
    project = make_project(
        tmp_path, {"fishnet_tpu/client/queue.py": src}
    )
    result = run_lint(project, only_families={"concurrency"})
    blob = json.loads(dump_baseline(result.findings))
    assert blob["version"] == 1
    assert blob["entries"] == [f.fingerprint() for f in result.findings]


def test_cli_exit_codes(tmp_path):
    from fishnet_tpu.lint.__main__ import main

    make_project(
        tmp_path, {"fishnet_tpu/client/queue.py":
                   "def f(q):\n    return q.get()\n"}
    )
    assert main(["--root", str(tmp_path)]) == 1
    assert main(["--root", str(tmp_path), "--write-baseline"]) == 0
    assert main(["--root", str(tmp_path)]) == 0  # baselined now
    assert main(["--list-rules"]) == 0


def test_cli_github_format(tmp_path, capsys):
    from fishnet_tpu.lint.__main__ import main

    make_project(
        tmp_path, {"fishnet_tpu/client/queue.py":
                   "def f(q):\n    return q.get()\n"}
    )
    assert main(["--root", str(tmp_path), "--format", "github"]) == 1
    out = capsys.readouterr().out
    assert "::error file=fishnet_tpu/client/queue.py,line=2," in out


# ------------------------------------------------------------ repo gates


def test_repo_is_lint_clean():
    """The acceptance gate: the real repo lints clean."""
    project = Project.load(REPO_ROOT)
    baseline_path = REPO_ROOT / "lint-baseline.json"
    baseline = []
    if baseline_path.is_file():
        from fishnet_tpu.lint import load_baseline

        baseline = load_baseline(baseline_path)
    result = run_lint(project, baseline=baseline)
    assert not result.failed, "\n".join(
        f.format_text() for f in result.active)
    assert result.stale_baseline == []


def test_baseline_has_no_config_or_wire_entries():
    """Registry and serde findings must be FIXED, never baselined."""
    baseline_path = REPO_ROOT / "lint-baseline.json"
    if not baseline_path.is_file():
        return
    entries = json.loads(baseline_path.read_text())["entries"]
    offenders = [e for e in entries
                 if e.startswith(("config-", "wire-"))]
    assert offenders == []


def test_cli_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "fishnet_tpu.lint"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_breaking_an_invariant_fails_the_gate(tmp_path):
    """End-to-end mutation: copy the real settings + a consumer into a
    fixture repo, add an off-registry env read, and watch the gate go
    red."""
    for rel in ("fishnet_tpu/utils/settings.py",):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO_ROOT / rel, dst)
    (tmp_path / "docs").mkdir()
    from fishnet_tpu.utils.settings import render_config_md

    (tmp_path / "docs" / "config.md").write_text(render_config_md(),
                                                 encoding="utf-8")
    (tmp_path / "fishnet_tpu" / "rogue.py").write_text(
        'import os\nFOO = os.environ.get("FISHNET_TPU_FOO")\n',
        encoding="utf-8")
    result = run_lint(Project.load(tmp_path), only_families={"config"})
    assert result.failed
    assert set(rules_of(result.active)) == {
        "config-env-read", "config-env-unregistered",
    }


# ------------------------------------------------- loadgen scope extension


LOADGEN_SCOPED_BAD = '''
import asyncio
import time


async def fire(sock):
    time.sleep(0.5)                     # conc-sock-in-loop
    t0 = time.time()                    # obs-wall-clock
    while True:                         # conc-unbounded-retry
        try:
            return await asyncio.open_connection("h", 80), t0
        except OSError:
            await asyncio.sleep(0.1)
'''


def test_loadgen_is_inside_conc_and_obs_scope(tmp_path):
    """tools/loadgen.py fires the open-loop schedule from inside the
    serve event loop, so it carries the same async-hygiene and
    clock-discipline contracts as the serve/fleet packages — the scope
    extension must catch a careless edit there."""
    project = make_project(tmp_path, {"tools/loadgen.py": LOADGEN_SCOPED_BAD})
    result = run_lint(project, only_families={"concurrency", "obs"})
    found = rules_of(result.findings)
    assert "conc-sock-in-loop" in found
    assert "conc-unbounded-retry" in found
    assert "obs-wall-clock" in found


def test_other_tools_stay_out_of_scope(tmp_path):
    # the extension is surgical: one file, not the tools/ directory
    project = make_project(
        tmp_path, {"tools/hop_probe.py": LOADGEN_SCOPED_BAD})
    result = run_lint(project, only_families={"concurrency", "obs"})
    assert rules_of(result.findings) == []


def test_autoscaler_is_inside_fleet_conc_scope(tmp_path):
    # fishnet_tpu/fleet/ covers autoscaler.py by directory prefix; a
    # blocking call inside its control loop must be flagged
    src = '''
import time


async def tick():
    time.sleep(1.0)
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/autoscaler.py": src})
    result = run_lint(project, only_families={"concurrency"})
    assert "conc-sock-in-loop" in rules_of(result.findings)


# ---------------------------------------------- dataflow: use-after-donate


DONATE_BAD = '''
def step(params, state, tt, steps):
    out = _run_segment_jit(params, state, tt, steps)
    lanes = state.lane          # jit-donate-use-after: never rebound
    return out, lanes
'''

DONATE_GOOD = '''
def step(params, state, tt, steps):
    state, tt, n, summ = _run_segment_jit(params, state, tt, steps)
    lanes = state.lane          # ok: reads the rebound state
    return state, tt, n, lanes
'''


def test_donate_use_after_flags_unrebound_read(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": DONATE_BAD})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "jit-donate-use-after")
    assert len(found) == 1 and found[0].line == 4
    assert "_run_segment_jit() at line 3" in found[0].message


def test_donate_rebind_discipline_is_clean(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": DONATE_GOOD})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "jit-donate-use-after") == []


def test_donate_if_join_intersects(tmp_path):
    # the pipelined-scheduler shape: donate speculatively on one branch,
    # read the same name only on the mutually exclusive other branch —
    # dead on ONE path must not poison the join
    src = '''
def step(params, state, tt, steps, pipelined):
    if pipelined:
        nxt = _run_segment_jit(params, state, tt, steps)
    else:
        nxt = (state, tt)
    probe = state.lane           # live on the else path: no finding
    return nxt, probe
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": src})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "jit-donate-use-after") == []


def test_donate_loop_carried_donation_is_caught(tmp_path):
    # a donation at the body's tail reaches the read at its head on the
    # next iteration — the two-pass loop analysis
    src = '''
def drive(params, state, tt, steps, n_chunks):
    for _ in range(n_chunks):
        lanes = state.lane       # dead on iteration 2+
        out = _run_segment_jit(params, state, tt, steps)
    return out
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": src})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "jit-donate-use-after")
    # line 4: the loop-carried read; line 5: the dead name passed back
    # into the donating call itself (also a buffer use)
    assert [f.line for f in found] == [4, 5]


def test_donate_alias_propagates_without_flagging(tmp_path):
    # `y = state` after donation copies the dead handle — the alias
    # itself is not a buffer read, but reading THROUGH it is
    src = '''
def step(params, state, tt, steps):
    out = _run_segment_jit(params, state, tt, steps)
    y = state                   # alias: no finding here
    lanes = y.lane              # finding: reads the dead buffer
    return out, lanes
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": src})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "jit-donate-use-after")
    assert [f.line for f in found] == [5]


def test_donate_module_level_jit_registration(tmp_path):
    # a module-local `jax.jit(..., donate_argnums=...)` assignment joins
    # the registry for that module, whatever it is named
    src = '''
import jax


def _merge(a, b):
    return a + b


_local_jit = jax.jit(_merge, donate_argnums=(0,))


def run(a, b):
    c = _local_jit(a, b)
    return a + c                 # `a` was donated at position 0
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/driver.py": src})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "jit-donate-use-after")
    assert [f.line for f in found] == [14]
    assert "_local_jit() at line 13" in found[0].message


def test_donate_scope_excludes_tests(tmp_path):
    # tests/ deliberately poke dead handles (the is_deleted regression
    # tests assert the read RAISES)
    project = make_project(tmp_path, {"tests/test_x.py": DONATE_BAD})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "jit-donate-use-after") == []


def test_mutated_search_driver_is_caught(tmp_path):
    # both directions on the REAL scheduler code: unmutated ops/search.py
    # is clean, and un-rebinding the segment dispatch (the PR-5 bug
    # shape) is flagged
    text = (REPO_ROOT / "fishnet_tpu/ops/search.py").read_text(
        encoding="utf-8")
    project = make_project(tmp_path, {"fishnet_tpu/ops/search.py": text})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "jit-donate-use-after") == []

    mutated = text.replace(
        "            state, tt, n, _summ = _run_segment_jit(",
        "            state2, tt, n, _summ = _run_segment_jit(",
    )
    assert mutated != text
    project = make_project(
        tmp_path / "mut", {"fishnet_tpu/ops/search.py": mutated})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "jit-donate-use-after")
    assert found and all("_run_segment_jit" in f.message for f in found)


# ------------------------------------------ dataflow: await-shared-mutate


STRADDLE_BAD = '''
async def tick(self):
    if self._streak > 3:         # read ...
        await self.scale_up()    # ... suspension ...
        self._streak = 0         # ... write: check-then-act race
'''

STRADDLE_LOCKED = '''
async def tick(self):
    async with self._lock:
        if self._streak > 3:
            await self.scale_up()
            self._streak = 0
'''

STRADDLE_ANNOTATED = '''
# fishnet-lint: single-writer
async def tick(self):
    if self._streak > 3:
        await self.scale_up()
        self._streak = 0
'''

STRADDLE_SYNC_HELPER = '''
async def tick(self):
    def bump():
        if self._streak > 3:
            self._streak = 0
    await self.scale_up()
    bump()
'''


def test_await_straddle_flagged(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/autoscaler.py": STRADDLE_BAD})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "conc-await-shared-mutate")
    assert [f.line for f in found] == [5]
    assert "self._streak" in found[0].message


def test_await_straddle_lock_annotation_and_helper_pass(tmp_path):
    project = make_project(tmp_path, {
        "fishnet_tpu/fleet/a.py": STRADDLE_LOCKED,
        "fishnet_tpu/fleet/b.py": STRADDLE_ANNOTATED,
        "fishnet_tpu/serve/c.py": STRADDLE_SYNC_HELPER,
    })
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "conc-await-shared-mutate") == []


def test_await_straddle_augassign_is_atomic(tmp_path):
    # stats counters: the += read-modify-write happens at ONE point
    src = '''
async def record(self):
    n = self.stats.ticks
    await self.flush(n)
    self.stats.ticks += 1
'''
    project = make_project(tmp_path, {"fishnet_tpu/serve/s.py": src})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "conc-await-shared-mutate") == []


def test_await_straddle_scope_is_async_serve_fleet_cache(tmp_path):
    # same shape outside the event-loop dirs, or in a sync def: clean
    sync_src = STRADDLE_BAD.replace("async def", "def").replace(
        "await ", "")
    project = make_project(tmp_path, {
        "fishnet_tpu/engine/e.py": STRADDLE_BAD,
        "fishnet_tpu/fleet/s.py": sync_src,
    })
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "conc-await-shared-mutate") == []


def test_mutated_autoscaler_race_is_caught(tmp_path):
    # both directions on the REAL control loop: as-committed it is clean
    # (stop() claims the task before awaiting; tick() is annotated), and
    # reintroducing the stop() check-then-act race is flagged
    text = (REPO_ROOT / "fishnet_tpu/fleet/autoscaler.py").read_text(
        encoding="utf-8")
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/autoscaler.py": text})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "conc-await-shared-mutate") == []

    racy = """\
        if self._task is not None:
            try:
                await asyncio.wait_for(self._task, timeout=10.0)
            except asyncio.TimeoutError:
                self._task.cancel()
            self._task = None
"""
    fixed = """\
        # claim the task before awaiting: a second concurrent stop()
        # sees None and returns instead of cancelling a cleared slot
        task, self._task = self._task, None
        if task is not None:
            try:
                await asyncio.wait_for(task, timeout=10.0)
            except asyncio.TimeoutError:
                task.cancel()
"""
    mutated = text.replace(fixed, racy)
    assert mutated != text
    project = make_project(
        tmp_path / "mut", {"fishnet_tpu/fleet/autoscaler.py": mutated})
    result = run_lint(project, only_families={"dataflow"})
    found = by_rule(result.findings, "conc-await-shared-mutate")
    assert found and any("self._task" in f.message for f in found)


def test_stripped_single_writer_annotation_is_caught(tmp_path):
    # the annotation carries the tick() exemption; removing it without
    # adding a lock re-exposes the straddles
    text = (REPO_ROOT / "fishnet_tpu/fleet/autoscaler.py").read_text(
        encoding="utf-8")
    mutated = text.replace("    # fishnet-lint: single-writer\n", "")
    assert mutated != text
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/autoscaler.py": mutated})
    result = run_lint(project, only_families={"dataflow"})
    assert by_rule(result.findings, "conc-await-shared-mutate")


# ------------------------------------------ mesh: unregistered specs


MESH_BAD = '''
import jax
import jax.sharding as jsh
from jax.sharding import PartitionSpec as P, NamedSharding
from jax.experimental import shard_map as smod
from jax.experimental.shard_map import shard_map


def sneak(mesh, f):
    a = P("dp")                                                # 1
    b = jsh.PartitionSpec("dp", None)                          # 2
    c = NamedSharding(mesh, a)                                 # 3
    d = jax.sharding.NamedSharding(mesh, b)                    # 4
    e = shard_map(f, mesh=mesh, in_specs=a, out_specs=b)       # 5
    g = smod.shard_map(f, mesh=mesh, in_specs=a, out_specs=b)  # 6
    h = jax.shard_map(f, mesh=mesh, in_specs=a, out_specs=b)   # 7
    return a, b, c, d, e, g, h
'''


def test_mesh_unregistered_spec_catches_every_alias_form(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/engine/rogue.py": MESH_BAD})
    result = run_lint(project, only_families={"mesh"})
    found = by_rule(result.findings, "mesh-unregistered-spec")
    assert len(found) == 7
    assert {f.line for f in found} == set(range(10, 17))


def test_mesh_spec_sanctioned_in_partition_and_mesh_modules(tmp_path):
    project = make_project(tmp_path, {
        "fishnet_tpu/parallel/partition.py": MESH_BAD,
        "fishnet_tpu/parallel/mesh.py": MESH_BAD,
    })
    result = run_lint(project, only_families={"mesh"})
    assert not result.findings


def test_mesh_scope_covers_tools_and_bench_not_tests(tmp_path):
    rogue = 'from jax.sharding import PartitionSpec\nS = PartitionSpec("dp")\n'
    project = make_project(tmp_path, {
        "tools/shardtool.py": rogue,
        "bench.py": rogue,
        "tests/test_whatever.py": rogue,
    })
    result = run_lint(project, only_families={"mesh"})
    found = by_rule(result.findings, "mesh-unregistered-spec")
    assert sorted(f.path for f in found) == ["bench.py",
                                            "tools/shardtool.py"]


def test_relocated_partition_registry_is_caught(tmp_path):
    """Mutation test: lift the REAL registry module (which legitimately
    builds PartitionSpec/NamedSharding) into another module — the exact
    drift the rule exists for — and assert the lint flags the copy while
    the sanctioned original stays clean."""
    real = (REPO_ROOT / "fishnet_tpu/parallel/partition.py").read_text()
    assert "NamedSharding(mesh, spec)" in real
    project = make_project(tmp_path, {
        "fishnet_tpu/parallel/partition.py": real,
        "fishnet_tpu/ops/layout.py": real,
    })
    result = run_lint(project, only_families={"mesh"})
    found = by_rule(result.findings, "mesh-unregistered-spec")
    assert found and all(
        f.path == "fishnet_tpu/ops/layout.py" for f in found)


# ------------------------------------------------- lint-core edge cases


def test_suppression_multi_rule_list(tmp_path):
    src = '''
import jax.numpy as jnp
import jax


def kernel(x):
    # fishnet-lint: disable=trace-int-dtype,trace-host-item
    y = jnp.arange(8).item()
    return y


run = jax.jit(kernel)
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/k.py": src})
    result = run_lint(project, only_families={"trace"})
    assert result.findings == []


def test_suppression_above_decorated_def_governs_def_line(tmp_path):
    # the comment-line-above rule governs the NEXT line only: above a
    # decorator it reaches the decorator line, not findings inside the
    # function — suppressions cannot blanket a whole def
    src = '''
import jax.numpy as jnp
import jax


# fishnet-lint: disable=trace-int-dtype
@jax.jit
def kernel(x):
    return jnp.arange(8)
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/k.py": src})
    result = run_lint(project, only_families={"trace"})
    assert [f.rule for f in result.findings] == ["trace-int-dtype"]


def test_suppression_on_continuation_line(tmp_path):
    # findings anchor to the expression's first line; a suppression on
    # the line ABOVE the statement works even when the expression spans
    # several physical lines
    src = '''
import jax.numpy as jnp
import jax


def kernel(x):
    # fishnet-lint: disable=trace-int-dtype
    y = jnp.arange(
        8,
    )
    return y


run = jax.jit(kernel)
'''
    project = make_project(tmp_path, {"fishnet_tpu/ops/k.py": src})
    result = run_lint(project, only_families={"trace"})
    assert result.findings == []


def test_baseline_round_trips_empty(tmp_path):
    # zero findings -> empty baseline -> loads -> still zero, no stale
    blob = json.loads(dump_baseline([]))
    assert blob == {"version": 1, "entries": []}
    p = tmp_path / "lint-baseline.json"
    p.write_text(dump_baseline([]), encoding="utf-8")
    from fishnet_tpu.lint import load_baseline

    assert load_baseline(p) == []
    project = make_project(
        tmp_path, {"fishnet_tpu/ops/clean.py": "X = 1\n"})
    result = run_lint(project, baseline=load_baseline(p))
    assert not result.failed and result.stale_baseline == []


# --------------------------------------------------- CLI: changed/explain


def _git(tmp_path, *args):
    subprocess.run(
        ["git", *args], cwd=tmp_path, check=True, capture_output=True,
        env={"HOME": str(tmp_path), "GIT_AUTHOR_NAME": "t",
             "GIT_AUTHOR_EMAIL": "t@t", "GIT_COMMITTER_NAME": "t",
             "GIT_COMMITTER_EMAIL": "t@t", "PATH": "/usr/bin:/bin:/usr/local/bin"},
    )


def test_cli_changed_scopes_report_to_dirty_files(tmp_path):
    from fishnet_tpu.lint.__main__ import main

    make_project(tmp_path, {
        "fishnet_tpu/serve/old.py": "def f(q):\n    return q.get()\n",
        "fishnet_tpu/serve/new.py": "X = 1\n",
    })
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    # the committed finding exists but is out of diff scope
    assert main(["--root", str(tmp_path), "--changed"]) == 0
    # dirty the clean file with a finding: now in scope, gate fails
    (tmp_path / "fishnet_tpu/serve/new.py").write_text(
        "def g(q):\n    return q.get()\n", encoding="utf-8")
    assert main(["--root", str(tmp_path), "--changed"]) == 1
    # an untracked new file is in scope too
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "more")
    (tmp_path / "fishnet_tpu/serve/born.py").write_text(
        "def h(q):\n    return q.get()\n", encoding="utf-8")
    assert main(["--root", str(tmp_path), "--changed"]) == 1


def test_cli_changed_outside_git_errors(tmp_path):
    from fishnet_tpu.lint.__main__ import main

    make_project(tmp_path, {"fishnet_tpu/client/x.py": "X = 1\n"})
    assert main(["--root", str(tmp_path), "--changed"]) == 2


def test_cli_explain_rule_and_family(capsys):
    from fishnet_tpu.lint.__main__ import main

    assert main(["--explain", "jit-donate-use-after"]) == 0
    out = capsys.readouterr().out
    assert "jit-donate-use-after" in out and "donated" in out

    assert main(["--explain", "dataflow"]) == 0
    out = capsys.readouterr().out
    assert "jit-donate-use-after" in out  # whole family section

    assert main(["--explain", "not-a-rule"]) == 2


def test_lint_report_sarif(tmp_path):
    import tools.lint_report as lint_report

    make_project(tmp_path, {
        "fishnet_tpu/client/queue.py": "def f(q):\n    return q.get()\n"})
    out = tmp_path / "out.sarif"
    rc = lint_report.main(
        ["--root", str(tmp_path), "--sarif", str(out)])
    assert rc == 1
    doc = json.loads(out.read_text(encoding="utf-8"))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "fishnet-lint"
    res = run["results"]
    assert res and res[0]["ruleId"] == "conc-no-timeout"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "fishnet_tpu/client/queue.py"
    assert loc["region"]["startLine"] == 2


# ----------------------------------------------------------- metric names


METRIC_BAD = '''
from fishnet_tpu.obs.metrics import REGISTRY


def fold(reg, tenant):
    reg.counter("hedges_total")                     # outside fishnet_
    reg.counter("fishnet_hedges")                   # counter, no _total
    reg.histogram("fishnet_latency")                # histogram, no unit
    REGISTRY.gauge("Fishnet_Bad-Name")              # charset
    reg.counter(f"cache_{tenant}_total")            # f-string namespace
    reg.absorb_totals("supervisor", {})             # prefix namespace
'''

METRIC_CLEAN = '''
def fold(reg, rec, tenant, name):
    reg.counter("fishnet_fleet_hedges_total")
    reg.counter("fishnet_compile_seconds_total")
    reg.gauge("fishnet_lanes_live")                 # gauges: charset only
    reg.gauge("fishnet_fleet_members_total")        # mirrored total
    reg.histogram("fishnet_boundary_host_ms")
    reg.histogram(f"fishnet_cache_hit_ratio_{tenant}")
    reg.counter(f"fishnet_serve_{name}_total_{tenant}")
    reg.absorb_totals("fishnet_supervisor", {})
    reg.counter(name)                               # dynamic: unchecked
    rec.counter("lanes.live", 3, "engine")          # trace recorder
'''


def test_metric_name_violations_flagged(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/obs/fold.py": METRIC_BAD}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-metric-name")
    assert len(found) == 6
    assert [f.line for f in found] == [6, 7, 8, 9, 10, 11]


def test_metric_name_clean_forms(tmp_path):
    project = make_project(
        tmp_path, {"fishnet_tpu/obs/fold.py": METRIC_CLEAN}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-metric-name") == []


def test_metric_name_slo_prefix_is_callers_choice(tmp_path):
    # SloRecorder-style names lead with an interpolated prefix; the
    # namespace decision happens at the construction site, not here
    src = '''
class SloRecorder:
    def observe(self, reg, what, kind, tenant, v):
        reg.histogram(f"{self.prefix}_{what}_ms_{kind}_{tenant}").observe(v)
'''
    project = make_project(
        tmp_path, {"fishnet_tpu/obs/slo.py": src}
    )
    result = run_lint(project, only_families={"obs"})
    assert by_rule(result.findings, "obs-metric-name") == []


def test_mutated_hedge_counter_name_is_caught(tmp_path):
    """Mutation test: strip the namespace prefix back off the fleet
    hedge counters (the exact drift this rule exists to stop) and
    assert both registrations are flagged."""
    real = (REPO_ROOT / "fishnet_tpu/fleet/coordinator.py").read_text()
    assert real.count('"fishnet_fleet_hedges_total"') == 1
    broken = real.replace(
        '"fishnet_fleet_hedges_total"', '"fleet_hedges_total"').replace(
        '"fishnet_fleet_hedge_wins_total"', '"fleet_hedge_wins_total"')
    project = make_project(
        tmp_path, {"fishnet_tpu/fleet/coordinator.py": broken}
    )
    result = run_lint(project, only_families={"obs"})
    found = by_rule(result.findings, "obs-metric-name")
    assert len(found) == 2
    assert all("fishnet_" in f.message for f in found)
