"""Analysis-cache tests: the satisfaction rule's edges, key agreement
between the serve and chunk builders, LRU bounds, sqlite persistence
across restarts, identity invalidation, per-entry quarantine,
coalescing, fleet hit-sharing, and the TT warm-slice layer.

Everything except the fleet-sharing test and the splice round-trip is
pure python — no subprocesses, no HTTP.
"""
import asyncio
import json
import time

import pytest

from fishnet_tpu.cache.keys import (
    DEPTH_DEFAULT,
    CacheKey,
    content_fingerprint,
    key_for_chunk_position,
    key_for_request,
    keys_for_requests,
    satisfies,
)
from fishnet_tpu.cache.store import (
    AnalysisCache,
    attach_engine,
    cache_from_settings,
)
from fishnet_tpu.client.ipc import (
    Chunk,
    Matrix,
    PositionResponse,
    WorkPosition,
    response_to_wire,
)
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import (
    AnalysisWork,
    EngineFlavor,
    MoveWork,
    NodeLimit,
    Score,
    SkillLevel,
)
from fishnet_tpu.engine.session import PositionRequest
from fishnet_tpu.obs.metrics import MetricsRegistry

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
NET = "cafe0123deadbeef"


class WarnLog(Logger):
    def __init__(self):
        super().__init__(verbose=0)
        self.warnings = []

    def warn(self, text):
        self.warnings.append(text)


def make_chunk(n=1, moves_per=None, depth=3, multipv=None,
               flavor=EngineFlavor.TPU, batch="cachetest",
               nodes=None):
    work = AnalysisWork(
        id=batch,
        nodes=nodes or NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0, depth=depth, multipv=multipv,
    )
    line = ["e2e4", "e7e5", "g1f3", "b8c6", "f1b5"]
    positions = [
        WorkPosition(
            work=work, position_index=i, url=None, skip=False,
            root_fen=START,
            moves=list(moves_per[i]) if moves_per is not None
            else line[:i],
        )
        for i in range(n)
    ]
    return Chunk(work=work, deadline=time.monotonic() + 30.0,
                 variant="standard", flavor=flavor, positions=positions)


def fake_wire(best_move="e2e4", depth=3, nodes=100):
    scores = Matrix()
    scores.set(1, 2, Score.cp(13))
    pvs = Matrix()
    pvs.set(1, 2, [best_move])
    return response_to_wire(PositionResponse(
        work=None, position_index=0, url=None, scores=scores, pvs=pvs,
        best_move=best_move, depth=depth, nodes=nodes, time_s=0.01,
        nps=10_000,
    ))


def some_key(fp="aa", depth=3):
    chunk = make_chunk(1, moves_per=[[]], depth=depth)
    return key_for_chunk_position(chunk, chunk.positions[0], NET)


# ------------------------------------------------------ satisfaction rule


def test_satisfies_at_least_as_deep():
    assert satisfies(12, 12)
    assert satisfies(20, 12)  # deeper answers shallower
    assert not satisfies(12, 20)  # never the reverse
    assert satisfies(1, 1)
    assert not satisfies(0, 1)


def test_satisfies_default_depth_only_matches_itself():
    assert satisfies(DEPTH_DEFAULT, DEPTH_DEFAULT)
    assert not satisfies(DEPTH_DEFAULT, 5)
    assert not satisfies(5, DEPTH_DEFAULT)
    # exhaustively, against every plausible axis value
    for cached in range(-1, 30):
        for wanted in range(-1, 30):
            expect = (
                cached == wanted
                if DEPTH_DEFAULT in (cached, wanted)
                else cached >= wanted
            )
            assert satisfies(cached, wanted) is expect


def test_shape_axes_never_alias():
    """Every request-shape axis that changes the answer changes the
    KEY (not the depth axis): narrower multipv, different budget,
    variant, identity."""
    base_chunk = make_chunk(1, moves_per=[["e2e4"]], depth=8)
    base, base_depth = key_for_chunk_position(
        base_chunk, base_chunk.positions[0], NET
    )
    assert base_depth == 8

    variations = [
        make_chunk(1, moves_per=[["e2e4"]], depth=8, multipv=3),
        make_chunk(1, moves_per=[["e2e4"]], depth=8,  # different budget
                   nodes=NodeLimit(sf16=2_000_000, classical=8_000_000)),
        make_chunk(1, moves_per=[["e2e4"]], depth=8,  # HCE budget axis
                   flavor=EngineFlavor.MULTI_VARIANT),
        make_chunk(1, moves_per=[["d2d4"]], depth=8),  # different position
    ]
    for chunk in variations:
        key, _ = key_for_chunk_position(chunk, chunk.positions[0], NET)
        assert key != base
    other_net, _ = key_for_chunk_position(
        base_chunk, base_chunk.positions[0], "feedbeeffeedbeef"
    )
    assert other_net != base

    # depth is NOT in the shape key: a deeper ask of the same shape
    # shares the key and differs only on the satisfaction axis
    deeper = make_chunk(1, moves_per=[["e2e4"]], depth=20)
    key, depth = key_for_chunk_position(deeper, deeper.positions[0], NET)
    assert key == base and depth == 20


def test_multipv_none_and_one_do_not_alias():
    # same search, different answer matrix shape -> different entries
    none_chunk = make_chunk(1, moves_per=[[]], depth=5, multipv=None)
    one_chunk = make_chunk(1, moves_per=[[]], depth=5, multipv=1)
    k_none, _ = key_for_chunk_position(none_chunk, none_chunk.positions[0],
                                       NET)
    k_one, _ = key_for_chunk_position(one_chunk, one_chunk.positions[0],
                                      NET)
    assert k_none.multipv == -1 and k_one.multipv == 1
    assert k_none != k_one


def test_bestmove_keys_use_the_default_depth_sentinel():
    work = MoveWork(id="bm", level=SkillLevel(5))
    chunk = Chunk(
        work=work, deadline=time.monotonic() + 30.0, variant="standard",
        flavor=EngineFlavor.OFFICIAL,
        positions=[WorkPosition(work=work, position_index=0, url=None,
                                skip=False, root_fen=START, moves=[])],
    )
    key, depth = key_for_chunk_position(chunk, chunk.positions[0], NET)
    assert key.kind == "bestmove" and key.level == 5
    assert key.multipv == -1 and key.nodes == -1
    assert depth == DEPTH_DEFAULT
    # a different skill level is a different key entirely
    work2 = MoveWork(id="bm2", level=SkillLevel(2))
    chunk2 = Chunk(
        work=work2, deadline=time.monotonic() + 30.0, variant="standard",
        flavor=EngineFlavor.OFFICIAL,
        positions=[WorkPosition(work=work2, position_index=0, url=None,
                                skip=False, root_fen=START, moves=[])],
    )
    key2, _ = key_for_chunk_position(chunk2, chunk2.positions[0], NET)
    assert key2 != key


def test_content_fingerprint_ignores_slot_index():
    chunk = make_chunk(2, moves_per=[["e2e4"], ["e2e4"]])
    k0, _ = key_for_chunk_position(chunk, chunk.positions[0], NET)
    k1, _ = key_for_chunk_position(chunk, chunk.positions[1], NET)
    assert k0 == k1  # same board, different slot: one entry
    assert content_fingerprint(START, ["e2e4"]) != \
        content_fingerprint(START, [])


def test_serve_and_chunk_builders_agree():
    """keys_for_requests (the serve consult) and key_for_chunk_position
    (the coordinator/engine fill) produce identical keys for the same
    positions — by construction, since the former routes through the
    session's own chunk planner."""
    reqs = [
        PositionRequest(fen=START, moves=("e2e4",), depth=6,
                        deadline=time.monotonic() + 8.0),
        PositionRequest(fen=START, moves=(), depth=6,
                        deadline=time.monotonic() + 8.0),
    ]
    served = keys_for_requests(reqs, NET, flavor=EngineFlavor.TPU)
    assert len(served) == 2 and served[0][1] == 6

    from fishnet_tpu.engine.session import requests_to_chunks

    filled = {}
    for chunk, indices in requests_to_chunks(
        list(reqs), flavor=EngineFlavor.TPU
    ):
        for wp, idx in zip(chunk.positions, indices):
            filled[idx] = key_for_chunk_position(chunk, wp, NET)
    assert [filled[i] for i in range(2)] == served
    assert key_for_request(reqs[0], NET) == served[0]


# ----------------------------------------------------------- memory tier


def test_store_lookup_and_satisfaction_gate():
    cache = AnalysisCache(NET)
    key, depth = some_key(depth=5)
    assert cache.lookup(key, 5) is None  # miss
    assert cache.store(key, 5, fake_wire(depth=5)) == "inserted"
    assert cache.lookup(key, 5)["depth"] == 5  # exact
    assert cache.lookup(key, 3)["depth"] == 5  # deeper satisfies
    assert cache.lookup(key, 8) is None  # shallower never serves deeper
    c = cache.counters()
    assert c["hits"] == 2 and c["misses"] == 2 and c["fills"] == 1


def test_store_is_idempotent_and_deepens():
    cache = AnalysisCache(NET)
    key, _ = some_key()
    assert cache.store(key, 5, fake_wire(depth=5)) == "inserted"
    # replayed/re-dispatched deliveries of the same (or shallower) work
    assert cache.store(key, 5, fake_wire(depth=5)) == "kept"
    assert cache.store(key, 3, fake_wire(depth=3)) == "kept"
    assert cache.stats.dup_fills == 2
    # a deeper result replaces
    assert cache.store(key, 9, fake_wire(depth=9)) == "deepened"
    assert cache.lookup(key, 9)["depth"] == 9


def test_store_refuses_foreign_identity():
    cache = AnalysisCache(NET)
    chunk = make_chunk(1, moves_per=[[]])
    key, depth = key_for_chunk_position(chunk, chunk.positions[0],
                                        "feedbeeffeedbeef")
    assert cache.store(key, depth, fake_wire()) == "kept"
    assert cache.counters()["entries"] == 0


def test_lru_bounds_by_entries_and_bytes():
    cache = AnalysisCache(NET, max_entries=2)
    chunk = make_chunk(3)
    keys = [key_for_chunk_position(chunk, wp, NET)
            for wp in chunk.positions]
    for key, depth in keys:
        cache.store(key, depth, fake_wire())
    assert cache.counters()["entries"] == 2
    assert cache.stats.evictions == 1
    assert cache.lookup(*keys[0]) is None  # the oldest fell out
    assert cache.lookup(*keys[2]) is not None

    one_entry = len(json.dumps(fake_wire(), sort_keys=True))
    tight = AnalysisCache(NET, max_bytes=one_entry * 2)
    for key, depth in keys:
        tight.store(key, depth, fake_wire())
    assert tight.stats.evictions >= 1
    assert tight.counters()["bytes"] <= one_entry * 2


def test_hydrate_rewrites_requester_bookkeeping():
    wire = fake_wire(best_move="g1f3", depth=4)
    resp = AnalysisCache.hydrate(wire, 7, url="http://x/y")
    assert resp.position_index == 7 and resp.url == "http://x/y"
    assert resp.best_move == "g1f3" and resp.depth == 4
    # the stored wire was not mutated for the next requester
    assert "position_index" not in wire or \
        wire.get("position_index") != 7 or True


# ------------------------------------------------------------ persistence


def test_persisted_entries_survive_restart(tmp_path):
    key, depth = some_key(depth=5)
    cache1 = AnalysisCache(NET, directory=str(tmp_path))
    cache1.store(key, depth, fake_wire(depth=5))
    assert (tmp_path / "entries").glob("*.json")

    cache2 = AnalysisCache(NET, directory=str(tmp_path))
    assert cache2.counters()["disk_entries"] == 1
    assert cache2.counters()["entries"] == 0  # memory starts cold
    wire = cache2.lookup(key, 3)  # deeper-on-disk satisfies
    assert wire is not None and wire["depth"] == 5
    assert cache2.stats.disk_hits == 1
    # promoted into memory: the second read never touches the disk
    cache2.lookup(key, 3)
    assert cache2.stats.disk_hits == 1 and cache2.stats.hits == 2
    # the satisfaction gate applies to the disk tier too
    assert cache2.lookup(key, 9) is None


def test_identity_change_invalidates_with_log_line(tmp_path):
    key, depth = some_key()
    cache1 = AnalysisCache(NET, directory=str(tmp_path))
    cache1.store(key, depth, fake_wire())
    assert cache1.counters()["disk_entries"] == 1

    log = WarnLog()
    cache2 = AnalysisCache("feedbeeffeedbeef", directory=str(tmp_path),
                           logger=log)
    assert cache2.counters()["disk_entries"] == 0
    assert cache2.stats.invalidated == 1
    assert len(log.warnings) == 1
    assert "identity fingerprint changed" in log.warnings[0]
    assert "invalidated 1 persisted entry" in log.warnings[0]
    assert list((tmp_path / "entries").glob("*.json")) == []

    # a same-identity reopen is NOT an invalidation
    log3 = WarnLog()
    cache3 = AnalysisCache("feedbeeffeedbeef", directory=str(tmp_path),
                           logger=log3)
    assert cache3.stats.invalidated == 0 and log3.warnings == []


def test_corrupt_payload_quarantined_exactly_once(tmp_path):
    chunk = make_chunk(2, moves_per=[[], ["e2e4"]])
    keys = [key_for_chunk_position(chunk, wp, NET)
            for wp in chunk.positions]
    cache1 = AnalysisCache(NET, directory=str(tmp_path))
    for key, depth in keys:
        cache1.store(key, depth, fake_wire())

    poisoned = keys[0][0].row_id() + ".json"
    path = tmp_path / "entries" / poisoned
    path.write_bytes(path.read_bytes()[:-4] + b"ruin")

    log = WarnLog()
    cache2 = AnalysisCache(NET, directory=str(tmp_path), logger=log)
    assert cache2.lookup(*keys[0]) is None  # corruption reads as a miss
    assert cache2.stats.quarantined == 1
    assert not path.exists()
    assert (tmp_path / "entries" / (poisoned + ".bad")).exists()
    assert [w for w in log.warnings if "integrity check failed" in w] \
        and len(log.warnings) == 1
    # exactly that entry: the sibling still serves off the disk
    assert cache2.lookup(*keys[1]) is not None
    assert cache2.stats.disk_hits == 1
    # the index row is gone for good: a fresh open sees one entry and
    # the poisoned key stays a plain miss (no second quarantine)
    assert cache2.lookup(*keys[0]) is None
    assert cache2.stats.quarantined == 1 and len(log.warnings) == 1
    cache3 = AnalysisCache(NET, directory=str(tmp_path))
    assert cache3.counters()["disk_entries"] == 1


# ------------------------------------------------------------- coalescing


def test_lease_coalesces_one_search_n_deliveries():
    async def scenario():
        cache = AnalysisCache(NET)
        key, depth = some_key(depth=5)
        state, lease = cache.lease(key, depth)
        assert state == "lead"
        # identical and shallower requests join the in-flight search
        joins = [cache.lease(key, depth), cache.lease(key, 3)]
        assert [s for s, _ in joins] == ["join", "join"]
        assert cache.stats.coalesced == 2
        # a deeper ask cannot ride a shallower search: its own lead
        state, deeper = cache.lease(key, 9)
        assert state == "lead"

        # the leader's fill lands via the delivery hook, then settle
        # resolves the followers; settle itself never writes the cache
        wire = fake_wire(depth=5)
        cache.store(key, 5, wire)
        lease.settle(wire)
        for _, fut in joins:
            assert await asyncio.wait_for(fut, 1.0) == wire
        deeper.settle(None)

        # the fill landed: the next consult is a plain hit
        state, got = cache.lease(key, depth)
        assert state == "hit" and got["depth"] == 5

    asyncio.run(scenario())


def test_lease_leader_failure_resolves_followers_with_none():
    async def scenario():
        cache = AnalysisCache(NET)
        key, depth = some_key(depth=4)
        _, lease = cache.lease(key, depth)
        _, fut = cache.lease(key, depth)
        lease.settle(None)  # the leader's search failed
        assert await asyncio.wait_for(fut, 1.0) is None
        # the pending slot was released: the retry leads its own search
        state, retry = cache.lease(key, depth)
        assert state == "lead"
        retry.settle(None)
        # settle is idempotent (the serve layer settles defensively)
        retry.settle(fake_wire())
        assert cache.lookup(key, depth) is None

    asyncio.run(scenario())


# ------------------------------------------------------------ fleet sharing


class MustNotSearch:
    """A member engine that fails the test if any position reaches it."""

    max_depth = 2

    async def go_multiple(self, chunk):
        raise AssertionError(
            "a fully-cached chunk was dispatched to a member"
        )

    async def close(self):
        pass


def test_second_member_inherits_the_fleet_hit_set():
    """A second coordinator sharing the cache answers a corpus it has
    NEVER searched entirely from its sibling's fills — the fleet-wide
    '>= 50% hit ratio on an unseen corpus' acceptance bar, met at 100%
    here because the corpus is fully covered."""
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.fleet import FleetCoordinator, FleetMember

    def corpus_chunk(batch):
        work = AnalysisWork(
            id=batch, nodes=NodeLimit(sf16=200_000, classical=400_000),
            timeout_s=20.0, depth=2, multipv=None,
        )
        line = ["e2e4", "e7e5", "g1f3", "b8c6"]
        return Chunk(
            work=work, deadline=time.monotonic() + 20.0,
            variant="standard", flavor=EngineFlavor.OFFICIAL,
            positions=[
                WorkPosition(work=work, position_index=i, url=None,
                             skip=False, root_fen=START, moves=line[:i])
                for i in range(4)
            ],
        )

    async def scenario():
        cache = AnalysisCache("fleet-shared-identity")
        coord_a = FleetCoordinator(
            [FleetMember(name="a0", engine=PyEngine(max_depth=2))],
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            cache=cache,
        )
        try:
            first = await coord_a.go_multiple(corpus_chunk("warmup"))
        finally:
            await coord_a.close()
        assert cache.stats.fills == 4

        hits_before = cache.stats.hits
        coord_b = FleetCoordinator(
            [FleetMember(name="b0", engine=MustNotSearch())],
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            cache=cache,
        )
        try:
            second = await coord_b.go_multiple(corpus_chunk("unseen"))
        finally:
            await coord_b.close()
        hits = cache.stats.hits - hits_before
        assert hits / 4 >= 0.5  # the acceptance bar
        assert hits == 4  # and in fact the whole corpus
        assert [r.position_index for r in second] == list(range(4))

        def comp(r):
            wire = response_to_wire(r)
            return {k: wire[k] for k in ("scores", "pvs", "best_move",
                                         "depth", "nodes")}

        assert [comp(r) for r in second] == [comp(r) for r in first]

    asyncio.run(scenario())


# ---------------------------------------------------------------- wiring


def test_cache_from_settings_gates(tmp_path, monkeypatch):
    from fishnet_tpu.engine.pyengine import PyEngine

    monkeypatch.setenv("FISHNET_TPU_CACHE", "0")
    assert cache_from_settings(PyEngine(max_depth=2),
                               EngineFlavor.OFFICIAL) is None

    monkeypatch.setenv("FISHNET_TPU_CACHE", "1")
    monkeypatch.setenv("FISHNET_TPU_CACHE_PERSIST", "0")
    cache = cache_from_settings(PyEngine(max_depth=2),
                                EngineFlavor.OFFICIAL)
    assert cache is not None and cache.recorder is None  # memory-only

    monkeypatch.setenv("FISHNET_TPU_CACHE_PERSIST", "1")
    monkeypatch.setenv("FISHNET_TPU_CACHE_DIR", str(tmp_path / "c"))
    monkeypatch.setenv("FISHNET_TPU_CACHE_MAX_ENTRIES", "7")
    cache = cache_from_settings(PyEngine(max_depth=2),
                                EngineFlavor.OFFICIAL)
    assert cache.recorder is not None and cache.max_entries == 7
    # identity is pinned to the engine fingerprint, not a constant
    from fishnet_tpu.cache.keys import engine_identity

    assert cache.net == engine_identity(PyEngine(max_depth=2),
                                        EngineFlavor.OFFICIAL)


def test_attach_engine_chains_the_delivery_hook():
    class HookedEngine:
        on_deliver = None

    eng = HookedEngine()
    seen = []
    eng.on_deliver = lambda chunk, wp, resp: seen.append("prev")
    cache = AnalysisCache(NET)
    assert attach_engine(eng, cache) is True

    chunk = make_chunk(1, moves_per=[["e2e4"]], depth=3)
    scores = Matrix()
    scores.set(1, 2, Score.cp(9))
    pvs = Matrix()
    pvs.set(1, 2, ["e7e5"])
    resp = PositionResponse(
        work=None, position_index=0, url=None, scores=scores, pvs=pvs,
        best_move="e7e5", depth=3, nodes=50, time_s=0.01, nps=5_000,
    )
    eng.on_deliver(chunk, chunk.positions[0], resp)
    assert seen == ["prev"]  # the previous hook still ran
    key, depth = key_for_chunk_position(chunk, chunk.positions[0], NET)
    assert cache.lookup(key, depth)["best_move"] == "e7e5"

    assert attach_engine(object(), cache) is False  # no delivery hook


def test_metrics_export_and_tenant_histogram():
    registry = MetricsRegistry()
    cache = AnalysisCache(NET, registry=registry)
    key, depth = some_key()
    cache.store(key, depth, fake_wire())
    cache.lookup(key, depth)
    cache.observe_request("team-a", 1, 2)
    cache.export_metrics()
    text = registry.render_prometheus()
    assert "fishnet_cache_hits 1" in text
    assert "fishnet_cache_entries 1" in text
    assert "fishnet_cache_hit_ratio_team_a" in text or \
        "fishnet_cache_hit_ratio_team-a" in text


# --------------------------------------------------------- tt warm slices


def test_prefix_fingerprint_truncates_at_the_prefix():
    from fishnet_tpu.cache.ttwarm import prefix_fingerprint

    a = prefix_fingerprint(START, ["e2e4", "e7e5", "g1f3"], 2)
    b = prefix_fingerprint(START, ["e2e4", "e7e5", "b8c6"], 2)
    assert a == b  # divergence past the prefix shares a slice
    c = prefix_fingerprint(START, ["d2d4", "e7e5", "g1f3"], 2)
    assert c != a  # divergence inside it does not


def test_extract_and_splice_round_trip():
    jnp = pytest.importorskip("jax.numpy")
    import numpy as np

    from fishnet_tpu.cache.ttwarm import extract_rows, splice_rows

    data = jnp.zeros((16, 4), dtype=jnp.int32)
    data = data.at[5].set(jnp.array([9, 9, 9, 9], dtype=jnp.int32))

    block = np.array([[11, 12, 13, 1], [0, 0, 0, 0], [11, 12, 13, 1]])
    rows = extract_rows(block, [3, 7, 3])
    assert rows == [[3, 11, 12, 13, 1]]  # empty + duplicate slots drop

    spliced, n = splice_rows(
        data, [[3, 11, 12, 13, 1], [5, 1, 2, 3, 4], [99, 1, 1, 1, 1]]
    )
    assert n == 1  # slot 5 is LIVE and never clobbered; 99 out of range
    assert list(np.asarray(spliced[3])) == [11, 12, 13, 1]
    assert list(np.asarray(spliced[5])) == [9, 9, 9, 9]


def test_ttwarm_store_persists_and_quarantines(tmp_path):
    from fishnet_tpu.cache.ttwarm import TTWarmStore

    store = TTWarmStore(directory=str(tmp_path), logger=WarnLog())
    store.record(8, "prefix-a", [[3, 1, 2, 3, 4]])
    # merge: a fresher row for the same slot wins, new slots append
    store.record(8, "prefix-a", [[3, 9, 9, 9, 9], [7, 1, 1, 1, 1]])
    assert sorted(store.lookup(8, "prefix-a")) == [
        [3, 9, 9, 9, 9], [7, 1, 1, 1, 1]
    ]
    # slot indices are size-scoped: another table size is another slice
    assert store.lookup(9, "prefix-a") == []

    fresh = TTWarmStore(directory=str(tmp_path), logger=WarnLog())
    assert sorted(fresh.lookup(8, "prefix-a")) == [
        [3, 9, 9, 9, 9], [7, 1, 1, 1, 1]
    ]

    path = next((tmp_path / "tt").glob("*.json"))
    path.write_bytes(path.read_bytes()[:-4] + b"ruin")
    log = WarnLog()
    poisoned = TTWarmStore(directory=str(tmp_path), logger=log)
    assert poisoned.lookup(8, "prefix-a") == []
    assert poisoned.quarantined == 1
    assert not path.exists()
    assert (tmp_path / "tt" / (path.name + ".bad")).exists()
    assert len(log.warnings) == 1
