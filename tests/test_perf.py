"""fishnet-perf tests: the sqlite ledger round-trip, backfill
idempotence over the checked-in bench artifacts, the direction/noise
math behind the regression gate, report-only semantics for rows without
a matching env fingerprint, bench-round emission, and a CPU smoke of
the cost_analysis capture path.

The gate's acceptance contract lives here: a first run (no baseline)
passes, a seeded 10% regression in a deterministic counter metric
fails, and a wall-clock swing or fingerprint mismatch never hard-fails.
"""
import json
from pathlib import Path

import pytest

from fishnet_tpu.obs import metrics as obs_metrics
from fishnet_tpu.obs import perf
from tools import perf_report

REPO_ROOT = Path(__file__).resolve().parents[1]

FP = "feedc0de9abc"


def seed(ledger, runs, fingerprint=FP, bench_row="search",
         metric="positions_per_kstep"):
    """n runs of {bench_row: {metric: value}} under one fingerprint."""
    for i, value in enumerate(runs):
        ledger.ingest_run(
            f"run{i}", {bench_row: {metric: float(value)}},
            sha=f"sha{i}", fingerprint=fingerprint,
        )


# ------------------------------------------------------------------ ledger


def test_ledger_round_trip(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    n = led.ingest_run(
        "r1", {"search": {"nps": 123.5, "nodes": 9.0}},
        sha="abc", fingerprint=FP,
    )
    assert n == 2
    led.close()

    led = perf.PerfLedger.open(p)
    run = led.latest_run()
    assert run["run_id"] == "r1"
    assert run["fingerprint"] == FP
    assert led.run_metrics("r1") == {
        "search": {"nps": 123.5, "nodes": 9.0}
    }
    led.close()


def test_ledger_replace_is_idempotent(tmp_path):
    led = perf.PerfLedger.open(str(tmp_path / "perf.db"))
    led.ingest_run("r1", {"search": {"nodes": 1.0}})
    seq1 = led.latest_run()["seq"]
    led.ingest_run("r1", {"search": {"nodes": 2.0}})
    assert led.latest_run()["seq"] == seq1  # same run keeps its seq
    assert led.run_metrics("r1") == {"search": {"nodes": 2.0}}
    led.close()


def test_backfill_ingests_checked_in_artifacts_idempotently():
    led = perf.PerfLedger.open(":memory:")
    n1 = led.backfill(str(REPO_ROOT))
    n2 = led.backfill(str(REPO_ROOT))
    assert n1 > 0 and n1 == n2
    runs = {r["run_id"]: r for r in led.runs()}
    # every checked-in round ingests, including the failed early ones
    for i in range(1, 6):
        assert f"backfill:BENCH_r0{i}" in runs
        assert f"backfill:MULTICHIP_r0{i}" in runs
    # backfilled history carries no env fingerprint: never gated
    assert all(r["fingerprint"] == "" for r in runs.values())
    led.close()


def test_history_filters_on_fingerprint(tmp_path):
    led = perf.PerfLedger.open(str(tmp_path / "perf.db"))
    seed(led, [100, 101, 102])
    led.ingest_run("other", {"search": {"positions_per_kstep": 55.0}},
                   fingerprint="0ther")
    hist = led.history("search", "positions_per_kstep", fingerprint=FP)
    assert [v for _, v in hist] == [100.0, 101.0, 102.0]
    led.close()


def test_flatten_result():
    flat = perf.flatten_result({
        "nps": 10, "ok": True, "name": "skipped", "lanes": [1, 2],
        "summary": {"p99": 4.5, "deep": {"x": 1}},
    })
    assert flat == {
        "nps": 10.0, "ok": 1.0, "summary.p99": 4.5, "summary.deep.x": 1.0,
    }


def test_split_mesh_rows():
    rows = {}
    rest = perf.split_mesh_rows(rows, "mesh_scaling", {
        "ndev": {"1": {"positions_per_s": 5.0},
                 "2": {"positions_per_s": 9.0}},
        "warm_x": 1.2,
    })
    assert set(rows) == {"mesh_scaling_ndev1", "mesh_scaling_ndev2"}
    assert rest == {"warm_x": 1.2}
    # a stage's own RESULT carries ndev as an int: passes through
    res = {"ndev": 8, "nps": 1.0}
    assert perf.split_mesh_rows({}, "stage", res) is res


def test_emit_bench_round(tmp_path):
    (tmp_path / "BENCH_r04.json").write_text("{}", encoding="utf-8")
    led = perf.PerfLedger.open(":memory:")
    led.ingest_run("r1", {"search": {"nodes": 7.0}},
                   sha="abc", fingerprint=FP)
    out = led.emit_bench_round("r1", root=str(tmp_path))
    assert out.endswith("BENCH_r05.json")  # next round after r04
    obj = json.loads(Path(out).read_text(encoding="utf-8"))
    assert obj["n"] == 5
    assert obj["run_id"] == "r1"
    assert obj["git_sha"] == "abc"
    assert obj["fingerprint"] == FP
    assert "build_info" in obj
    assert obj["rows"] == {"search": {"nodes": 7.0}}
    # the emitted artifact parses back into the same rows
    assert perf._parse_bench_artifact(out) == {"search": {"nodes": 7.0}}
    led.close()


# --------------------------------------------------------------- direction


@pytest.mark.parametrize("metric,direction,tier", [
    ("positions_per_kstep", "up", "counter"),
    ("scaling_x", "up", "counter"),
    ("mean_live_occupancy", "up", "counter"),
    ("transfers_per_boundary", "down", "counter"),
    ("nodes", "flat", "counter"),
    ("steps_per_shard", "flat", "counter"),
    ("rc", "flat", "counter"),
    ("flops", "down", "counter"),
    ("bytes_accessed", "down", "counter"),
    ("positions_per_s", "up", "wallclock"),
    ("summary.p99", "down", "wallclock"),
    ("compile_ms", "down", "wallclock"),
    ("dt", "down", "wallclock"),
    ("unknown_metric", "flat", "wallclock"),
])
def test_direction_table(metric, direction, tier):
    assert perf_report.classify(metric) == (direction, tier)


def test_noise_band_floor_and_spread():
    # identical history: the floor applies
    assert perf_report.noise_band([100.0] * 5, "counter") == \
        pytest.approx(perf_report.DEFAULT_COUNTER_BAND)
    # noisy history: 2x relative stdev beats the floor
    band = perf_report.noise_band([90.0, 110.0, 95.0, 105.0], "counter")
    assert band > perf_report.DEFAULT_COUNTER_BAND
    # wall-clock series always get the wide band
    assert perf_report.noise_band([100.0] * 5, "wallclock") == \
        pytest.approx(perf_report.WALLCLOCK_BAND)


# -------------------------------------------------------------------- gate


def test_first_run_passes(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100])  # one run: nothing to compare against
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 0


def test_seeded_counter_regression_fails(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100, 100.5, 101, 100.2, 90])  # 10% drop on an up-counter
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 1
    # report-only mode still exits clean on the same ledger
    assert perf_report.main(["--ledger", p, "--no-backfill"]) == 0


def test_improvement_passes(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100, 100.5, 101, 110])  # up-counter moving up
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 0


def test_flat_metric_regresses_in_both_directions(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [1000, 1000, 1000, 1100], metric="nodes")
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 1


def test_fingerprint_mismatch_is_report_only(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100, 100, 100, 100])
    # same metric collapses 10% on DIFFERENT hardware/env: not gated
    led.ingest_run(
        "hw", {"search": {"positions_per_kstep": 90.0}},
        sha="zzz", fingerprint="0therhardware",
    )
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 0


def test_unfingerprinted_run_is_report_only(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100, 100, 100, 100], fingerprint="")
    led.close()
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 0


def test_wallclock_swing_never_gates(tmp_path):
    p = str(tmp_path / "perf.db")
    led = perf.PerfLedger.open(p)
    seed(led, [100, 100, 100, 50], metric="positions_per_s")
    led.close()
    report = None
    assert perf_report.main(
        ["--ledger", p, "--check", "--no-backfill"]) == 0
    led = perf.PerfLedger.open(p)
    report = perf_report.evaluate(led)
    led.close()
    (row,) = report["rows"]
    assert row["status"] == "regression" and not row["gated"]


def test_check_passes_on_unmodified_repo(tmp_path):
    """Acceptance: a fresh ledger built from the checked-in artifacts
    gates nothing (backfilled history has no fingerprint)."""
    p = str(tmp_path / "fresh.db")
    assert perf_report.main(["--ledger", p, "--check"]) == 0


# ------------------------------------------------------------------- costs


def test_program_cost_cpu_smoke():
    jax = pytest.importorskip("jax")
    jnp = jax.numpy

    def f(x):
        return (x @ x).sum()

    compiled = jax.jit(f).lower(
        jax.ShapeDtypeStruct((16, 16), jnp.float32)).compile()
    cost = perf.program_cost(compiled)
    assert cost.get("flops", 0.0) > 0
    reg = obs_metrics.MetricsRegistry()
    recorded = perf.record_program_cost("run_segment!", compiled,
                                        registry=reg)
    assert recorded
    snap = reg.snapshot()
    assert snap["fishnet_program_flops_run_segment"] > 0


def test_build_info_gauge_renders():
    reg = obs_metrics.MetricsRegistry()
    info = perf.register_build_info(registry=reg)
    assert "git_sha" in info
    text = reg.render_prometheus()
    assert "fishnet_build_info 1" in text
    assert f"git_sha={info['git_sha']}" in text


def test_live_snapshot_shape():
    reg = obs_metrics.MetricsRegistry()
    reg.gauge("fishnet_lanes_live").set(3)
    snap = perf.live_snapshot(registry=reg, ledger_path=":memory:")
    assert snap["build"]
    assert snap["metrics"] == {"fishnet_lanes_live": 3.0}
    assert "fingerprint" in snap and "programs" in snap
