"""Continuous lane refill (round 7): scheduler and search_stream tests.

Three contracts from the round-7 change (engine/tpu.py LaneScheduler,
ops/search.py refill_lanes/search_stream):

1. Refill OFF is bit-identical to the chunk-serial engine — same routing,
   same scores, same node counts. The refill path must be a pure opt-in.
2. Refill ON produces the SAME per-position results as refill off when
   nothing couples the lanes (no TT, no helpers): resplicing a DONE lane
   mid-flight must not perturb live lanes.
3. Every submitted position gets exactly one response, even when several
   chunks share the engine concurrently through the combining driver.

conftest.py sets FISHNET_TPU_REFILL=0, so engines here opt in explicitly
with refill=True. This file pins the SINGLE-DEVICE scheduler semantics:
refill engines force engine.mesh = None, which is exactly what a
single-device production host looks like (conftest's 8 virtual CPU
devices would otherwise give every engine a mesh — the sharded
scheduler path has its own suite, tests/test_mesh_refill.py).
"""
import asyncio
import threading
import time

import numpy as np
import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.tpu import TpuEngine

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
GAME = ["e2e4", "c7c5", "g1f3", "d7d6"]


def analysis_work(depth=3):
    return AnalysisWork(
        id="refill01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=None,
    )


def make_chunk(work, n_positions=3, moves=GAME):
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=moves[:i])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + 120,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


def run(engine, chunk):
    return asyncio.run(engine.go_multiple(chunk))


def make_refill_engine(**kw):
    """Refill-on engine in the single-device configuration this suite
    pins (mesh=None), no helper coupling unless asked."""
    kw.setdefault("max_depth", 3)
    kw.setdefault("tt_size_log2", 0)
    kw.setdefault("helper_lanes", 1)
    engine = TpuEngine(refill=True, **kw)
    engine.mesh = None  # single-device semantics (mesh suite is separate)
    engine.n_dev = 1
    return engine


def test_refill_defaults_to_registry():
    """refill=None defers to FISHNET_TPU_REFILL, which conftest pins to 0;
    an explicit constructor argument wins over the registry."""
    assert TpuEngine(max_depth=2, tt_size_log2=0).refill is False
    assert TpuEngine(max_depth=2, tt_size_log2=0, refill=True).refill is True


def _stub_search(engine):
    """Routing tests need the dispatch path, not a real search — stub
    the device program (same pattern as test_tpu_engine.py)."""

    def fake_search(roots, depth_arr, budget_arr, deadline=None, **kw):
        B = len(depth_arr)
        return {
            "done": np.ones(B, bool),
            "score": np.full(B, 20, np.int32),
            "move": np.full(B, 12 | (28 << 6), np.int32),  # e2e4
            "pv": np.full((B, 4), -1, np.int32),
            "pv_len": np.zeros(B, np.int32),
            "nodes": np.ones(B, np.int32),
        }

    engine._search = fake_search


def test_refill_off_never_touches_scheduler():
    """The refill-off engine must route every chunk through the serial
    path: a poisoned scheduler proves the routing never reaches it."""
    engine = TpuEngine(max_depth=2, tt_size_log2=0, refill=False)
    _stub_search(engine)

    def boom(chunk):
        raise AssertionError("scheduler engaged with refill disabled")

    engine._scheduler.run_chunk = boom
    responses = run(engine, make_chunk(analysis_work(depth=2)))
    assert len(responses) == 3
    assert all(r.best_move for r in responses)


def test_mesh_refill_optout_falls_back_to_serial():
    """FISHNET_TPU_MESH_REFILL=0 (mesh_refill=False) pins a MESHED
    engine back to strict chunk-serial dispatch even with refill on —
    the scheduler must never engage. (With mesh_refill on, the meshed
    scheduler path is covered by tests/test_mesh_refill.py.)"""
    engine = TpuEngine(max_depth=2, tt_size_log2=0, helper_lanes=1,
                       refill=True, mesh_refill=False)
    assert engine.mesh is not None  # conftest provides 8 virtual devices
    _stub_search(engine)

    def boom(chunk):
        raise AssertionError("scheduler engaged with mesh refill opted out")

    engine._scheduler.run_chunk = boom
    responses = run(engine, make_chunk(analysis_work(depth=2)))
    assert len(responses) == 3


def test_refill_on_matches_refill_off():
    """Uncoupled lanes (no TT, no helpers): the scheduler must reproduce
    the chunk-serial engine's results exactly — scores, PVs, node counts,
    per-depth matrices. This is the refill-off bit-identity guarantee
    from the other side: resplicing DONE lanes never perturbs live ones."""
    serial = TpuEngine(max_depth=3, tt_size_log2=0, helper_lanes=1,
                       refill=False)
    serial.mesh = None
    serial.n_dev = 1
    refill = make_refill_engine()
    chunk = make_chunk(analysis_work(depth=3), n_positions=4)
    want = run(serial, chunk)
    got = run(refill, make_chunk(analysis_work(depth=3), n_positions=4))
    assert refill.occupancy_totals["positions_done"] == 4
    assert refill.occupancy_totals["refills"] >= 4
    for w, g in zip(want, got):
        assert g.position_index == w.position_index
        assert g.best_move == w.best_move
        assert g.depth == w.depth
        assert g.nodes == w.nodes
        assert g.scores.matrix == w.scores.matrix
        assert g.pvs.matrix == w.pvs.matrix


def test_concurrent_chunks_exactly_once():
    """Two chunks submitted from two threads share one driver session;
    every position of both chunks gets exactly one response, in order."""
    engine = make_refill_engine(max_depth=2)
    chunks = [
        make_chunk(analysis_work(depth=2), n_positions=3, moves=GAME),
        make_chunk(analysis_work(depth=2), n_positions=3,
                   moves=["d2d4", "g8f6", "c2c4"]),
    ]
    results = [None, None]
    errors = []

    def go(i):
        try:
            results[i] = run(engine, chunks[i])
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(e)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors
    for i, responses in enumerate(results):
        assert responses is not None and len(responses) == 3
        assert [r.position_index for r in responses] == [0, 1, 2]
        assert all(r.best_move for r in responses)
    assert engine.occupancy_totals["positions_done"] == 6


def test_occupancy_accounting():
    """Per-segment occupancy rows carry the lane breakdown the bench and
    tools/occupancy_report.py consume; totals tie out against the log."""
    engine = make_refill_engine(max_depth=2)
    run(engine, make_chunk(analysis_work(depth=2)))
    log = engine.occupancy_log
    assert log, "no occupancy rows recorded"
    for row in log:
        assert row["live"] + row["helpers"] + row["idle"] == row["width"]
        assert row["steps"] > 0
    totals = engine.occupancy_totals
    assert totals["segments"] == len(log)
    assert totals["refills"] == sum(r["refilled"] for r in log)
    assert totals["lane_steps"] == (
        totals["live_lane_steps"] + totals["helper_lane_steps"]
        + totals["idle_lane_steps"])


def test_search_stream_matches_batch():
    """Ops-level: streaming N positions through a narrower width yields
    the same per-position results as one full-width batch (no TT)."""
    import jax

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from fishnet_tpu.ops.board import from_position, stack_boards

    params = nnue.init_params(jax.random.PRNGKey(0), l1=64,
                              feature_set="board768")
    pos = Position.from_fen(START)
    boards, p = [], pos
    for uci in [None] + GAME[:5]:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    roots = stack_boards(boards)
    n = len(boards)
    depth = np.full(n, 2, np.int32)
    budget = np.full(n, 50_000, np.int32)
    batch = S.search_batch_resumable(params, roots, depth, budget,
                                     max_ply=6, segment_steps=200)
    stream = S.search_stream(params, roots, depth, budget, max_ply=6,
                             width=4, segment_steps=200)
    assert bool(np.asarray(stream["done"]).all())
    assert stream["refills"] >= n - 4
    for key in ("score", "move", "nodes", "pv_len"):
        np.testing.assert_array_equal(
            np.asarray(stream[key]), np.asarray(batch[key]), err_msg=key)
    np.testing.assert_array_equal(
        np.asarray(stream["pv"]), np.asarray(batch["pv"]))


@pytest.mark.slow
def test_refill_never_corrupts_live_lanes():
    """Mixed-depth stream with a shared TT: each finished position must
    match its single-position oracle search run against the same TT
    snapshot discipline — i.e. refilled neighbors never corrupt a live
    lane's accumulator or history state. TT stores only ever tighten
    move ordering, so node counts may differ; the depth-complete SCORE
    of a finished position must match a fresh solo search's score within
    the window the TT can shift it — here we pin exact equality by
    streaming with tt=None, where no sharing channel exists at all, and
    assert oracle equality position by position at unequal depths."""
    import jax

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from fishnet_tpu.ops.board import from_position, stack_boards

    params = nnue.init_params(jax.random.PRNGKey(7), l1=64,
                              feature_set="board768")
    pos = Position.from_fen(START)
    boards, p = [], pos
    for uci in [None] + GAME:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    roots = stack_boards(boards)
    n = len(boards)
    # staggered depths: lanes finish at different segments, forcing
    # refills to land next to still-live lanes at every boundary
    depth = np.asarray([1, 3, 2, 1, 3], np.int32)[:n]
    budget = np.full(n, 200_000, np.int32)
    stream = S.search_stream(params, roots, depth, budget, max_ply=6,
                             width=2, segment_steps=150)
    assert bool(np.asarray(stream["done"]).all())
    for i in range(n):
        solo = S.search_batch_resumable(
            params, stack_boards([boards[i]]),
            np.asarray([depth[i]]), np.asarray([budget[i]]),
            max_ply=6, segment_steps=150)
        assert int(np.asarray(stream["score"])[i]) == int(
            np.asarray(solo["score"])[0]), f"position {i} score diverged"
        assert int(np.asarray(stream["nodes"])[i]) == int(
            np.asarray(solo["nodes"])[0]), f"position {i} nodes diverged"
