"""Serving front-end tests: protocol serde, admission policy, HTTP
backpressure, graceful drain, and the bit-identity contract (an answer
through the HTTP layer equals the same chunk through go_multiple).

All async tests drive a real asyncio server on an ephemeral loopback
port through asyncio.run — no external HTTP client, no extra deps.
"""
import asyncio
import json
import time

import pytest

from fishnet_tpu.client.ipc import Matrix, PositionResponse
from fishnet_tpu.client.wire import EngineFlavor, Score
from fishnet_tpu.engine.pyengine import PyEngine
from fishnet_tpu.engine.session import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    EngineSession,
    PositionRequest,
    requests_to_chunks,
)
from fishnet_tpu.obs.metrics import MetricsRegistry
from fishnet_tpu.serve.admission import AdmissionController, Shed
from fishnet_tpu.serve.protocol import (
    ProtocolError,
    ServeRequest,
    parse_request,
    request_to_json,
)
from fishnet_tpu.serve.server import ServeApp

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


# ------------------------------------------------------------ protocol


def test_request_round_trip():
    reqs = [
        ServeRequest(
            kind="analysis",
            positions=((STARTPOS, ("e2e4", "e7e5")), (STARTPOS, ())),
            id="req-1",
            tenant="team-a",
            depth=6,
            multipv=3,
            nodes=250_000,
            timeout_ms=4000,
        ),
        ServeRequest(
            kind="bestmove",
            positions=((STARTPOS, ()),),
            id="bm-9",
            tenant="bot-x",
            level=5,
            priority=PRIORITY_INTERACTIVE,
        ),
        ServeRequest(kind="analysis", positions=((STARTPOS, ()),)),
    ]
    for req in reqs:
        assert parse_request(req.kind, request_to_json(req)) == req


def test_parse_request_defaults():
    req = parse_request("analysis", {"positions": [{"fen": STARTPOS}]})
    assert req.tenant == "default"
    assert req.priority == PRIORITY_BATCH
    # bestmove defaults to the interactive tier
    req = parse_request("bestmove", {"positions": [{"fen": STARTPOS}]})
    assert req.priority == PRIORITY_INTERACTIVE


@pytest.mark.parametrize(
    "body",
    [
        {},  # no positions
        {"positions": []},
        {"positions": [{"fen": ""}]},
        {"positions": [{"fen": STARTPOS, "moves": [1, 2]}]},
        {"positions": [{"fen": STARTPOS}], "depth": 0},
        {"positions": [{"fen": STARTPOS}], "multipv": 6},
        {"positions": [{"fen": STARTPOS}], "priority": "urgent"},
        {"positions": [{"fen": STARTPOS}], "level": 9},
        {"positions": [{"fen": STARTPOS}], "tenant": ""},
        "not an object",
    ],
)
def test_parse_request_rejects(body):
    with pytest.raises(ProtocolError):
        parse_request("analysis", body)


# ------------------------------------------------------------ admission


def test_admission_hardest_deadline_first_across_tenants():
    """Waiters drain in (priority tier, deadline) order regardless of
    arrival order or tenant."""

    async def scenario():
        adm = AdmissionController(
            max_inflight=1, max_queue=10, registry=MetricsRegistry()
        )
        now = time.monotonic()
        blocker = await adm.admit("seed", 1, now + 30.0, PRIORITY_BATCH)

        order = []

        async def waiter(tag, deadline, priority):
            ticket = await adm.admit(tag, 1, deadline, priority)
            order.append(tag)
            await asyncio.sleep(0)  # let the next grant interleave
            adm.release(ticket)

        # arrival order deliberately scrambled vs expected service order
        tasks = []
        for tag, dl, prio in [
            ("batch-late", now + 20.0, PRIORITY_BATCH),
            ("interactive-late", now + 15.0, PRIORITY_INTERACTIVE),
            ("batch-soon", now + 6.0, PRIORITY_BATCH),
            ("interactive-soon", now + 5.0, PRIORITY_INTERACTIVE),
        ]:
            tasks.append(asyncio.ensure_future(waiter(tag, dl, prio)))
            await asyncio.sleep(0)  # enqueue in this order

        assert adm.occupancy() == (1, 4)
        adm.release(blocker)
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=5.0)
        # interactive tier first; hardest deadline first within a tier
        assert order == [
            "interactive-soon",
            "interactive-late",
            "batch-soon",
            "batch-late",
        ]
        assert adm.occupancy() == (0, 0)

    asyncio.run(scenario())


def test_admission_sheds_when_room_full():
    async def scenario():
        registry = MetricsRegistry()
        adm = AdmissionController(
            max_inflight=1, max_queue=0, registry=registry
        )
        now = time.monotonic()
        ticket = await adm.admit("a", 1, now + 30.0, PRIORITY_BATCH)
        with pytest.raises(Shed) as exc:
            await adm.admit("b", 1, now + 30.0, PRIORITY_BATCH)
        assert 1 <= exc.value.retry_after <= 60
        snap = registry.snapshot()
        assert snap["fishnet_serve_shed_total_b"] == 1
        adm.release(ticket)

    asyncio.run(scenario())


def test_retry_after_with_no_drain_history_is_the_cap():
    # a cold saturated server has no completion history to extrapolate
    # from: the only honest Retry-After is the pessimistic cap
    adm = AdmissionController(
        max_inflight=4, max_queue=4, registry=MetricsRegistry()
    )
    assert adm.drain_rate() == 0.0
    assert adm.retry_after() == 60
    assert adm.retry_after(extra_positions=1) == 60


def test_retry_after_zero_drain_stall_is_the_cap():
    # a measured-then-collapsed drain rate (stall) must behave like no
    # history at all — dividing by ~0 must not leak a huge number out
    adm = AdmissionController(
        max_inflight=8, max_queue=8, registry=MetricsRegistry()
    )
    adm._drain_rate = 0.0
    assert adm.retry_after(extra_positions=100) == 60


def test_retry_after_clamped_to_one_second_floor():
    # backlog drains in well under a second: the header still says 1,
    # never 0 (a 0 would invite an immediate retry storm)
    adm = AdmissionController(
        max_inflight=8, max_queue=8, registry=MetricsRegistry()
    )
    adm._drain_rate = 1000.0
    assert adm.retry_after(extra_positions=1) == 1


def test_retry_after_clamped_to_sixty_second_cap():
    adm = AdmissionController(
        max_inflight=8, max_queue=8, registry=MetricsRegistry()
    )
    adm._drain_rate = 0.5
    assert adm.retry_after(extra_positions=10_000) == 60


def test_retry_after_interior_estimate():
    # 10 queued positions at 2 positions/s -> ~5s, +1 for the partial
    adm = AdmissionController(
        max_inflight=8, max_queue=8, registry=MetricsRegistry()
    )
    adm._drain_rate = 2.0
    assert adm.retry_after(extra_positions=10) == 6


def test_release_establishes_drain_rate():
    async def scenario():
        adm = AdmissionController(
            max_inflight=4, max_queue=4, registry=MetricsRegistry()
        )
        ticket = await adm.admit(
            "a", 2, time.monotonic() + 30.0, PRIORITY_BATCH)
        await asyncio.sleep(0.01)
        adm.release(ticket, ok=True)
        assert adm.drain_rate() > 0.0
        assert 1 <= adm.retry_after(extra_positions=4) <= 60

    asyncio.run(scenario())


def test_admission_sheds_expired_deadline():
    async def scenario():
        adm = AdmissionController(
            max_inflight=4, max_queue=4, registry=MetricsRegistry()
        )
        with pytest.raises(Shed):
            await adm.admit("a", 1, time.monotonic() - 0.1, PRIORITY_BATCH)

    asyncio.run(scenario())


# ------------------------------------------------------------ HTTP layer


async def _http(host, port, method, path, obj=None):
    """Minimal one-shot HTTP/1.1 client over asyncio streams."""
    reader, writer = await asyncio.open_connection(host, port)
    body = json.dumps(obj).encode("utf-8") if obj is not None else b""
    head = (
        f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    )
    writer.write(head.encode("latin-1") + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head_raw, _, payload = raw.partition(b"\r\n\r\n")
    lines = head_raw.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for ln in lines[1:]:
        name, _, value = ln.partition(":")
        headers[name.strip().lower()] = value.strip()
    return status, headers, json.loads(payload) if payload else {}


def _fake_response():
    scores = Matrix()
    scores.set(1, 2, Score.cp(13))
    pvs = Matrix()
    pvs.set(1, 2, ["e2e4"])
    return PositionResponse(
        work=None,
        position_index=0,
        url=None,
        scores=scores,
        pvs=pvs,
        best_move="e2e4",
        depth=2,
        nodes=100,
        time_s=0.01,
        nps=10_000,
    )


class GatedSession:
    """Stub EngineSession: submit_many parks on a gate so tests control
    exactly when in-flight work completes."""

    def __init__(self):
        self.gate = asyncio.Event()
        self.calls = 0

    async def submit_many(self, requests):
        self.calls += 1
        await asyncio.wait_for(self.gate.wait(), timeout=30.0)
        return [_fake_response() for _ in requests]


def _analysis_body(rid, tenant="default"):
    return {
        "id": rid,
        "tenant": tenant,
        "positions": [{"fen": STARTPOS, "moves": ["e2e4"]}],
        "depth": 2,
    }


def test_http_backpressure_429_and_shed_metrics():
    """At the in-flight cap with no waiting room, the second request is
    shed with 429 + Retry-After and the tenant's shed counter moves."""

    async def scenario():
        registry = MetricsRegistry()
        session = GatedSession()
        app = ServeApp(
            session,
            max_inflight=1,
            max_queue=0,
            default_timeout_ms=8000,
            drain_s=5.0,
            registry=registry,
        )
        host, port = await app.start("127.0.0.1", 0)
        try:
            first = asyncio.ensure_future(
                _http(host, port, "POST", "/analyse", _analysis_body("r1"))
            )
            for _ in range(50):
                await asyncio.sleep(0.01)
                if app.admission.occupancy()[0] == 1:
                    break
            assert app.admission.occupancy()[0] == 1

            status, headers, payload = await _http(
                host, port, "POST", "/analyse",
                _analysis_body("r2", tenant="team-b"),
            )
            assert status == 429
            assert "retry-after" in headers
            assert int(headers["retry-after"]) >= 1
            assert payload["retry_after"] == int(headers["retry-after"])
            assert "error" in payload
            # registry sanitizes metric names: tenant "team-b" -> team_b
            assert registry.snapshot()["fishnet_serve_shed_total_team_b"] == 1

            session.gate.set()
            status, _, payload = await asyncio.wait_for(first, timeout=10.0)
            assert status == 200
            assert payload["id"] == "r1"
        finally:
            session.gate.set()
            await app.drain_and_stop()

    asyncio.run(scenario())


def test_http_graceful_drain_completes_inflight():
    """begin_drain() mid-request: the in-flight request still answers
    200 and drain_and_stop returns once it does."""

    async def scenario():
        session = GatedSession()
        app = ServeApp(
            session,
            max_inflight=4,
            max_queue=4,
            default_timeout_ms=8000,
            drain_s=10.0,
            registry=MetricsRegistry(),
        )
        host, port = await app.start("127.0.0.1", 0)
        inflight = asyncio.ensure_future(
            _http(host, port, "POST", "/analyse", _analysis_body("d1"))
        )
        for _ in range(50):
            await asyncio.sleep(0.01)
            if session.calls == 1:
                break
        assert session.calls == 1

        app.begin_drain()
        drainer = asyncio.ensure_future(app.drain_and_stop())
        await asyncio.sleep(0.05)
        assert not drainer.done()  # still waiting on the in-flight request

        session.gate.set()
        status, _, payload = await asyncio.wait_for(inflight, timeout=10.0)
        assert status == 200
        assert payload["id"] == "d1"
        await asyncio.wait_for(drainer, timeout=10.0)

    asyncio.run(scenario())


def test_http_rejects_and_healthz():
    async def scenario():
        session = GatedSession()
        app = ServeApp(
            session, max_inflight=4, max_queue=4,
            default_timeout_ms=8000, drain_s=5.0, registry=MetricsRegistry(),
        )
        host, port = await app.start("127.0.0.1", 0)
        try:
            status, _, payload = await _http(host, port, "GET", "/healthz")
            assert status == 200
            assert payload["status"] == "ok"
            assert payload["inflight"] == 0

            status, _, _ = await _http(host, port, "POST", "/nope", {})
            assert status == 404
            status, _, _ = await _http(host, port, "GET", "/analyse")
            assert status == 405
            status, _, payload = await _http(
                host, port, "POST", "/analyse", {"positions": []}
            )
            assert status == 400
            assert "error" in payload
        finally:
            session.gate.set()
            await app.drain_and_stop()

    asyncio.run(scenario())


# ------------------------------------------------------------ bit-identity


def _comparable(wire: dict) -> dict:
    """The deterministic result fields; time_s/nps are wall-clock."""
    return {
        k: wire[k] for k in ("scores", "pvs", "best_move", "depth", "nodes")
    }


def test_http_bit_identical_to_direct_go_multiple():
    """An /analyse answer equals the same positions pushed straight
    through Engine.go_multiple — the HTTP layer adds no search-visible
    state."""

    async def scenario():
        engine = PyEngine(max_depth=2)
        app = ServeApp(
            EngineSession(engine, flavor=EngineFlavor.OFFICIAL),
            max_inflight=8,
            max_queue=4,
            default_timeout_ms=8000,
            drain_s=5.0,
            registry=MetricsRegistry(),
        )
        host, port = await app.start("127.0.0.1", 0)
        try:
            body = {
                "id": "bit-1",
                "positions": [
                    {"fen": STARTPOS, "moves": ["e2e4"]},
                    {"fen": STARTPOS, "moves": []},
                ],
                "depth": 2,
                "nodes": 300_000,
            }
            status, _, payload = await _http(
                host, port, "POST", "/analyse", body
            )
            assert status == 200
            assert payload["id"] == "bit-1"
            assert len(payload["results"]) == 2

            direct_engine = PyEngine(max_depth=2)
            reqs = [
                PositionRequest(
                    fen=STARTPOS, moves=("e2e4",), depth=2, nodes=300_000,
                    deadline=time.monotonic() + 8.0,
                ),
                PositionRequest(
                    fen=STARTPOS, moves=(), depth=2, nodes=300_000,
                    deadline=time.monotonic() + 8.0,
                ),
            ]
            plan = requests_to_chunks(reqs, flavor=EngineFlavor.OFFICIAL)
            direct = [None, None]
            for chunk, indices in plan:
                responses = await direct_engine.go_multiple(chunk)
                for slot, i in enumerate(indices):
                    direct[i] = responses[slot]

            from fishnet_tpu.client.ipc import response_to_wire

            for http_res, direct_res in zip(payload["results"], direct):
                assert _comparable(http_res) == _comparable(
                    response_to_wire(direct_res)
                )
        finally:
            await app.drain_and_stop()

    asyncio.run(scenario())


# ----------------------------------------------------------- analysis cache


def _cache_app(cache):
    return ServeApp(
        EngineSession(PyEngine(max_depth=2), flavor=EngineFlavor.OFFICIAL),
        max_inflight=8,
        max_queue=4,
        default_timeout_ms=8000,
        drain_s=5.0,
        registry=MetricsRegistry(),
        cache=cache,
    )


def _searched(payload):
    """The search-determined part of a response body (wall-clock fields
    legitimately differ between a cached entry and a fresh search)."""
    return [
        {k: r.get(k) for k in ("scores", "pvs", "best_move", "depth",
                               "nodes")}
        for r in payload["results"]
    ]


def test_cache_header_miss_then_hit():
    """The same position twice: first response is X-Fishnet-Cache: miss,
    the repeat is a hit with an identical search payload — and the
    cached hit never reaches the session layer."""
    from fishnet_tpu.cache.store import AnalysisCache

    async def scenario():
        cache = AnalysisCache("serve-test-identity")
        app = _cache_app(cache)
        host, port = await app.start("127.0.0.1", 0)
        try:
            status, headers, first = await _http(
                host, port, "POST", "/analyse", _analysis_body("c-1")
            )
            assert status == 200
            assert headers["x-fishnet-cache"] == "miss"
            status, headers, second = await _http(
                host, port, "POST", "/analyse", _analysis_body("c-2")
            )
            assert status == 200
            assert headers["x-fishnet-cache"] == "hit"
            assert _searched(first) == _searched(second)
            assert cache.stats.hits == 1 and cache.stats.fills == 1
        finally:
            await app.drain_and_stop()

    asyncio.run(scenario())


def test_cache_header_partial_and_absent_when_off():
    """A request mixing one cached and one cold position answers
    `partial`; with the cache off the header is absent entirely."""
    from fishnet_tpu.cache.store import AnalysisCache

    async def scenario():
        cache = AnalysisCache("serve-test-identity")
        app = _cache_app(cache)
        host, port = await app.start("127.0.0.1", 0)
        try:
            await _http(host, port, "POST", "/analyse",
                        _analysis_body("p-1"))
            mixed = {
                "id": "p-2",
                "positions": [
                    {"fen": STARTPOS, "moves": ["e2e4"]},  # cached by p-1
                    {"fen": STARTPOS, "moves": []},  # cold
                ],
                "depth": 2,
            }
            status, headers, _ = await _http(
                host, port, "POST", "/analyse", mixed
            )
            assert status == 200
            assert headers["x-fishnet-cache"] == "partial"
        finally:
            await app.drain_and_stop()

        off = _cache_app(None)
        host, port = await off.start("127.0.0.1", 0)
        try:
            status, headers, _ = await _http(
                host, port, "POST", "/analyse", _analysis_body("p-3")
            )
            assert status == 200
            assert "x-fishnet-cache" not in headers
        finally:
            await off.drain_and_stop()

    asyncio.run(scenario())


def test_healthz_reports_cache_counters():
    """/healthz carries the live cache counters when the cache is on,
    and an explicit null when it is off."""
    from fishnet_tpu.cache.store import AnalysisCache

    async def scenario():
        cache = AnalysisCache("serve-test-identity")
        app = _cache_app(cache)
        host, port = await app.start("127.0.0.1", 0)
        try:
            await _http(host, port, "POST", "/analyse",
                        _analysis_body("h-1"))
            await _http(host, port, "POST", "/analyse",
                        _analysis_body("h-2"))
            status, _, health = await _http(host, port, "GET", "/healthz")
            assert status == 200
            c = health["cache"]
            assert c["hits"] == 1 and c["misses"] == 1
            assert c["fills"] == 1 and c["entries"] == 1
            assert c["hit_ratio"] == 0.5
        finally:
            await app.drain_and_stop()

        off = _cache_app(None)
        host, port = await off.start("127.0.0.1", 0)
        try:
            status, _, health = await _http(host, port, "GET", "/healthz")
            assert status == 200 and health["cache"] is None
        finally:
            await off.drain_and_stop()

    asyncio.run(scenario())
