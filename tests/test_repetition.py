"""Twofold repetition draws along the search path.

Stockfish scores repetitions as draws (observable through the reference's
UCI score stream, src/stockfish.rs:361-464); the device search implements
the same path-stack rule, and the host oracle implements it independently
in Python. Sparse reversible endgames at depth 5 hit repetitions by the
thousands — exact score AND node-count equality proves the device rule
matches the oracle's, and the instrumented rep_hits counter proves the
rule actually fired (rather than the positions never repeating).
"""
import jax
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.oracle import oracle_search
from fishnet_tpu.ops.search import search_batch_jit

# reversible-shuffle endgames: kings (+rooks) with nothing irreversible
# nearby, so depth-5 trees revisit earlier path positions constantly
FENS = [
    "7k/8/8/8/8/8/8/K7 w - - 0 1",
    "7k/8/8/8/8/8/8/KR6 w - - 0 1",
    "1r5k/8/8/8/8/8/8/K7 b - - 0 1",
    "1r5k/8/8/8/8/8/8/KR6 w - - 0 1",
]
DEPTH = 5
MAX_PLY = 7
BUDGET = 300_000


@pytest.fixture(scope="module")
def params():
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set="board768"
    )


def test_repetition_draws_match_oracle(params):
    roots = stack_boards([from_position(Position.from_fen(f)) for f in FENS])
    out = search_batch_jit(
        params, roots, DEPTH, BUDGET, max_ply=MAX_PLY
    )
    out = {k: np.asarray(v) for k, v in out.items() if k != "tt"}
    total_reps = 0
    for i, fen in enumerate(FENS):
        exp = oracle_search(
            params, from_position(Position.from_fen(fen)), DEPTH, BUDGET, MAX_PLY
        )
        assert int(out["score"][i]) == exp["score"], fen
        assert int(out["nodes"][i]) == exp["nodes"], fen
        total_reps += exp["rep_hits"]
    # the scenario must actually exercise the rule. The pruning stack
    # keeps shaving these shuffle trees: thousands of hits unpruned,
    # ~99 after NMP/LMR (round 4), 13 after the measured aspiration-delta
    # narrowing to (15,120) (round 6) — the score/node parity asserts
    # above are the contract; this floor only proves the rule still fires
    assert total_reps > 5, f"only {total_reps} repetition hits"


def _shuffle_game(n_plies):
    """King shuffle from a K-vs-K start; returns (prefix list, root)."""
    pos = Position.from_fen("k7/8/8/8/8/8/8/K7 w - - 0 1")
    prefix = []
    for uci in ["a1b1", "a8b8", "b1a1", "b8a8"] * 2:
        if len(prefix) == n_plies:
            break
        prefix.append(pos)
        pos = pos.push(pos.parse_uci(uci))
    return prefix, pos


def _oracle_history(game):
    """Game prefix → oracle history quadruples, via the same doubled-
    position filter the engine applies for the device."""
    from fishnet_tpu.engine.tpu import TpuEngine
    from fishnet_tpu.ops.search import HIST_HM_SENTINEL, MAX_HIST

    hh, hm = TpuEngine._history_arrays([game], 1)
    return (hh, hm), [
        (int(hh[0, k, 0]), int(hh[0, k, 1]), int(hm[0, k]), MAX_HIST - k)
        for k in range(MAX_HIST)
        if hm[0, k] != HIST_HM_SENTINEL
    ]


def test_game_history_repetition_draws(params):
    """After 8 shuffle plies every pre-root placement occurred twice, so
    (Stockfish Position::is_draw: 'repeats twice before or at the root')
    the root and each child read as immediate draws; device == oracle
    exactly, and the game history is what makes it a draw."""
    game, pos = _shuffle_game(8)
    root = from_position(pos)
    (hh, hm), triples = _oracle_history(game)
    assert triples, "8-ply shuffle must yield doubled positions"

    roots = stack_boards([root] * len(FENS))
    B = len(FENS)
    out = search_batch_jit(
        params, roots, DEPTH, BUDGET, max_ply=MAX_PLY,
        hist=(np.repeat(hh, B, axis=0), np.repeat(hm, B, axis=0)),
    )
    exp = oracle_search(params, root, DEPTH, BUDGET, MAX_PLY, history=triples)
    assert exp["rep_hits"] > 0
    assert int(np.asarray(out["score"])[0]) == exp["score"] == 0
    assert int(np.asarray(out["nodes"])[0]) == exp["nodes"]

    # without history the same position searches normally (no draw leaf
    # at the root)
    plain = search_batch_jit(params, roots, DEPTH, BUDGET, max_ply=MAX_PLY)
    assert int(np.asarray(plain["nodes"])[0]) > int(np.asarray(out["nodes"])[0])


def test_single_game_occurrence_is_not_a_draw(params):
    """4 shuffle plies: the root repeats the start position once — by the
    reference rule (distance > ply) that is NOT a draw, so the doubled-
    position filter must plant nothing and results must equal plain
    search."""
    game, pos = _shuffle_game(4)
    root = from_position(pos)
    (hh, hm), triples = _oracle_history(game)
    assert not triples, "singly-occurring positions must be filtered out"

    roots = stack_boards([root] * len(FENS))
    B = len(FENS)
    out = search_batch_jit(
        params, roots, DEPTH, BUDGET, max_ply=MAX_PLY,
        hist=(np.repeat(hh, B, axis=0), np.repeat(hm, B, axis=0)),
    )
    plain = search_batch_jit(params, roots, DEPTH, BUDGET, max_ply=MAX_PLY)
    assert int(np.asarray(out["score"])[0]) == int(np.asarray(plain["score"])[0])
    assert int(np.asarray(out["nodes"])[0]) == int(np.asarray(plain["nodes"])[0])


def test_repetition_not_confused_by_irreversible_moves(params):
    """A pawn move between two visually identical placements breaks the
    reversible chain — a position 'repeated' across a pawn move is NOT a
    repetition (the halfmove-continuity condition)."""
    fen = "7k/8/8/8/8/P7/8/K7 w - - 0 1"
    root = from_position(Position.from_fen(fen))
    roots = stack_boards([root] * len(FENS))
    out = search_batch_jit(params, roots, DEPTH, BUDGET, max_ply=MAX_PLY)
    exp = oracle_search(params, root, DEPTH, BUDGET, MAX_PLY)
    assert int(np.asarray(out["score"])[0]) == exp["score"]
    assert int(np.asarray(out["nodes"])[0]) == exp["nodes"]
