"""Autoscaler control-loop tests: hysteresis, loss-cooldown veto,
clamps, drain serialization, cost accounting — and the contract that
capacity changes never alter answers (bit-identity through a real
FleetCoordinator while the loop scales it up and back down).

The loop is driven deterministically through the public `tick()`
against stub signals — no timers, no sleeps on the decision paths.
"""
import asyncio
import io
import time
from types import SimpleNamespace

import pytest

from fishnet_tpu.client.logger import Logger
from fishnet_tpu.engine.pyengine import PyEngine
from fishnet_tpu.fleet import FleetCoordinator, FleetMember
from fishnet_tpu.fleet.autoscaler import (
    AutoscaleConfig,
    Autoscaler,
    CapacityProvider,
)
from fishnet_tpu.obs.metrics import MetricsRegistry

# ------------------------------------------------------------------ stubs


class StubMember:
    """Just the three member attributes the autoscaler reads."""

    def __init__(self, name, backlog=0, lifecycle="serving"):
        self.name = name
        self.backlog = backlog
        self.lifecycle = lifecycle

    def state(self, now):
        return self.lifecycle


class StubCoordinator:
    def __init__(self, names=("m0",)):
        self.members = [StubMember(n) for n in names]
        self.stats = SimpleNamespace(losses=0)


class StubProvider(CapacityProvider):
    """In-memory capacity: add appends a member, drain completes only
    when the test says so."""

    def __init__(self, coord):
        self.coord = coord
        self.added = 0
        self.drain_ready = {}

    async def add(self):
        name = f"auto{self.added}"
        self.added += 1
        self.coord.members.append(StubMember(name))
        return name

    def begin_drain(self, name):
        self.drain_ready.setdefault(name, False)

    def drained(self, name):
        return self.drain_ready.get(name, False)

    async def remove(self, name):
        self.coord.members = [
            m for m in self.coord.members if m.name != name
        ]


class StubAdmission:
    def __init__(self):
        self.inflight = 0
        self.queued = 0

    def occupancy(self):
        return self.inflight, self.queued


def make_scaler(names=("m0",), **cfg_kw):
    cfg = dict(min_members=1, max_members=4, interval_s=0.01,
               up_queue=1, up_ticks=2, down_ticks=3,
               loss_cooldown_s=30.0, drain_timeout_s=30.0)
    cfg.update(cfg_kw)
    coord = StubCoordinator(names)
    adm = StubAdmission()
    provider = StubProvider(coord)
    scaler = Autoscaler(
        coord, adm, provider=provider,
        config=AutoscaleConfig(**cfg),
        registry=MetricsRegistry(),
        logger=Logger(verbose=0, stream=io.StringIO()),
    )
    return scaler, coord, adm, provider


def actions(scaler):
    return [d.action for d in scaler.decisions]


# ------------------------------------------------------------------ config


def test_config_validation():
    coord, adm = StubCoordinator(), StubAdmission()
    with pytest.raises(ValueError):
        Autoscaler(coord, adm, config=AutoscaleConfig(min_members=0),
                   registry=MetricsRegistry())
    with pytest.raises(ValueError):
        Autoscaler(coord, adm,
                   config=AutoscaleConfig(min_members=3, max_members=2),
                   registry=MetricsRegistry())


def test_config_from_settings(monkeypatch):
    monkeypatch.setenv("FISHNET_TPU_AUTOSCALE_MIN", "2")
    monkeypatch.setenv("FISHNET_TPU_AUTOSCALE_MAX", "6")
    monkeypatch.setenv("FISHNET_TPU_AUTOSCALE_INTERVAL_MS", "250")
    monkeypatch.setenv("FISHNET_TPU_AUTOSCALE_UP_TICKS", "3")
    monkeypatch.setenv("FISHNET_TPU_AUTOSCALE_LOSS_COOLDOWN_S", "7")
    cfg = AutoscaleConfig.from_settings()
    assert cfg.min_members == 2
    assert cfg.max_members == 6
    assert cfg.interval_s == 0.25
    assert cfg.up_ticks == 3
    assert cfg.loss_cooldown_s == 7.0


# -------------------------------------------------------------- hysteresis


def test_scale_up_only_after_consecutive_pressure_ticks():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(up_ticks=2)
        adm.queued = 2
        await scaler.tick()  # streak 1 of 2: no action yet
        assert scaler.stats.ups == 0 and len(coord.members) == 1
        await scaler.tick()  # streak 2: scale up
        assert scaler.stats.ups == 1
        assert [m.name for m in coord.members] == ["m0", "auto0"]
        assert actions(scaler) == ["up"]
        # the streak resets after acting: one more pressure tick is not
        # enough for a second member
        await scaler.tick()
        assert scaler.stats.ups == 1
        await scaler.tick()
        assert scaler.stats.ups == 2 and len(coord.members) == 3

    asyncio.run(scenario())


def test_quiet_tick_resets_pressure_streak():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(up_ticks=2)
        adm.queued = 2
        await scaler.tick()
        adm.queued = 0
        await scaler.tick()  # quiet: streak back to 0
        adm.queued = 2
        await scaler.tick()  # streak 1 again — still no up
        assert scaler.stats.ups == 0 and len(coord.members) == 1
        await scaler.tick()
        assert scaler.stats.ups == 1

    asyncio.run(scenario())


def test_deadline_miss_counts_as_pressure():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(up_ticks=2)
        miss = scaler.registry.counter(
            "fishnet_slo_deadline_miss_total_analysis_t0", "test")
        await scaler.tick()  # baseline snapshot of the miss counters
        miss.inc()
        await scaler.tick()  # delta 1: pressure streak 1
        miss.inc()
        await scaler.tick()  # delta 1 again: streak 2 -> up
        assert scaler.stats.ups == 1
        assert "misses=1" in scaler.decisions[0].reason

    asyncio.run(scenario())


def test_scale_up_clamped_at_max_members():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(max_members=2)
        adm.queued = 5
        for _ in range(8):
            await scaler.tick()
        assert len(coord.members) == 2
        assert scaler.stats.ups == 1

    asyncio.run(scenario())


# -------------------------------------------------- scale-down and drains


async def scale_up_one(scaler, adm):
    adm.queued = 2
    await scaler.tick()
    await scaler.tick()
    assert scaler.stats.ups == 1
    adm.queued = 0


def test_scale_down_drains_then_removes():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(down_ticks=3)
        await scale_up_one(scaler, adm)
        for _ in range(3):
            await scaler.tick()
        # down decision taken: the member is draining, not yet removed
        assert scaler.stats.downs == 1
        assert scaler.snapshot()["draining"] == "auto0"
        assert len(coord.members) == 2
        # drain still pending: the loop takes NO other structural
        # decision, even under fresh pressure (serialization)
        adm.queued = 10
        for _ in range(4):
            await scaler.tick()
        assert scaler.stats.ups == 1 and scaler.stats.downs == 1
        adm.queued = 0
        # drain completes -> removed on the next tick
        provider.drain_ready["auto0"] = True
        await scaler.tick()
        assert [m.name for m in coord.members] == ["m0"]
        assert scaler.snapshot()["draining"] is None
        assert scaler.snapshot()["owned"] == []
        assert actions(scaler) == ["up", "down", "removed"]

    asyncio.run(scenario())


def test_floor_members_are_never_drained():
    async def scenario():
        # two configured members, floor 1, nothing autoscaler-owned:
        # idleness alone must never shrink the hand-built fleet
        scaler, coord, adm, provider = make_scaler(
            names=("m0", "m1"), down_ticks=2)
        for _ in range(10):
            await scaler.tick()
        assert scaler.stats.downs == 0
        assert len(coord.members) == 2

    asyncio.run(scenario())


def test_drain_stall_reported_once_and_never_abandoned():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(
            down_ticks=2, drain_timeout_s=0.0)
        await scale_up_one(scaler, adm)
        await scaler.tick()
        await scaler.tick()  # down: begin drain (deadline already past)
        assert scaler.stats.downs == 1
        await scaler.tick()  # overdue -> drain-stalled, reported once
        await scaler.tick()
        await scaler.tick()
        assert actions(scaler).count("drain-stalled") == 1
        assert len(coord.members) == 2  # work is never abandoned
        provider.drain_ready["auto0"] = True
        await scaler.tick()
        assert actions(scaler)[-1] == "removed"
        assert len(coord.members) == 1

    asyncio.run(scenario())


# ------------------------------------------------------ loss-cooldown veto


def test_member_loss_blocks_scale_down():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(
            down_ticks=2, loss_cooldown_s=30.0)
        await scale_up_one(scaler, adm)
        coord.stats.losses += 1  # loss lands mid-idle
        for _ in range(6):
            await scaler.tick()
        # every would-be down is vetoed while the cooldown window is
        # open; the idle streak resets each time (re-earn idleness)
        assert scaler.stats.downs == 0
        assert scaler.stats.downs_blocked >= 1
        assert "down-blocked" in actions(scaler)
        assert len(coord.members) == 2
        assert scaler.recovery_ladder_active()

    asyncio.run(scenario())


def test_scale_down_resumes_after_cooldown_expires():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(
            down_ticks=2, loss_cooldown_s=0.05)
        await scale_up_one(scaler, adm)
        coord.stats.losses += 1
        await scaler.tick()  # observes the loss, opens the veto window
        await asyncio.sleep(0.1)
        assert not scaler.recovery_ladder_active()
        await scaler.tick()
        await scaler.tick()
        assert scaler.stats.downs == 1

    asyncio.run(scenario())


def test_ladder_state_blocks_scale_down():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(down_ticks=2)
        await scale_up_one(scaler, adm)
        # a member sitting on the recovery ladder is the same veto as a
        # fresh loss event — capacity holds until it clears
        coord.members[0].lifecycle = "probing"
        await scaler.tick()
        await scaler.tick()
        assert scaler.stats.downs == 0
        assert scaler.stats.downs_blocked == 1
        coord.members[0].lifecycle = "serving"
        await scaler.tick()
        await scaler.tick()
        assert scaler.stats.downs == 1

    asyncio.run(scenario())


# ------------------------------------------------------------- accounting


def test_member_seconds_accrue_with_member_count():
    async def scenario():
        scaler, coord, adm, provider = make_scaler(names=("m0", "m1"))
        await scaler.tick()
        await asyncio.sleep(0.05)
        await scaler.tick()
        elapsed = scaler.stats.member_seconds
        assert elapsed >= 2 * 0.05 * 0.5  # 2 members x wall-clock
        snap = scaler.registry.snapshot()
        assert snap["fishnet_autoscale_member_seconds_total"] == \
            pytest.approx(elapsed, abs=1e-6)
        assert snap["fishnet_autoscale_members"] == 2
        assert snap["fishnet_autoscale_floor"] == 1
        assert snap["fishnet_autoscale_ceiling"] == 4

    asyncio.run(scenario())


def test_snapshot_shape():
    async def scenario():
        scaler, coord, adm, provider = make_scaler()
        await scaler.tick()
        snap = scaler.snapshot()
        assert snap["members"] == 1
        assert snap["floor"] == 1 and snap["ceiling"] == 4
        assert snap["owned"] == [] and snap["draining"] is None
        assert snap["ticks"] == 1
        assert snap["decisions"] == []

    asyncio.run(scenario())


# ----------------------------------------------------------- bit identity


START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def _py_chunk(n=4, depth=2):
    import time as _time

    from fishnet_tpu.client.ipc import Chunk, WorkPosition
    from fishnet_tpu.client.wire import (
        AnalysisWork,
        EngineFlavor,
        NodeLimit,
    )

    work = AnalysisWork(
        id="asjob001",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0, depth=depth, multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=["e2e4"])
        for i in range(n)
    ]
    return Chunk(work=work, deadline=_time.monotonic() + 30.0,
                 variant="standard", flavor=EngineFlavor.OFFICIAL,
                 positions=positions)


def _comparable(res):
    from fishnet_tpu.client.ipc import response_to_wire

    wire = response_to_wire(res)
    return {k: wire[k]
            for k in ("scores", "pvs", "best_move", "depth", "nodes")}


def test_capacity_changes_never_alter_answers():
    """The whole contract in one pass: answers from a 1-member fleet,
    the same fleet scaled up by the autoscaler, and the fleet scaled
    back down to the floor are bit-identical to a direct engine run —
    through the real FleetCoordinator membership path the
    LocalProcessProvider uses, not a stub."""

    async def scenario():
        direct = await PyEngine(max_depth=2).go_multiple(_py_chunk())

        coord = FleetCoordinator(
            [FleetMember(name="base0", engine=PyEngine(max_depth=2))],
            logger=Logger(verbose=0, stream=io.StringIO()),
            registry=MetricsRegistry(),
            loss_window=0.1,
            local_factory=lambda name: FleetMember(
                name=name, engine=PyEngine(max_depth=2)),
        )
        adm = StubAdmission()
        scaler = Autoscaler(
            coord, adm,
            config=AutoscaleConfig(min_members=1, max_members=2,
                                   up_ticks=2, down_ticks=2,
                                   loss_cooldown_s=0.01),
            registry=MetricsRegistry(),
            logger=Logger(verbose=0, stream=io.StringIO()),
        )
        try:
            at_floor = await coord.go_multiple(_py_chunk())

            adm.queued = 4
            await scaler.tick()
            await scaler.tick()
            assert len(coord.members) == 2
            scaled_up = await coord.go_multiple(_py_chunk())

            adm.queued = 0
            for _ in range(8):
                await scaler.tick()
                if len(coord.members) == 1:
                    break
                await asyncio.sleep(0.02)
            assert len(coord.members) == 1
            back_down = await coord.go_multiple(_py_chunk())
        finally:
            await coord.close()

        for fleet_run in (at_floor, scaled_up, back_down):
            assert [r.position_index for r in fleet_run] == [0, 1, 2, 3]
            for a, b in zip(fleet_run, direct):
                assert _comparable(a) == _comparable(b)

    asyncio.run(scenario())
