"""Worker failure-path tests: the deadline race, engine drop-and-respawn,
backoff gating, ChunkFailed reporting, and clean shutdown mid-flight.

Uses in-process fake engines and a scripted queue — the real engine
failure modes (hang, crash, wedge) are exercised end-to-end against a
child process in test_supervisor.py; here the WORKER's reactions are
isolated."""
import asyncio
import time

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.ipc import Chunk, ChunkFailed, WorkPosition
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.queue import ShuttingDown
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.client.workers import worker
from fishnet_tpu.engine.base import EngineError

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def make_chunk(ttl=5.0):
    work = AnalysisWork(
        id="wrkjob01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0, depth=1, multipv=None,
    )
    return Chunk(
        work=work, deadline=time.monotonic() + ttl, variant="standard",
        flavor=EngineFlavor.TPU,
        positions=[WorkPosition(work=work, position_index=0, url=None,
                                skip=False, root_fen=START, moves=[])],
    )


class ScriptQueue:
    """Hands out chunks built lazily at pull time (deadlines are absolute
    monotonic stamps — building them up front would start their clocks
    early), records what each pull reports, then raises ShuttingDown."""

    def __init__(self, chunk_thunks):
        self.thunks = list(chunk_thunks)
        self.reports = []

    async def pull(self, responses):
        self.reports.append(responses)
        if not self.thunks:
            raise ShuttingDown()
        return self.thunks.pop(0)()


class OkEngine:
    def __init__(self):
        self.closed = False
        self.calls = 0

    async def go_multiple(self, chunk):
        self.calls += 1
        return ["fake-response"]

    async def close(self):
        self.closed = True


class HangingEngine(OkEngine):
    async def go_multiple(self, chunk):
        self.calls += 1
        await asyncio.sleep(3600)


class FailingEngine(OkEngine):
    async def go_multiple(self, chunk):
        self.calls += 1
        raise EngineError("injected engine failure")


class SucceedThenFail(OkEngine):
    async def go_multiple(self, chunk):
        self.calls += 1
        if self.calls == 1:
            return ["fake-response"]
        raise EngineError("second call fails")


def run_worker(queue, factory):
    asyncio.run(worker(0, queue, factory, Logger(verbose=0)))


def listing_factory(engines, classes):
    def factory(flavor):
        engines.append(classes[len(engines)]())
        return engines[-1]

    return factory


def test_hanging_engine_loses_deadline_race_and_is_dropped():
    queue = ScriptQueue([lambda: make_chunk(ttl=0.3)] * 2)
    engines = []
    run_worker(queue, listing_factory(engines, [HangingEngine, HangingEngine]))
    # both chunks timed out and were reported failed
    failed = [r for r in queue.reports if isinstance(r, ChunkFailed)]
    assert len(failed) == 2
    assert all(f.batch_id == "wrkjob01" for f in failed)
    # the wedged engine was dropped (closed) after each overrun, and a
    # fresh one was built for the second chunk
    assert len(engines) == 2
    assert all(e.closed for e in engines)


def test_engine_error_drops_engine_and_respawns():
    queue = ScriptQueue([make_chunk] * 2)
    engines = []
    run_worker(queue, listing_factory(engines, [FailingEngine, OkEngine]))
    assert isinstance(queue.reports[1], ChunkFailed)  # first chunk failed
    assert queue.reports[2] == ["fake-response"]  # second chunk recovered
    assert len(engines) == 2
    assert engines[0].closed  # dropped on error
    assert engines[1].closed  # closed at shutdown


def test_factory_failure_reports_chunk_failed():
    queue = ScriptQueue([make_chunk])

    def factory(flavor):
        raise RuntimeError("no engine for you")

    run_worker(queue, factory)
    assert isinstance(queue.reports[1], ChunkFailed)


def test_expired_chunk_fails_without_touching_engine():
    queue = ScriptQueue([lambda: make_chunk(ttl=-1.0)])
    engines = []
    run_worker(queue, listing_factory(engines, [OkEngine]))
    assert isinstance(queue.reports[1], ChunkFailed)
    assert engines[0].calls == 0


def test_success_resets_the_tracked_backoff(monkeypatch):
    """Regression: the old code called backoffs.get(flavor, ...).reset(),
    resetting a THROWAAWAY instance — the tracked one kept its armed
    delay forever, so every later respawn waited longer than it should."""
    instances = []

    class Recorder(RandomizedBackoff):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.resets = 0
            instances.append(self)

        def reset(self):
            self.resets += 1
            super().reset()

    monkeypatch.setattr(
        "fishnet_tpu.client.workers.RandomizedBackoff", Recorder
    )
    queue = ScriptQueue([make_chunk] * 3)
    engines = []
    run_worker(
        queue, listing_factory(engines, [FailingEngine, SucceedThenFail])
    )
    # fail → armed backoff → respawn (gated) → success → fail again
    assert isinstance(queue.reports[1], ChunkFailed)
    assert queue.reports[2] == ["fake-response"]
    assert isinstance(queue.reports[3], ChunkFailed)
    # the TRACKED backoff (first instance stored for the flavor) was the
    # one reset by the success
    assert instances[0].resets >= 1


def test_shutdown_mid_flight_closes_engines():
    queue = ScriptQueue([make_chunk])
    engines = []
    run_worker(queue, listing_factory(engines, [OkEngine]))
    # the final pull reported the completed chunk, then ShuttingDown
    assert queue.reports[-1] == ["fake-response"]
    assert all(e.closed for e in engines)


def test_backoff_pending_accessor():
    b = RandomizedBackoff()
    assert not b.pending()
    b.next()
    assert b.pending()
    b.reset()
    assert not b.pending()
