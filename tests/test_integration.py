"""End-to-end client integration tests against the fake lichess server:
acquire → plan → workers/engine → reassemble → submit."""
import asyncio

import pytest

from fishnet_tpu.client.api import ApiClient, Endpoint
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.queue import BacklogOpt, Queue
from fishnet_tpu.client.stats import StatsRecorder
from fishnet_tpu.client.workers import worker
from fishnet_tpu.engine.pyengine import PyEngine

from fake_server import FakeLichess

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def run_client_until(server, condition, n_workers=2, timeout=60.0,
                     tpu_variants=None, tpu_moves=False, factory=None):
    """Run queue+workers until condition(server) or timeout; returns queue."""

    async def main():
        api = ApiClient(Endpoint(server.url), "testkey")
        queue = Queue(
            api,
            cores=n_workers,
            backlog=BacklogOpt(),
            stats=StatsRecorder(no_stats_file=True, cores=n_workers),
            logger=Logger(verbose=0),
            tpu_variants=tpu_variants,
            tpu_moves=tpu_moves,
        )
        fct = factory or (lambda flavor: PyEngine(max_depth=2))
        tasks = [
            asyncio.create_task(worker(i, queue, fct)) for i in range(n_workers)
        ]
        deadline = asyncio.get_running_loop().time() + timeout
        while not condition(server):
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.05)
        queue.stop_acquiring()
        await asyncio.gather(*tasks, return_exceptions=True)
        await queue.drain_submissions()
        return queue

    return asyncio.run(main())


@pytest.fixture()
def server():
    s = FakeLichess().start()
    yield s
    s.stop()


def test_analysis_end_to_end(server):
    moves = ["e2e4", "c7c5", "g1f3", "d7d6"]
    server.add_analysis_job("job00001", START, moves, timeout_ms=4000)
    run_client_until(server, lambda s: "job00001" in s.analyses)
    submissions = server.analyses["job00001"]
    assert submissions, "no analysis submitted"
    final = submissions[-1]
    assert final["fishnet"]["apikey"] == "testkey"
    assert final["stockfish"]["flavor"] == "nnue"
    analysis = final["analysis"]
    assert len(analysis) == 5  # 4 moves → 5 positions
    for part in analysis:
        assert part is not None
        assert "score" in part and "depth" in part and "nodes" in part
        assert "cp" in part["score"] or "mate" in part["score"]


def test_analysis_with_skips(server):
    moves = ["e2e4", "e7e5", "g1f3"]
    server.add_analysis_job("job00002", START, moves, skip=[1], timeout_ms=4000)
    run_client_until(server, lambda s: "job00002" in s.analyses)
    final = server.analyses["job00002"][-1]
    analysis = final["analysis"]
    assert len(analysis) == 4
    assert analysis[1] == {"skipped": True}
    assert analysis[0] is not None and "score" in analysis[0]


def test_move_job_end_to_end(server):
    server.add_move_job("mv000001", START, ["e2e4", "e7e5"], level=8)
    run_client_until(server, lambda s: "mv000001" in s.moves)
    body = server.moves["mv000001"]
    assert body["move"]["bestmove"], "no bestmove submitted"
    # bestmove must be a legal reply in the position after e4 e5
    from fishnet_tpu.chess import Position

    pos = Position.initial().push_uci("e2e4").push_uci("e7e5")
    legal = {m.uci() for m in pos.legal_moves()}
    assert body["move"]["bestmove"] in legal


def test_mate_position_reports_mate_zero(server):
    # fool's mate: final position is checkmate; its analysis part must be
    # depth 0 / mate 0 (reference: doc/protocol.md:99-104)
    moves = ["f2f3", "e7e5", "g2g4", "d8h4"]
    server.add_analysis_job("job00003", START, moves, timeout_ms=4000)
    run_client_until(server, lambda s: "job00003" in s.analyses)
    final = server.analyses["job00003"][-1]
    last_part = final["analysis"][-1]
    assert last_part["score"] == {"mate": 0}
    assert last_part["depth"] == 0


def test_checkmate_in_one_found(server):
    # position before the mating move: engine should find mate
    moves = ["f2f3", "e7e5", "g2g4"]
    server.add_analysis_job("job00004", START, moves, timeout_ms=4000)
    run_client_until(server, lambda s: "job00004" in s.analyses)
    final = server.analyses["job00004"][-1]
    last_part = final["analysis"][-1]  # black to move, mate in 1
    assert last_part["score"] == {"mate": 1}


def test_variant_analysis_reports_hce(server):
    server.add_analysis_job(
        "job00005", START, ["e2e4"], variant="kingOfTheHill", timeout_ms=4000
    )
    run_client_until(server, lambda s: "job00005" in s.analyses)
    final = server.analyses["job00005"][-1]
    assert final["stockfish"]["flavor"] == "classical"


def test_abort_on_shutdown(server):
    # a job with many positions: shut down before completion → abort POSTed
    moves = ["e2e4", "c7c5", "g1f3", "d7d6", "d2d4", "c5d4", "f3d4", "g8f6",
             "b1c3", "a7a6", "f1e2", "e7e5", "d4b3", "f8e7", "e1h1", "e8h8"]

    async def main():
        api = ApiClient(Endpoint(server.url), "testkey")
        queue = Queue(api, cores=1, logger=Logger())
        server.add_analysis_job("job00006", START, moves, timeout_ms=60000)
        factory = lambda flavor: PyEngine(max_depth=1)
        task = asyncio.create_task(worker(0, queue, factory))
        # wait for the batch to be acquired
        deadline = asyncio.get_running_loop().time() + 30
        while not queue.pending and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.02)
        assert queue.pending
        await queue.shutdown()
        await asyncio.wait_for(task, timeout=30)

    asyncio.run(main())
    assert "job00006" in server.aborted


def test_move_job_on_tpu_flavor(server):
    """Play jobs ride the TPU engine when tpu_moves is on (reference runs
    ALL move jobs on its bundled engine, src/queue.rs:562-568; skill
    semantics in engine/tpu.py _move_job)."""
    from fishnet_tpu.engine.tpu import TpuEngine
    from fishnet_tpu.client.wire import EngineFlavor

    engine = TpuEngine(max_depth=2)
    # move jobs carry a hard 7 s deadline (src/api.rs:163-168): pre-compile
    # the 64-lane program so the deadline race is about search, not XLA —
    # deep=True because move jobs run the distinct deep-TT program
    engine.warmup(buckets=(64,), deep=True)
    server.add_move_job("mvtpu001", START, ["e2e4", "e7e5"], level=3)
    py = PyEngine(max_depth=2)

    def factory(flavor):
        return engine if flavor is EngineFlavor.TPU else py

    run_client_until(
        server, lambda s: "mvtpu001" in s.moves,
        tpu_variants={"standard"}, tpu_moves=True, factory=factory,
        timeout=240.0,
    )
    body = server.moves["mvtpu001"]
    from fishnet_tpu.chess import Position

    pos = Position.initial().push_uci("e2e4").push_uci("e7e5")
    legal = {m.uci() for m in pos.legal_moves()}
    assert body["move"]["bestmove"] in legal
