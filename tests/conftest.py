"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-run-compiles the
multichip path via __graft_entry__.dryrun_multichip).

This environment registers a remote-TPU ("axon") PJRT plugin from
sitecustomize at interpreter start; once registered, even JAX_PLATFORMS=cpu
still initializes it on first use (and hangs when the tunnel is down).
Backend *initialization* is lazy though, so deregistering the factory here —
before any jax operation — cleanly forces CPU.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# small engine search stack + one warmup bucket: the MAX_PLY=24 production
# program takes minutes to compile on XLA:CPU; engine tests search depth ≤3
os.environ.setdefault("FISHNET_TPU_MAX_PLY", "8")
os.environ.setdefault("FISHNET_TPU_WARMUP_BUCKETS", "16")
# Lazy-SMP helpers off by default under pytest: the production default
# (K=4) widens every engine dispatch ~4x, which XLA:CPU pays in both
# compile and step time across dozens of engine tests. Helper-lane
# behavior is covered explicitly in tests/test_helper_lanes.py, which
# constructs TpuEngine(helper_lanes=...) itself.
os.environ.setdefault("FISHNET_TPU_HELPERS", "1")
# Continuous lane refill off by default under pytest for the same reason:
# the LaneScheduler is a second dispatch path through the engine, and the
# dozens of existing engine tests assert against the chunk-serial path's
# exact behavior. Refill behavior is covered explicitly in
# tests/test_refill.py, which constructs TpuEngine(refill=True) itself.
os.environ.setdefault("FISHNET_TPU_REFILL", "0")

# make the package importable regardless of how pytest was invoked; the
# settings registry (pure stdlib, safe before jax) is the single source
# of truth for FISHNET_TPU_* reads — including the two below
import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from fishnet_tpu.utils import settings  # noqa: E402

# persistent XLA compile cache for the whole suite (VERDICT r4 weak #7:
# the fast tier outgrew its box — XLA:CPU compiles of unchanged search
# programs dominated its wall clock). Enabled below via jax.config (this
# JAX version ignores the JAX_COMPILATION_CACHE_DIR env var); the
# FISHNET_TPU_COMPILE_CACHE env var makes engine subprocesses (which call
# utils.enable_compile_cache themselves) share the same directory.
# Unchanged programs then compile once per code change, not once per run.
if not settings.get_bool("FISHNET_TPU_NO_COMPILE_CACHE"):
    os.environ.setdefault(
        "FISHNET_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "fishnet-tpu", "xla"),
    )

try:
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    if not settings.get_bool("FISHNET_TPU_NO_COMPILE_CACHE"):
        from fishnet_tpu.utils import enable_compile_cache

        enable_compile_cache()
except Exception:
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running tests (deep perft, big batches)")
    config.addinivalue_line("markers", "tpu: tests that require a real TPU device")
    config.addinivalue_line(
        "markers",
        "mesh: sharded-scheduler tests that require the 8-device "
        "virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_"
        "device_count=8, which conftest forces anyway)")
    config.addinivalue_line(
        "markers",
        "subproc: subprocess-heavy integration suites (spawned fakehost/"
        "serve/full-app children); excluded from the fast tier and run "
        "in their own per-commit CI step")


def pytest_collection_modifyitems(config, items):
    # subproc implies slow so BOTH exclusion spellings drop the tier:
    # pytest.ini's addopts (-m "not slow and not tpu") and the roadmap's
    # tier-1 command, which passes -m 'not slow' on the CLI and thereby
    # REPLACES addopts' -m — a bare `-m "... and not subproc"` edit to
    # the ini would not survive that override.
    for item in items:
        if "subproc" in item.keywords:
            item.add_marker(pytest.mark.slow)


class EngineHostPool:
    """Session-scoped pool of supervised fake-engine hosts.

    Every fakehost-backed test pays a fresh interpreter boot per
    SupervisedEngine spawn, and the subproc tier spawns dozens. Tests
    whose script carries no cross-chunk fault state (plain "ok" serving)
    can share one long-lived child instead: the pool owns a private
    event loop on a background thread — SupervisedEngine's reader task
    is bound to the loop it spawned on, so a pooled engine cannot hop
    between the per-test asyncio.run() loops — and caches one engine per
    host command line. `run()` submits a coroutine to the pool loop and
    blocks for its result.

    Tests that assert spawn/death/kill counters or script specific
    faults must keep constructing their own SupervisedEngine: pooled
    stats accumulate across tests by design.
    """

    def __init__(self):
        import asyncio
        import threading

        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="engine-host-pool",
            daemon=True)
        self._thread.start()
        self._engines = {}

    def run(self, coro, timeout=120.0):
        import asyncio

        return asyncio.run_coroutine_threadsafe(
            coro, self._loop).result(timeout)

    def get(self, cmd, **kw):
        """Get-or-spawn the pooled SupervisedEngine for a host command
        line. Construction kwargs apply on first use only — callers
        sharing a command line share one incarnation and its settings.
        """
        key = tuple(cmd)
        eng = self._engines.get(key)
        if eng is None:
            from fishnet_tpu.client.logger import Logger
            from fishnet_tpu.engine.supervisor import SupervisedEngine

            kw.setdefault("hb_interval", 0.05)
            kw.setdefault("hb_timeout", 0.6)
            kw.setdefault("deadline_margin", 0.15)
            kw.setdefault("logger", Logger(verbose=0))
            eng = self._engines[key] = SupervisedEngine(list(cmd), **kw)
        return eng

    def close(self):
        async def _close_all():
            for eng in self._engines.values():
                await eng.close()

        self.run(_close_all())
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10.0)


@pytest.fixture(scope="session")
def engine_host_pool():
    pool = EngineHostPool()
    yield pool
    pool.close()
