"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-run-compiles the
multichip path via __graft_entry__.dryrun_multichip).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running tests (deep perft, big batches)")
    config.addinivalue_line("markers", "tpu: tests that require a real TPU device")
