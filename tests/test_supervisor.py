"""Supervisor fault-injection tests: every watchdog/breaker path on CPU.

Each test drives `SupervisedEngine` against the scriptable fake host
(fishnet_tpu/engine/fakehost.py) — no JAX, no device, deterministic
faults. One asyncio.run() per test: the supervisor's reader task and
pipe transports are bound to the loop they were created on.
"""
import asyncio
import json
import sys
import time

import pytest

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.fakehost import FAKE_CP
from fishnet_tpu.engine.supervisor import SupervisedEngine

pytestmark = [pytest.mark.faultinject, pytest.mark.subproc]

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def fake_cmd(script, state_path=None, hb_interval=0.05):
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", script if isinstance(script, str) else json.dumps(script),
        "--hb-interval", str(hb_interval),
    ]
    if state_path is not None:
        cmd += ["--state", str(state_path)]
    return cmd


def make_supervisor(script, state_path=None, **kw):
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 0.6)
    kw.setdefault("deadline_margin", 0.15)
    kw.setdefault("logger", Logger(verbose=0))
    return SupervisedEngine(fake_cmd(script, state_path), **kw)


def make_chunk(ttl=30.0, n_positions=2, depth=1):
    work = AnalysisWork(
        id="supjob01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=[])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + ttl,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


async def closing(sup):
    return _Closing(sup)


class _Closing:
    def __init__(self, sup):
        self.sup = sup

    async def __aenter__(self):
        return self.sup

    async def __aexit__(self, *exc):
        await self.sup.close()


def fake_cp(responses):
    return [r.scores.best().value for r in responses]


def test_ok_roundtrip():
    async def main():
        async with await closing(make_supervisor({"chunks": ["ok"]})) as sup:
            responses = await sup.go_multiple(make_chunk(n_positions=3))
            assert len(responses) == 3
            assert fake_cp(responses) == [FAKE_CP] * 3
            assert [r.position_index for r in responses] == [0, 1, 2]
            assert all(r.best_move == "e2e4" for r in responses)
            assert sup.stats.chunks_ok == 1
            assert sup.stats.spawns == 1

    asyncio.run(main())


def test_hang_killed_before_deadline_then_respawn(tmp_path):
    """Device-hang signature: heartbeats keep flowing but the search never
    returns — the watchdog must kill at the chunk deadline (not the
    heartbeat timeout) and the failure must surface BEFORE the worker's
    own deadline race would fire."""
    async def main():
        sup = make_supervisor({"chunks": ["hang", "ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            chunk = make_chunk(ttl=1.5)
            with pytest.raises(EngineError):
                await sup.go_multiple(chunk)
            # surfaced before the deadline: the worker reports ChunkFailed
            # instead of tripping its own asyncio.wait_for race
            assert time.monotonic() < chunk.deadline
            assert sup.stats.deadline_kills == 1
            assert sup.stats.hb_stalls == 0  # heartbeats never stopped
            # respawn (backoff-gated) serves the next chunk
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 2

    asyncio.run(main())


def test_stall_killed_by_heartbeat_watchdog(tmp_path):
    """Frozen process: ALL output stops. Killed by missed heartbeats long
    before the (distant) chunk deadline — and the recovery ladder retries
    in-chunk, so the caller sees a served chunk, not an error."""
    async def main():
        sup = make_supervisor({"chunks": ["stall", "ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            t0 = time.monotonic()
            responses = await sup.go_multiple(make_chunk(ttl=30.0))
            assert time.monotonic() - t0 < 10.0  # hb_timeout, not deadline
            assert sup.stats.hb_stalls == 1
            assert sup.stats.deadline_kills == 0
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 2

    asyncio.run(main())


def test_stall_surfaces_with_replay_disabled(tmp_path):
    """replay=False restores the pre-round-9 whole-chunk semantics: the
    first failure surfaces to the caller, the NEXT chunk recovers."""
    async def main():
        sup = make_supervisor({"chunks": ["stall", "ok"]},
                              tmp_path / "state.json", replay=False)
        async with await closing(sup):
            with pytest.raises(EngineError):
                await sup.go_multiple(make_chunk(ttl=30.0))
            assert sup.stats.hb_stalls == 1
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2

    asyncio.run(main())


def test_crash_respawn_and_recover(tmp_path):
    async def main():
        sup = make_supervisor({"chunks": ["crash:9", "ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            responses = await sup.go_multiple(make_chunk())
            assert sup.stats.deaths == 1
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 2
            # success clears the respawn backoff and the death window
            assert not sup._backoff.pending()

    asyncio.run(main())


def test_corrupt_frame_kills_child(tmp_path):
    async def main():
        sup = make_supervisor({"chunks": ["corrupt", "ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            responses = await sup.go_multiple(make_chunk(ttl=30.0))
            assert sup.stats.protocol_errors >= 1
            assert sup.stats.kills >= 1
            assert fake_cp(responses) == [FAKE_CP] * 2

    asyncio.run(main())


def test_err_frame_keeps_child_alive(tmp_path):
    """An err reply means the child handled its own failure — no kill, no
    respawn, next chunk goes to the same incarnation."""
    async def main():
        sup = make_supervisor({"chunks": ["err", "ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            with pytest.raises(EngineError, match="scripted engine error"):
                await sup.go_multiple(make_chunk())
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 1
            assert sup.stats.deaths == 0

    asyncio.run(main())


def test_slow_chunk_survives_on_heartbeats():
    """Slow but alive: the reply takes ~2× hb_timeout, yet flowing
    heartbeats must keep the watchdog from a false-positive kill."""
    async def main():
        sup = make_supervisor({"chunks": ["slow:1.2"]}, hb_timeout=0.5)
        async with await closing(sup):
            responses = await sup.go_multiple(make_chunk(ttl=30.0))
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.kills == 0

    asyncio.run(main())


def test_boot_stall_killed_then_recovers(tmp_path):
    """Warmup has no deadline (XLA compiles run minutes) but a SILENT
    warmup is dead — the heartbeat watchdog still applies, and the ladder
    respawns in-chunk."""
    async def main():
        sup = make_supervisor({"boot": ["stall", "ready"], "chunks": ["ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            responses = await sup.go_multiple(make_chunk())
            assert sup.stats.hb_stalls == 1
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 2

    asyncio.run(main())


def test_boot_crash_recovers_in_chunk(tmp_path):
    async def main():
        sup = make_supervisor({"boot": ["crash:7", "ready"], "chunks": ["ok"]},
                              tmp_path / "state.json")
        async with await closing(sup):
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert sup.stats.spawns == 2

    asyncio.run(main())


def test_breaker_trips_to_cpu_fallback_and_probe_recovers(tmp_path):
    """Acceptance path: N exhausted recovery ladders open the breaker
    (one breaker-visible death per given-up ladder — in-ladder deaths
    stay invisible to the window), chunks degrade to the pure-Python CPU
    engine (responses still produced), and a later successful probe
    restores the child path."""
    async def main():
        sup = make_supervisor(
            {"chunks": ["crash:1", "crash:1", "crash:1", "crash:1", "ok"]},
            tmp_path / "state.json",
            breaker_threshold=2,
            breaker_window=600.0,
            probe_interval=0.4,
            bisect_max=1,  # each call: 2 deaths, then the ladder gives up
            backoff=RandomizedBackoff(max_s=0.05),
        )
        async with await closing(sup):
            # ladder 1 exhausts (2 child deaths → ONE breaker-visible
            # death): plain failure, breaker still closed
            with pytest.raises(EngineError):
                await sup.go_multiple(make_chunk())
            assert not sup._breaker_open
            assert sup.stats.deaths == 2
            assert len(sup._deaths) == 1

            # ladder 2 exhausts and trips the breaker; the SAME chunk is
            # salvaged on the CPU fallback, so responses are still produced
            responses = await sup.go_multiple(make_chunk(ttl=60.0))
            assert sup._breaker_open
            assert sup.stats.breaker_trips == 1
            assert sup.stats.fallback_chunks == 1
            assert len(responses) == 2
            # PyEngine really searched: its scores are not the fake host's
            # signature constant
            assert all(r.best_move is not None for r in responses)
            assert fake_cp(responses) != [FAKE_CP, FAKE_CP]

            # breaker open, probe not due: straight to fallback, child
            # untouched
            responses = await sup.go_multiple(make_chunk(ttl=60.0))
            assert sup.stats.fallback_chunks == 2
            assert sup.stats.probes == 0

            # probe due: child respawns, script says ok → breaker closes
            await asyncio.sleep(0.45)
            responses = await sup.go_multiple(make_chunk(ttl=60.0))
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert not sup._breaker_open
            assert sup.stats.probes == 1
            assert sup.stats.breaker_resets == 1

            # back on the child path for good
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2

    asyncio.run(main())


def test_failed_probe_stays_on_fallback(tmp_path):
    async def main():
        sup = make_supervisor(
            {"chunks": ["crash:1", "crash:1", "crash:1", "crash:1",
                        "crash:1", "ok"]},
            tmp_path / "state.json",
            breaker_threshold=2,
            probe_interval=0.3,
            bisect_max=1,
            backoff=RandomizedBackoff(max_s=0.05),
        )
        async with await closing(sup):
            with pytest.raises(EngineError):
                await sup.go_multiple(make_chunk())
            await sup.go_multiple(make_chunk(ttl=60.0))  # trips + salvages
            assert sup._breaker_open
            await asyncio.sleep(0.35)
            # probe (single dispatch, no ladder) hits crash #5: breaker
            # stays open, chunk still served
            responses = await sup.go_multiple(make_chunk(ttl=60.0))
            assert len(responses) == 2
            assert sup._breaker_open
            assert sup.stats.probes == 1
            assert sup.stats.breaker_resets == 0
            # next probe succeeds
            await asyncio.sleep(0.35)
            responses = await sup.go_multiple(make_chunk(ttl=60.0))
            assert fake_cp(responses) == [FAKE_CP] * 2
            assert not sup._breaker_open

    asyncio.run(main())


def test_close_is_clean_and_object_is_reusable(tmp_path):
    """The app's engine factory caches one supervisor; workers close() it
    when dropping an engine. close() must not count as a death and the
    object must serve again afterwards (fresh child)."""
    async def main():
        sup = make_supervisor({"chunks": ["ok"]}, tmp_path / "state.json")
        responses = await sup.go_multiple(make_chunk())
        assert fake_cp(responses) == [FAKE_CP] * 2
        await sup.close()
        assert sup.proc is None
        assert sup.stats.deaths == 0
        responses = await sup.go_multiple(make_chunk())
        assert fake_cp(responses) == [FAKE_CP] * 2
        assert sup.stats.spawns == 2
        assert sup.stats.deaths == 0
        await sup.close()

    asyncio.run(main())


def test_start_waits_for_ready():
    async def main():
        sup = make_supervisor({"boot": ["slow:0.5"], "chunks": ["ok"]})
        async with await closing(sup):
            t0 = time.monotonic()
            await sup.start()
            assert time.monotonic() - t0 >= 0.4
            responses = await sup.go_multiple(make_chunk())
            assert fake_cp(responses) == [FAKE_CP] * 2

    asyncio.run(main())


def test_latency_jitter_deterministic_and_timing_only():
    """--jitter-ms layers seeded uniform service-time jitter on top of
    --latency-ms: the delay for chunk k is a pure function of
    (--jitter-seed, k), so the test can compute the exact sleep the
    host will take — and the answers are byte-identical to a
    jitter-free run (the knob moves timing, never results)."""
    import random as _random

    async def main():
        # replicate fakehost's draw for chunk 0 under seed 9
        expected_s = _random.Random("9:0").uniform(0.0, 200.0) / 1000.0
        cmd = fake_cmd({"chunks": ["ok"]}) + [
            "--latency-ms", "50", "--jitter-ms", "200",
            "--jitter-seed", "9",
        ]
        sup = SupervisedEngine(cmd, hb_interval=0.05, hb_timeout=1.0,
                               deadline_margin=0.15,
                               logger=Logger(verbose=0))
        async with await closing(sup):
            began = time.monotonic()
            jittered = await sup.go_multiple(make_chunk(n_positions=2))
            elapsed = time.monotonic() - began
        # the scripted service delay really happened: fixed + jittered
        assert elapsed >= 0.05 + expected_s
        assert sup.stats.chunks_ok == 1

        async with await closing(
                make_supervisor({"chunks": ["ok"]})) as plain:
            baseline = await plain.go_multiple(make_chunk(n_positions=2))

        assert fake_cp(jittered) == fake_cp(baseline) == [FAKE_CP] * 2
        assert [r.best_move for r in jittered] == \
            [r.best_move for r in baseline]

    asyncio.run(main())
