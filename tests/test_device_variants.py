"""Device-side crazyhouse + threeCheck vs the host variant rules.

The reference analyses variants with Fairy-Stockfish (src/stockfish.rs:
245-260 sets UCI_Variant); the device implements them as statically
compiled program variants. Property tests: move SETS and make_move state
(incl. pockets, promoted bits, check counters) must match the host
library over random playouts; searches must match the host oracle
exactly; a variant chunk must flow through TpuEngine end to end.
"""
import asyncio
import random
import time

import jax
import numpy as np
import pytest

from fishnet_tpu.chess import Move
from fishnet_tpu.chess.variants import from_fen, position_class
from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.models import nnue
from fishnet_tpu.ops import tables as T
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.movegen import DROP_FLAG, generate_moves
from fishnet_tpu.ops.board import make_move
from fishnet_tpu.ops.oracle import oracle_search
from fishnet_tpu.ops.search import search_batch_jit

_PROMO_MAP = {
    1: T.PROMO_N, 2: T.PROMO_B, 3: T.PROMO_R, 4: T.PROMO_Q,
    5: T.PROMO_K,  # antichess promotes to king (host piece type 5)
}


def encode_host_move(m: Move) -> int:
    if m.drop is not None:
        return DROP_FLAG | (m.drop << 12) | (m.to_sq << 6) | m.to_sq
    promo = _PROMO_MAP[m.promotion] if m.promotion is not None else 0
    return m.from_sq | (m.to_sq << 6) | (promo << 12)


@pytest.fixture(scope="module")
def params():
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set="board768"
    )


ALL_VARIANTS = [
    "crazyhouse", "threeCheck", "antichess", "atomic", "horde",
    "kingOfTheHill", "racingKings",
]


@pytest.fixture(scope="module", params=ALL_VARIANTS)
def variant(request):
    return request.param


@pytest.fixture(scope="module")
def kernels(variant):
    gen = jax.jit(lambda b: generate_moves(b, variant))
    mk = jax.jit(lambda b, m: make_move(b, m, variant))
    return gen, mk


def _boards_equal(b1, b2) -> bool:
    return (
        np.array_equal(np.asarray(b1.board), np.asarray(b2.board))
        and int(b1.stm) == int(b2.stm)
        and int(b1.ep) == int(b2.ep)
        and sorted(np.asarray(b1.castling).tolist())
        == sorted(np.asarray(b2.castling).tolist())
        and int(b1.halfmove) == int(b2.halfmove)
        and np.array_equal(np.asarray(b1.extra), np.asarray(b2.extra))
    )


def test_playouts_match_host(variant, kernels):
    gen, mk = kernels
    rng = random.Random(42)
    for game in range(6):
        pos = position_class(variant).from_fen(
            position_class(variant).starting_fen()
        )
        for ply in range(40):
            legal = pos.legal_moves()
            if not legal or pos.outcome() is not None:
                break
            if variant == "antichess":
                # the device folds capture compulsion into generation
                # (no check concept, so legal == compulsion-filtered)
                host_set = {encode_host_move(m) for m in legal}
            else:
                host_set = {
                    encode_host_move(m) for m in pos.generate_pseudo_legal()
                }
            b = from_position(pos)
            moves, count, _ = gen(b)
            dev_set = set(np.asarray(moves)[: int(count)].tolist())
            assert dev_set == host_set, (
                f"{variant} move set mismatch\nfen={pos.to_fen()}\n"
                f"host-only={sorted(host_set - dev_set)}\n"
                f"device-only={sorted(dev_set - host_set)}"
            )
            move = rng.choice(legal)
            child = pos.push(move)
            dev_child = mk(b, encode_host_move(move))
            assert _boards_equal(dev_child, from_position(child)), (
                f"{variant} make_move mismatch: {move.uci()}\n"
                f"fen={pos.to_fen()} → {child.to_fen()}"
            )
            pos = child


def _variant_fens(variant, n, seed=11):
    rng = random.Random(seed)
    fens = []
    while len(fens) < n:
        pos = position_class(variant).from_fen(
            position_class(variant).starting_fen()
        )
        for _ in range(rng.randrange(4, 40)):
            legal = pos.legal_moves()
            if not legal or pos.outcome() is not None:
                break
            pos = pos.push(rng.choice(legal))
        if pos.outcome() is None and pos.legal_moves():
            fens.append(pos.to_fen())
    return fens


def _oracle_check(params, variant, depth, n_fens=8):
    fens = _variant_fens(variant, n_fens)
    roots = stack_boards([from_position(from_fen(f, variant)) for f in fens])
    out = search_batch_jit(
        params, roots, depth, 100_000, max_ply=4, variant=variant
    )
    out = {k: np.asarray(v) for k, v in out.items() if k != "tt"}
    for i, fen in enumerate(fens):
        exp = oracle_search(
            params, from_position(from_fen(fen, variant)), depth, 100_000, 4,
            variant=variant,
        )
        assert int(out["score"][i]) == exp["score"], (variant, fen, depth)
        assert int(out["nodes"][i]) == exp["nodes"], (variant, fen, depth)


def test_search_matches_oracle_depth1(params, variant):
    _oracle_check(params, variant, 1)


@pytest.mark.slow
def test_search_matches_oracle_depth2(params, variant):
    _oracle_check(params, variant, 2)


def test_three_check_win_is_mate_scored(params):
    """2 checks given + a check available: delivering the 3rd check ends
    the game — the search must find a forced win."""
    from fishnet_tpu.ops.search import MATE

    # white Qd2+Ke1 vs black Ke8; white has given 2 checks already and
    # has checks at will (e.g. Qd8+) — any check is the 3rd
    fen = "4k3/8/8/8/8/8/3Q4/4K3 w - - +2+0 0 1"
    root = from_position(from_fen(fen, "threeCheck"))
    roots = stack_boards([root] * 8)
    out = search_batch_jit(
        params, roots, 2, 100_000, max_ply=4, variant="threeCheck"
    )
    score = int(np.asarray(out["score"])[0])
    assert score >= MATE - 10, f"expected 3check win, got {score}"


def _spot_score(params, fen, variant, depth=2, lanes=8):
    root = from_position(from_fen(fen, variant))
    roots = stack_boards([root] * lanes)
    out = search_batch_jit(
        params, roots, depth, 100_000, max_ply=4, variant=variant
    )
    return int(np.asarray(out["score"])[0])


def test_atomic_exploding_the_king_wins(params):
    from fishnet_tpu.ops.search import MATE

    # Qxd8 explodes the knight; the blast removes the adjacent king
    score = _spot_score(params, "3nk3/8/8/8/8/8/8/3QK3 w - - 0 1", "atomic")
    assert score >= MATE - 10, score


def test_atomic_explosion_reaches_a1(params):
    """Regression: the blast zone must cover square a1 (a clipped -1 pad
    in KING_TARGETS once overwrote a1's membership), so a non-pawn on a1
    dies when a capture lands next to it."""
    pos = from_fen("4k3/8/8/8/8/8/1r6/nR2K3 w - - 0 1", "atomic")
    mv = next(m for m in pos.legal_moves() if m.uci() == "b1b2")
    child = pos.push(mv)
    dev = jax.jit(lambda b, m: make_move(b, m, "atomic"))(
        from_position(pos), encode_host_move(mv)
    )
    assert _boards_equal(dev, from_position(child))
    assert int(np.asarray(dev.board)[0]) == 0  # the a1 knight exploded


def test_koth_reaching_the_hill_wins(params):
    from fishnet_tpu.ops.search import MATE

    # Kd3-d4 steps onto the hill
    score = _spot_score(params, "7k/8/8/8/8/3K4/8/8 w - - 0 1", "kingOfTheHill")
    assert score >= MATE - 10, score


def test_racing_kings_goal_with_failed_rejoinder_wins(params):
    from fishnet_tpu.ops.search import MATE

    # Kg7-g8 reaches the goal; the black king on a1 cannot answer in one
    score = _spot_score(params, "8/6K1/8/8/8/8/8/k7 w - - 0 1", "racingKings")
    assert score >= MATE - 10, score


def test_racing_kings_rejoinder_draws(params):
    # white already on the goal, black to move one step below: Ka8
    # equalizes (draw); every other reply loses — so black scores 0
    score = _spot_score(params, "6K1/k7/8/8/8/8/8/8 b - - 0 1", "racingKings")
    assert score == 0, score


def test_horde_destroying_the_horde_wins(params):
    from fishnet_tpu.ops.search import MATE

    # black queen takes white's last pawn → horde destroyed
    score = _spot_score(params, "4k3/8/8/8/8/8/q6P/8 b - - 0 1", "horde")
    assert score >= MATE - 10, score


def test_antichess_capture_compulsion(params):
    # white pawn e4 can capture d5: ONLY captures may be generated
    pos = from_fen(
        "rnbqkbnr/ppp1pppp/8/3p4/4P3/8/PPPP1PPP/RNBQKBNR w - - 0 2",
        "antichess",
    )
    moves, count, _ = jax.jit(
        lambda b: generate_moves(b, "antichess")
    )(from_position(pos))
    dev = set(np.asarray(moves)[: int(count)].tolist())
    assert dev == {encode_host_move(m) for m in pos.legal_moves()}
    assert len(dev) == 1  # exd5 is the only legal move


def test_antichess_running_out_of_pieces_wins(params):
    from fishnet_tpu.ops.search import MATE

    # white's lone pawn must capture (compulsion) and is then taken:
    # white runs out of pieces and WINS
    score = _spot_score(
        params, "8/8/8/8/2q5/3q4/2P5/8 w - - 0 1", "antichess", depth=3
    )
    assert score >= MATE - 10, score


def test_decode_uci_handles_king_promotion():
    from fishnet_tpu.engine.tpu import _decode_uci
    from fishnet_tpu.ops import tables as T

    # e7e8k (antichess): promo code 5 must decode, not IndexError
    m = 52 | (60 << 6) | (T.PROMO_K << 12)
    assert _decode_uci(m) == "e7e8k"


def test_variant_chunk_through_engine(variant):
    from fishnet_tpu.engine.tpu import TpuEngine

    engine = TpuEngine(max_depth=2)
    work = AnalysisWork(
        id="varjob01",
        nodes=NodeLimit(sf16=500_000, classical=500_000),
        timeout_s=30.0,
        depth=2,
    )
    start_fen = position_class(variant).starting_fen()
    positions = [
        WorkPosition(
            work=work, position_index=i, url=None, skip=False,
            root_fen=start_fen, moves=[],
        )
        for i in range(2)
    ]
    chunk = Chunk(
        work=work, deadline=time.monotonic() + 300, variant=variant,
        flavor=EngineFlavor.TPU, positions=positions,
    )
    responses = asyncio.run(engine.go_multiple(chunk))
    assert len(responses) == 2
    for res in responses:
        assert res.depth == 2
        assert res.nodes > 0
        assert res.best_move is not None
        # the engine's move must be legal under the variant rules
        pos = from_fen(start_fen, variant)
        pos.push(pos.parse_uci(res.best_move))
