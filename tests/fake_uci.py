#!/usr/bin/env python
"""Minimal fake UCI engine for adapter tests: legal play via the host rules
library, fixed shallow 'analysis', standard info/bestmove output."""
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from fishnet_tpu.chess.variants import from_fen  # noqa: E402


def main():
    position = None
    variant = "standard"
    multipv = 1
    out = sys.stdout
    for raw in sys.stdin:
        line = raw.strip()
        if line == "quit":
            return
        if line == "isready":
            print("readyok", flush=True)
        elif line.startswith("setoption name UCI_Variant value "):
            uci_name = line.rsplit(" ", 1)[1]
            variant = {
                "chess": "standard", "3check": "threeCheck",
                "kingofthehill": "kingOfTheHill", "racingkings": "racingKings",
            }.get(uci_name, uci_name)
        elif line.startswith("setoption name MultiPV value "):
            multipv = int(line.rsplit(" ", 1)[1])
        elif line.startswith("position fen "):
            rest = line[len("position fen "):]
            if " moves " in rest:
                fen, moves_s = rest.split(" moves ", 1)
                moves = moves_s.split()
            else:
                fen, moves = rest, []
            # trailing "moves" with no moves
            fen = fen.rsplit(" moves", 1)[0] if fen.endswith(" moves") else fen
            position = from_fen(fen.strip(), variant)
            for uci in moves:
                position = position.push(position.parse_uci(uci))
        elif line.startswith("go"):
            legal = position.legal_moves() if position else []
            if not legal:
                print("info depth 0 score mate 0", flush=True)
                print("bestmove (none)", flush=True)
                continue
            for rank, move in enumerate(legal[:multipv], start=1):
                print(
                    f"info depth 1 seldepth 1 multipv {rank} score cp {10 * rank} "
                    f"nodes {len(legal)} nps 1000 time 1 pv {move.uci()}",
                    flush=True,
                )
            print(f"bestmove {legal[0].uci()}", flush=True)
    return


if __name__ == "__main__":
    main()
