"""In-process fake lichess server implementing the fishnet protocol
(doc/protocol.md) for integration tests: acquire/analysis/move/abort/status/
key over localhost HTTP."""
from __future__ import annotations

import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeLichess:
    def __init__(self, key: str = "testkey", with_status: bool = True):
        self.key = key
        self.with_status = with_status
        self.jobs = deque()
        self.analyses = {}  # work_id -> list of submitted analysis bodies
        self.moves = {}  # work_id -> submitted move bodies
        self.aborted = []
        self.acquire_count = 0
        self.status_body = {
            "analysis": {
                "user": {"acquired": 1, "queued": 0, "oldest": 0},
                "system": {"acquired": 0, "queued": 0, "oldest": 0},
            }
        }
        self.lock = threading.Lock()
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), self._make_handler())
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        host, port = self.server.server_address
        return f"http://{host}:{port}/fishnet"

    def start(self):
        self.thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()

    def add_analysis_job(self, job_id, position, moves, variant="standard",
                         skip=(), nodes=None, multipv=None, timeout_ms=7000):
        self.jobs.append({
            "work": {
                "type": "analysis",
                "id": job_id,
                "nodes": nodes or {"sf16": 1500000, "classical": 4050000},
                "timeout": timeout_ms,
                **({"multipv": multipv} if multipv else {}),
            },
            "game_id": job_id,
            "position": position,
            "variant": variant,
            "moves": " ".join(moves),
            "skipPositions": list(skip),
        })

    def add_move_job(self, job_id, position, moves, level=5, variant="standard"):
        self.jobs.append({
            "work": {"type": "move", "id": job_id, "level": level},
            "game_id": job_id,
            "position": position,
            "variant": variant,
            "moves": " ".join(moves),
        })

    def _make_handler(server_self):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, status, body=None):
                self.send_response(status)
                if body is not None:
                    payload = json.dumps(body).encode()
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def _read_body(self):
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    return json.loads(raw) if raw else {}
                except ValueError:
                    return {}

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/fishnet/status":
                    if server_self.with_status:
                        self._reply(200, server_self.status_body)
                    else:
                        self._reply(404)
                elif path == "/fishnet/key":
                    auth = self.headers.get("Authorization", "")
                    ok = auth == f"Bearer {server_self.key}"
                    self._reply(200 if ok else 404)
                elif path.startswith("/fishnet/key/"):
                    ok = path.rsplit("/", 1)[1] == server_self.key
                    self._reply(200 if ok else 404)
                else:
                    self._reply(404)

            def do_POST(self):
                path = self.path.split("?")[0]
                body = self._read_body()
                with server_self.lock:
                    if path == "/fishnet/acquire":
                        server_self.acquire_count += 1
                        if server_self.jobs:
                            self._reply(202, server_self.jobs.popleft())
                        else:
                            self._reply(204)
                    elif path.startswith("/fishnet/analysis/"):
                        work_id = path.rsplit("/", 1)[1]
                        server_self.analyses.setdefault(work_id, []).append(body)
                        self._reply(204)
                    elif path.startswith("/fishnet/move/"):
                        work_id = path.rsplit("/", 1)[1]
                        server_self.moves[work_id] = body
                        if server_self.jobs:
                            self._reply(202, server_self.jobs.popleft())
                        else:
                            self._reply(204)
                    elif path.startswith("/fishnet/abort/"):
                        server_self.aborted.append(path.rsplit("/", 1)[1])
                        self._reply(204)
                    else:
                        self._reply(404)

        return Handler
