"""Round-trip property tests for every serde pair, generated from the
dataclass fields themselves.

The static wire-schema lint (fishnet_tpu/lint/wire_rules.py) proves the
two sides of each pair mention the same fields and keys; these tests
prove the *values* survive. The field lists are enumerated with
`dataclasses.fields()` at run time, so adding a field to any wire
dataclass automatically extends the suite — a new field with no value
factory fails loudly instead of silently going untested.

No JAX imports: this file stays in the sub-second tier.
"""
import dataclasses
import time

import pytest

from fishnet_tpu.client.ipc import (
    Chunk,
    Matrix,
    PositionResponse,
    WorkPosition,
    chunk_from_wire,
    chunk_to_wire,
    response_to_wire,
    responses_from_wire,
)
from fishnet_tpu.client.wire import (
    AnalysisWork,
    Clock,
    EngineFlavor,
    MoveWork,
    NodeLimit,
    Score,
    SkillLevel,
    work_from_json,
    work_to_json,
)


def _score_matrix(values):
    m = Matrix()
    for depth, v in enumerate(values, start=1):
        m.set(1, depth, Score.cp(v))
    return m


def _pv_matrix(rows):
    m = Matrix()
    for depth, pv in enumerate(rows, start=1):
        m.set(1, depth, list(pv))
    return m


# (base, alternate) per annotation string; the alternate must differ
# from the base so a dropped field is guaranteed to change the output
_BY_TYPE = {
    "str": ("abc", "xyz"),
    "int": (3, 7),
    "float": (1.5, 2.25),
    "bool": (True, False),
    "Optional[int]": (2, 5),
    "Optional[str]": ("u1", "u2"),
    # request context (obs/trace.py CTX_KEYS): the wire reader
    # normalizes through ctx_from_wire, so factories carry all keys
    "Optional[dict]": (
        {"trace_id": "aa" * 8, "span_id": "bb" * 8, "tenant": "t1",
         "kind": "analysis", "deadline_ms": None},
        {"trace_id": "cc" * 8, "span_id": "dd" * 8, "tenant": "t2",
         "kind": "bestmove", "deadline_ms": 500},
    ),
    "List[str]": (["e2e4"], ["d2d4", "g8f6"]),
    "NodeLimit": (NodeLimit(4000, 8000), NodeLimit(1000, 2000)),
    "Optional[Clock]": (Clock(600, 600, 2), Clock(300, 300, 0)),
    "SkillLevel": (SkillLevel(3), SkillLevel(5)),
    "EngineFlavor": (EngineFlavor.TPU, EngineFlavor.OFFICIAL),
    "Work": (
        AnalysisWork(id="w1", nodes=NodeLimit(4000, 8000), timeout_s=6.0),
        AnalysisWork(id="w2", nodes=NodeLimit(1000, 2000), timeout_s=3.0),
    ),
}

# per-field overrides where the annotation alone is ambiguous (the two
# Matrix fields carry different cell types)
_BY_FIELD = {
    ("PositionResponse", "scores"): (
        _score_matrix([10, 25]), _score_matrix([-40])),
    ("PositionResponse", "pvs"): (
        _pv_matrix([["e2e4"], ["e2e4", "e7e5"]]), _pv_matrix([["d2d4"]])),
    ("Chunk", "positions"): (None, None),  # built in the chunk factory
    ("Chunk", "deadline"): (None, None),   # ttl-based, compared by slack
}


def _values_for(cls, f):
    key = (cls.__name__, f.name)
    if key in _BY_FIELD:
        return _BY_FIELD[key]
    ann = f.type if isinstance(f.type, str) else getattr(
        f.type, "__name__", str(f.type))
    if ann in _BY_TYPE:
        return _BY_TYPE[ann]
    pytest.fail(
        f"no value factory for {cls.__name__}.{f.name}: {ann!r} — a new "
        "wire field needs an entry here so the round-trip suite covers it"
    )


def canon(obj):
    """Comparable structure; WorkPosition.work is dropped (rebuilt from
    the chunk's work on the far side) and Chunk.deadline is compared
    separately (monotonic-clock re-anchoring)."""
    if isinstance(obj, Matrix):
        return ("Matrix", canon(obj.matrix))
    if isinstance(obj, EngineFlavor):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        skip = {"WorkPosition": {"work"}, "Chunk": {"deadline"}}.get(
            type(obj).__name__, set())
        return (type(obj).__name__, {
            f.name: canon(getattr(obj, f.name))
            for f in dataclasses.fields(obj) if f.name not in skip
        })
    if isinstance(obj, (list, tuple)):
        return [canon(v) for v in obj]
    return obj


def _base_analysis():
    return AnalysisWork(
        id="batch01", nodes=NodeLimit(4000, 8000), timeout_s=6.0,
        depth=None, multipv=None,
    )


def _base_move():
    return MoveWork(id="batch02", level=SkillLevel(4), clock=None)


def _base_chunk(work=None):
    work = work or _base_analysis()
    position = WorkPosition(
        work=work, position_index=0, url=None, skip=False,
        root_fen="rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        moves=["e2e4"],
    )
    return Chunk(
        work=work, deadline=time.monotonic() + 30.0, variant="standard",
        flavor=EngineFlavor.TPU, positions=[position],
    )


def _base_response():
    return PositionResponse(
        work=_base_analysis(), position_index=1, url=None,
        scores=_score_matrix([15]), pvs=_pv_matrix([["e2e4"]]),
        best_move="e2e4", depth=6, nodes=12345, time_s=0.25, nps=49380,
    )


def _rt_work(work):
    return work_from_json(work_to_json(work))


def _rt_chunk(chunk):
    return chunk_from_wire(chunk_to_wire(chunk))


def _rt_response(res):
    out = responses_from_wire(res.work, [response_to_wire(res)])
    assert len(out) == 1
    return out[0]


# ------------------------------------------------------------------- work


@pytest.mark.parametrize("field", [
    f.name for f in dataclasses.fields(AnalysisWork)])
def test_analysis_work_field_roundtrip(field):
    base = _base_analysis()
    f = {f.name: f for f in dataclasses.fields(AnalysisWork)}[field]
    _, alt = _values_for(AnalysisWork, f)
    mutated = dataclasses.replace(base, **{field: alt})
    assert canon(_rt_work(mutated)) == canon(mutated)


@pytest.mark.parametrize("field", [
    f.name for f in dataclasses.fields(MoveWork)])
def test_move_work_field_roundtrip(field):
    base = _base_move()
    f = {f.name: f for f in dataclasses.fields(MoveWork)}[field]
    _, alt = _values_for(MoveWork, f)
    mutated = dataclasses.replace(base, **{field: alt})
    assert canon(_rt_work(mutated)) == canon(mutated)


def test_work_base_roundtrip():
    assert canon(_rt_work(_base_analysis())) == canon(_base_analysis())
    assert canon(_rt_work(_base_move())) == canon(_base_move())


def test_nodelimit_and_clock_fields_covered():
    # nested serde types ride inside the work pair; enumerate them too so
    # a new NodeLimit/Clock field can't silently skip the suite
    work = AnalysisWork(id="n", nodes=NodeLimit(111, 222), timeout_s=1.0)
    assert canon(_rt_work(work).nodes) == canon(work.nodes)
    for f in dataclasses.fields(NodeLimit):
        assert f.type in ("int",), f"extend the suite for NodeLimit.{f.name}"
    move = MoveWork(id="m", level=SkillLevel(2), clock=Clock(123, 456, 7))
    assert canon(_rt_work(move).clock) == canon(move.clock)
    for f in dataclasses.fields(Clock):
        assert f.type in ("int",), f"extend the suite for Clock.{f.name}"


# ------------------------------------------------------------------ chunk


@pytest.mark.parametrize("field", [
    f.name for f in dataclasses.fields(Chunk)])
def test_chunk_field_roundtrip(field):
    if field == "deadline":
        chunk = _base_chunk()
        ttl = chunk.deadline - time.monotonic()
        rt = _rt_chunk(chunk)
        assert abs((rt.deadline - time.monotonic()) - ttl) < 0.5
        return
    if field == "positions":
        chunk = _base_chunk()
        extra = WorkPosition(
            work=chunk.work, position_index=None, url="http://x/1",
            skip=True, root_fen="8/8/8/8/8/8/8/k1K5 w - - 0 1", moves=[],
        )
        mutated = dataclasses.replace(
            chunk, positions=chunk.positions + [extra])
        assert canon(_rt_chunk(mutated)) == canon(mutated)
        return
    chunk = _base_chunk()
    f = {f.name: f for f in dataclasses.fields(Chunk)}[field]
    _, alt = _values_for(Chunk, f)
    mutated = dataclasses.replace(chunk, **{field: alt})
    assert canon(_rt_chunk(mutated)) == canon(mutated)


@pytest.mark.parametrize("field", [
    f.name for f in dataclasses.fields(WorkPosition)
    if f.name != "work"])  # rebuilt from the chunk's work by design
def test_work_position_field_roundtrip(field):
    chunk = _base_chunk()
    f = {f.name: f for f in dataclasses.fields(WorkPosition)}[field]
    _, alt = _values_for(WorkPosition, f)
    mutated_pos = dataclasses.replace(chunk.positions[0], **{field: alt})
    mutated = dataclasses.replace(chunk, positions=[mutated_pos])
    assert canon(_rt_chunk(mutated)) == canon(mutated)


def test_chunk_rebinds_position_work_to_chunk_work():
    chunk = _base_chunk()
    rt = _rt_chunk(chunk)
    assert all(p.work is rt.work for p in rt.positions)


# --------------------------------------------------------------- response


@pytest.mark.parametrize("field", [
    f.name for f in dataclasses.fields(PositionResponse)
    if f.name != "work"])  # travels in the frame header, not the wire dict
def test_response_field_roundtrip(field):
    base = _base_response()
    f = {f.name: f for f in dataclasses.fields(PositionResponse)}[field]
    _, alt = _values_for(PositionResponse, f)
    mutated = dataclasses.replace(base, **{field: alt})
    assert canon(_rt_response(mutated)) == canon(mutated)


def test_response_none_nps_roundtrip():
    base = dataclasses.replace(_base_response(), nps=None)
    assert _rt_response(base).nps is None


# ------------------------------------------------------------------ score


@pytest.mark.parametrize("score", [Score.cp(13), Score.cp(-200),
                                   Score.mate(3), Score.mate(-1)])
def test_score_roundtrip(score):
    assert Score.from_json(score.to_json()) == score
    for f in dataclasses.fields(Score):
        assert f.name in ("kind", "value"), \
            f"extend the suite for Score.{f.name}"
