"""Lazy-SMP helper lanes: planner, lane-group search plumbing, K=1 purity.

The helper-lane feature (engine/tpu.py) replicates hard positions across
spare lanes with perturbed move ordering, communicating only through the
shared TT. Its safety contract is that K=1 is byte-for-byte today's
search — these tests pin that, the planner's allocation order, the
required-lane early stop, and (slow tier) that helpers actually reduce
lockstep steps-to-depth on a hard middlegame position.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops import tt
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.search import MATE, search_batch_resumable

KIWIPETE = "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1"
FENS = [
    "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    KIWIPETE,
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
]
B = 16  # one compiled width for the whole file


@pytest.fixture(scope="module")
def params():
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set="board768"
    )


def _roots(fens):
    boards = [from_position(Position.from_fen(f)) for f in fens]
    return stack_boards(boards + [boards[0]] * (B - len(boards)))


def test_k1_lane_group_config_is_bit_identical(params):
    """The K=1 helper configuration — zero jitter, identity groups, all
    lanes required — must reproduce today's search exactly: scores,
    moves, PVs, node counts AND step count. This is the oracle-equality
    guarantee that lets helper plumbing ship inside the analysis path."""
    roots = _roots(FENS)
    plain = search_batch_resumable(
        params, roots, 3, 200_000, max_ply=4, tt=tt.make_table(14),
    )
    lane_group = search_batch_resumable(
        params, roots, 3, 200_000, max_ply=4, tt=tt.make_table(14),
        order_jitter=jnp.zeros(B, jnp.int32),
        group=jnp.arange(B, dtype=jnp.int32),
        required=np.ones(B, bool),
    )
    for key in ("score", "move", "nodes", "pv", "pv_len", "done"):
        np.testing.assert_array_equal(
            np.asarray(plain[key]), np.asarray(lane_group[key]), err_msg=key
        )
    assert int(plain["steps"]) == int(lane_group["steps"])


def test_jittered_helpers_still_find_mate(params):
    """Ordering jitter perturbs WHICH move is tried first, never the
    result: every jittered lane on a mate-in-1 must still report it."""
    mate1 = "6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1"
    boards = [from_position(Position.from_fen(mate1))] * B
    out = search_batch_resumable(
        params, stack_boards(boards), 2, 200_000, max_ply=4,
        tt=tt.make_table(14),
        order_jitter=jnp.arange(B, dtype=jnp.int32),  # lane 0 unjittered
        group=jnp.zeros(B, jnp.int32),
        prefer_deep_store=True, tt_gen=1,
    )
    assert (np.asarray(out["score"]) == MATE - 1).all()
    assert np.asarray(out["done"]).all()


def test_required_mask_stops_when_primaries_finish(params):
    """Helpers at depth+1 must not extend the lockstep wall: the dispatch
    ends the moment every REQUIRED lane parks in DONE, abandoning the
    others mid-search."""
    fens = [FENS[0]] * B
    roots = _roots(fens)
    depth = jnp.asarray([1] + [4] * (B - 1), jnp.int32)
    budget = jnp.full((B,), 200_000, jnp.int32)
    req = np.zeros(B, bool)
    req[0] = True
    seg = 100  # fine-grained segments so the early stop is visible
    full = search_batch_resumable(
        params, roots, depth, budget, max_ply=4, segment_steps=seg,
        narrow=False, tt=tt.make_table(14),
    )
    stopped = search_batch_resumable(
        params, roots, depth, budget, max_ply=4, segment_steps=seg,
        narrow=False, tt=tt.make_table(14), required=req,
    )
    assert bool(np.asarray(stopped["done"])[0])
    assert not np.asarray(stopped["done"])[1:].all()
    assert int(stopped["steps"]) < int(full["steps"])


def test_plan_helpers_hardest_first_round_robin():
    from fishnet_tpu.engine.tpu import TpuEngine

    # 3 primaries in an 8-wide dispatch, K=4: 5 spare rows. Hardest
    # (row 1) gets its first helper first; every primary gets one
    # before any gets two.
    plan = TpuEngine._plan_helpers(3, 8, 4, [10, 100, 1])
    assert plan == [(1, 1), (0, 1), (2, 1), (1, 2), (0, 2)]
    # hardness <= 0 excludes a primary entirely (settled/terminal lanes)
    plan = TpuEngine._plan_helpers(3, 8, 4, [10, 0, 1])
    assert plan == [(0, 1), (2, 1), (0, 2), (2, 2), (0, 3)]
    # per-primary cap k_max-1 even with spare rows left over
    plan = TpuEngine._plan_helpers(1, 8, 3, [5])
    assert plan == [(0, 1), (0, 2)]
    # no helpers when the dispatch is full or K=1
    assert TpuEngine._plan_helpers(8, 8, 4, [1] * 8) == []
    assert TpuEngine._plan_helpers(3, 8, 1, [1, 1, 1]) == []


def _host_engine(helper_lanes):
    """Engine with the device program stubbed out: records every _search
    dispatch so the host-side helper layout is testable without XLA."""
    from fishnet_tpu.engine.tpu import TpuEngine

    engine = TpuEngine(max_depth=2, max_lanes=16, helper_lanes=helper_lanes)
    calls = []

    def fake_search(roots, depth_arr, budget_arr, deadline=None, **kw):
        n = len(depth_arr)
        calls.append({"B": n, **kw})
        return {
            "done": np.ones(n, bool),
            "score": np.full(n, 20, np.int32),
            "move": np.full(n, 8 | (16 << 6), np.int32),  # a2a3
            "pv": np.full((n, 4), -1, np.int32),
            "pv_len": np.zeros(n, np.int32),
            "nodes": np.ones(n, np.int32),
        }

    engine._search = fake_search
    return engine, calls


def _analysis_chunk(n_positions=3, depth=2):
    import time

    from fishnet_tpu.client.ipc import Chunk, WorkPosition
    from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit

    work = AnalysisWork(
        id="helperjb", nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0, depth=depth, multipv=None,
    )
    positions = [
        WorkPosition(
            work=work, position_index=i, url=None, skip=False,
            root_fen=KIWIPETE, moves=[],
        )
        for i in range(n_positions)
    ]
    return Chunk(
        work=work, deadline=time.monotonic() + 120, variant="standard",
        flavor=EngineFlavor.TPU, positions=positions,
    )


def test_engine_k1_dispatches_no_helper_lanes():
    import asyncio

    engine, calls = _host_engine(helper_lanes=1)
    asyncio.run(engine.go_multiple(_analysis_chunk()))
    assert calls, "no dispatches recorded"
    for c in calls:
        assert c.get("order_jitter") is None
        assert c.get("required") is None
        assert not c.get("helper_store", False)


def test_engine_k4_allocates_helpers_to_spare_lanes():
    import asyncio

    engine, calls = _host_engine(helper_lanes=4)
    asyncio.run(engine.go_multiple(_analysis_chunk(n_positions=3)))
    assert calls
    c = calls[0]  # first depth iteration
    assert c["helper_store"]
    jit_arr = np.asarray(c["order_jitter"])
    grp = np.asarray(c["group"])
    req = np.asarray(c["required"])
    n = 3
    # primaries: unjittered, required, grouped to themselves
    assert (jit_arr[:n] == 0).all()
    assert req[:n].all()
    np.testing.assert_array_equal(grp[:n], np.arange(n))
    # helpers: jittered, NOT required, grouped to a primary row
    helper_rows = np.nonzero(jit_arr)[0]
    assert len(helper_rows) > 0, "no helper lanes allocated"
    assert not req[helper_rows].any()
    assert (grp[helper_rows] < n).all()


@pytest.mark.slow
def test_helpers_reduce_steps_to_depth_kiwipete(params):
    """Acceptance (ISSUE): helpers must strictly reduce the cost of
    reaching depth N on kiwipete. Lockstep steps are the platform-honest
    proxy: at EQUAL width every step costs the same wall-clock, so
    steps-to-primary-done ∝ wall-clock-to-depth on any platform, and on
    CPU the count is deterministic."""
    W = 8
    boards = [from_position(Position.from_fen(KIWIPETE))] * W
    roots = stack_boards(boards)
    # depth 3 keeps the test inside the slow tier's per-test budget on
    # XLA:CPU (~3-4 min with the compile); the measured margin is wide
    # (23040 vs 34697 steps, a 34% reduction — docs/depth.md)
    depth = 3
    req = np.zeros(W, bool)
    req[0] = True
    base = search_batch_resumable(
        params, roots, depth, 5_000_000, max_ply=8, narrow=False,
        segment_steps=512, tt=tt.make_table(16), required=req,
    )
    # rows 1..W-1 become jittered helpers of row 0 (the K=W config)
    helped = search_batch_resumable(
        params, roots, depth, 5_000_000, max_ply=8, narrow=False,
        segment_steps=512, tt=tt.make_table(16), required=req,
        order_jitter=jnp.asarray([0] + list(range(1, W)), jnp.int32),
        group=jnp.zeros(W, jnp.int32),
        prefer_deep_store=True, tt_gen=1,
    )
    assert bool(np.asarray(base["done"])[0])
    assert bool(np.asarray(helped["done"])[0])
    s_base, s_helped = int(base["steps"]), int(helped["steps"])
    assert s_helped < s_base, (
        f"helpers did not reduce steps-to-depth: {s_helped} vs {s_base}"
    )
