"""Parity tests for the fused Pallas NNUE kernel (interpret mode on CPU).

The kernel must agree with the XLA evaluation path bit-for-bit-ish
(float32 tolerances) on arbitrary positions, paddings, and both sides to
move. Real-TPU lowering is exercised by the driver's bench/graft runs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops import pallas_nnue
from fishnet_tpu.ops.board import from_position


@pytest.fixture(scope="module")
def params():
    return nnue.init_params(
        jax.random.PRNGKey(3), l1=64, h1=16, h2=32, feature_set="board768"
    )


FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 b - - 0 1",
    "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
    "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
]


def boards_and_stms(fens):
    bs = [from_position(Position.from_fen(f)) for f in fens]
    boards = jnp.stack([b.board for b in bs])
    stms = jnp.stack([b.stm for b in bs])
    return boards, stms


def test_kernel_matches_xla_path(params):
    boards, stms = boards_and_stms(FENS)
    want = nnue.v_evaluate(params, boards, stms)
    got = pallas_nnue.evaluate_batch(params, boards, stms, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=0.05
    )


def test_kernel_handles_padding(params):
    # 5 lanes pad to 8; padding lanes must not disturb real lanes
    boards, stms = boards_and_stms(FENS[:5])
    got5 = pallas_nnue.evaluate_batch(params, boards, stms, interpret=True)
    boards3, stms3 = boards_and_stms(FENS[:3])
    got3 = pallas_nnue.evaluate_batch(params, boards3, stms3, interpret=True)
    np.testing.assert_allclose(np.asarray(got5[:3]), np.asarray(got3), rtol=1e-5)
    assert got5.shape == (5,)


def test_kernel_rejects_halfkav2(params):
    hk = nnue.init_params(jax.random.PRNGKey(0), l1=32, feature_set="halfkav2_hm")
    boards, stms = boards_and_stms(FENS[:1])
    with pytest.raises(ValueError):
        pallas_nnue.evaluate_batch(hk, boards, stms, interpret=True)


def test_batched_forward_env_toggle(params, monkeypatch):
    boards, stms = boards_and_stms(FENS)
    from fishnet_tpu.models.train import batched_forward

    base = batched_forward(params, boards, stms)
    monkeypatch.setenv("FISHNET_TPU_PALLAS", "1")
    # on CPU the non-interpret kernel can't lower; assert routing happens
    # by matching against the interpret-mode kernel result instead
    got = pallas_nnue.evaluate_batch(params, boards, stms, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), rtol=2e-4, atol=0.05)


def test_trainable_wrapper_gradients(params):
    """custom-vjp wrapper: pallas forward, XLA backward — gradients must
    match the pure-XLA path."""
    boards, stms = boards_and_stms(FENS[:3])
    targets = jnp.asarray([50.0, -120.0, 10.0])

    def loss_pallas(p):
        pred = pallas_nnue.evaluate_batch_trainable(p, boards, stms)
        return jnp.mean((pred - targets) ** 2)

    def loss_xla(p):
        pred = nnue.v_evaluate(p, boards, stms)
        return jnp.mean((pred - targets) ** 2)

    g_pallas = jax.grad(loss_pallas)(params)
    g_xla = jax.grad(loss_xla)(params)
    for name in params._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(g_pallas, name)),
            np.asarray(getattr(g_xla, name)),
            # pallas and XLA forwards differ by f32 rounding; that
            # difference enters g = dL/dpred and scales the backward
            rtol=1e-2, atol=2e-3, err_msg=name,
        )
