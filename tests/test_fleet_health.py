"""Self-healing fleet tests (ISSUE 15): fault taxonomy, in-dispatch
retry, probed readmission, hedged dispatch, runtime membership.

All on CPU with the scriptable fake host / PyEngine / FlakyProxy — no
JAX:

- the fault table: connect-phase faults are transient, anything after
  the request hit the wire is a loss, 429 is backpressure;
- a transient fault inside the retry budget never becomes a loss event
  (in-dispatch retry through a FlakyProxy refusal window);
- the retry backoff is bounded by the dispatch deadline — a dead peer
  costs bounded time, not retry_max * max_pause;
- a 429 shed reroutes the sub-chunk to a free member with ZERO loss
  events (satellite bugfix: typed MemberBusy carrying Retry-After);
- probed readmission: a lost member re-enters only through healthz +
  one canary chunk; a failed probe escalates the cooldown but is NOT
  a loss event; cooldown escalation caps at cooldown_max;
- hedged dispatch duplicates the straggler's unfinished positions,
  first answer wins exactly-once, the counters tie out, and results
  are bit-identical with hedging on or off;
- runtime membership: drain completes in-flight work, remove/add cycle
  a member with zero lost or re-searched positions (rolling restart),
  and the /fleet/members HTTP admin surface drives all of it.
"""
import asyncio
import json
import sys
import time

import pytest

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.ipc import (
    Chunk,
    WorkPosition,
    position_fingerprint,
    response_to_wire,
)
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.fakehost import FAKE_CP, FlakyProxy
from fishnet_tpu.engine.pyengine import PyEngine
from fishnet_tpu.fleet import FleetCoordinator, FleetMember
from fishnet_tpu.fleet.faults import (
    FAULT_BUSY,
    FAULT_LOSS,
    FAULT_TRANSIENT,
    MemberBusy,
    MemberFault,
    classify,
)
from fishnet_tpu.fleet.member import make_local_member
from fishnet_tpu.fleet.remote import HttpEngine
from fishnet_tpu.obs.metrics import MetricsRegistry

pytestmark = [pytest.mark.faultinject, pytest.mark.subproc]

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def fake_cmd(script, state_path, hb=0.05, echo=None, extra=()):
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", json.dumps(script),
        "--state", str(state_path),
        "--hb-interval", str(hb),
    ]
    if echo is not None:
        cmd += ["--echo", str(echo)]
    return cmd + list(extra)


def fake_member(name, script, tmp_path, echo=None, extra=()):
    return make_local_member(
        name,
        host_cmd=fake_cmd(script, tmp_path / f"{name}.json",
                          echo=echo, extra=extra),
        logger=Logger(verbose=0),
        hb_interval=0.05,
        hb_timeout=1.0,
        backoff=RandomizedBackoff(max_s=0.05),
    )


def make_chunk(n=4, ttl=30.0, moves=(), depth=1,
               flavor=EngineFlavor.TPU, batch="healthjob"):
    work = AnalysisWork(
        id=batch,
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=depth, multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=list(moves))
        for i in range(n)
    ]
    return Chunk(work=work, deadline=time.monotonic() + ttl,
                 variant="standard", flavor=flavor, positions=positions)


def comparable(res):
    wire = response_to_wire(res)
    return {k: wire[k]
            for k in ("scores", "pvs", "best_move", "depth", "nodes")}


def read_echo(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def make_coord(members, **kw):
    kw.setdefault("logger", Logger(verbose=0))
    kw.setdefault("registry", MetricsRegistry())
    return FleetCoordinator(members, **kw)


async def busy_server(retry_after=0.25):
    """One-trick serve stand-in: every request is answered 429 with a
    Retry-After hint — the admission controller in full shed."""

    async def handle(reader, writer):
        head = await reader.readuntil(b"\r\n\r\n")
        length = 0
        for line in head.decode("latin-1").split("\r\n"):
            if line.lower().startswith("content-length:"):
                length = int(line.split(":", 1)[1])
        if length:
            await reader.readexactly(length)
        body = json.dumps({"error": "shed", "retry_after": retry_after})
        writer.write(
            (
                "HTTP/1.1 429 Too Many Requests\r\n"
                "Content-Type: application/json\r\n"
                f"Retry-After: {max(int(retry_after), 1)}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n\r\n" + body
            ).encode("latin-1")
        )
        await writer.drain()
        writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


# ------------------------------------------------------------ fault table


def test_fault_classification_table():
    """The taxonomy that decides retry-vs-loss: connect-phase transport
    faults are transient (safe to retry — the request never reached the
    peer); anything after the request hit the wire is a loss (the peer
    may be mid-search: a blind retry would double-search); unknown
    exceptions default to loss (fail safe, never spin)."""
    table = [
        (ConnectionRefusedError("refused"), False, FAULT_TRANSIENT),
        (ConnectionResetError("reset"), False, FAULT_TRANSIENT),
        (OSError("no route"), False, FAULT_TRANSIENT),
        (asyncio.TimeoutError(), False, FAULT_TRANSIENT),
        (asyncio.IncompleteReadError(b"", 10), False, FAULT_TRANSIENT),
        # the same faults after the request was written: loss
        (ConnectionResetError("reset"), True, FAULT_LOSS),
        (asyncio.TimeoutError(), True, FAULT_LOSS),
        (OSError("broken pipe"), True, FAULT_LOSS),
        # non-transport failures never retry
        (ValueError("garbage"), False, FAULT_LOSS),
    ]
    for exc, wrote, want in table:
        assert classify(exc, wrote=wrote) == want, (exc, wrote)

    assert MemberFault("x").kind == FAULT_LOSS
    assert not MemberFault("x").retriable
    assert MemberFault("x", kind=FAULT_TRANSIENT).retriable
    busy = MemberBusy("shed", retry_after=2.5)
    assert busy.kind == FAULT_BUSY
    assert busy.retry_after == 2.5
    assert not busy.retriable  # backpressure is rerouted, not redialed
    assert MemberBusy("shed", retry_after=-3.0).retry_after == 0.0
    assert isinstance(busy, EngineError)  # coordinator-visible hierarchy


# ------------------------------------------------------ in-dispatch retry


def test_transient_fault_retried_inside_dispatch():
    """A connect-refused window shorter than the retry budget is
    invisible above the dispatch: the chunk answers normally, the
    engine counts retries, and no EngineError ever surfaces."""
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.serve.server import ServeApp

    async def scenario():
        app = ServeApp(
            EngineSession(PyEngine(max_depth=1),
                          flavor=EngineFlavor.OFFICIAL),
            registry=MetricsRegistry(),
            logger=Logger(verbose=0),
        )
        host, port = await app.start("127.0.0.1", 0)
        proxy = FlakyProxy(host, port)
        phost, pport = await proxy.start()
        engine = HttpEngine(f"http://{phost}:{pport}", retry_max=8)
        try:
            await proxy.set_fault("refuse-for:0.3")
            chunk = make_chunk(n=2, ttl=20.0, depth=1,
                               flavor=EngineFlavor.OFFICIAL)
            responses = await engine.go_multiple(chunk)
            assert [r.position_index for r in responses] == [0, 1]
            assert engine.retries >= 1  # the refusal window was real
        finally:
            await engine.close()
            await proxy.close()
            await app.drain_and_stop()

    asyncio.run(scenario())


def test_retry_backoff_bounded_by_deadline():
    """Against a permanently-refusing endpoint the retry loop must give
    up when the dispatch budget runs out — not after retry_max maximum
    pauses. 50 nominal attempts against a 0.5s budget returns in ~0.5s
    with a loss-kind fault chaining the last transient one."""

    async def scenario():
        engine = HttpEngine("http://127.0.0.1:1", timeout_s=0.5,
                            retry_max=50)
        t0 = time.monotonic()
        with pytest.raises(MemberFault) as exc:
            await engine.go_multiple(make_chunk(n=1, ttl=30.0))
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0  # deadline-bounded, not 50 * max-pause
        assert exc.value.kind == FAULT_LOSS  # escalated past the budget
        assert isinstance(exc.value.__cause__, MemberFault)
        assert exc.value.__cause__.kind == FAULT_TRANSIENT

    asyncio.run(scenario())


# -------------------------------------------------------- 429 backpressure


def test_429_is_typed_backpressure_not_loss(tmp_path):
    """Satellite bugfix: a member shedding with 429 raises MemberBusy
    carrying the Retry-After hint, and the coordinator reroutes the
    sub-chunk to a free member with ZERO loss events — designed
    backpressure must not look like member death."""

    async def scenario():
        server, port = await busy_server(retry_after=0.25)
        busy = FleetMember(
            name="busy",
            engine=HttpEngine(f"http://127.0.0.1:{port}", retry_max=0),
            kind="remote",
        )
        # raw engine surface first: the typed fault and its hint
        with pytest.raises(MemberBusy) as exc:
            await busy.engine.go_multiple(make_chunk(n=1))
        assert exc.value.retry_after == 0.25

        coord = make_coord(
            [busy, fake_member("m1", {"chunks": ["ok", "ok"]}, tmp_path)],
            loss_window=5.0,
        )
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=2))
            assert [r.position_index for r in responses] == [0, 1]
            assert all(r.scores.best().value == FAKE_CP
                       for r in responses)
        finally:
            await coord.close()
            server.close()
            await server.wait_closed()

        assert coord.stats.losses == 0  # backpressure, not death
        assert coord.loss_log == []
        assert coord.stats.busy_reroutes >= 1
        assert busy.consecutive_losses == 0
        assert not busy.probation  # busy members skip the gauntlet

    asyncio.run(scenario())


# --------------------------------------------------- probation / readmission


def test_probation_canary_readmission(tmp_path):
    """The readmission gauntlet: a lost member sits out its cooldown,
    then must pass healthz + one canary chunk before the planner sees
    it again. The canary is synthetic — no queue position ever rides
    probation — and a served sub-chunk resets the flap counter."""

    async def scenario():
        m0 = fake_member("m0", {"chunks": ["die-after:1", "ok", "ok"]},
                         tmp_path)
        m1 = fake_member("m1", {"chunks": ["ok", "ok", "ok"]}, tmp_path)
        coord = make_coord([m0, m1], loss_window=0.05, cooldown_max=10.0)
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=4))
            assert len(responses) == 4
            assert coord.stats.losses == 1
            assert m0.probation and not m0.available()
            assert m0.state() in ("cooldown", "probation")

            await asyncio.sleep(0.1)  # cooldown expires -> probe due
            assert m0.state() == "probation"
            await coord.probe_members()
            assert not m0.probation
            assert m0.available()
            assert m0.state() == "eligible"
            assert coord.stats.probes == 1
            assert coord.stats.canaries_ok == 1
            assert coord.stats.readmissions == 1
            assert m0.canaries_ok == 1
            assert not m0.acked  # the canary left no ledger residue

            # back in rotation: a real chunk lands on it and resets the
            # flap counter
            responses = await coord.go_multiple(
                make_chunk(n=2, batch="healthjob2"))
            assert len(responses) == 2
            assert m0.consecutive_losses == 0
            assert coord.stats.losses == 1  # no new losses
        finally:
            await coord.close()

    asyncio.run(scenario())


def test_failed_probe_escalates_cooldown_not_loss(tmp_path):
    """A permanently-dead member costs probes, never work: the failed
    probe escalates its cooldown (exponentially, toward cooldown_max)
    and counts probe_failures — but is NOT a loss event, because no
    queue position was at risk."""

    async def scenario():
        dead = FleetMember(
            name="dead",
            engine=HttpEngine("http://127.0.0.1:1", retry_max=1,
                              timeout_s=1.0),
            kind="remote",
        )
        coord = make_coord(
            [dead, fake_member("m1", {"chunks": ["ok"]}, tmp_path)],
            loss_window=0.05, cooldown_max=10.0,
        )
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=2))
            assert len(responses) == 2
            assert coord.stats.losses == 1
            losses_before = dead.consecutive_losses

            await asyncio.sleep(0.1)
            await coord.probe_members()
            assert coord.stats.probes == 1
            assert coord.stats.probe_failures == 1
            assert coord.stats.losses == 1  # unchanged: not a loss event
            assert coord.stats.readmissions == 0
            assert dead.probation  # still outside the planner
            assert not dead.available()
            assert dead.consecutive_losses == losses_before + 1
        finally:
            await coord.close()

    asyncio.run(scenario())


def test_cooldown_escalates_exponentially_and_caps():
    """Flap damping: each consecutive loss doubles the cooldown until
    cooldown_max; a flapping member converges to probing at the cap
    instead of thrashing the planner."""

    async def scenario():
        member = FleetMember(name="flappy", engine=PyEngine(max_depth=1))
        coord = make_coord(
            [member, FleetMember(name="ok", engine=PyEngine(max_depth=1))],
            loss_window=0.5, cooldown_max=4.0,
        )
        try:
            seen = []
            for _ in range(5):
                t0 = time.monotonic()
                coord._note_loss(member, "test", [], {})
                seen.append(member.down_until - t0)
            # 0.5, 1, 2, 4, 4 — doubling, then the cap
            for got, want in zip(seen, [0.5, 1.0, 2.0, 4.0, 4.0]):
                assert abs(got - want) < 0.1, seen
            assert member.probation
            assert coord.stats.losses == 5
        finally:
            await coord.close()

    asyncio.run(scenario())


# ------------------------------------------------------------------ hedging


def test_hedged_dispatch_first_answer_wins(tmp_path):
    """A straggling member's unfinished positions are duplicated to the
    free member once deadline slack runs low; the first answer wins
    through the exactly-once ledger, the loser is discarded and
    counted, and the answers are bit-identical to a hedge-off run."""
    echo_fast = tmp_path / "fast.jsonl"

    def members():
        return [
            fake_member("slow", {"chunks": ["ok", "ok"]}, tmp_path,
                        extra=["--latency-ms", "800"]),
            fake_member("fast", {"chunks": ["ok", "ok"]}, tmp_path,
                        echo=echo_fast),
        ]

    async def run(hedge):
        registry = MetricsRegistry()
        coord = make_coord(
            members(), registry=registry, loss_window=5.0,
            hedge=hedge, hedge_slack_ms=3500,
        )
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=2, ttl=4.0))
            assert [r.position_index for r in responses] == [0, 1]
        finally:
            await coord.close()
        return coord, registry, [comparable(r) for r in responses]

    async def scenario():
        hedged, registry, on = await run(hedge=True)
        assert hedged.stats.hedges >= 1
        assert hedged.stats.hedge_wins >= 1
        assert hedged.stats.losses == 0  # the straggler was slow, not dead
        snap = registry.snapshot()
        assert snap["fishnet_fleet_hedges_total"] == hedged.stats.hedges
        assert snap["fishnet_fleet_hedge_wins_total"] == hedged.stats.hedge_wins
        # the fast member served its own sub-chunk AND the hedge copy
        fast_gos = [r for r in read_echo(echo_fast) if r["t"] == "go"]
        assert len(fast_gos) == 2

        echo_fast.unlink()
        plain, _, off = await run(hedge=False)
        assert plain.stats.hedges == 0
        assert on == off  # bit-identical with hedging on or off

    asyncio.run(scenario())


# ------------------------------------------------------- runtime membership


def test_rolling_restart_drain_remove_readd(tmp_path):
    """The docs/fleet.md rolling restart: drain a member mid-chunk (its
    in-flight work finishes, nothing new lands on it), remove it once
    drained, re-add a replacement — zero lost and zero re-searched
    positions across the whole cycle."""
    echos = {n: tmp_path / f"{n}.jsonl" for n in ("m0", "m1", "r0")}

    async def scenario():
        coord = make_coord(
            [
                fake_member("m0", {"chunks": ["ok", "ok"]}, tmp_path,
                            echo=echos["m0"],
                            extra=["--latency-ms", "300"]),
                fake_member("m1", {"chunks": ["ok", "ok", "ok"]},
                            tmp_path, echo=echos["m1"]),
            ],
            loss_window=5.0,
            local_factory=lambda name: fake_member(
                name, {"chunks": ["ok", "ok"]}, tmp_path,
                echo=echos["r0"]),
        )
        try:
            await coord.start()
            # a chunk is in flight on m0 when the drain begins
            first = asyncio.ensure_future(
                coord.go_multiple(make_chunk(n=2, batch="job-a")))
            await asyncio.sleep(0.1)
            out = coord.drain_member("m0")
            assert out["drained"] is False  # still holds in-flight work
            assert coord._member("m0").state() == "draining"
            # draining refuses new work but finishes what it holds
            with pytest.raises(EngineError):
                await coord.remove_member("m0")
            responses = await first
            assert [r.position_index for r in responses] == [0, 1]
            assert coord.drained("m0")

            removed = await coord.remove_member("m0")
            assert removed["name"] == "m0"
            assert [m.name for m in coord.members] == ["m1"]

            # the shrunken fleet still serves
            mid = await coord.go_multiple(make_chunk(n=1, batch="job-b"))
            assert len(mid) == 1

            added = await coord.add_member("local")
            assert added["name"] == "local0"
            assert len(coord.members) == 2
            last = await coord.go_multiple(make_chunk(n=2, batch="job-c"))
            assert [r.position_index for r in last] == [0, 1]
        finally:
            await coord.close()

        assert coord.stats.losses == 0
        assert coord.stats.drains == 1
        assert coord.stats.members_removed == 1
        assert coord.stats.members_added == 1
        # zero re-searched positions: the members collectively received
        # exactly the 5 positions the three chunks submitted
        gos = [g for path in echos.values() if path.exists()
               for g in read_echo(path) if g["t"] == "go"]
        assert sum(g["positions"] for g in gos) == 5
        # and the replacement actually joined the rotation
        assert any(g["positions"] for g in read_echo(echos["r0"])
                   if g["t"] == "go")

    asyncio.run(scenario())


def test_http_admin_surface(tmp_path):
    """GET /fleet/members is the health table; POST add/drain/remove is
    how fleet-ctl (and a rolling restart) drives membership. Non-fleet
    front-ends 404 the path; validation errors come back 400/409."""
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.serve.server import ServeApp

    async def _http(host, port, method, path, obj=None):
        reader, writer = await asyncio.open_connection(host, port)
        body = json.dumps(obj).encode("utf-8") if obj is not None else b""
        head = (
            f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
            f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
        )
        writer.write(head.encode("latin-1") + body)
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        head_raw, _, payload = raw.partition(b"\r\n\r\n")
        status = int(head_raw.decode("latin-1").split("\r\n")[0].split()[1])
        return status, json.loads(payload) if payload else {}

    async def scenario():
        coord = make_coord(
            [FleetMember(name="py0", engine=PyEngine(max_depth=1)),
             FleetMember(name="py1", engine=PyEngine(max_depth=1))],
            loss_window=5.0,
            local_factory=lambda name: FleetMember(
                name=name, engine=PyEngine(max_depth=1)),
        )
        app = ServeApp(
            EngineSession(PyEngine(max_depth=1),
                          flavor=EngineFlavor.OFFICIAL),
            registry=MetricsRegistry(),
            logger=Logger(verbose=0),
            fleet=coord,
        )
        host, port = await app.start("127.0.0.1", 0)
        try:
            status, table = await _http(host, port, "GET", "/fleet/members")
            assert status == 200
            assert [m["name"] for m in table["members"]] == ["py0", "py1"]
            assert table["members_live"] == 2
            assert all(m["state"] == "eligible" for m in table["members"])

            status, row = await _http(
                host, port, "POST", "/fleet/members",
                {"action": "add", "spec": "local"})
            assert status == 200
            assert row["ok"] and row["member"]["name"] == "local0"

            status, out = await _http(
                host, port, "POST", "/fleet/members",
                {"action": "drain", "member": "local0"})
            assert status == 200 and out["drained"] is True

            status, row = await _http(
                host, port, "POST", "/fleet/members",
                {"action": "remove", "member": "local0"})
            assert status == 200 and row["member"]["name"] == "local0"
            status, table = await _http(host, port, "GET", "/fleet/members")
            assert [m["name"] for m in table["members"]] == ["py0", "py1"]

            # validation surfaces as HTTP codes, not connection drops
            status, _ = await _http(
                host, port, "POST", "/fleet/members",
                {"action": "remove", "member": "nope"})
            assert status == 409
            status, _ = await _http(
                host, port, "POST", "/fleet/members", {"action": "wat"})
            assert status == 400
        finally:
            await app.drain_and_stop()
            await coord.close()

        # a plain (non-fleet) front-end does not expose the surface
        app2 = ServeApp(
            EngineSession(PyEngine(max_depth=1),
                          flavor=EngineFlavor.OFFICIAL),
            registry=MetricsRegistry(),
            logger=Logger(verbose=0),
        )
        host2, port2 = await app2.start("127.0.0.1", 0)
        try:
            status, _ = await _http(host2, port2, "GET", "/fleet/members")
            assert status == 404
        finally:
            await app2.drain_and_stop()

    asyncio.run(scenario())
