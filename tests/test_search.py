"""Tests for the lockstep batched alpha-beta search."""
import jax
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.search import MATE, search_batch_jit
from fishnet_tpu.ops import tables as T


@pytest.fixture(scope="module", params=["board768", "halfkav2_hm"])
def params(request):
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set=request.param
    )


B = 16  # all dispatches share one padded lane shape → one compile per
        # (max_ply, tt-presence) across the whole file (and files using
        # the same l1=32 params in the same pytest process)


def run(params, fens, depth, budget=100_000, max_ply=None):
    boards = [from_position(Position.from_fen(f)) for f in fens]
    roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
    out = search_batch_jit(
        params, roots, depth, budget, max_ply=(max_ply or 4)
    )
    return {
        k: (np.asarray(v)[: len(fens)] if np.ndim(v) else np.asarray(v))
        for k, v in out.items() if k != "tt"
    }


def decode(m):
    frm, to, promo = m & 63, (m >> 6) & 63, (m >> 12) & 7
    s = "abcdefgh"[frm & 7] + str((frm >> 3) + 1) + "abcdefgh"[to & 7] + str((to >> 3) + 1)
    if promo:
        s += " nbrq"[promo]
    return s


def test_mate_in_one(params):
    out = run(params, ["6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1"], depth=2)
    assert out["score"][0] == MATE - 1
    assert decode(out["move"][0]) == "e1e8"


def test_mated_root(params):
    # checkmated root: score is -MATE, no move
    out = run(params, ["R5k1/5ppp/8/8/8/8/8/6K1 b - - 0 1"], depth=2)
    assert out["score"][0] == -MATE
    assert out["move"][0] == -1


def test_stalemate_root(params):
    out = run(params, ["7k/5Q2/6K1/8/8/8/8/8 b - - 0 1"], depth=2)
    assert out["score"][0] == 0
    assert out["move"][0] == -1


def test_depth1_matches_host_oracle(params):
    """Depth 1 = one ply of all moves + capture quiescence at the
    children; the host oracle (ops/oracle.py) models exactly that."""
    from fishnet_tpu.ops.oracle import oracle_search

    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    ]
    out = run(params, fens, depth=1)
    for i, fen in enumerate(fens):
        exp = oracle_search(
            params, from_position(Position.from_fen(fen)), 1, 100_000, 4
        )
        assert out["score"][i] == exp["score"], fen
        assert out["nodes"][i] == exp["nodes"], fen


def test_lmr_depth3_matches_host_oracle(params):
    """Depth 3 activates late-move reductions (depth_left >= 3, move
    index >= 3) and their full-depth re-search; the oracle mirrors the
    reduction schedule exactly, so scores AND node counts must agree."""
    from fishnet_tpu.ops.oracle import oracle_search

    fens = [
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    ]
    out = run(params, fens, depth=3, budget=50_000)
    for i, fen in enumerate(fens):
        exp = oracle_search(
            params, from_position(Position.from_fen(fen)), 3, 50_000, 4
        )
        assert out["score"][i] == exp["score"], fen
        assert out["nodes"][i] == exp["nodes"], fen


@pytest.mark.slow
def test_nmp_depth4_matches_host_oracle(params):
    """Depth 4 activates null-move pruning at the root's children
    (depth_left >= 3 at ply >= 1). max_ply=5 is a distinct compile."""
    from fishnet_tpu.ops.oracle import oracle_search

    fens = [
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "6k1/5ppp/8/8/2Q5/8/5PPP/6K1 w - - 0 1",
    ]
    out = run(params, fens, depth=4, budget=200_000, max_ply=5)
    for i, fen in enumerate(fens):
        exp = oracle_search(
            params, from_position(Position.from_fen(fen)), 4, 200_000, 5
        )
        assert out["score"][i] == exp["score"], fen
        assert out["nodes"][i] == exp["nodes"], fen


@pytest.mark.slow
def test_pruning_reduces_nodes(params):
    """FISHNET_TPU_NO_PRUNING=1 must search MORE nodes than the default
    pruned search at depth 4 (the whole point of NMP+LMR). Subprocess per
    mode: the flag is read at import."""
    import json
    import os
    import subprocess
    import sys

    if not nnue.is_board768(params):
        pytest.skip("one feature set is enough")
    prog = (
        "import sys, json; sys.path.insert(0, '.')\n"
        "import tools.force_cpu\n"
        "import numpy as np, jax\n"
        "from fishnet_tpu.chess import Position\n"
        "from fishnet_tpu.models import nnue\n"
        "from fishnet_tpu.ops.board import from_position, stack_boards\n"
        "from fishnet_tpu.ops.search import search_batch_jit\n"
        "p = nnue.init_params(jax.random.PRNGKey(0), l1=32, h1=8, h2=8,"
        " feature_set='board768')\n"
        "b = [from_position(Position.from_fen("
        "'r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3'))]\n"
        "roots = stack_boards(b * 8)\n"
        "out = search_batch_jit(p, roots, 4, 500000, max_ply=5)\n"
        "print(json.dumps({'nodes': int(np.asarray(out['nodes'])[0]),"
        " 'score': int(np.asarray(out['score'])[0])}))\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {}
    for flag in ("0", "1"):
        env = dict(os.environ)
        env["FISHNET_TPU_NO_PRUNING"] = flag
        r = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            cwd=repo, env=env, timeout=900,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        results[flag] = json.loads(r.stdout.splitlines()[-1])
    assert results["0"]["nodes"] < results["1"]["nodes"], results


def test_pv_is_legal_line(params):
    fens = [
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    ]
    out = run(params, fens, depth=3)
    for i, fen in enumerate(fens):
        pos = Position.from_fen(fen)
        n = int(out["pv_len"][i])
        assert n >= 1
        for j in range(n):
            uci = decode(out["pv"][i][j])
            pos = pos.push_uci(uci)  # raises if illegal


def test_mate_in_two(params):
    # classic mate in 2: 1.Qf7+?? no — use a known forced mate-in-2
    # "k7/8/2K5/8/8/8/8/7Q w": 1.Qh8? stalemate risk... use rook staircase:
    out = run(params, ["k7/8/1K6/8/8/8/8/7R w - - 0 1"], depth=4,
              budget=500_000, max_ply=5)
    # Rh8# is immediate mate in 1 actually (a8 king, b6 K guards a7/b7/b8)
    assert out["score"][0] == MATE - 1
    assert decode(out["move"][0]) == "h1h8"


def test_node_budget_respected(params):
    out = run(
        params,
        ["rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"],
        depth=4,
        budget=500,
        max_ply=5,
    )
    # budget degrades deep nodes to leaf evals; total visits stay bounded
    assert out["nodes"][0] <= 500 + 250


def test_batch_independence(params):
    # searching two positions together must give the same result as alone
    fens = [
        "6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    ]
    together = run(params, fens, depth=2)
    alone0 = run(params, [fens[0]], depth=2)
    alone1 = run(params, [fens[1]], depth=2)
    assert together["score"][0] == alone0["score"][0]
    assert together["score"][1] == alone1["score"][0]
    assert together["move"][0] == alone0["move"][0]
    assert together["move"][1] == alone1["move"][0]


def test_resumable_matches_oneshot(params):
    # segmented dispatch (tiny segments → many host round-trips) must be
    # bit-identical to the single while_loop program
    from fishnet_tpu.ops.search import search_batch_resumable

    fens = [
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    ]
    boards = [from_position(Position.from_fen(f)) for f in fens]
    roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
    one = {k: np.asarray(v) for k, v in search_batch_jit(
        params, roots, 3, 5_000, max_ply=4).items() if k != "tt"}
    seg = {k: np.asarray(v) for k, v in search_batch_resumable(
        params, roots, 3, 5_000, max_ply=4, segment_steps=97).items()
        if k != "tt"}
    for k in ("score", "move", "nodes", "pv_len"):
        assert (one[k] == seg[k]).all(), k
    assert (one["pv"] == seg["pv"]).all()
    assert seg["done"].all()


@pytest.mark.slow
def test_select_updates_mode_bit_identical(params):
    """FISHNET_TPU_SELECT_UPDATES=1 (one-hot selects instead of dynamic
    row scatters — the docs/tpu-hang.md device-fault candidate fix) must
    produce bit-identical results. Runs in a subprocess because the flag
    is read at import."""
    import json
    import os
    import subprocess
    import sys

    if not nnue.is_board768(params):
        pytest.skip("one feature set is enough")
    prog = (
        "import sys, json; sys.path.insert(0, '.')\n"
        "import tools.force_cpu\n"
        "import numpy as np, jax\n"
        "from fishnet_tpu.chess import Position\n"
        "from fishnet_tpu.models import nnue\n"
        "from fishnet_tpu.ops.board import from_position, stack_boards\n"
        "from fishnet_tpu.ops.search import search_batch_jit\n"
        "p = nnue.init_params(jax.random.PRNGKey(0), l1=32, h1=8, h2=8,"
        " feature_set='board768')\n"
        "b = [from_position(Position.from_fen("
        "'r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1'))]\n"
        "roots = stack_boards(b * 8)\n"
        "out = search_batch_jit(p, roots, 2, 20000, max_ply=4)\n"
        "print(json.dumps({k: np.asarray(v).tolist() for k, v in out.items()"
        " if k in ('score', 'move', 'nodes', 'pv_len')}))\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = []
    for flag in ("0", "1"):
        env = dict(os.environ)
        env["FISHNET_TPU_SELECT_UPDATES"] = flag
        r = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            cwd=repo, env=env, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        results.append(json.loads(r.stdout.splitlines()[-1]))
    assert results[0] == results[1]


def test_resumable_deadline_stops_early(params):
    # an already-passed deadline stops after one segment; unfinished lanes
    # report done=False so callers ignore their scores
    import time

    from fishnet_tpu.ops.search import search_batch_resumable

    roots = stack_boards(
        [from_position(Position.from_fen(
            "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1"))]
        * B
    )
    out = search_batch_resumable(
        params, roots, 4, 500_000, max_ply=5, segment_steps=50,
        deadline=time.monotonic() - 1.0,
    )
    assert int(out["steps"]) <= 100  # stopped after the first segment
    assert not bool(np.asarray(out["done"])[0])


@pytest.mark.slow
def test_narrowing_matches_unnarrowed(params):
    """Lane narrowing (search_batch_resumable narrow=True) must be
    invisible in the results: retiring finished lanes into half-width
    programs relocates lanes but never changes any lane's search. A
    B=256 batch whose lanes finish in strongly uneven cohorts (tiny
    endgames vs a dense middlegame) with tiny segments forces REPEATED
    narrows (256 → 128 → 64), covering the twice-remapped `orig` /
    invalid-pad bookkeeping, not just a single halving."""
    if not nnue.is_board768(params):
        pytest.skip("one feature set is enough")
    from fishnet_tpu.ops.search import search_batch_resumable

    fens = [
        "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",  # tiny tree: finishes early
        "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    ]
    boards = [from_position(Position.from_fen(f)) for f in fens]
    roots = stack_boards([boards[i % len(boards)] for i in range(256)])
    outs = {}
    for narrow in (False, True):
        out = search_batch_resumable(
            params, roots, 2, 20_000, max_ply=4, segment_steps=48,
            narrow=narrow,
        )
        out.pop("tt")
        outs[narrow] = {k: np.asarray(v) for k, v in out.items()}
    for k in ("score", "move", "nodes", "pv_len", "done"):
        assert (outs[False][k] == outs[True][k]).all(), k
    assert (outs[False]["pv"] == outs[True]["pv"]).all()
    assert outs[True]["done"].all()
