"""Trace-core tests: ring recorder, clock sync, cross-process merge,
and the SyncStats <-> trace_report cross-validation contract.

Everything here runs without JAX — obs/trace.py is pure stdlib and the
cross-process tests drive the supervisor against the scriptable fake
host (fishnet_tpu/engine/fakehost.py), including its --trace-skew
clock-sync fault injection.
"""
import asyncio
import json
import sys
import time

import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.supervisor import SupervisedEngine
from fishnet_tpu.obs import trace
from fishnet_tpu.utils.syncstats import SyncStats
from tools import trace_report

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Tracing state is a module global; never leak it across tests."""
    trace.uninstall()
    yield
    trace.uninstall()


# ------------------------------------------------------------- recorder


def test_ring_eviction_keeps_newest():
    rec = trace.TraceRecorder(capacity=32, process_name="t")
    for i in range(100):
        rec.instant(f"ev{i}")
    evs = rec.snapshot()
    assert len(evs) == 32
    # the ring holds the *last* window: oldest events fell off the back
    assert [e["name"] for e in evs] == [f"ev{i}" for i in range(68, 100)]
    assert rec.emitted == 100


def test_capacity_floor():
    rec = trace.TraceRecorder(capacity=1)
    assert rec.capacity == 16


def test_span_nesting_and_exception_safety():
    rec = trace.install(trace.TraceRecorder(capacity=256,
                                            process_name="t"))
    with rec.span("outer", "test", k=1):
        with rec.span("inner", "test"):
            pass
        with pytest.raises(ValueError):
            with rec.span("failing", "test"):
                raise ValueError("boom")
    evs = rec.snapshot()
    by_name = {e["name"]: e for e in evs}
    # inner closes before outer (emitted on exit), and the failing span
    # still landed — annotated, with the exception propagated above
    assert [e["name"] for e in evs] == ["inner", "failing", "outer"]
    assert by_name["failing"]["args"]["error"] == "ValueError"
    assert by_name["outer"]["args"] == {"k": 1}
    # nesting is consistent: outer's window contains inner's
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0


def test_tracing_off_is_free():
    assert trace.RECORDER is None
    # the module helpers are no-ops returning the SHARED null span — no
    # per-call allocation on the hot path
    s1 = trace.span("anything", "x", a=1)
    s2 = trace.span("else")
    assert s1 is s2 is trace.NULL_SPAN
    with s1:
        pass
    trace.instant("nothing")
    trace.counter("nothing", 1.0)


def test_drain_and_absorb_with_offset():
    child = trace.TraceRecorder(capacity=64, pid=4242)
    child.complete("work", ts_us=1000.0, dur_us=500.0)
    parent = trace.TraceRecorder(capacity=64, pid=1)
    batch = child.drain()
    assert len(batch) == 1
    assert child.snapshot() == []  # drain empties the ring exactly once
    n = parent.absorb(batch, offset_us=1e6)
    assert n == 1
    ev = parent.snapshot()[0]
    assert ev["ts"] == pytest.approx(1000.0 + 1e6)
    assert ev["pid"] == 4242  # provenance survives the merge
    # malformed foreign events are skipped, not crashed on
    assert parent.absorb([{"no": "ph"}, "junk", None]) == 0


def test_dump_is_valid_chrome_trace(tmp_path):
    rec = trace.TraceRecorder(capacity=64, process_name="proc-a")
    rec.set_thread_name("main")
    with rec.span("phase", "test", detail="x"):
        time.sleep(0.001)
    rec.instant("marker", "test")
    rec.counter("depth", 3)
    path = rec.dump(str(tmp_path / "trace.json"))
    obj = json.loads((tmp_path / "trace.json").read_text())
    assert path == str(tmp_path / "trace.json")
    assert obj["displayTimeUnit"] == "ms"
    evs = obj["traceEvents"]
    assert all("ph" in e for e in evs)
    meta = [e for e in evs if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in meta}
    assert any(e["args"]["name"] == "proc-a" for e in meta)
    data = [e for e in evs if e["ph"] != "M"]
    assert {e["ph"] for e in data} == {"X", "i", "C"}
    # non-meta events are time-sorted for viewers that care
    ts = [e["ts"] for e in data]
    assert ts == sorted(ts)
    # and trace_report loads it as-is
    assert len(trace_report.load_events(str(path))) == len(evs)


def test_flight_dump_names_do_not_collide(tmp_path):
    rec = trace.TraceRecorder(capacity=64)
    rec.instant("x")
    p1 = rec.flight_dump(str(tmp_path), "child death!")
    p2 = rec.flight_dump(str(tmp_path), "child death!")
    assert p1 != p2
    assert "child-death-" in p1  # reason sanitized into the filename
    for p in (p1, p2):
        json.loads(open(p).read())


def test_clock_sync_takes_minimum():
    cs = trace.ClockSync()
    assert cs.sample(10.0, 12.0) == pytest.approx(2e6)
    # a slower round-trip must not loosen the estimate
    assert cs.sample(20.0, 23.0) == pytest.approx(2e6)
    # a tighter one improves it
    assert cs.sample(30.0, 31.5) == pytest.approx(1.5e6)
    assert cs.samples == 3


# ------------------------------------- SyncStats cross-validation (1%)


def test_syncstats_segments_crosscheck_within_1pct():
    """The acceptance contract: per-segment device/host totals derived
    from the trace's child spans agree with the SyncStats snapshots the
    spans were rendered from, within trace_report's 1% tolerance."""
    rec = trace.install(trace.TraceRecorder(capacity=4096,
                                            process_name="t"))
    stats = SyncStats()
    import numpy as np

    for _ in range(5):
        for _ in range(3):
            stats.fetch(np.arange(100), label="test")
        time.sleep(0.002)
        snap = stats.boundary()
        assert snap["transfers"] == 3
    report = trace_report.summarize(rec.export()["traceEvents"])
    assert report["segments"]["count"] == 5
    assert trace_report.crosscheck(report, tolerance=0.01) == []
    # fetch spans are on the timeline too
    assert report["phases"]["fetch"]["count"] == 15
    # segment windows are contiguous by construction (boundary() reuses
    # one clock reading to close a window and open the next), so any
    # gaps that survive float rounding are negligible
    assert report["boundary_gaps"]["max_ms"] < 1.0


def test_boundary_gap_histogram_buckets():
    rec = trace.TraceRecorder(capacity=256)
    # four segments on one track with known start-to-start gaps:
    # 200us, 3ms, 100ms after the preceding segment's 1ms window
    starts_us = [0.0, 1200.0, 5200.0, 106200.0]
    for ts in starts_us:
        rec.complete("segment", ts, 1000.0, cat="sync", tid=7)
    report = trace_report.summarize(rec.snapshot())
    gaps = report["boundary_gaps"]
    assert gaps["count"] == 3
    assert gaps["max_ms"] == pytest.approx(100.0)
    by_bucket = dict(zip(
        [*gaps["buckets_ms"], "inf"], gaps["histogram"]))
    assert by_bucket[0.25] == 1   # 0.2ms gap
    assert by_bucket[5.0] == 1    # 3ms gap
    assert by_bucket[250.0] == 1  # 100ms gap


def test_trace_report_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text('{"notATrace": true}')
    with pytest.raises(ValueError):
        trace_report.load_events(str(bad))
    assert trace_report.main([str(bad)]) == 2


def _gap_dump(path, n_seg, seg_us, gap_us, extra=None):
    evs = []
    ts = 0.0
    for _ in range(n_seg):
        evs.append({"ph": "X", "name": "segment", "ts": ts, "dur": seg_us,
                    "pid": 1, "tid": 1, "args": {}})
        ts += seg_us + gap_us
    if extra:
        evs.append(extra)
    doc = {"traceEvents": evs,
           "buildInfo": {"git_sha": "abc", "backend": "cpu"}}
    path.write_text(json.dumps(doc), encoding="utf-8")


def test_trace_report_compare(tmp_path, capsys):
    """--compare A B: the boundary-gap shift and per-phase share
    movement between two dumps, each labeled with its buildInfo."""
    a, b = tmp_path / "A.json", tmp_path / "B.json"
    _gap_dump(a, 4, 1000.0, 200.0)
    # candidate: 3x the boundary gap plus a phase A never had
    _gap_dump(b, 4, 1000.0, 600.0,
              extra={"ph": "X", "name": "warmup", "ts": 0.0,
                     "dur": 2000.0, "pid": 1, "tid": 2})
    ra = trace_report.summarize(trace_report.load_events(str(a)))
    rb = trace_report.summarize(trace_report.load_events(str(b)))
    cmp = trace_report.compare(ra, rb)
    gaps = cmp["boundary_gaps"]
    assert gaps["a_mean_ms"] == pytest.approx(0.2)
    assert gaps["b_mean_ms"] == pytest.approx(0.6)
    assert gaps["mean_delta_ms"] == pytest.approx(0.4)
    assert cmp["phases"]["warmup"]["ratio"] is None  # new phase
    assert cmp["phases"]["segment"]["share_delta"] < 0  # diluted

    assert trace_report.main(["--compare", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "boundary gaps" in out
    assert "git_sha=abc" in out  # both sides' build stamps render


# ------------------------------------------- cross-process (fake host)


def fake_cmd(script, extra=(), hb_interval=0.05):
    return [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script",
        script if isinstance(script, str) else json.dumps(script),
        "--hb-interval", str(hb_interval),
        *extra,
    ]


def make_chunk(ttl=30.0, n_positions=2, depth=1):
    work = AnalysisWork(
        id="trjob001",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=[])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + ttl,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


def make_supervisor(script, extra=(), **kw):
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 0.6)
    kw.setdefault("deadline_margin", 0.15)
    kw.setdefault("logger", Logger(verbose=0))
    return SupervisedEngine(fake_cmd(script, extra=extra), **kw)


@pytest.mark.faultinject
@pytest.mark.subproc
def test_skewed_child_clock_lands_on_parent_timeline(tmp_path, monkeypatch):
    """fakehost --trace-skew 123 reports a monotonic clock 123 s behind
    the real one in its mono fields AND stamps its streamed trace events
    on that same skewed clock. ClockSync must therefore estimate a
    ~+123 s offset and absorb() must land `fake.search` within the
    supervisor's real dispatch window — not two minutes in the past."""
    skew = 123.0
    monkeypatch.setenv("FISHNET_TPU_TRACE_DIR", str(tmp_path))

    async def main():
        sup = make_supervisor({"chunks": ["ok"]},
                              extra=["--trace-skew", str(skew)])
        try:
            t0_us = trace.now_us()
            await sup.go_multiple(make_chunk())
            t1_us = trace.now_us()
            rec = trace.RECORDER
            assert rec is not None  # supervisor installed it from env
            assert sup._clock.offset_us == pytest.approx(
                skew * 1e6, abs=5e6)
            fake = [e for e in rec.snapshot()
                    if e.get("name") == "fake.search"]
            assert fake, "child trace frame never absorbed"
            for ev in fake:
                # on the parent timeline, inside the dispatch window
                # (generous slack: offset error is bounded by pipe
                # latency, microseconds — seconds here catch only the
                # catastrophic un-shifted case, which would be off by
                # the full 123 s)
                assert t0_us - 5e6 <= ev["ts"] <= t1_us + 5e6
        finally:
            await sup.close()

    asyncio.run(main())


@pytest.mark.faultinject
@pytest.mark.subproc
def test_child_death_flight_dump(tmp_path, monkeypatch):
    """A crashed child must leave a loadable merged flight dump: the
    supervisor's recovery ladder writes trace-child-death-*.json into
    FISHNET_TPU_TRACE_DIR, and trace_report parses it."""
    monkeypatch.setenv("FISHNET_TPU_TRACE_DIR", str(tmp_path))

    async def main():
        sup = make_supervisor({"chunks": ["crash:9", "ok"]})
        try:
            # the recovery ladder may replay/quarantine its way to a
            # result or surface the failure — either way the child died
            # and the flight recorder must have fired
            try:
                await sup.go_multiple(make_chunk(ttl=10.0))
            except EngineError:
                pass
            assert sup.stats.deaths >= 1
        finally:
            await sup.close()

    asyncio.run(main())
    dumps = sorted(tmp_path.glob("trace-child-death-*.json"))
    assert dumps, "no flight dump written on child death"
    # every dump parses; the supervisor's ladder markers are on the
    # timeline of each, and — because the ring persists across dumps and
    # the ladder re-dispatches after the first death — the dispatch span
    # (closed with its error annotation) appears in the union
    names = set()
    for dump in dumps:
        events = trace_report.load_events(str(dump))
        report = trace_report.summarize(events)
        assert report["events"] == len(events)
        names |= {e.get("name") for e in events}
    assert "flight-dump" in names
    assert "spawn" in names
    assert "supervisor.dispatch" in names


@pytest.mark.faultinject
@pytest.mark.subproc
def test_tracing_off_no_dump_no_recorder(tmp_path, monkeypatch):
    """Default path: FISHNET_TPU_TRACE_DIR unset — no recorder is
    installed, a crash writes nothing, and the run still recovers."""
    monkeypatch.delenv("FISHNET_TPU_TRACE_DIR", raising=False)

    async def main():
        sup = make_supervisor({"chunks": ["crash:9", "ok"]})
        try:
            try:
                await sup.go_multiple(make_chunk(ttl=10.0))
            except EngineError:
                pass
            assert sup.stats.deaths >= 1
            assert trace.RECORDER is None
        finally:
            await sup.close()

    asyncio.run(main())
    assert list(tmp_path.glob("trace-*.json")) == []
