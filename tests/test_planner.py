"""Golden tests for the batch planner — the richest client logic
(chunking/overlap/skip edge cases, reference: src/queue.rs:548-700)."""
import pytest

from fishnet_tpu.client.planner import (
    SKIP,
    AllSkipped,
    CompletedBatch,
    IncomingBatch,
    IncomingError,
    PendingBatch,
)
from fishnet_tpu.client.wire import (
    AcquireResponseBody,
    EngineFlavor,
    MAX_CHUNK_POSITIONS,
)
from fishnet_tpu.client.ipc import Matrix, PositionResponse
from fishnet_tpu.client.wire import Score

ENDPOINT = "https://lichess.org/fishnet"


def analysis_body(moves, skip=(), variant="standard", multipv=None):
    return AcquireResponseBody.from_json({
        "work": {
            "type": "analysis",
            "id": "job1",
            "nodes": {"sf16": 1500000, "classical": 4050000},
            "timeout": 7000,
            **({"multipv": multipv} if multipv else {}),
        },
        "game_id": "abcdefgh",
        "position": "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "variant": variant,
        "moves": " ".join(moves),
        "skipPositions": list(skip),
    })


def move_body(moves, level=5):
    return AcquireResponseBody.from_json({
        "work": {"type": "move", "id": "mv1", "level": level},
        "game_id": "abcdefgh",
        "position": "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "variant": "standard",
        "moves": " ".join(moves),
    })


GAME_12 = "e2e4 c7c5 g1f3 d7d6 d2d4 c5d4 f3d4 g8f6 b1c3 a7a6 f1e2".split()


def test_backwards_chunking_with_overlap():
    batch = IncomingBatch.from_acquired(ENDPOINT, analysis_body(GAME_12))
    # 11 moves → 12 positions, reversed, tiled in groups of 5 real positions
    assert batch.flavor is EngineFlavor.OFFICIAL
    chunks = batch.chunks
    assert len(chunks) == 3
    idx = [[p.position_index for p in c.positions] for c in chunks]
    # first chunk starts at the last ply, no overlap available
    assert idx[0] == [11, 10, 9, 8, 7]
    # later chunks carry one discarded overlap position (None) up front
    assert idx[1] == [None, 6, 5, 4, 3, 2]
    assert idx[2] == [None, 1, 0]
    # overlap of chunk 2 replays the position before (in analysis order)
    assert len(chunks[1].positions[0].moves) == 7  # same moves as index 7
    for c in chunks:
        assert len(c.positions) <= MAX_CHUNK_POSITIONS


def test_moves_reencoded_chess960_style():
    # standard-notation castling e1g1 must re-encode as king-takes-rook e1h1
    moves = "e2e4 e7e5 g1f3 b8c6 f1c4 g8f6 e1g1".split()
    batch = IncomingBatch.from_acquired(ENDPOINT, analysis_body(moves))
    deepest = batch.chunks[0].positions[0]
    assert deepest.moves[-1] == "e1h1"


def test_skip_positions():
    batch = IncomingBatch.from_acquired(
        ENDPOINT, analysis_body(GAME_12, skip=[11, 10, 3])
    )
    all_idx = [p.position_index for c in batch.chunks for p in c.positions]
    assert 11 not in all_idx and 10 not in all_idx and 3 not in all_idx
    # a skipped predecessor forces the overlap into the chunk
    # (prev.skip || empty → push prev; reference: src/queue.rs:663-667)
    assert all_idx.count(None) >= 1


def test_all_skipped_completes_immediately():
    with pytest.raises(AllSkipped) as exc:
        IncomingBatch.from_acquired(
            ENDPOINT, analysis_body(["e2e4"], skip=[0, 1])
        )
    completed = exc.value.completed
    assert completed.positions == [SKIP, SKIP]
    parts = completed.into_analysis()
    assert parts == [{"skipped": True}, {"skipped": True}]


def test_move_job_single_chunk():
    batch = IncomingBatch.from_acquired(ENDPOINT, move_body(GAME_12))
    assert batch.flavor is EngineFlavor.MULTI_VARIANT  # moves never Official
    assert len(batch.chunks) == 1
    (pos,) = batch.chunks[0].positions
    assert pos.position_index == 0
    assert pos.moves == GAME_12


def test_variant_flavor():
    body = analysis_body(["e2e4"], variant="kingOfTheHill")
    batch = IncomingBatch.from_acquired(ENDPOINT, body)
    assert batch.flavor is EngineFlavor.MULTI_VARIANT


def test_tpu_flavor_routing():
    batch = IncomingBatch.from_acquired(
        ENDPOINT, analysis_body(GAME_12), tpu_variants={"standard"}
    )
    assert batch.flavor is EngineFlavor.TPU
    # move jobs stay on the subprocess engine unless tpu_moves is set
    mv = IncomingBatch.from_acquired(
        ENDPOINT, move_body(GAME_12), tpu_variants={"standard"}
    )
    assert mv.flavor is EngineFlavor.MULTI_VARIANT
    mv2 = IncomingBatch.from_acquired(
        ENDPOINT, move_body(GAME_12), tpu_variants={"standard"}, tpu_moves=True
    )
    assert mv2.flavor is EngineFlavor.TPU


def test_illegal_move_rejected():
    with pytest.raises(IncomingError):
        IncomingBatch.from_acquired(ENDPOINT, analysis_body(["e2e5"]))


def test_invalid_fen_rejected():
    body = analysis_body([])
    body.position = "not a fen"
    with pytest.raises(IncomingError):
        IncomingBatch.from_acquired(ENDPOINT, body)


def _response(work, index, nodes=1000):
    scores = Matrix()
    scores.set(1, 12, Score.cp(17))
    pvs = Matrix()
    pvs.set(1, 12, ["e2e4"])
    return PositionResponse(
        work=work, position_index=index, url=None, scores=scores, pvs=pvs,
        best_move="e2e4", depth=12, nodes=nodes, time_s=0.5,
    )


def test_progress_report_first_part_none():
    batch = IncomingBatch.from_acquired(ENDPOINT, analysis_body(["e2e4", "e7e5"]))
    pending = PendingBatch(
        work=batch.work, url=batch.url, flavor=batch.flavor,
        variant=batch.variant, positions=[None, None, None],
    )
    pending.positions[0] = _response(batch.work, 0)
    pending.positions[1] = _response(batch.work, 1)
    report = pending.progress_report()
    # lila quirk: first part must be None even though it is present
    assert report[0] is None
    assert report[1] is not None
    assert report[2] is None
    assert pending.try_into_completed() is None
    pending.positions[2] = _response(batch.work, 2)
    completed = pending.try_into_completed()
    assert completed is not None
    assert len(completed.into_analysis()) == 3


def test_node_budget_overlap_scaling():
    body = analysis_body(["e2e4"])
    # 6/7 scaling pays for the overlap position (reference: src/api.rs:220-233)
    assert body.work.nodes.get(EngineFlavor.OFFICIAL.eval_flavor()) == 1500000 * 6 // 7
    assert body.work.nodes.get(EngineFlavor.MULTI_VARIANT.eval_flavor()) == 4050000 * 6 // 7


def test_nps_accounting():
    completed = CompletedBatch(
        work=None, url=None, flavor=EngineFlavor.OFFICIAL, variant="standard",
        positions=[], total_nodes=3_000_000, total_cpu_time=2.0,
    )
    assert completed.nps() == 1_500_000
