"""Round-trip and parity tests for the Stockfish .nnue importer.

No real .nnue files exist in this environment (the reference's engine
submodules are empty mount points), so the parser is validated against
its own writer: quantized arrays → file bytes → parsed net, raw and
LEB128-compressed, plus jax-vs-numpy forward parity.
"""
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue_import as ni
from fishnet_tpu.ops.board import from_position

L1 = 64  # small for test speed; real nets use 1024-3072
RNG = np.random.default_rng(7)


def synthetic_quantized():
    nf = ni.NUM_FEATURES
    return {
        "ft_b": RNG.integers(-500, 500, L1).astype(np.int16),
        "ft_w": RNG.integers(-127, 128, (nf, L1)).astype(np.int16),
        "psqt": RNG.integers(-2000, 2000, (nf, 8)).astype(np.int32),
        "fc0_b": RNG.integers(-8000, 8000, (8, ni.FC0_OUT)).astype(np.int32),
        "fc0_w": RNG.integers(-127, 128, (8, ni.FC0_OUT, L1)).astype(np.int8),
        "fc1_b": RNG.integers(-8000, 8000, (8, ni.FC1_OUT)).astype(np.int32),
        "fc1_w": RNG.integers(-127, 128, (8, ni.FC1_OUT, ni.FC1_IN)).astype(np.int8),
        "fc2_b": RNG.integers(-8000, 8000, (8, 1)).astype(np.int32),
        "fc2_w": RNG.integers(-127, 128, (8, 1, ni.FC1_OUT)).astype(np.int8),
        "description": b"test net",
    }


@pytest.fixture(scope="module")
def quantized():
    return synthetic_quantized()


def test_roundtrip_raw(tmp_path, quantized):
    path = tmp_path / "test.nnue"
    ni.write_nnue(path, quantized)
    net = ni.load_nnue(path)  # L1 inferred from file size
    assert net.l1 == L1
    assert net.description == b"test net"
    np.testing.assert_allclose(net.ft_w, quantized["ft_w"] / ni.QA, atol=1e-6)
    np.testing.assert_allclose(net.ft_b, quantized["ft_b"] / ni.QA, atol=1e-6)
    np.testing.assert_allclose(
        net.fc0_w[3], quantized["fc0_w"][3] / ni.QB, atol=1e-6
    )
    np.testing.assert_allclose(
        net.fc2_b[0],
        quantized["fc2_b"][0] / (ni.NNUE2SCORE * ni.OUTPUT_SCALE),
        atol=1e-9,
    )
    np.testing.assert_allclose(
        net.fc2_w[0],
        quantized["fc2_w"][0] / (ni.NNUE2SCORE * ni.OUTPUT_SCALE / ni.QA),
        atol=1e-9,
    )


def test_roundtrip_leb128(tmp_path, quantized):
    raw = tmp_path / "raw.nnue"
    comp = tmp_path / "comp.nnue"
    ni.write_nnue(raw, quantized)
    ni.write_nnue(comp, quantized, compress_ft=True)
    assert comp.stat().st_size != raw.stat().st_size
    a = ni.load_nnue(raw)
    b = ni.load_nnue(comp, l1=L1)  # compressed: size inference unavailable
    np.testing.assert_array_equal(a.ft_w, b.ft_w)
    np.testing.assert_array_equal(a.fc1_w, b.fc1_w)


def test_leb128_codec_edges():
    vals = np.array([0, 1, -1, 63, 64, -64, -65, 127, -128, 32767, -32768])
    enc = ni._leb128_encode(vals)
    dec, used = ni._leb128_decode(memoryview(enc), len(vals))
    assert used == len(enc)
    np.testing.assert_array_equal(dec, vals)


def test_truncated_and_trailing_rejected(tmp_path, quantized):
    path = tmp_path / "test.nnue"
    ni.write_nnue(path, quantized)
    data = path.read_bytes()
    bad = tmp_path / "bad.nnue"
    bad.write_bytes(data[:-100])
    with pytest.raises(ni.UnsupportedNnueFormat):
        ni.load_nnue(bad)
    bad.write_bytes(data + b"\x00" * 8)
    with pytest.raises(ni.UnsupportedNnueFormat):
        ni.load_nnue(bad)


def test_forward_parity_jax_numpy(tmp_path, quantized):
    import jax

    path = tmp_path / "test.nnue"
    ni.write_nnue(path, quantized)
    net = ni.load_nnue(path)
    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 b - - 0 1",
    ]
    for fen in fens:
        pos = Position.from_fen(fen)
        b = from_position(pos)
        got = float(jax.jit(ni.evaluate_sf)(net, b.board, b.stm))
        want = ni.evaluate_sf_reference(net, np.asarray(b.board), int(b.stm))
        assert got == pytest.approx(want, rel=1e-4, abs=0.5), fen


def test_search_with_sf_net(tmp_path, quantized):
    """A parsed Stockfish net drives the batched search's compat path."""
    import jax.numpy as jnp

    from fishnet_tpu.ops.board import stack_boards
    from fishnet_tpu.ops.search import MATE, search_batch_jit

    path = tmp_path / "test.nnue"
    ni.write_nnue(path, quantized)
    net = ni.load_nnue(path).as_device()
    roots = stack_boards(
        [from_position(Position.from_fen("6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1"))]
    )
    out = search_batch_jit(net, roots, 2, 10_000, max_ply=3)
    assert int(out["score"][0]) == MATE - 1  # finds mate with any eval


def test_truncated_leb128_stream_rejected(tmp_path, quantized):
    comp = tmp_path / "comp.nnue"
    ni.write_nnue(comp, quantized, compress_ft=True)
    data = comp.read_bytes()
    bad = tmp_path / "bad.nnue"
    bad.write_bytes(data[: len(data) // 2])
    with pytest.raises(ni.UnsupportedNnueFormat):
        ni.load_nnue(bad, l1=L1)


def test_compressed_without_l1_gets_guidance(tmp_path, quantized):
    comp = tmp_path / "comp.nnue"
    ni.write_nnue(comp, quantized, compress_ft=True)
    with pytest.raises(ni.UnsupportedNnueFormat, match="pass l1="):
        ni.load_nnue(comp)
