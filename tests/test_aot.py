"""AOT program assets (fishnet_tpu/aot/): fingerprint keying, the
fallback ladder, and pack/warm bundle integrity.

The fast tier drives the registry with tiny jit programs so the whole
file runs in seconds; one engine-level pack -> warm-boot round-trip is
marked slow (and tools/aot_smoke.py covers the same contract in CI
across real process boundaries, which is the part an in-process test
cannot prove).
"""
import hashlib
import json
import os
import pickle
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.aot import keys, pack, registry
from fishnet_tpu.utils import compile_cache


def _mul(x, y, scale=2):
    return jnp.sum(x * y) * scale


def _wrap_mul(name="mul"):
    return registry.wrap(
        name,
        jax.jit(_mul, static_argnames=("scale",)),
        _mul,
        static_names=("scale",),
    )


@pytest.fixture
def aot_root(tmp_path):
    """A store root, with the process-wide registry AND compile-cache
    state snapshotted/restored: installing an exporting registry
    force-disables the persistent XLA cache, and the rest of the suite
    depends on it (conftest enables it for compile-time reasons)."""
    prev_reg = registry.REGISTRY
    prev_forced = compile_cache._force_disabled
    prev_path = compile_cache._enabled_path
    yield str(tmp_path / "store")
    registry.REGISTRY = prev_reg
    compile_cache._force_disabled = prev_forced
    compile_cache._enabled_path = None
    if not prev_forced and prev_path is not None:
        # no path argument: enable_compile_cache appends /<backend> to
        # whatever it is given, and prev_path is already namespaced —
        # passing it back would send the rest of the suite to a cold
        # <cache>/cpu/cpu directory. Argless re-enable rebuilds the
        # same path conftest built.
        restored = compile_cache.enable_compile_cache()
        assert restored == prev_path, (restored, prev_path)


def _export_tiny_bundle(root, warnings=None):
    """Export one tiny program into `root`; returns (store_dir, x, y, ref)."""
    reg = registry.install(root, export=True,
                           logger=(warnings.append if warnings is not None
                                   else None))
    prog = _wrap_mul()
    x = jnp.arange(8, dtype=jnp.float32)
    y = jnp.ones(8, dtype=jnp.float32)
    ref = np.asarray(prog(x, y, scale=3))
    reg.flush()
    reg.set_covers(["tiny"])
    assert reg.manifest["programs"], "export produced no artifact"
    return reg.dir, x, y, ref


# ------------------------------------------------------------ fingerprint


def test_fingerprint_roundtrip_and_digest_stability():
    fp = keys.store_fingerprint()
    assert fp["jax"] == jax.__version__
    assert keys.diff_fingerprints(fp, fp) == []
    # digest survives a JSON round-trip (manifests store the dict)
    again = json.loads(json.dumps(fp))
    assert keys.fingerprint_digest(again) == keys.fingerprint_digest(fp)


def test_fingerprint_skew_is_named_field_by_field():
    ours = keys.store_fingerprint()
    theirs = json.loads(json.dumps(ours))
    theirs["jaxlib"] = "0.0.1"
    theirs["settings"]["FISHNET_TPU_MAX_PLY"] = "99"
    diff = keys.diff_fingerprints(ours, theirs)
    assert any(d.startswith("jaxlib:") for d in diff)
    assert any(d.startswith("settings.FISHNET_TPU_MAX_PLY:") for d in diff)
    assert len(diff) == 2


def test_fingerprint_folds_mesh_topology():
    fp = keys.store_fingerprint()
    # topology fields present and coherent with the live process
    assert fp["mesh_axes"] == "dp"
    assert fp["process_count"] == jax.process_count()
    assert fp["mesh_shape"] == str(len(jax.devices()))


def test_topology_skewed_bundle_rejected_with_fields_named(aot_root):
    """A bundle packed on a different pod topology must be rejected with
    mesh_shape / process_count named — a sharded executable bakes its
    mesh in, and loading it cross-topology deserializes garbage."""
    theirs = json.loads(json.dumps(keys.store_fingerprint()))
    theirs["mesh_shape"] = "16x2"
    theirs["process_count"] = 4
    diff = keys.diff_fingerprints(keys.store_fingerprint(), theirs)
    assert any(d.startswith("mesh_shape:") for d in diff)
    assert any(d.startswith("process_count:") for d in diff)
    assert len(diff) == 2

    other = os.path.join(aot_root, keys.fingerprint_digest(theirs)[:12])
    os.makedirs(other)
    with open(os.path.join(other, "manifest.json"), "w") as f:
        json.dump({"version": registry.MANIFEST_VERSION,
                   "fingerprint": theirs, "programs": {"k": {}},
                   "covers": []}, f)
    warnings = []
    reg = registry.install(aot_root, logger=warnings.append)
    assert not reg.active
    assert any("incompatible" in w and "mesh_shape" in w
               and "process_count" in w for w in warnings)


def test_program_key_canonicalizes_statics_and_avals():
    x = jnp.arange(4, dtype=jnp.float32)
    k1, meta = keys.program_key("p", {"s": 1}, None, (x,))
    k2, _ = keys.program_key("p", {"s": 1}, None, (x + 1,))  # same aval
    assert k1 == k2
    k3, _ = keys.program_key("p", {"s": 2}, None, (x,))      # static skew
    k4, _ = keys.program_key(
        "p", {"s": 1}, None, (jnp.arange(5, dtype=jnp.float32),)
    )                                                        # shape skew
    assert len({k1, k3, k4}) == 3
    assert meta["entry"] == "p"


def test_incompatible_sibling_store_rejected_with_reason(aot_root):
    # a sibling fingerprint dir (e.g. packed under another jaxlib) must
    # produce an explicit rejection line, not a silent cold boot
    theirs = json.loads(json.dumps(keys.store_fingerprint()))
    theirs["jaxlib"] = "0.0.1"
    other = os.path.join(aot_root, keys.fingerprint_digest(theirs)[:12])
    os.makedirs(other)
    with open(os.path.join(other, "manifest.json"), "w") as f:
        json.dump({"version": registry.MANIFEST_VERSION,
                   "fingerprint": theirs, "programs": {"k": {}},
                   "covers": []}, f)
    warnings = []
    reg = registry.install(aot_root, logger=warnings.append)
    assert not reg.active
    assert any("incompatible" in w and "jaxlib" in w for w in warnings)


# --------------------------------------------------------- fallback ladder


def test_export_load_bit_identity_and_positional_statics(aot_root):
    _, x, y, ref = _export_tiny_bundle(aot_root)

    # fresh read-only registry + fresh wrapper (empty in-memory cache):
    # the call must come from a DISK load, and answer bit-identically
    reg = registry.install(aot_root)
    assert reg.active
    prog = _wrap_mul()
    out = np.asarray(prog(x, y, scale=3))
    assert reg.stats["loads"] == 1 and reg.stats["misses"] == 0
    np.testing.assert_array_equal(out, ref)

    # keyword vs positional static canonicalize to the same program
    out2 = np.asarray(prog(x, y, 3))
    assert reg.stats["loads"] == 1 and reg.stats["misses"] == 0
    assert reg.stats["hits"] == 2
    np.testing.assert_array_equal(out2, ref)


def test_miss_degrades_to_jit_with_one_warning(aot_root):
    _export_tiny_bundle(aot_root)
    warnings = []
    reg = registry.install(aot_root, logger=warnings.append)
    prog = _wrap_mul()
    x = jnp.arange(16, dtype=jnp.float32)  # shape the bundle never saw
    y = jnp.ones(16, dtype=jnp.float32)
    out = np.asarray(prog(x, y, scale=3))
    np.testing.assert_array_equal(out, np.asarray(_mul(x, y, 3)))
    assert reg.stats["misses"] == 1 and reg.stats["errors"] == 0
    assert sum("miss" in w for w in warnings) == 1
    # second call takes the cached-miss short-circuit: no new warning,
    # no second disk probe, and the count stays put
    np.asarray(prog(x, y, scale=3))
    assert reg.stats["misses"] == 1
    assert sum("miss" in w for w in warnings) == 1


def test_corrupted_artifact_quarantined_not_fatal(aot_root):
    store_dir, x, y, ref = _export_tiny_bundle(aot_root)
    blob_dir = os.path.join(store_dir, "blobs")
    (name,) = os.listdir(blob_dir)
    path = os.path.join(blob_dir, name)
    with open(path, "wb") as f:
        f.write(b"garbage")

    warnings = []
    reg = registry.install(aot_root, logger=warnings.append)
    prog = _wrap_mul()
    out = np.asarray(prog(x, y, scale=3))  # must not raise
    np.testing.assert_array_equal(out, ref)
    assert reg.stats["errors"] == 1 and reg.stats["loads"] == 0
    assert os.path.isfile(path + ".bad") and not os.path.isfile(path)
    assert any("quarantined" in w for w in warnings)


def test_undeserializable_artifact_quarantined(aot_root):
    # blob whose sha MATCHES its manifest entry but whose payload is not
    # a serialized executable: the deserialize step itself must
    # quarantine and fall back, covering the post-sha rung of the ladder
    store_dir, x, y, ref = _export_tiny_bundle(aot_root)
    blob_dir = os.path.join(store_dir, "blobs")
    (name,) = os.listdir(blob_dir)
    path = os.path.join(blob_dir, name)
    bogus = zlib.compress(pickle.dumps((b"not-an-executable", None, None)))
    with open(path, "wb") as f:
        f.write(bogus)
    man_path = os.path.join(store_dir, "manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    key = name[: -len(".bin")]
    man["programs"][key]["sha256"] = hashlib.sha256(bogus).hexdigest()
    with open(man_path, "w") as f:
        json.dump(man, f)

    reg = registry.install(aot_root)
    prog = _wrap_mul()
    out = np.asarray(prog(x, y, scale=3))
    np.testing.assert_array_equal(out, ref)
    assert reg.stats["errors"] == 1
    assert os.path.isfile(path + ".bad")


def test_star_args_signature_stays_plain_jit(aot_root):
    _export_tiny_bundle(aot_root)
    reg = registry.install(aot_root)

    def varargs(*xs):
        return sum(xs)

    prog = registry.wrap("varargs", jax.jit(varargs), varargs)
    assert np.asarray(prog(jnp.ones(2), jnp.ones(2))).tolist() == [2.0, 2.0]
    assert reg.stats == {"hits": 0, "misses": 0, "loads": 0,
                         "errors": 0, "exports": 0}


def test_warm_covers_semantics(aot_root):
    warnings = []
    # exporting registry never reports covered (pack IS the warmup)
    reg = registry.install(aot_root, export=True, logger=warnings.append)
    prog = _wrap_mul()
    prog(jnp.ones(4), jnp.ones(4), scale=2)
    reg.flush()
    reg.set_covers(["tiny"])
    assert not registry.warm_covers("tiny")

    registry.install(aot_root)
    assert registry.warm_covers("tiny")
    assert not registry.warm_covers("tiny", "variants")
    assert registry.boot_report()["enabled"]

    # an empty read-only store covers nothing and deactivates
    registry.install(os.path.join(aot_root, "empty"))
    assert not registry.warm_covers("tiny")
    assert not registry.boot_report()["enabled"]


# ------------------------------------------------------------- pack / warm


def test_pack_warm_load_manifest_integrity(aot_root):
    store_dir, x, y, ref = _export_tiny_bundle(aot_root)

    man = pack.verify_bundle(store_dir)
    assert man["covers"] == ["tiny"] and man["programs"]

    # warm into a second root: accepts the store ROOT (resolves the
    # nested fingerprint dir), re-verifies, and copies everything
    dest_root = os.path.join(os.path.dirname(aot_root), "live")
    rep = pack.warm(aot_root, dest_root, logger=lambda m: None)
    assert rep["programs"] == len(man["programs"])
    installed = pack.verify_bundle(rep["dir"])
    assert installed["programs"].keys() == man["programs"].keys()

    # the warmed copy serves a real load
    reg = registry.install(dest_root)
    out = np.asarray(_wrap_mul()(x, y, scale=3))
    np.testing.assert_array_equal(out, ref)
    assert reg.stats["loads"] == 1

    # verify names a corrupted artifact
    blob_dir = os.path.join(rep["dir"], "blobs")
    (name,) = os.listdir(blob_dir)
    with open(os.path.join(blob_dir, name), "ab") as f:
        f.write(b"x")
    with pytest.raises(ValueError, match="sha256"):
        pack.verify_bundle(rep["dir"])


def test_warm_rejects_fingerprint_skew(aot_root, tmp_path):
    store_dir, *_ = _export_tiny_bundle(aot_root)
    man_path = os.path.join(store_dir, "manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    man["fingerprint"]["jaxlib"] = "0.0.1"
    with open(man_path, "w") as f:
        json.dump(man, f)
    with pytest.raises(ValueError, match="jaxlib"):
        pack.warm(store_dir, str(tmp_path / "dest"), logger=lambda m: None)


# ------------------------------------------------------------ engine level


@pytest.mark.slow
def test_engine_pack_then_warm_boot_bit_identity(aot_root):
    """pack() over a real TpuEngine, then a warm in-process boot: warmup
    reports itself skipped, the first dispatch loads from disk, and the
    scores match a plain-JIT engine bit for bit."""
    from fishnet_tpu.chess.position import Position
    from fishnet_tpu.engine.tpu import TpuEngine
    from fishnet_tpu.ops import search as search_ops
    from fishnet_tpu.ops.board import from_position, stack_boards

    def run_search(eng):
        roots = stack_boards([from_position(Position.initial())] * 16)
        out = eng._search(
            roots, np.ones(16, np.int32), np.full(16, 64, np.int32)
        )
        return (np.asarray(out["score"]).tolist(),
                int(np.asarray(out["nodes"]).sum()))

    progs = (search_ops._run_segment_jit, search_ops._init_state_jit,
             search_ops._merge_lanes_jit)
    registry.uninstall()
    ref = run_search(TpuEngine())

    rep = pack.pack(aot_root, logger=lambda m: None)
    assert rep["programs"] > 0 and "buckets" in rep["covers"]

    # fresh-process simulation: drop the in-memory executables the pack
    # left behind so the warm boot must load from the store
    for p in progs:
        p.cache.clear()
    logs = []
    registry.install(aot_root, logger=logs.append)
    eng = TpuEngine()
    covered = eng.warmup(None, logs.append)
    assert "buckets" in covered
    assert any("skipped" in m and "AOT" in m for m in logs)
    warm = run_search(eng)
    reg = registry.REGISTRY
    assert reg.stats["loads"] >= 1 and reg.stats["misses"] == 0
    assert reg.stats["errors"] == 0
    assert warm == ref
