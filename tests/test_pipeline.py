"""Asynchronous segment pipeline (round 8) tests.

Four contracts from the pipeline change (ops/search.py packed boundary
summary + buffer donation, engine/tpu.py double-buffered LaneScheduler,
utils/syncstats.py):

1. Pipeline ON is bit-identical to the round-7 synchronous loop at both
   the ops level (search_stream) and the engine level (LaneScheduler):
   overlap and speculation must never change a result, only its timing.
2. Every submitted position gets exactly one PositionResponse even when
   boundaries are processed one segment behind the device (speculative
   dispatch) — no drops, no duplicates.
3. Buffer donation is real: the state handed to _run_segment_jit is dead
   after the call, and the jits always rebind to outputs (a use of the
   donated input is a bug this suite must catch before XLA does).
4. The pipelined boundary is cheap: one packed-summary transfer on a
   no-finish boundary at the stream level, and >= 5x fewer transfers
   than the synchronous loop at the engine level (ISSUE acceptance).

conftest.py pins REFILL=0/HELPERS=1; engine tests opt in via refill=True
exactly like tests/test_refill.py (mesh=None single-device scheduler).
"""
import asyncio
import os
import time

import numpy as np
import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.tpu import TpuEngine

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
GAME = ["e2e4", "c7c5", "g1f3", "d7d6", "d2d4"]


# ------------------------------------------------------------ ops level


def _stream_inputs(n=6, depth=2):
    import jax

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards

    params = nnue.init_params(jax.random.PRNGKey(0), l1=64,
                              feature_set="board768")
    boards, p = [], Position.from_fen(START)
    for uci in [None] + GAME:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    boards = boards[:n]
    roots = stack_boards(boards)
    depth_arr = np.full(n, depth, np.int32)
    budget = np.full(n, 200_000, np.int32)
    return params, roots, depth_arr, budget


@pytest.fixture(scope="module")
def stream_pair():
    """One search_stream run per mode over the same inputs; several
    tests assert against the pair (XLA:CPU runs are the slow part)."""
    from fishnet_tpu.ops import search as S

    params, roots, depth_arr, budget = _stream_inputs()
    out = {}
    for pipeline in (False, True):
        out[pipeline] = S.search_stream(
            params, roots, depth_arr, budget, max_ply=6, width=4,
            segment_steps=200, pipeline=pipeline)
    return out


def test_stream_bit_identity(stream_pair):
    """Same scores, moves, PVs and node counts with the pipeline on and
    off: speculation and summary-only boundaries are pure scheduling."""
    legacy, piped = stream_pair[False], stream_pair[True]
    assert bool(np.asarray(legacy["done"]).all())
    assert bool(np.asarray(piped["done"]).all())
    for key in ("score", "move", "nodes", "pv_len", "pv", "done"):
        np.testing.assert_array_equal(
            np.asarray(legacy[key]), np.asarray(piped[key]), err_msg=key)


def test_stream_pipelined_boundary_is_one_transfer(stream_pair):
    """A no-finish boundary in pipelined mode fetches exactly the packed
    summary — one transfer (the final boundary additionally drains
    results; refill boundaries pull the finished lanes' rows)."""
    occ = stream_pair[True]["occupancy"]
    assert occ, "no boundaries recorded"
    nofin = [o for o in occ[:-1] if o["refilled"] == 0]
    assert nofin, "shape produced no quiet boundaries; shrink the segment"
    assert all(o["transfers"] == 1 for o in nofin)
    # and the synchronous loop pays more at the same boundaries
    legacy_nofin = [o for o in stream_pair[False]["occupancy"][:-1]
                    if o["refilled"] == 0]
    assert min(o["transfers"] for o in legacy_nofin) >= 2


def test_stream_segment_auto_controller(monkeypatch):
    """segment_steps=None + FISHNET_TPU_SEGMENT=auto engages the
    measured-feedback controller and still finishes every position."""
    from fishnet_tpu.ops import search as S

    monkeypatch.setenv("FISHNET_TPU_SEGMENT", "auto")
    monkeypatch.setenv("FISHNET_TPU_SEGMENT_MIN", "64")
    monkeypatch.setenv("FISHNET_TPU_SEGMENT_MAX", "1024")
    params, roots, depth_arr, budget = _stream_inputs(n=4)
    out = S.search_stream(params, roots, depth_arr, budget, max_ply=6,
                          width=4, segment_steps=None, pipeline=True)
    assert bool(np.asarray(out["done"]).all())


def test_no_use_after_donate():
    """_run_segment_jit donates the state (and table): the input handles
    are dead after the call and any later use must raise, which pins the
    'always rebind to the outputs' discipline the engine relies on."""
    import jax

    from fishnet_tpu.ops import search as S

    params, roots, depth_arr, budget = _stream_inputs(n=4)
    state = S._init_state_jit(params, roots, depth_arr, budget, 6,
                              "standard")
    out_state, _, n, _summ = S._run_segment_jit(
        params, state, None, 50, "standard", False)
    jax.block_until_ready(out_state.lane)
    assert state.lane.is_deleted(), (
        "donated input still live: donate_argnums lost on _run_segment_jit")
    with pytest.raises(RuntimeError):
        np.asarray(state.lane)
    # the returned state is the live handle and remains usable
    assert np.asarray(out_state.lane).shape[0] == 4
    assert int(np.asarray(n)) > 0


# --------------------------------------------------------- engine level


def analysis_work(depth=3):
    return AnalysisWork(id="pipe01",
                        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
                        timeout_s=30.0, depth=depth, multipv=None)


def make_chunk(work, n_positions=4):
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=GAME[:i])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + 120,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


def make_refill_engine(**kw):
    kw.setdefault("max_depth", 3)
    kw.setdefault("tt_size_log2", 0)
    kw.setdefault("helper_lanes", 1)
    engine = TpuEngine(refill=True, **kw)
    engine.mesh = None  # single-device semantics (mesh suite is separate)
    engine.n_dev = 1
    return engine


@pytest.fixture(scope="module")
def engine_pair():
    """One LaneScheduler chunk per pipeline mode at a small segment (many
    boundaries, so the speculative path actually engages)."""
    saved = {k: os.environ.get(k)
             for k in ("FISHNET_TPU_PIPELINE", "FISHNET_TPU_SEGMENT")}
    out = {}
    try:
        os.environ["FISHNET_TPU_SEGMENT"] = "200"
        for mode in ("0", "1"):
            os.environ["FISHNET_TPU_PIPELINE"] = mode
            eng = make_refill_engine()
            resp = asyncio.run(eng.go_multiple(
                make_chunk(analysis_work(depth=3), n_positions=4)))
            out[mode] = (resp, list(eng.occupancy_log),
                         dict(eng.occupancy_totals))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def test_engine_exactly_once_under_speculation(engine_pair):
    """Every position answers exactly once even when the host stages
    admissions one segment behind the speculatively-dispatched device."""
    for mode in ("0", "1"):
        resp, _log, totals = engine_pair[mode]
        assert sorted(r.position_index for r in resp) == [0, 1, 2, 3]
        assert all(r.best_move for r in resp)
        assert totals["positions_done"] == 4


def test_engine_bit_identity(engine_pair):
    """Scheduler results are identical with the pipeline on and off:
    same best moves, scores, depths, node counts and PVs."""
    legacy = engine_pair["0"][0]
    piped = engine_pair["1"][0]

    def flat(resps):
        return [(r.position_index, r.best_move, r.depth, r.nodes,
                 r.scores.matrix, r.pvs.matrix) for r in resps]

    assert flat(legacy) == flat(piped)


def test_engine_boundary_transfer_reduction(engine_pair):
    """ISSUE acceptance: >= 5x fewer host transfers per no-finish
    boundary. The synchronous loop fetches the step count, the DONE mask
    and the six extract_results arrays every boundary; the pipelined
    loop fetches one packed summary."""
    quiet = {}
    for mode in ("0", "1"):
        log = engine_pair[mode][1]
        nofin = [r["transfers"] for r in log if r["refilled"] == 0]
        assert nofin, f"mode {mode}: no quiet boundaries recorded"
        # rows where a lane parked for re-admission also count
        # refilled == 0 (the admission lands in the NEXT row) but pay a
        # PV pull; the steady-state no-finish cost is the row minimum
        quiet[mode] = min(nofin)
    assert quiet["0"] >= 5 * quiet["1"], quiet
    # even the engine's most expensive pipelined boundary (summary + PV
    # pull) undercuts the synchronous loop's cheapest one
    assert max(r["transfers"] for r in engine_pair["1"][1]) < quiet["0"]
    # occupancy rows carry the host/device split for both modes
    for mode in ("0", "1"):
        row = engine_pair[mode][1][0]
        for key in ("transfers", "host_ms", "device_ms"):
            assert key in row
