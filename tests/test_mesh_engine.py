"""The production TpuEngine must actually use the device mesh.

VERDICT r1 #3: the engine previously never constructed a mesh — on a
v5e-8 it would use 1/8 of the machine. Under the test conftest jax
exposes 8 virtual CPU devices, so these assertions prove the sharded
path (parallel/mesh.py run_segment_sharded) is the engine's real code
path, not a demo.
"""
import asyncio
import time

import jax
import numpy as np
import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.tpu import TpuEngine

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def test_engine_uses_the_full_mesh():
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    engine = TpuEngine(max_depth=2)
    assert engine.mesh is not None
    assert engine.n_dev == 8
    # the TT is sharded per device
    assert engine.tt.check.shape[0] == 8
    # lane padding stays divisible over the devices
    for n in (1, 3, 16, 65, 200):
        assert engine._pad(n) % 8 == 0


def test_go_multiple_on_8_device_mesh():
    engine = TpuEngine(max_depth=2)
    work = AnalysisWork(
        id="meshjob1",
        nodes=NodeLimit(sf16=500_000, classical=500_000),
        timeout_s=60.0,
        depth=2,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=["e2e4"][:i])
        for i in range(2)
    ]
    chunk = Chunk(work=work, deadline=time.monotonic() + 300,
                  variant="standard", flavor=EngineFlavor.TPU,
                  positions=positions)
    responses = asyncio.run(engine.go_multiple(chunk))
    assert len(responses) == 2
    for res in responses:
        assert res.depth == 2 and res.nodes > 0
    # the sharded TT carried stores back from the run
    assert int(np.asarray(engine.tt.meta != 0).sum()) > 0
