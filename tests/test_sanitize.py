"""Runtime sanitizer tests (fishnet_tpu/utils/sanitize.py).

The seeded-violation tests are the teeth: a double delivery pushed
through the REAL LaneScheduler._deliver exactly-once point, and a real
post-donation read through a jit that donates — each must trip the
sanitizer with a message naming the site. The off-mode tests pin the
structural zero-overhead contract: guard_donation returns the wrapped
callable unchanged (the same object), so the default path cannot have
gained a frame.
"""
import types

import numpy as np
import pytest

from fishnet_tpu.utils import sanitize
from fishnet_tpu.utils.sanitize import SanitizeError


# ------------------------------------------------------ off-mode contract


def test_guard_donation_off_returns_fn_unchanged():
    def fn(x):
        return x

    assert sanitize.guard_donation("t::fn", fn, argnums=(0,)) is fn
    assert sanitize.guard_donation("t::fn", fn, force=False) is fn


def test_sanitize_defaults_off():
    # the suite runs without FISHNET_TPU_SANITIZE set; every
    # construction-time capture in the production modules sees False
    assert sanitize.enabled() is False


def test_sanitize_setting_reaches_engine_children():
    # engine=True in the registry: the supervised host child inherits
    # the flag through engine_env, so arming the parent arms the tree
    from fishnet_tpu.utils import settings

    entry = {s.name: s for s in settings.SETTINGS}["FISHNET_TPU_SANITIZE"]
    assert entry.engine and entry.kind == "bool" and entry.default == "0"


# ------------------------------------------------- donation poisoning


def test_seeded_post_donation_read_trips_sanitizer():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    jitted = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    guard = sanitize.guard_donation(
        "test::donating_jit", jitted, argnums=(0,), force=True)
    assert guard is not jitted  # forced on: wrapped

    x = jnp.arange(4, dtype=jnp.int32)
    y = guard(x)
    assert np.asarray(y).tolist() == [1, 2, 3, 4]
    # the input buffer is dead whether or not XLA:CPU actually donated
    # — the guard poisons what the platform left alive
    assert x.is_deleted()
    assert sanitize.deleted_site(x) == "test::donating_jit"
    # a direct read raises from JAX itself
    with pytest.raises(RuntimeError):
        np.asarray(x)
    # passing the dead handle back into a guarded call raises the
    # attributed error BEFORE JAX's siteless one
    with pytest.raises(SanitizeError, match="test::donating_jit"):
        guard(x)


def test_donation_guard_forwards_attributes():
    jax = pytest.importorskip("jax")

    jitted = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    guard = sanitize.guard_donation(
        "test::attrs", jitted, argnums=(0,), force=True)
    # AOT tooling reaches .lower through the guard
    assert guard.lower is jitted.lower


def test_donation_guard_keyword_argnames():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    jitted = jax.jit(lambda a, b: a + b, donate_argnames=("b",))
    guard = sanitize.guard_donation(
        "test::kw", jitted, argnames=("b",), force=True)
    a = jnp.arange(3, dtype=jnp.int32)
    b = jnp.arange(3, dtype=jnp.int32)
    guard(a, b=b)
    assert b.is_deleted() and not a.is_deleted()


# -------------------------------------------------- exactly-once ledgers


def _fake_scheduler(sanitize_on=True):
    """A LaneScheduler shell around the real _deliver: the exactly-once
    point itself, with the engine hooks stubbed out."""
    from fishnet_tpu.engine.tpu import LaneScheduler

    sched = LaneScheduler.__new__(LaneScheduler)
    sched._sanitize = sanitize_on
    sched.engine = types.SimpleNamespace(
        on_response=None, on_deliver=None, _warn=lambda msg: None)
    return sched


def test_seeded_double_delivery_trips_sanitizer():
    sched = _fake_scheduler(sanitize_on=True)
    entry = types.SimpleNamespace(responses={}, chunk=None)
    wp = types.SimpleNamespace(position_index=3, ctx=None)
    resp = object()

    sched._deliver(entry, wp, resp)
    assert entry.responses[3] is resp
    with pytest.raises(SanitizeError, match="LaneScheduler._deliver"):
        sched._deliver(entry, wp, resp)


def test_double_delivery_tolerated_when_off():
    # off-mode keeps the pre-sanitizer behavior bit-for-bit: last
    # write wins silently (the scheduler's own invariants prevent it;
    # the sanitizer is the net that PROVES they do)
    sched = _fake_scheduler(sanitize_on=False)
    entry = types.SimpleNamespace(responses={}, chunk=None)
    wp = types.SimpleNamespace(position_index=3, ctx=None)
    sched._deliver(entry, wp, "a")
    sched._deliver(entry, wp, "b")
    assert entry.responses[3] == "b"


def test_check_delivery_once():
    ledger = {}
    sanitize.check_delivery_once(ledger, "k", "t::site")
    ledger["k"] = 1
    with pytest.raises(SanitizeError, match="t::site"):
        sanitize.check_delivery_once(ledger, "k", "t::site")


def test_check_replay_consistent():
    ledger = {"fp": {"score": 10, "move": "e2e4"}}
    # identical replay is DESIGNED (journal resend after respawn)
    sanitize.check_replay_consistent(
        ledger, "fp", {"score": 10, "move": "e2e4"}, "t::journal")
    # unknown fingerprint: nothing to conflict with
    sanitize.check_replay_consistent(ledger, "other", {"x": 1}, "t::j")
    # same fingerprint, different payload: two answers for one position
    with pytest.raises(SanitizeError, match="conflicting"):
        sanitize.check_replay_consistent(
            ledger, "fp", {"score": -3, "move": "d2d4"}, "t::journal")


def test_supervisor_journal_replay_check_is_wired():
    # the duplicate-partial branch consults the sanitizer when armed;
    # source-level check so a refactor that drops the hook fails here
    import inspect

    from fishnet_tpu.engine import supervisor

    src = inspect.getsource(supervisor.SupervisedEngine._journal_record)
    assert "check_replay_consistent" in src


# ------------------------------------------------ in-flight stage labels


def test_inflight_strict_rejects_unknown_stage():
    from fishnet_tpu.obs.inflight import InflightRegistry

    reg = InflightRegistry()
    reg._strict = True
    reg.begin("t1", "r1", "tenant", "analyse")
    with pytest.raises(SanitizeError, match="unknown stage label"):
        reg.stage("t1", "despatched")  # typo'd label
    with pytest.raises(SanitizeError, match="unknown stage label"):
        reg.position("t1", 0, "lanes")
    # known labels keep working
    reg.stage("t1", "lane")
    reg.position("t1", 0, "delivered", lane=2)


def test_inflight_strict_clamps_backward_moves_without_raising():
    # re-dispatch after member loss legitimately replays positions
    # through earlier stages: clamped, NEVER an error
    from fishnet_tpu.obs.inflight import InflightRegistry

    reg = InflightRegistry()
    reg._strict = True
    reg.begin("t1", "r1", "tenant", "analyse")
    reg.stage("t1", "lane")
    reg.stage("t1", "admitted")  # backward: ignored
    snap = reg.snapshot()
    assert snap[0]["stage"] == "lane"


def test_inflight_lax_mode_ignores_unknown_stage():
    from fishnet_tpu.obs.inflight import InflightRegistry

    reg = InflightRegistry()
    assert reg._strict is False  # default: flag unset
    reg.begin("t1", "r1", "tenant", "analyse")
    reg.stage("t1", "despatched")  # silently ranked 0, as before


# ---------------------------------------------------------- TT integrity


def _meta(score, depth, flag):
    # mirror ops/tt.py pack_meta
    return ((score + 32768) << 10) | (depth << 2) | flag


def test_check_tt_rows_accepts_storable_rows():
    rows = [[7, 12345, _meta(150, 8, 1), 1028, 3],
            [9, 54321, _meta(-29999, 30, 2), 514, 3]]
    assert sanitize.check_tt_rows(rows, "t::tt", stride=1) == 2


def test_check_tt_rows_skips_empty_slots_and_handles_4col():
    rows = [[0, 0, 0, 0],
            [12345, _meta(0, 1, 0), 66, 1]]
    assert sanitize.check_tt_rows(rows, "t::tt", stride=1) == 1


def test_check_tt_rows_rejects_flag3_and_overrange_score():
    bad_flag = [[7, 1, _meta(0, 1, 3), 66, 1]]
    with pytest.raises(SanitizeError, match="flag=3"):
        sanitize.check_tt_rows(bad_flag, "t::tt", stride=1)
    bad_score = [[7, 1, _meta(31000, 1, 1), 66, 1]]
    with pytest.raises(SanitizeError, match="score=31000"):
        sanitize.check_tt_rows(bad_score, "t::tt", stride=1)


def test_check_tt_rows_sampling_stride():
    good = [7, 1, _meta(10, 4, 1), 66, 1]
    bad = [8, 1, _meta(0, 1, 3), 66, 1]
    rows = [good] * 130
    rows[65] = bad  # off-stride with the default 64: not sampled
    assert sanitize.check_tt_rows(rows, "t::tt") == 3  # 0, 64, 128
    with pytest.raises(SanitizeError):
        sanitize.check_tt_rows(rows, "t::tt", stride=1)


def test_ttwarm_store_checks_rows_when_armed(tmp_path):
    from fishnet_tpu.cache.ttwarm import TTWarmStore

    store = TTWarmStore(directory=str(tmp_path))
    store._sanitize = True
    good = [[7, 12345, _meta(150, 8, 1), 1028, 3]]
    store.record(10, "abcd", good)
    assert store.lookup(10, "abcd") == good

    bad = [[9, 1, _meta(0, 1, 3), 66, 1]]
    with pytest.raises(SanitizeError, match="TTWarmStore.record"):
        store.record(10, "efgh", bad)

    # a bad slice that reached disk (written by an unarmed process,
    # hashes fine) trips the LOOKUP check in an armed one
    unarmed = TTWarmStore(directory=str(tmp_path))
    assert unarmed._sanitize is False
    unarmed.record(10, "efgh", bad)
    fresh = TTWarmStore(directory=str(tmp_path))
    fresh._sanitize = True
    with pytest.raises(SanitizeError, match="TTWarmStore.lookup"):
        fresh.lookup(10, "efgh")
