"""Session-recovery ladder tests: replay → bisect → quarantine on CPU.

Round-9 acceptance coverage (ISSUE 7): partial-progress replay after a
mid-chunk kill re-searches strictly fewer positions than the chunk size;
hang bisection isolates a fingerprint-addressed poison position; the
quarantine list routes it (and only it) to the CPU fallback while every
other position completes on the engine path bit-identical to a
fault-free run. All driven through the scriptable fake host
(fishnet_tpu/engine/fakehost.py) — no JAX, deterministic faults. One
asyncio.run() per test.
"""
import asyncio
import json
import sys
import time

import pytest

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.ipc import Chunk, WorkPosition, position_fingerprint
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.fakehost import FAKE_CP
from fishnet_tpu.engine.supervisor import SupervisedEngine

pytestmark = [pytest.mark.faultinject, pytest.mark.subproc]

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def fake_cmd(script, state_path=None, hb_interval=0.05, echo=None, extra=()):
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", script if isinstance(script, str) else json.dumps(script),
        "--hb-interval", str(hb_interval),
    ]
    if state_path is not None:
        cmd += ["--state", str(state_path)]
    if echo is not None:
        cmd += ["--echo", str(echo)]
    cmd += list(extra)
    return cmd


def make_supervisor(script, state_path=None, echo=None, extra=(), **kw):
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 0.6)
    kw.setdefault("deadline_margin", 0.15)
    kw.setdefault("logger", Logger(verbose=0))
    kw.setdefault("backoff", RandomizedBackoff(max_s=0.05))
    return SupervisedEngine(
        fake_cmd(script, state_path, echo=echo, extra=extra), **kw
    )


def make_chunk(ttl=30.0, n_positions=4, depth=1):
    work = AnalysisWork(
        id="recjob01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=[])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + ttl,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


class closing:
    def __init__(self, sup):
        self.sup = sup

    async def __aenter__(self):
        return self.sup

    async def __aexit__(self, *exc):
        await self.sup.close()


def fake_cp(responses):
    return [r.scores.best().value for r in responses]


def read_echo(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_replay_resumes_suffix_after_mid_chunk_kill(tmp_path):
    """Kill after k=2 partials of a 4-position chunk: the journal replays
    the prefix, the respawned child is handed ONLY the 2-position suffix
    (strictly fewer re-searched than chunk size), and delivery stays
    exactly-once — no lost or duplicated PositionResponse."""
    echo = tmp_path / "echo.jsonl"
    async def main():
        sup = make_supervisor({"chunks": ["die-after:2", "partial-ok"]},
                              tmp_path / "state.json", echo=echo)
        async with closing(sup):
            chunk = make_chunk(n_positions=4)
            responses = await sup.go_multiple(chunk)
            # exactly-once end-to-end: every position exactly once, in order
            assert [r.position_index for r in responses] == [0, 1, 2, 3]
            assert fake_cp(responses) == [FAKE_CP] * 4
            assert sup.stats.replays == 1
            assert sup.stats.replayed_positions == 2
            assert sup.stats.partials == 4  # 2 journaled + 2 from the retry
            assert sup.stats.deaths == 1
            assert sup.stats.quarantined == 0
        # the respawned incarnation was asked to search ONLY the suffix
        gos = [r for r in read_echo(echo) if r["t"] == "go"]
        assert [g["positions"] for g in gos] == [4, 2]
        fps = [position_fingerprint(wp) for wp in chunk.positions]
        assert gos[0]["fps"] == fps
        assert gos[1]["fps"] == fps[2:]  # strictly fewer than chunk size

    asyncio.run(main())


def test_progress_stall_killed_before_deadline(tmp_path):
    """hang-at-segment signature: heartbeats flow but the partial stream
    goes silent after 1 of 4 positions. progress_timeout must kill well
    before the distant deadline and leave budget for in-chunk recovery."""
    async def main():
        sup = make_supervisor({"chunks": ["hang-at:1", "partial-ok"]},
                              tmp_path / "state.json",
                              progress_timeout=0.5)
        async with closing(sup):
            t0 = time.monotonic()
            responses = await sup.go_multiple(make_chunk(ttl=30.0))
            assert time.monotonic() - t0 < 10.0  # not the deadline
            assert sup.stats.progress_stalls == 1
            assert sup.stats.deadline_kills == 0
            assert fake_cp(responses) == [FAKE_CP] * 4
            assert sup.stats.replayed_positions == 1

    asyncio.run(main())


def test_quarantine_isolates_poison_position(tmp_path):
    """crash-on-fingerprint: the ladder must end with EXACTLY the poison
    position quarantined to the CPU fallback while all other positions
    complete via the (fake) engine path, bit-identical to a fault-free
    run — and a later chunk pre-routes the quarantined fingerprint with
    zero additional child deaths."""
    async def main():
        # fault-free reference run
        ref = make_supervisor({"chunks": ["partial-ok"]})
        async with closing(ref):
            ref_responses = await ref.go_multiple(make_chunk(n_positions=4))

        chunk = make_chunk(n_positions=4)
        poison = position_fingerprint(chunk.positions[2])
        sup = make_supervisor({"chunks": [f"crash-on-fp:{poison}"]},
                              tmp_path / "state.json")
        async with closing(sup):
            responses = await sup.go_multiple(chunk)
            assert [r.position_index for r in responses] == [0, 1, 2, 3]
            assert sup.stats.quarantined == 1
            assert sup.stats.bisections >= 1
            assert poison in sup._quarantine
            assert len(sup._quarantine) == 1
            # the ladder's deaths never tripped the breaker
            assert not sup._breaker_open
            assert sup.stats.breaker_trips == 0
            # poison position answered by the real CPU fallback...
            assert responses[2].scores.best().value != FAKE_CP
            # ...every other position bit-identical to the fault-free run
            for i in (0, 1, 3):
                got, want = responses[i], ref_responses[i]
                assert got.scores.best().value == want.scores.best().value
                assert got.best_move == want.best_move
                assert got.depth == want.depth
                assert got.nodes == want.nodes

            # second identical chunk: quarantine list pre-routes the
            # poison fingerprint — no further child deaths at all
            deaths = sup.stats.deaths
            responses2 = await sup.go_multiple(make_chunk(n_positions=4))
            assert sup.stats.quarantine_routed == 1
            assert sup.stats.deaths == deaths
            assert responses2[2].scores.best().value != FAKE_CP
            assert fake_cp(responses2)[:2] == [FAKE_CP, FAKE_CP]

    asyncio.run(main())


def test_quarantine_disabled_surfaces_failure(tmp_path):
    """quarantine=False: the isolated singleton is NOT routed to CPU —
    the ladder gives up and the failure surfaces (legacy semantics)."""
    async def main():
        chunk = make_chunk(n_positions=2)
        poison = position_fingerprint(chunk.positions[0])
        sup = make_supervisor({"chunks": [f"crash-on-fp:{poison}"]},
                              tmp_path / "state.json", quarantine=False)
        async with closing(sup):
            with pytest.raises(EngineError):
                await sup.go_multiple(chunk)
            assert sup.stats.quarantined == 0

    asyncio.run(main())


def test_duplicate_partials_are_ignored(tmp_path):
    """Exactly-once journaling: a child that re-sends every partial twice
    must not corrupt delivery; duplicates are counted, not stored."""
    async def main():
        sup = make_supervisor({"chunks": ["dup-partial"]},
                              tmp_path / "state.json")
        async with closing(sup):
            responses = await sup.go_multiple(make_chunk(n_positions=3))
            assert [r.position_index for r in responses] == [0, 1, 2]
            assert fake_cp(responses) == [FAKE_CP] * 3
            assert sup.stats.partials == 3
            assert sup.stats.duplicate_partials == 3

    asyncio.run(main())


def test_bisect_budget_bounds_the_ladder(tmp_path):
    """A chunk that dies on EVERY dispatch exhausts bisect_max and
    surfaces an error instead of retrying forever."""
    async def main():
        sup = make_supervisor({"chunks": ["crash:9"]},
                              tmp_path / "state.json", bisect_max=3)
        async with closing(sup):
            with pytest.raises(EngineError, match="exhausted|exited"):
                await sup.go_multiple(make_chunk(n_positions=4))
            assert sup.stats.deaths <= 4  # bisect_max + the final raise

    asyncio.run(main())


def test_respawn_rereceives_full_engine_config(tmp_path):
    """Config fidelity across respawns: after a mid-chunk kill, the new
    incarnation must come up with the SAME argv (helpers/refill/partials/
    depth flags) and the same engine-affecting FISHNET_TPU_* env."""
    echo = tmp_path / "echo.jsonl"
    async def main():
        sup = make_supervisor(
            {"chunks": ["die-after:1", "partial-ok"]},
            tmp_path / "state.json", echo=echo,
            extra=["--helpers", "4", "--refill", "1",
                   "--partials", "1", "--depth", "9"],
            env={"FISHNET_TPU_HELPERS": "4"},
        )
        async with closing(sup):
            responses = await sup.go_multiple(make_chunk(n_positions=3))
            assert fake_cp(responses) == [FAKE_CP] * 3
        boots = [r for r in read_echo(echo) if r["t"] == "boot"]
        assert len(boots) == 2  # original + respawn
        assert boots[1]["argv"] == boots[0]["argv"]
        for flag in ("--helpers", "--refill", "--partials", "--depth"):
            assert flag in boots[1]["argv"]
        assert boots[1]["env"].get("FISHNET_TPU_HELPERS") == "4"
        assert boots[1]["env"] == boots[0]["env"]

    asyncio.run(main())
