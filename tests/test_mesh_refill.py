"""Sharded scheduler (mesh parity) tests on the 8-device virtual CPU mesh.

The mesh-parity change (parallel/mesh.py sharded segment/refill/merge
callables, ops/search.py search_stream(mesh=...), engine/tpu.py
shard-aware LaneScheduler) promises that multi-chip hosts get the same
occupancy stack single-device hosts got in rounds 7-8, without changing
a single result. Contracts pinned here:

1. Shard-local refill is bit-identical to chunk-serial dispatch and to
   the single-device stream: resplicing lanes per shard is pure
   scheduling, never search behavior.
2. Pipeline ON under a mesh is bit-identical to the synchronous mesh
   loop, and a no-finish boundary still costs exactly one host transfer
   (the stacked per-shard summary is one fetch).
3. The sharded segment donates its operands like the single-device jit:
   inputs are dead after the call, callers must rebind to outputs.
4. Every position answers exactly once even when lanes finish on
   different shards at different boundaries (staggered depths), and the
   engine's padding handles position counts that don't divide over the
   mesh (B % ndev edge cases ride through _pad).
5. On a staggered-depth workload, refill keeps mean live-lane occupancy
   strictly above the chunk-serial mesh path (the point of the change).

conftest.py forces 8 virtual CPU devices (the `mesh` marker documents
the requirement) and pins FISHNET_TPU_REFILL=0; engines here opt in with
refill=True and keep the mesh conftest provides.
"""
import asyncio
import threading
import time

import numpy as np
import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.tpu import TpuEngine

# `slow` keeps the ~2 min of sharded compiles out of the quick tier's
# wall-clock budget; CI runs the module in its own step (-m mesh with
# addopts overridden), and `pytest -m mesh` runs it locally.
pytestmark = [pytest.mark.mesh, pytest.mark.slow]

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
# 11 plies of a Najdorf: START + prefixes give 12 distinct positions
GAME = ["e2e4", "c7c5", "g1f3", "d7d6", "d2d4", "c5d4", "f3d4", "g8f6",
        "b1c3", "a7a6", "f1e2"]
N_POS = 12
WIDTH = 8
# staggered depths: lanes park at different boundaries on different
# shards, so refill decisions and shard-local merges actually interleave
DEPTHS = np.asarray([1, 3, 1, 2, 3, 1, 2, 1, 3, 1, 2, 1], np.int32)


def _inputs():
    import jax

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards

    params = nnue.init_params(jax.random.PRNGKey(3), l1=64,
                              feature_set="board768")
    boards, p = [], Position.from_fen(START)
    for uci in [None] + GAME:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    assert len(boards) == N_POS
    return params, stack_boards(boards)


# ------------------------------------------------------------ ops level


@pytest.fixture(scope="module")
def mesh_streams():
    """One set of search_stream runs over the same staggered workload:
    single-device baseline, mesh sync, mesh pipelined, and the
    chunk-serial mesh baseline (same width, each chunk fits, so no
    refill ever fires). Several tests assert against the set — the
    XLA:CPU runs are the slow part, the asserts are free."""
    import jax

    from fishnet_tpu.ops import search as S
    from fishnet_tpu.parallel.mesh import make_mesh

    params, roots = _inputs()
    budget = np.full(N_POS, 200_000, np.int32)
    mesh = make_mesh()
    kw = dict(max_ply=6, width=WIDTH, segment_steps=150)
    out = {
        "base": S.search_stream(params, roots, DEPTHS, budget,
                                pipeline=False, **kw),
        "mesh_sync": S.search_stream(params, roots, DEPTHS, budget,
                                     mesh=mesh, pipeline=False, **kw),
        "mesh_piped": S.search_stream(params, roots, DEPTHS, budget,
                                      mesh=mesh, pipeline=True, **kw),
    }
    serial = {"occupancy": [], "score": [], "move": [], "nodes": [],
              "pv_len": [], "pv": []}
    for lo in range(0, N_POS, WIDTH):
        hi = min(lo + WIDTH, N_POS)
        sub = jax.tree.map(lambda a: a[lo:hi], roots)
        r = S.search_stream(params, sub, DEPTHS[lo:hi], budget[lo:hi],
                            mesh=mesh, pipeline=False, **kw)
        assert r["refills"] == 0, "chunk-serial baseline must never refill"
        serial["occupancy"].extend(r["occupancy"])
        for key in ("score", "move", "nodes", "pv_len", "pv"):
            serial[key].append(np.asarray(r[key]))
    for key in ("score", "move", "nodes", "pv_len", "pv"):
        serial[key] = np.concatenate(serial[key])
    out["serial"] = serial
    return out


def test_stream_mesh_matches_single_device(mesh_streams):
    """Sharded dispatch is bit-identical to the single-device stream:
    same scores, moves, PVs and node counts position by position."""
    base, sharded = mesh_streams["base"], mesh_streams["mesh_sync"]
    assert bool(np.asarray(base["done"]).all())
    assert bool(np.asarray(sharded["done"]).all())
    for key in ("score", "move", "nodes", "pv_len", "pv", "done"):
        np.testing.assert_array_equal(
            np.asarray(base[key]), np.asarray(sharded[key]), err_msg=key)


def test_stream_mesh_refill_matches_chunk_serial(mesh_streams):
    """ISSUE acceptance: shard-local refill reproduces the chunk-serial
    mesh path exactly — refill is scheduling, not search."""
    refill, serial = mesh_streams["mesh_sync"], mesh_streams["serial"]
    assert refill["refills"] >= N_POS - WIDTH
    for key in ("score", "move", "nodes", "pv_len", "pv"):
        np.testing.assert_array_equal(
            np.asarray(refill[key]), serial[key], err_msg=key)


def test_stream_mesh_pipeline_parity(mesh_streams):
    """Pipeline on/off parity holds under a mesh: speculation over the
    stacked per-shard summary never changes a result."""
    sync, piped = mesh_streams["mesh_sync"], mesh_streams["mesh_piped"]
    for key in ("score", "move", "nodes", "pv_len", "pv", "done"):
        np.testing.assert_array_equal(
            np.asarray(sync[key]), np.asarray(piped[key]), err_msg=key)


def test_stream_mesh_occupancy_shard_columns(mesh_streams):
    """Mesh occupancy rows carry per-shard live/refilled/steps lists (one
    entry per device) consistent with the scalar columns."""
    for mode in ("mesh_sync", "mesh_piped"):
        occ = mesh_streams[mode]["occupancy"]
        assert occ, f"{mode}: no boundaries recorded"
        for row in occ:
            for key in ("shard_live", "shard_refilled", "shard_steps"):
                assert len(row[key]) == 8, (mode, key)
            assert sum(row["shard_live"]) == row["live"]
            assert sum(row["shard_refilled"]) == row["refilled"]
            assert max(row["shard_steps"]) == row["steps"]
    # the single-device run must NOT grow shard columns
    assert "shard_live" not in mesh_streams["base"]["occupancy"][0]


def test_stream_mesh_pipelined_boundary_is_one_transfer(mesh_streams):
    """ISSUE acceptance: a no-finish boundary under the pipelined mesh
    loop is ONE host transfer — the stacked (ndev, local+1, 4) summary
    comes back as a single fetch, not one per shard."""
    occ = mesh_streams["mesh_piped"]["occupancy"]
    nofin = [o for o in occ[:-1] if o["refilled"] == 0]
    assert nofin, "shape produced no quiet boundaries; shrink the segment"
    assert all(o["transfers"] == 1 for o in nofin)
    # and the synchronous mesh loop pays more at the same boundaries
    sync_nofin = [o for o in mesh_streams["mesh_sync"]["occupancy"][:-1]
                  if o["refilled"] == 0]
    assert min(o["transfers"] for o in sync_nofin) >= 2


def _mean_live_occupancy(rows):
    """Steps-weighted mean fraction of lanes live across boundaries."""
    lane_steps = sum(r["live"] * r["steps"] for r in rows)
    total = sum(WIDTH * r["steps"] for r in rows)
    return lane_steps / total


def test_stream_mesh_refill_occupancy_beats_serial(mesh_streams):
    """ISSUE acceptance: on the staggered-depth workload, mean live-lane
    occupancy with shard-local refill is strictly higher than the
    chunk-serial mesh path at the same width — idle lanes get respliced
    instead of spinning until the deepest lane in the chunk finishes."""
    refill = _mean_live_occupancy(mesh_streams["mesh_sync"]["occupancy"])
    serial = _mean_live_occupancy(mesh_streams["serial"]["occupancy"])
    assert refill > serial, (refill, serial)


def test_no_use_after_donate_sharded():
    """run_segment_sharded donates state (and TT) exactly like the
    single-device _run_segment_jit: the sharded input handles are dead
    after the call and any later use must raise — pins the 'always
    rebind to outputs' discipline the scheduler relies on under a mesh."""
    import jax

    from fishnet_tpu.ops import search as S
    from fishnet_tpu.parallel.mesh import (
        make_mesh,
        run_segment_sharded,
        shard_batch,
    )

    params, roots = _inputs()
    mesh = make_mesh()
    sub = jax.tree.map(lambda a: a[:WIDTH], roots)
    state = S._init_state_jit(
        params, sub, DEPTHS[:WIDTH].copy(),
        np.full(WIDTH, 200_000, np.int32), 6, "standard")
    state = shard_batch(mesh, state)
    out_state, _tt, n, _summ = run_segment_sharded(
        mesh, params, state, None, 50)
    jax.block_until_ready(out_state.lane)
    assert state.lane.is_deleted(), (
        "donated sharded input still live: donate_argnums lost on the "
        "shard_map'd segment callable")
    with pytest.raises(RuntimeError):
        np.asarray(state.lane)
    # the returned state is the live handle and remains usable
    assert np.asarray(out_state.lane).shape[0] == WIDTH
    assert int(np.asarray(n).max()) > 0


# --------------------------------------------------------- engine level


def analysis_work(depth=3):
    return AnalysisWork(id="mesh01",
                        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
                        timeout_s=30.0, depth=depth, multipv=None)


def make_chunk(work, n_positions=4, moves=GAME):
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=moves[:i])
        for i in range(n_positions)
    ]
    return Chunk(work=work, deadline=time.monotonic() + 120,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


def run(engine, chunk):
    return asyncio.run(engine.go_multiple(chunk))


def make_mesh_engine(refill=True, **kw):
    """Engine that KEEPS conftest's 8-device mesh (unlike the refill and
    pipeline suites, which pin mesh=None for single-device semantics).
    refill=True engages the shard-aware scheduler (FISHNET_TPU_MESH_REFILL
    defaults on); refill=False is the chunk-serial sharded baseline."""
    kw.setdefault("max_depth", 3)
    kw.setdefault("tt_size_log2", 0)
    kw.setdefault("helper_lanes", 1)
    engine = TpuEngine(refill=refill, **kw)
    assert engine.mesh is not None, "conftest should provide 8 devices"
    assert engine.n_dev == 8
    return engine


def _flat(resps):
    return [(r.position_index, r.best_move, r.depth, r.nodes,
             r.scores.matrix, r.pvs.matrix) for r in resps]


@pytest.fixture(scope="module")
def mesh_engine_pair():
    """One chunk through the shard-aware scheduler and one through the
    chunk-serial sharded path, same positions (uncoupled lanes: no TT,
    no helpers)."""
    out = {}
    for mode, refill in (("serial", False), ("refill", True)):
        eng = make_mesh_engine(refill=refill)
        resp = run(eng, make_chunk(analysis_work(depth=3), n_positions=4))
        out[mode] = (resp, list(eng.occupancy_log),
                     dict(eng.occupancy_totals))
    return out


def test_engine_mesh_refill_matches_serial(mesh_engine_pair):
    """The shard-aware scheduler reproduces the chunk-serial sharded
    engine exactly — scores, PVs, node counts, per-depth matrices."""
    serial, refill = mesh_engine_pair["serial"][0], mesh_engine_pair["refill"][0]
    assert _flat(serial) == _flat(refill)


def test_engine_mesh_exactly_once(mesh_engine_pair):
    """Every position answers exactly once through the sharded scheduler,
    and the totals tie out."""
    resp, _log, totals = mesh_engine_pair["refill"]
    assert sorted(r.position_index for r in resp) == [0, 1, 2, 3]
    assert all(r.best_move for r in resp)
    assert totals["positions_done"] == 4


def test_engine_mesh_occupancy_shard_columns(mesh_engine_pair):
    """Scheduler occupancy rows under a mesh carry the per-shard columns
    the bench and occupancy report consume, and admissions balance over
    shards (most-free-shard policy: the first 4 primaries land on 4
    DIFFERENT shards, never stacked on one)."""
    log = mesh_engine_pair["refill"][1]
    assert log, "no occupancy rows recorded"
    for row in log:
        for key in ("shard_live", "shard_refilled", "shard_steps"):
            assert len(row[key]) == 8, key
        assert sum(row["shard_refilled"]) == row["refilled"]
    first = log[0]
    assert sum(1 for x in first["shard_refilled"] if x > 0) == 4
    # the serial path records no scheduler rows at all
    assert mesh_engine_pair["serial"][1] == []


@pytest.mark.parametrize("n_positions", [3, 10])
def test_engine_mesh_pad_edge_cases(n_positions):
    """Position counts that don't divide over 8 shards ride through the
    engine's _pad (3 -> width 8, 10 -> width 16): exactly-once delivery
    and bit-identity with the chunk-serial sharded path both hold."""
    serial = make_mesh_engine(refill=False, max_depth=2)
    want = run(serial, make_chunk(analysis_work(depth=2), n_positions))
    engine = make_mesh_engine(max_depth=2)
    got = run(engine, make_chunk(analysis_work(depth=2), n_positions))
    assert sorted(r.position_index for r in got) == list(range(n_positions))
    assert engine.occupancy_totals["positions_done"] == n_positions
    assert _flat(want) == _flat(got)


def test_engine_mesh_concurrent_chunks_exactly_once():
    """Two chunks at DIFFERENT depths share one driver session: lanes
    finish on different shards at different boundaries, refills land
    mid-flight, and both chunks still answer exactly once, in order."""
    engine = make_mesh_engine(max_depth=3)
    chunks = [
        make_chunk(analysis_work(depth=2), n_positions=3, moves=GAME),
        make_chunk(analysis_work(depth=3), n_positions=3,
                   moves=["d2d4", "g8f6", "c2c4"]),
    ]
    results = [None, None]
    errors = []

    def go(i):
        try:
            results[i] = run(engine, chunks[i])
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(e)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors
    for responses in results:
        assert responses is not None and len(responses) == 3
        assert [r.position_index for r in responses] == [0, 1, 2]
        assert all(r.best_move for r in responses)
    assert engine.occupancy_totals["positions_done"] == 6
