"""Fleet coordinator tests: N members behind one Engine, exactly-once.

Round-12 acceptance coverage (ISSUE 12), all on CPU with the scriptable
fake host or PyEngine — no JAX:

- N-member results are bit-identical to a single-member run;
- the least-backlog planner routes around a busy member;
- a member SIGKILLed mid-chunk re-dispatches exactly its un-acked
  in-flight positions to survivors (strictly fewer re-searches than a
  chunk resubmit), with one loss event;
- a fingerprint that kills two different members is quarantined
  fleet-wide and pre-routed to the CPU fallback on later chunks;
- a remote (HTTP) member answers identically to the same engine driven
  directly — serve/protocol.py round-trips the work faithfully;
- the merged metrics registry and trace ring tie out to the per-member
  ledgers (one Prometheus endpoint, one timeline).
"""
import asyncio
import json
import sys
import time

import pytest

from fishnet_tpu.client.backoff import RandomizedBackoff
from fishnet_tpu.client.ipc import (
    Chunk,
    WorkPosition,
    position_fingerprint,
    response_to_wire,
)
from fishnet_tpu.client.logger import Logger
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.fakehost import FAKE_CP
from fishnet_tpu.engine.pyengine import PyEngine
from fishnet_tpu.fleet import FleetCoordinator, FleetMember
from fishnet_tpu.fleet.member import make_local_member, members_from_specs
from fishnet_tpu.obs import trace as obs_trace
from fishnet_tpu.obs.metrics import MetricsRegistry

pytestmark = [pytest.mark.faultinject, pytest.mark.subproc]

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def fake_cmd(script, state_path, hb=0.05, echo=None, extra=()):
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", json.dumps(script),
        "--state", str(state_path),
        "--hb-interval", str(hb),
    ]
    if echo is not None:
        cmd += ["--echo", str(echo)]
    return cmd + list(extra)


def fake_member(name, script, tmp_path, echo=None, extra=()):
    return make_local_member(
        name,
        host_cmd=fake_cmd(script, tmp_path / f"{name}.json",
                          echo=echo, extra=extra),
        logger=Logger(verbose=0),
        hb_interval=0.05,
        hb_timeout=1.0,
        backoff=RandomizedBackoff(max_s=0.05),
    )


def make_chunk(n=4, ttl=30.0, moves=(), depth=1,
               flavor=EngineFlavor.TPU, batch="fleetjob"):
    work = AnalysisWork(
        id=batch,
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=depth, multipv=None,
    )
    positions = [
        WorkPosition(work=work, position_index=i, url=None, skip=False,
                     root_fen=START, moves=list(moves))
        for i in range(n)
    ]
    return Chunk(work=work, deadline=time.monotonic() + ttl,
                 variant="standard", flavor=flavor, positions=positions)


def comparable(res):
    wire = response_to_wire(res)
    return {k: wire[k]
            for k in ("scores", "pvs", "best_move", "depth", "nodes")}


def read_echo(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------------ member specs


def test_member_spec_grammar():
    members = members_from_specs(
        "local*2, http://h1:9670, h2:9671",
        local_factory=lambda name: FleetMember(name=name, engine=object()),
        logger=Logger(verbose=0),
    )
    assert [(m.name, m.kind) for m in members] == [
        ("local0", "local"), ("local1", "local"),
        ("h1:9670", "remote"), ("h2:9671", "remote"),
    ]
    with pytest.raises(ValueError):
        members_from_specs("", logger=Logger(verbose=0))
    with pytest.raises(ValueError):
        members_from_specs("local*0", logger=Logger(verbose=0))
    with pytest.raises(ValueError):
        members_from_specs("https://h:1", logger=Logger(verbose=0))
    with pytest.raises(ValueError):
        members_from_specs("h:1,h:1", logger=Logger(verbose=0))


def test_pod_spec_parse_round_trip():
    from fishnet_tpu.fleet.member import parse_pod_spec, pod_member_env

    assert parse_pod_spec("pod:2") == (2, "127.0.0.1:9791")
    assert parse_pod_spec("pod:4@10.0.0.5:7000") == (4, "10.0.0.5:7000")
    for bad in ("pod:x", "pod:0", "pod:-1", "pod:2@nohost",
                "pod:2@:7000", "pod:2@h:"):
        with pytest.raises(ValueError):
            parse_pod_spec(bad)
    # the env overlay IS the runbook contract: the host child boots as
    # process 0 of an N-host mesh pointed at the coordinator
    assert pod_member_env(2, "10.0.0.5:7000") == {
        "FISHNET_TPU_MESH_HOSTS": "2",
        "FISHNET_TPU_MESH_COORDINATOR": "10.0.0.5:7000",
        "FISHNET_TPU_MESH_PROCESS_ID": "0",
    }


def test_pod_member_spec_grammar():
    made = []

    def pod_factory(name, env):
        made.append((name, env))
        return FleetMember(name=name, engine=object(), kind="local")

    members = members_from_specs(
        "pod:2, local, pod:3@h9:7100",
        local_factory=lambda name: FleetMember(name=name, engine=object()),
        pod_factory=pod_factory,
        logger=Logger(verbose=0),
    )
    assert [m.name for m in members] == ["pod0", "local0", "pod1"]
    assert made == [
        ("pod0", {"FISHNET_TPU_MESH_HOSTS": "2",
                  "FISHNET_TPU_MESH_COORDINATOR": "127.0.0.1:9791",
                  "FISHNET_TPU_MESH_PROCESS_ID": "0"}),
        ("pod1", {"FISHNET_TPU_MESH_HOSTS": "3",
                  "FISHNET_TPU_MESH_COORDINATOR": "h9:7100",
                  "FISHNET_TPU_MESH_PROCESS_ID": "0"}),
    ]
    with pytest.raises(ValueError):
        members_from_specs("pod:zero", logger=Logger(verbose=0))


# ------------------------------------------------------------- bit identity


def test_n_member_results_bit_identical_to_single_member():
    """Splitting a chunk over 2 members changes nothing about any
    position's answer: per-position node budgets are independent of the
    sub-chunk shape, so the fleet adds no search-visible state."""

    async def scenario():
        chunk = make_chunk(n=4, depth=2, flavor=EngineFlavor.OFFICIAL,
                           moves=["e2e4"])
        direct = await PyEngine(max_depth=2).go_multiple(chunk)

        coord = FleetCoordinator(
            [FleetMember(name=f"py{i}", engine=PyEngine(max_depth=2))
             for i in range(2)],
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            loss_window=0.1,
        )
        try:
            fleet = await coord.go_multiple(make_chunk(
                n=4, depth=2, flavor=EngineFlavor.OFFICIAL,
                moves=["e2e4"]))
        finally:
            await coord.close()

        assert [r.position_index for r in fleet] == [0, 1, 2, 3]
        for a, b in zip(fleet, direct):
            assert comparable(a) == comparable(b)
        # the spread was real: both members searched
        assert all(m.dispatched_positions == 2 for m in coord.members)

    asyncio.run(scenario())


# -------------------------------------------------------- least-backlog plan


def test_least_backlog_routes_around_busy_member(tmp_path):
    """While the slow member digests its chunk, new chunks must land on
    the idle one — backlog, not round-robin, drives admission."""
    echo_slow = tmp_path / "slow.jsonl"
    echo_fast = tmp_path / "fast.jsonl"

    async def scenario():
        coord = FleetCoordinator(
            [
                fake_member("slow", {"chunks": ["ok"]}, tmp_path,
                            echo=echo_slow, extra=["--latency-ms", "400"]),
                fake_member("fast", {"chunks": ["ok"]}, tmp_path,
                            echo=echo_fast),
            ],
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            loss_window=0.1,
        )
        try:
            await coord.start()
            # ties break in member order, so the first chunk occupies
            # the slow member ...
            first = asyncio.ensure_future(coord.go_multiple(
                make_chunk(n=1, moves=["e2e4"], batch="job-a")))
            await asyncio.sleep(0.1)
            # ... and while its backlog is up, later chunks must avoid
            # it. Staggered so the fast member's backlog drains between
            # them — admission charges are visible synchronously, so a
            # concurrent pair would tie at backlog 1 and split.
            second = await coord.go_multiple(
                make_chunk(n=1, moves=["d2d4"], batch="job-b"))
            third = await coord.go_multiple(
                make_chunk(n=1, moves=["c2c4"], batch="job-c"))
            later = [second, third]
            await first
            for responses in later:
                assert responses[0].scores.best().value == FAKE_CP
        finally:
            await coord.close()

        slow_gos = [r for r in read_echo(echo_slow) if r["t"] == "go"]
        fast_gos = [r for r in read_echo(echo_fast) if r["t"] == "go"]
        assert len(slow_gos) == 1  # only the chunk that made it busy
        assert len(fast_gos) == 2  # everything submitted while it was

    asyncio.run(scenario())


# ------------------------------------------------------- member loss ledger


def test_member_loss_redispatches_exactly_the_unacked_subset(tmp_path):
    """3 members, 6 positions (2 each); m0 acks one position then dies.
    Exactly one response per position, exactly one loss event, and the
    survivors re-search only m0's un-acked position — 7 positions
    touched fleet-wide, not 12."""
    echos = {f"m{i}": tmp_path / f"m{i}.jsonl" for i in range(3)}

    async def scenario():
        members = [
            fake_member("m0", {"chunks": ["die-after:1", "ok"]},
                        tmp_path, echo=echos["m0"]),
            fake_member("m1", {"chunks": ["ok"]}, tmp_path,
                        echo=echos["m1"]),
            fake_member("m2", {"chunks": ["ok"]}, tmp_path,
                        echo=echos["m2"]),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(),
            redispatch_max=3, loss_window=0.2,
        )
        try:
            await coord.start()
            chunk = make_chunk(n=6)
            responses = await coord.go_multiple(chunk)
            # exactly-once, in request order, all on the engine path
            assert [r.position_index for r in responses] == list(range(6))
            assert [r.scores.best().value for r in responses] == \
                [FAKE_CP] * 6
        finally:
            await coord.close()

        assert coord.stats.losses == 1
        assert len(coord.loss_log) == 1
        ev = coord.loss_log[0]
        assert ev.member == "m0"
        redisp = set(ev.redispatched_fps)
        inflight = set(ev.inflight_fps)
        assert len(inflight) == 2  # the 2-position sub-chunk
        assert set(ev.acked_fps) == inflight - redisp
        assert redisp < inflight  # strict subset: the ack was harvested
        assert coord.stats.redispatches == 1
        assert coord.stats.acks_harvested == 1

        # strictly fewer re-searches than resubmitting the chunk: the
        # members collectively received 6 + 1 positions, and the
        # re-dispatched fingerprint went to a survivor, not m0
        gos = {name: [r for r in read_echo(path) if r["t"] == "go"]
               for name, path in echos.items()}
        total = sum(g["positions"] for gs in gos.values() for g in gs)
        assert total == 6 + len(redisp) < 12
        assert len(gos["m0"]) == 1
        survivor_fps = [fp for name in ("m1", "m2")
                        for g in gos[name] for fp in g["fps"]]
        assert all(fp in survivor_fps for fp in redisp)

    asyncio.run(scenario())


def test_cache_fill_exactly_once_under_member_loss(tmp_path):
    """The shared analysis cache fills exactly once per unique position
    even when a member dies mid-chunk: 6 distinct positions, m0 acks
    one then dies (its other position re-dispatches to a survivor) —
    fills == 6 with zero dup_fills, and an identical second chunk is
    answered entirely from the hit set without touching any member."""
    from fishnet_tpu.cache.store import AnalysisCache

    line = ["e2e4", "e7e5", "g1f3", "b8c6", "f1b5"]
    echos = {f"m{i}": tmp_path / f"m{i}.jsonl" for i in range(3)}

    def distinct_chunk(n=6, batch="fleetjob"):
        work = AnalysisWork(
            id=batch,
            nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
            timeout_s=30.0, depth=1, multipv=None,
        )
        positions = [
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=line[:i])
            for i in range(n)
        ]
        return Chunk(work=work, deadline=time.monotonic() + 30.0,
                     variant="standard", flavor=EngineFlavor.TPU,
                     positions=positions)

    async def scenario():
        members = [
            fake_member("m0", {"chunks": ["die-after:1", "ok"]},
                        tmp_path, echo=echos["m0"]),
            fake_member("m1", {"chunks": ["ok"]}, tmp_path,
                        echo=echos["m1"]),
            fake_member("m2", {"chunks": ["ok"]}, tmp_path,
                        echo=echos["m2"]),
        ]
        cache = AnalysisCache("fleet-test-identity")
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(),
            redispatch_max=3, loss_window=0.2,
        )
        coord.attach_cache(cache)
        try:
            await coord.start()
            first = await coord.go_multiple(distinct_chunk())
            assert [r.position_index for r in first] == list(range(6))
            assert coord.stats.losses == 1  # the fault actually fired

            # one fill per unique position, no double-insert from the
            # harvested ack or the re-dispatched copy
            assert cache.stats.fills == 6
            assert cache.stats.dup_fills == 0
            assert cache.stats.misses == 6 and cache.stats.hits == 0

            gos_before = sum(
                1 for path in echos.values() for r in read_echo(path)
                if r["t"] == "go"
            )
            second = await coord.go_multiple(distinct_chunk(batch="again"))
            assert [r.position_index for r in second] == list(range(6))
            assert cache.stats.hits == 6
            assert [comparable(r) for r in second] == \
                [comparable(r) for r in first]
            gos_after = sum(
                1 for path in echos.values() for r in read_echo(path)
                if r["t"] == "go"
            )
            assert gos_after == gos_before  # no member saw the re-ask
        finally:
            await coord.close()

        assert coord.health()["cache"]["hit_ratio"] == 0.5

    asyncio.run(scenario())


# -------------------------------------------------------------- quarantine


def test_poison_fingerprint_quarantined_fleet_wide(tmp_path):
    """A position whose fingerprint kills two different members is the
    poison, not the hosts: it gets quarantined fleet-wide, answered by
    the CPU fallback, and pre-routed on every later chunk so it never
    touches a member again."""

    async def scenario():
        chunk = make_chunk(n=3)
        # planning is deterministic: [p0,p2] -> first member, [p1] ->
        # second; make the LAST of the first member's positions the
        # poison so its earlier position is acked before the crash
        poison = position_fingerprint(chunk.positions[2])
        script = {"chunks": [f"crash-on-fp:{poison}"]}
        members = [
            fake_member("ma", script, tmp_path),
            fake_member("mb", script, tmp_path),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(),
            redispatch_max=4, loss_window=0.05,
        )
        try:
            await coord.start()
            responses = await coord.go_multiple(chunk)
            assert [r.position_index for r in responses] == [0, 1, 2]
            cps = [r.scores.best().value for r in responses]
            assert cps[0] == FAKE_CP and cps[1] == FAKE_CP
            assert cps[2] != FAKE_CP  # fallback answered the poison
            assert coord.stats.losses == 2
            assert coord.stats.quarantined == 1
            assert coord.stats.quarantine_routed == 1

            # second chunk, same fingerprints: pre-routed, no new loss
            chunk2 = make_chunk(n=3, batch="fleetjob2")
            responses2 = await coord.go_multiple(chunk2)
            cps2 = [r.scores.best().value for r in responses2]
            assert cps2[0] == FAKE_CP and cps2[1] == FAKE_CP
            assert cps2[2] != FAKE_CP
            assert coord.stats.losses == 2  # unchanged
            assert coord.stats.quarantine_routed == 2
        finally:
            await coord.close()

    asyncio.run(scenario())


# ------------------------------------------------------------ remote member


def test_remote_http_member_parity_with_local_engine():
    """A chunk through a remote member (HttpEngine -> ServeApp over
    PyEngine) answers identically to the same chunk through that engine
    directly: serve/protocol.py preserves the work definition across
    the hop (depth binds; the node budget survives within rounding)."""
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.serve.server import ServeApp

    async def scenario():
        app = ServeApp(
            EngineSession(PyEngine(max_depth=2),
                          flavor=EngineFlavor.OFFICIAL),
            registry=MetricsRegistry(),
            logger=Logger(verbose=0),
        )
        host, port = await app.start("127.0.0.1", 0)
        coord = FleetCoordinator(
            members_from_specs(f"http://{host}:{port}",
                               logger=Logger(verbose=0)),
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            loss_window=0.1,
        )
        try:
            chunk = make_chunk(n=3, depth=2, flavor=EngineFlavor.OFFICIAL,
                               moves=["e2e4"])
            remote = await coord.go_multiple(chunk)
            direct = await PyEngine(max_depth=2).go_multiple(
                make_chunk(n=3, depth=2, flavor=EngineFlavor.OFFICIAL,
                           moves=["e2e4"]))
            assert [r.position_index for r in remote] == [0, 1, 2]
            for a, b in zip(remote, direct):
                assert comparable(a) == comparable(b)
        finally:
            await coord.close()
            await app.drain_and_stop()

    asyncio.run(scenario())


def test_remote_member_error_surfaces_as_member_loss(tmp_path):
    """An unreachable HTTP member is a member loss like any other: the
    dispatch raises EngineError inside the coordinator, the work lands
    on a survivor, and the dead endpoint enters cooldown."""

    async def scenario():
        members = members_from_specs(
            # port 1 on loopback: connection refused, instantly
            "http://127.0.0.1:1,local*1",
            local_factory=lambda name: fake_member(
                name, {"chunks": ["ok"]}, tmp_path),
            logger=Logger(verbose=0),
        )
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(),
            redispatch_max=3, loss_window=5.0,
        )
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=4))
            assert [r.position_index for r in responses] == [0, 1, 2, 3]
            assert all(r.scores.best().value == FAKE_CP
                       for r in responses)
        finally:
            await coord.close()

        assert coord.stats.losses == 1
        assert coord.loss_log[0].member == "127.0.0.1:1"
        # remote members have no partial stream: the whole sub-chunk
        # was un-acked, so the whole sub-chunk re-dispatched
        ev = coord.loss_log[0]
        assert set(ev.redispatched_fps) == set(ev.inflight_fps)
        dead = coord.members[0]
        assert not dead.available()  # cooling down, out of admission

    asyncio.run(scenario())


# --------------------------------------------------------- merged obs pane


def test_merged_metrics_and_trace_tie_out(tmp_path, monkeypatch):
    """One registry and one trace ring describe the whole fleet: the
    folded gauges/counters equal the per-member ledgers, and the ring
    holds clock-synced spans from every member process."""
    monkeypatch.setenv("FISHNET_TPU_TRACE_DIR", str(tmp_path / "traces"))
    obs_trace.uninstall()

    async def scenario():
        members = [
            fake_member("m0", {"chunks": ["die-after:1", "ok"]}, tmp_path,
                        extra=["--trace-skew", "5.0"]),
            fake_member("m1", {"chunks": ["ok"]}, tmp_path,
                        extra=["--trace-skew", "0.0"]),
            fake_member("m2", {"chunks": ["ok"]}, tmp_path,
                        extra=["--trace-skew", "2.5"]),
        ]
        registry = MetricsRegistry()
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0), registry=registry,
            redispatch_max=3, loss_window=0.2,
        )
        t0_us = obs_trace.now_us()
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(n=6))
            assert len(responses) == 6
        finally:
            events = (obs_trace.RECORDER.snapshot()
                      if obs_trace.RECORDER else [])
            await coord.close()
        t1_us = obs_trace.now_us()

        # ---- metrics: the one registry equals the per-member ledgers
        snap = registry.snapshot()
        assert snap["fishnet_fleet_members_total"] == 3
        assert sum(
            snap[f"fishnet_fleet_dispatch_positions_total_{m.name}"]
            for m in members
        ) == coord.stats.dispatched_positions
        assert sum(
            snap[f"fishnet_fleet_losses_total_{m.name}"] for m in members
        ) == coord.stats.losses == 1
        assert snap["fishnet_fleet_redispatches"] == \
            coord.stats.redispatches
        # local members' own SupervisorStats fold in under their prefix
        assert snap["fishnet_fleet_member_m0_deaths"] >= 1
        assert snap["fishnet_fleet_member_m1_chunks_ok"] >= 1

        # ---- trace: spans from all three member processes, shifted
        # onto the parent clock despite 5.0s/2.5s child skews
        searches = [e for e in events if e.get("name") == "fake.search"]
        assert len({e.get("pid") for e in searches}) == 3
        slack = 1_000_000
        for e in searches:
            assert t0_us - slack <= e["ts"] <= t1_us + slack
        names = {e.get("name") for e in events}
        assert "fleet.dispatch" in names
        assert "fleet.member-loss" in names

    try:
        asyncio.run(scenario())
    finally:
        obs_trace.uninstall()


# -------------------------------------------------------------- no members


def test_all_members_lost_fails_loudly(tmp_path):
    """When every member is down and cooling, the chunk fails with an
    EngineError naming the stranded positions — never a silent drop."""

    async def scenario():
        coord = FleetCoordinator(
            [fake_member("m0", {"chunks": ["crash:9"]}, tmp_path)],
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            redispatch_max=2, loss_window=60.0,
        )
        try:
            with pytest.raises(EngineError, match="no live members"):
                await coord.go_multiple(make_chunk(n=2))
        finally:
            await coord.close()
        assert coord.stats.losses == 1

    asyncio.run(scenario())
