"""UCI subprocess adapter tests against the fake UCI engine."""
import asyncio
import os
import sys
import time

import pytest

from fishnet_tpu.client.ipc import Chunk, WorkPosition
from fishnet_tpu.client.wire import (
    AnalysisWork,
    EngineFlavor,
    MoveWork,
    NodeLimit,
    SkillLevel,
)
from fishnet_tpu.engine.base import EngineError
from fishnet_tpu.engine.uci import UciEngine

FAKE = os.path.join(os.path.dirname(__file__), "fake_uci.py")
START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


class FakeUci(UciEngine):
    """Run the fake engine via the current interpreter."""

    async def _ensure_started(self):
        if self.proc is not None and self.proc.returncode is None:
            return
        self.proc = await asyncio.create_subprocess_exec(
            sys.executable, FAKE,
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL,
            start_new_session=True,
        )
        self._initialized = False


def chunk_of(work, flavor, positions, variant="standard"):
    return Chunk(work=work, deadline=time.monotonic() + 30, variant=variant,
                 flavor=flavor, positions=positions)


def test_analysis_dialogue():
    async def main():
        engine = FakeUci("unused")
        work = AnalysisWork(
            id="ucijob01", nodes=NodeLimit(sf16=100000, classical=200000),
            timeout_s=10.0, multipv=2,
        )
        positions = [
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=["e2e4"][:i])
            for i in range(2)
        ]
        res = await engine.go_multiple(
            chunk_of(work, EngineFlavor.OFFICIAL, positions)
        )
        await engine.close()
        return res

    res = asyncio.run(main())
    assert len(res) == 2
    for r in res:
        assert r.scores.best() is not None
        assert r.best_move is not None
        assert len(r.scores.matrix) == 2  # multipv 2


def test_move_dialogue_and_variant_options():
    async def main():
        engine = FakeUci("unused")
        work = MoveWork(id="ucimv001", level=SkillLevel(3))
        positions = [
            WorkPosition(work=work, position_index=0, url=None, skip=False,
                         root_fen=START, moves=[]),
        ]
        res = await engine.go_multiple(
            chunk_of(work, EngineFlavor.MULTI_VARIANT, positions,
                     variant="kingOfTheHill")
        )
        await engine.close()
        return res

    (r,) = asyncio.run(main())
    assert r.best_move is not None


def test_spawn_failure_is_engine_error():
    async def main():
        engine = UciEngine("/nonexistent/engine/binary")
        work = MoveWork(id="ucimv002", level=SkillLevel(1))
        positions = [
            WorkPosition(work=work, position_index=0, url=None, skip=False,
                         root_fen=START, moves=[]),
        ]
        await engine.go_multiple(
            chunk_of(work, EngineFlavor.MULTI_VARIANT, positions)
        )

    with pytest.raises(EngineError):
        asyncio.run(main())
