"""Variant rules tests.

Shallow perft of most variants from the start equals standard chess (rule
differences only bite after the first capture/check), which pins the
inheritance wiring. Hand-computed anchors cover the divergent rules.
"""
import pytest

from fishnet_tpu.chess import Move, Position, perft
from fishnet_tpu.chess.variants import (
    AntichessPosition,
    AtomicPosition,
    CrazyhousePosition,
    HordePosition,
    KingOfTheHillPosition,
    RacingKingsPosition,
    ThreeCheckPosition,
    from_fen,
    position_class,
)


def test_variant_registry():
    assert position_class("standard") is Position
    assert position_class("threeCheck") is ThreeCheckPosition
    with pytest.raises(ValueError):
        position_class("shogi")


@pytest.mark.parametrize("cls", [ThreeCheckPosition, KingOfTheHillPosition,
                                 AtomicPosition, CrazyhousePosition])
def test_variant_shallow_perft_matches_standard(cls):
    pos = cls.initial()
    assert perft(pos, 1) == 20
    assert perft(pos, 2) == 400


def test_racing_kings_start():
    pos = RacingKingsPosition.initial()
    # hand-verified: Ne2{d4,f4,g3} (Nc3 would check), Ne1{xc2,d3,f3},
    # Bf2{e3,d4,c5,b6,a7,g3,h4}, Rg2{g3..g8}, Kh2{g3,h3}
    assert len(pos.legal_moves()) == 21


def test_racing_kings_win_and_rejoinder():
    pos = RacingKingsPosition.from_fen("4K3/8/8/8/8/8/1k6/8 b - - 0 1")
    # white king reached rank 8, black king too far: white wins
    assert pos.outcome() == (0, "king in the goal")
    pos = RacingKingsPosition.from_fen("4K3/1k6/8/8/8/8/8/8 b - - 0 1")
    # black can still step onto rank 8: game not over yet
    assert pos.outcome() is None
    both = pos.push_uci("b7b8")
    assert both.outcome() == (None, "both kings in the goal")


def test_horde_start():
    pos = HordePosition.initial()
    assert perft(pos, 1) == 8  # hand-verified
    assert perft(pos, 2) == 128  # hand-verified (17*4 + 15*4 black replies)
    # rank-1 horde pawns may double push once unblocked
    p = HordePosition.from_fen("k7/8/8/8/8/8/8/4P3 w - - 0 1")
    ucis = {m.uci() for m in p.legal_moves()}
    assert "e1e2" in ucis and "e1e3" in ucis


def test_horde_destroyed():
    pos = HordePosition.from_fen("k7/1P6/8/8/8/8/8/8 b - - 0 1")
    child = pos.push_uci("a8b7")  # black king captures the last horde pawn
    assert child.outcome() == (1, "horde destroyed")


def test_three_check_outcome():
    pos = ThreeCheckPosition.from_fen(
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 1+3 0 1"
    )
    assert pos.checks_given == [2, 0]
    # deliver the third check
    pos = ThreeCheckPosition.from_fen("4k3/8/8/8/8/8/8/4KQ2 w - - 1+3 0 1")
    child = pos.push_uci("f1f7")
    assert child.outcome() == (0, "three checks")


def test_koth_outcome():
    pos = KingOfTheHillPosition.from_fen("4k3/8/8/8/8/4K3/8/8 w - - 0 1")
    child = pos.push_uci("e3e4")
    assert child.outcome() == (0, "king in the center")


def test_atomic_explosion():
    # white queen takes d5 pawn: explosion removes knight c6 & bishop e6
    pos = AtomicPosition.from_fen("k7/8/2n1b3/3p4/8/8/8/K2Q4 w - - 0 1")
    child = pos.push_uci("d1d5")
    fen = child.to_fen()
    assert child.piece_at(35) is None  # queen exploded
    assert child.bbs[1][1] == 0  # knight gone
    assert child.bbs[1][2] == 0  # bishop gone


def test_atomic_pawns_survive_explosion():
    pos = AtomicPosition.from_fen("k7/8/8/2pp4/3P4/8/8/K7 w - - 0 1")
    child = pos.push_uci("d4c5")
    # captured c5 gone, capturer gone, but d5 pawn survives (pawns immune)
    assert child.piece_at(34) is None
    assert child.piece_at(35) is not None


def test_atomic_king_cannot_capture():
    pos = AtomicPosition.from_fen("k7/8/8/8/8/8/1p6/K7 w - - 0 1")
    ucis = {m.uci() for m in pos.legal_moves()}
    assert "a1b2" not in ucis


def test_atomic_adjacent_kings_no_check():
    pos = AtomicPosition.from_fen("8/8/8/8/8/1k6/1K6/4Q3 w - - 0 1")
    assert not pos.is_check()
    child = pos.push_uci("e1e3")  # queen checks... but kings adjacent
    assert not child.is_check()


def test_atomic_win_by_explosion():
    pos = AtomicPosition.from_fen("kr6/8/8/8/8/8/8/KQ6 w - - 0 1")
    child = pos.push_uci("b1b8")  # Qxb8 explodes the a8 king
    assert child.outcome() == (0, "king exploded")


def test_antichess_forced_capture():
    pos = AntichessPosition.from_fen("8/8/8/8/3p4/2P5/8/8 w - - 0 1")
    ucis = {m.uci() for m in pos.legal_moves()}
    assert ucis == {"c3d4"}  # capture is mandatory


def test_antichess_king_promotion_and_stalemate_win():
    pos = AntichessPosition.from_fen("8/P7/8/8/8/8/8/8 w - - 0 1")
    ucis = {m.uci() for m in pos.legal_moves()}
    assert "a7a8k" in ucis
    lost = AntichessPosition.from_fen("8/8/8/8/8/8/8/8 w - - 0 1")
    # no pieces: side to move wins
    assert lost.outcome() == (0, "all pieces lost")


def test_crazyhouse_pocket_and_drop():
    pos = CrazyhousePosition.from_fen(
        "rnbqkbnr/ppp1pppp/8/3p4/4P3/8/PPPP1PPP/RNBQKBNR w KQkq - 0 2"
    )
    child = pos.push_uci("e4d5")
    assert child.pockets[0][0] == 1  # white pawn in pocket
    fen = child.to_fen()
    assert "[P]" in fen
    # round-trip and drop
    again = CrazyhousePosition.from_fen(fen)
    assert again.pockets[0][0] == 1
    after_black = child.push_uci("g8f6")
    drop = after_black.push_uci("P@e5")
    assert drop.piece_at(36) == (0, 0)
    assert drop.pockets[0][0] == 0


def test_crazyhouse_no_pawn_drop_on_back_rank():
    pos = CrazyhousePosition.from_fen("k7/8/8/8/8/8/8/K7[Pp] w - - 0 1")
    ucis = {m.uci() for m in pos.legal_moves()}
    assert "P@e4" in ucis
    assert not any(u.startswith("P@") and (u.endswith("1") or u.endswith("8")) for u in ucis)


def test_crazyhouse_promoted_capture_gives_pawn():
    pos = CrazyhousePosition.from_fen("k6K/8/8/8/8/8/p7/1R6[] b - - 0 1")
    promoted = pos.push_uci("a2a1q")
    assert promoted.to_fen().startswith("k6K/8/8/8/8/8/8/q~R6")
    captured = promoted.push_uci("b1a1")
    assert captured.pockets[0][0] == 1  # promoted queen reverts to pawn
    assert captured.pockets[0][4] == 0
