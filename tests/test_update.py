"""Auto-updater end-to-end: fake bucket XML → self-replaced artifact →
restart wiring (reference: src/update.rs:13-61, src/main.rs:50-68,
180-200, 399-425)."""
import asyncio
import sys

import pytest

from fishnet_tpu.client import update
from fishnet_tpu.client.update import auto_update, current_target


class _Log:
    def __init__(self):
        self.lines = []

    def debug(self, m):
        self.lines.append(("D", m))

    def info(self, m):
        self.lines.append(("I", m))

    def warn(self, m):
        self.lines.append(("W", m))


def _bucket_xml(keys):
    items = "".join(
        f"<Contents><Key>{k}</Key></Contents>" for k in keys
    )
    return (
        '<?xml version="1.0"?>'
        '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        f"{items}</ListBucketResult>"
    )


def test_auto_update_swaps_artifact(tmp_path, monkeypatch):
    artifact = tmp_path / "fishnet-tpu.pyz"
    artifact.write_bytes(b"old-zipapp")
    monkeypatch.setattr(sys, "argv", [str(artifact), "run"])

    target = current_target()
    new_key = f"fishnet-tpu-v9.9.9-{target}.pyz"
    xml = _bucket_xml(
        [f"fishnet-tpu-v0.0.1-{target}.pyz", new_key, "other-v5.0.0-foo.pyz"]
    )
    fetched = []

    async def http_get(url):
        fetched.append(url)
        if url.endswith(new_key):
            return b"new-zipapp-bytes"
        return xml.encode()

    log = _Log()
    ver = asyncio.run(auto_update(http_get, "https://bucket.example/", log))
    assert ver == "9.9.9"
    assert artifact.read_bytes() == b"new-zipapp-bytes"
    assert fetched[-1].endswith(new_key)


def test_auto_update_up_to_date(tmp_path, monkeypatch):
    artifact = tmp_path / "fishnet-tpu.pyz"
    artifact.write_bytes(b"current")
    monkeypatch.setattr(sys, "argv", [str(artifact), "run"])
    xml = _bucket_xml([f"fishnet-tpu-v0.0.1-{current_target()}.pyz"])

    async def http_get(url):
        return xml.encode()

    ver = asyncio.run(auto_update(http_get, "https://bucket.example/", _Log()))
    assert ver is None
    assert artifact.read_bytes() == b"current"


def test_auto_update_noop_from_source_tree(monkeypatch):
    # running from a .py entry point: nothing replaceable, no fetches
    monkeypatch.setattr(sys, "argv", ["/some/tree/__main__.py", "run"])
    calls = []

    async def http_get(url):
        calls.append(url)
        return b""

    ver = asyncio.run(auto_update(http_get, "https://bucket.example/", _Log()))
    assert ver is None
    assert calls == []


def test_app_startup_update_then_restart(monkeypatch):
    """`run()` with --auto-update checks the bucket FIRST and re-execs on a
    new version (reference: src/main.rs:50-68)."""
    from fishnet_tpu.client import app
    from fishnet_tpu.client.configure import Config

    async def fake_auto_update(http_get, bucket, logger):
        return "9.9.9"

    class Restarted(BaseException):
        pass

    def fake_restart():
        raise Restarted

    monkeypatch.setattr(app, "auto_update", fake_auto_update)
    monkeypatch.setattr(app, "restart_process", fake_restart)

    cfg = Config(auto_update=True, key="testkey", cores=1)
    with pytest.raises(Restarted):
        asyncio.run(app.run(cfg))
