"""Request-scoped tracing tests: the context protocol (make_ctx /
ctx_from_wire / ctx_args), deterministic sampling, the flow-event
primitive, the in-flight registry, SLO accounting, the serve edge's
/debug/requests surface, the inflight CLI rendering, and the
LaneScheduler's per-position lifecycle spans.

The cross-process story (supervisor replay, fleet re-dispatch, the
merged flight dump) is covered by tools/chaos.py --scenario
request-trace in CI; this file pins the in-process contracts each hop
relies on — including the one that matters most: tracing on produces
bit-identical search results to tracing off.
"""
import asyncio
import contextlib
import io
import json
import socket
import time
import types

import pytest

from fishnet_tpu.client.ipc import Chunk, Matrix, PositionResponse, WorkPosition
from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit, Score
from fishnet_tpu.engine.tpu import TpuEngine
from fishnet_tpu.obs import inflight as obs_inflight
from fishnet_tpu.obs import trace as obs_trace
from fishnet_tpu.obs.metrics import MetricsRegistry, SloRecorder
from fishnet_tpu.serve.server import ServeApp

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
GAME = ["e2e4", "c7c5", "g1f3", "d7d6"]


# ------------------------------------------------------- context protocol


def test_make_ctx_mints_ids_and_truncates():
    ctx = obs_trace.make_ctx("t" * 40, "k" * 20, deadline_ms=250)
    assert set(ctx) == set(obs_trace.CTX_KEYS)
    assert len(ctx["trace_id"]) == 16
    assert len(ctx["span_id"]) == 16
    int(ctx["trace_id"], 16)  # hex
    assert ctx["tenant"] == "t" * 32
    assert ctx["kind"] == "k" * 16
    assert ctx["deadline_ms"] == 250
    # ids are fresh per stamp
    assert obs_trace.make_ctx("a", "b")["trace_id"] != ctx["trace_id"]


def test_make_ctx_reuses_upstream_trace_id():
    ctx = obs_trace.make_ctx("t", "analysis", trace_id="feedc0defeedc0de")
    assert ctx["trace_id"] == "feedc0defeedc0de"
    assert ctx["span_id"] != ctx["trace_id"]


def test_ctx_from_wire_round_trip():
    ctx = obs_trace.make_ctx("team-a", "bestmove", deadline_ms=900)
    assert obs_trace.ctx_from_wire(dict(ctx)) == ctx
    # survives a JSON hop (the pipe / HTTP re-dispatch path)
    assert obs_trace.ctx_from_wire(json.loads(json.dumps(ctx))) == ctx


@pytest.mark.parametrize(
    "junk",
    [None, 7, "feedc0de", [], {}, {"trace_id": ""}, {"span_id": "x"}],
)
def test_ctx_from_wire_rejects_junk(junk):
    assert obs_trace.ctx_from_wire(junk) is None


def test_ctx_from_wire_truncates_oversized_ids():
    ctx = obs_trace.ctx_from_wire({"trace_id": "a" * 99, "span_id": "b" * 99})
    assert ctx["trace_id"] == "a" * 32
    assert ctx["span_id"] == "b" * 32


def test_ctx_args_annotation():
    ctx = obs_trace.make_ctx("team-a", "analysis")
    args = obs_trace.ctx_args(ctx, lane=3)
    assert args == {
        "trace_id": ctx["trace_id"],
        "tenant": "team-a",
        "kind": "analysis",
        "lane": 3,
    }
    # no context degrades to just the extras, never a crash
    assert obs_trace.ctx_args(None, lane=3) == {"lane": 3}


# ------------------------------------------------------------- sampling


def test_sampled_rate_bounds(monkeypatch):
    ids = [obs_trace.new_id() for _ in range(64)]
    monkeypatch.setenv("FISHNET_TPU_TRACE_SAMPLE", "1.0")
    assert all(obs_trace.sampled(t) for t in ids)
    monkeypatch.setenv("FISHNET_TPU_TRACE_SAMPLE", "0.0")
    assert not any(obs_trace.sampled(t) for t in ids)


def test_sampled_mid_rate_is_deterministic(monkeypatch):
    """The verdict is a pure function of the trace_id — every process
    that sees the id reaches the same decision with no coordination."""
    monkeypatch.setenv("FISHNET_TPU_TRACE_SAMPLE", "0.5")
    ids = [obs_trace.new_id() for _ in range(256)]
    verdicts = [obs_trace.sampled(t) for t in ids]
    assert verdicts == [obs_trace.sampled(t) for t in ids]  # stable
    assert any(verdicts) and not all(verdicts)  # actually samples
    # junk rates fall back to trace-everything, never crash
    monkeypatch.setenv("FISHNET_TPU_TRACE_SAMPLE", "not-a-rate")
    assert obs_trace.sampled(ids[0])


# ------------------------------------------------------- flow primitive


def test_flow_event_shape():
    rec = obs_trace.TraceRecorder(capacity=64)
    rec.flow("request", 12345, "s")
    rec.flow("request", "feedc0de", "t")
    rec.flow("request", "feedc0de", "f")
    s, t, f = rec.snapshot()
    assert s["ph"] == "s" and s["id"] == "12345"  # ids coerced to str
    assert t["ph"] == "t" and "bp" not in t
    # the finish binds to the enclosing slice's END, not the next start
    assert f["ph"] == "f" and f["bp"] == "e"
    assert all(e["name"] == "request" for e in (s, t, f))
    with pytest.raises(ValueError):
        rec.flow("request", "feedc0de", "x")


def test_flow_ids_survive_absorb_shift():
    """Clock-sync absorb() shifts timestamps; flow ids are strings and
    must come through untouched or the arrows break at process seams."""
    child = obs_trace.TraceRecorder(capacity=64)
    child.flow("request", "feedc0de", "t")
    parent = obs_trace.TraceRecorder(capacity=64)
    child_ev = child.snapshot()[0]
    assert parent.absorb(child.drain(), offset_us=1_000_000.0) == 1
    merged = parent.snapshot()[0]
    assert merged["id"] == "feedc0de"
    assert merged["ts"] == pytest.approx(child_ev["ts"] + 1_000_000.0)


# ------------------------------------------------------ inflight registry


def test_inflight_lifecycle_and_snapshot():
    reg = obs_inflight.InflightRegistry()
    reg.begin("tid-1", "req-1", "team-a", "analysis",
              deadline_mono_s=time.monotonic() + 5.0, n_positions=2)
    assert len(reg) == 1
    reg.stage("tid-1", "admitted")
    reg.stage("tid-1", "dispatched")
    # stages are monotone: a replayed position must not rewind the view
    reg.stage("tid-1", "received")
    reg.position("tid-1", 0, "lane", lane=3)
    reg.position("tid-1", 1, "queued")
    (snap,) = reg.snapshot()
    assert snap["trace_id"] == "tid-1"
    assert snap["id"] == "req-1"
    assert snap["stage"] == "lane"  # position progress bumped the stage
    assert snap["lanes"] == [3]
    assert snap["positions"] == {
        "0": {"stage": "lane", "lane": 3},
        "1": {"stage": "queued", "lane": None},
    }
    assert snap["age_ms"] >= 0.0
    assert 0.0 < snap["slack_ms"] <= 5000.0
    json.dumps(snap)  # the /debug/requests payload must be JSON-safe
    reg.end("tid-1")
    assert len(reg) == 0 and reg.snapshot() == []


def test_inflight_ignores_empty_and_unknown_ids():
    reg = obs_inflight.InflightRegistry()
    reg.begin("", "req", "t", "analysis")  # unstamped path: no-op
    reg.stage("", "admitted")
    reg.stage("nobody", "admitted")  # client-path ctx nobody begin()s
    reg.position("nobody", 0, "lane", lane=1)
    reg.end("nobody")
    assert len(reg) == 0


def test_inflight_snapshot_oldest_first():
    reg = obs_inflight.InflightRegistry()
    reg.begin("tid-a", "a", "t", "analysis")
    reg.begin("tid-b", "b", "t", "analysis")
    assert [e["trace_id"] for e in reg.snapshot()] == ["tid-a", "tid-b"]
    # no deadline → slack is unknown, not a crash
    assert reg.snapshot()[0]["slack_ms"] is None


# --------------------------------------------------------- SLO recorder


def test_slo_observe_clamps_the_split():
    """queue ≤ total, device ≤ total − queue, host = the remainder —
    the three shares can never sum past the latency they explain."""
    registry = MetricsRegistry()
    slo = SloRecorder(registry)
    slo.observe("team-a", "analysis", 100.0, queue_ms=150.0, device_ms=80.0)
    snap = registry.snapshot()
    # registry names sanitize the tenant's dash to an underscore
    assert snap["fishnet_slo_latency_ms_analysis_team_a_sum"] == 100.0
    assert snap["fishnet_slo_queue_ms_analysis_team_a_sum"] == 100.0
    assert snap["fishnet_slo_device_ms_analysis_team_a_sum"] == 0.0
    assert snap["fishnet_slo_host_ms_analysis_team_a_sum"] == 0.0
    assert snap["fishnet_slo_requests_total_analysis_team_a"] == 1
    assert "fishnet_slo_deadline_miss_total_analysis_team_a" not in snap


def test_slo_counters_and_prometheus_render():
    registry = MetricsRegistry()
    slo = SloRecorder(registry)
    slo.observe("bot", "bestmove", 40.0, queue_ms=10.0, device_ms=25.0,
                deadline_missed=True)
    slo.shed("bot", "bestmove")
    snap = registry.snapshot()
    assert snap["fishnet_slo_deadline_miss_total_bestmove_bot"] == 1
    assert snap["fishnet_slo_shed_total_bestmove_bot"] == 1
    assert snap["fishnet_slo_host_ms_bestmove_bot_sum"] == 5.0
    text = registry.render_prometheus()
    assert "fishnet_slo_latency_ms_bestmove_bot_count 1" in text
    assert 'fishnet_slo_latency_ms_bestmove_bot_bucket{le="+Inf"} 1' in text


# ------------------------------------------------- serve edge + registry


async def _http(host, port, method, path, obj=None, headers=None):
    """One-shot HTTP/1.1 client over asyncio streams, with headers."""
    reader, writer = await asyncio.open_connection(host, port)
    body = json.dumps(obj).encode("utf-8") if obj is not None else b""
    head = [
        f"{method} {path} HTTP/1.1", f"Host: {host}",
        f"Content-Length: {len(body)}", "Connection: close",
    ]
    head.extend(f"{k}: {v}" for k, v in (headers or {}).items())
    writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head_raw, _, payload = raw.partition(b"\r\n\r\n")
    status = int(head_raw.decode("latin-1").split("\r\n")[0].split()[1])
    return status, json.loads(payload) if payload else {}


def _fake_response(i=0):
    scores = Matrix()
    scores.set(1, 2, Score.cp(13))
    pvs = Matrix()
    pvs.set(1, 2, ["e2e4"])
    return PositionResponse(
        work=None, position_index=i, url=None, scores=scores, pvs=pvs,
        best_move="e2e4", depth=2, nodes=100, time_s=0.01, nps=10_000,
    )


class GatedSession:
    """Stub EngineSession parking on a gate so the request stays
    observable in flight; remembers the ctx each position carried."""

    def __init__(self):
        self.gate = asyncio.Event()
        self.seen_ctx = []

    async def submit_many(self, requests):
        self.seen_ctx = [r.ctx() for r in requests]
        await asyncio.wait_for(self.gate.wait(), timeout=30.0)
        return [_fake_response(i) for i in range(len(requests))]


@pytest.fixture
def recorder():
    rec = obs_trace.install(obs_trace.TraceRecorder(capacity=4096,
                                                    process_name="test"))
    try:
        yield rec
    finally:
        obs_trace.uninstall()


def _body(tid=""):
    body = {
        "id": "req-trace-1",
        "tenant": "team-a",
        "positions": [{"fen": START, "moves": ["e2e4"]},
                      {"fen": START, "moves": []}],
        "depth": 2,
        "timeout_ms": 8000,
    }
    if tid:
        body["trace_id"] = tid
    return body


def test_debug_requests_and_edge_spans(recorder):
    """One traced request through the HTTP edge: /debug/requests shows
    it mid-flight at its stage, the context reaches the session's
    PositionRequests, the SLO histograms move, and the ring holds the
    edge spans + the s/f flow pair under the client's trace_id."""
    tid = "feedc0defeedc0de"

    async def scenario():
        registry = MetricsRegistry()
        session = GatedSession()
        app = ServeApp(session, max_inflight=4, max_queue=4,
                       default_timeout_ms=8000, drain_s=5.0,
                       registry=registry)
        host, port = await app.start("127.0.0.1", 0)
        try:
            post = asyncio.ensure_future(
                _http(host, port, "POST", "/analyse", _body(tid))
            )
            seen = None
            for _ in range(200):
                _, dbg = await _http(host, port, "GET", "/debug/requests")
                hits = [e for e in dbg["requests"]
                        if e["trace_id"] == tid]
                if hits:
                    seen = hits[0]
                    if seen["stage"] == "dispatched":
                        break
                await asyncio.sleep(0.02)
            session.gate.set()
            status, payload = await asyncio.wait_for(post, timeout=10.0)
            _, dbg = await _http(host, port, "GET", "/debug/requests")
            return status, payload, seen, dbg, registry, session
        finally:
            await app.drain_and_stop()

    status, payload, seen, dbg, registry, session = asyncio.run(scenario())
    assert status == 200 and len(payload["results"]) == 2
    # live introspection caught the request at its dispatch stage
    assert seen is not None and seen["stage"] == "dispatched"
    assert seen["tenant"] == "team-a" and seen["n_positions"] == 2
    assert dbg["inflight"] == 0 and dbg["requests"] == []  # end() ran
    # the edge context rode next to the work into the session
    assert [c["trace_id"] for c in session.seen_ctx] == [tid, tid]
    # SLO accounting keyed by (kind, tenant) observed it
    snap = registry.snapshot()
    assert snap["fishnet_slo_requests_total_analysis_team_a"] == 1
    assert snap["fishnet_slo_device_ms_analysis_team_a_sum"] > 0.0
    # and the ring carries the waterfall: spans, flow pair, slo instant
    events = obs_trace.RECORDER.snapshot()
    mine = [e for e in events if (e.get("args") or {}).get("trace_id") == tid]
    names = {e["name"] for e in mine}
    assert {"http.request", "serve.admission", "slo.observe"} <= names
    http_span = next(e for e in mine if e["name"] == "http.request")
    assert http_span["ph"] == "X" and http_span["args"]["n"] == 2
    slo_ev = next(e for e in mine if e["name"] == "slo.observe")
    assert slo_ev["args"]["total_ms"] >= slo_ev["args"]["queue_ms"]
    flows = [e for e in events
             if e["name"] == "request" and e.get("id") == tid]
    assert {"s", "f"} <= {e["ph"] for e in flows}


def test_trace_header_stamps_the_context(recorder):
    """X-Fishnet-Trace alone (no body field) names the request's id."""
    tid = "ab1ef1ee7ab1ef1e"

    async def scenario():
        session = GatedSession()
        session.gate.set()  # no need to observe mid-flight here
        app = ServeApp(session, max_inflight=4, max_queue=4,
                       default_timeout_ms=8000, drain_s=5.0,
                       registry=MetricsRegistry())
        host, port = await app.start("127.0.0.1", 0)
        try:
            return await _http(host, port, "POST", "/analyse", _body(),
                               headers={"X-Fishnet-Trace": tid})
        finally:
            await app.drain_and_stop()

    status, _ = asyncio.run(scenario())
    assert status == 200
    events = obs_trace.RECORDER.snapshot()
    assert any(e["name"] == "http.request"
               and (e.get("args") or {}).get("trace_id") == tid
               for e in events)


def test_inflight_cli_renders_live_requests():
    """`fishnet-tpu inflight` against a live serve process: one row per
    in-flight request with stage and progress columns."""
    from fishnet_tpu.client.app import run_inflight

    tid = "c0ffeec0ffeec0ff"

    async def scenario():
        session = GatedSession()
        app = ServeApp(session, max_inflight=4, max_queue=4,
                       default_timeout_ms=8000, drain_s=5.0,
                       registry=MetricsRegistry())
        host, port = await app.start("127.0.0.1", 0)
        try:
            post = asyncio.ensure_future(
                _http(host, port, "POST", "/analyse", _body(tid))
            )
            for _ in range(200):
                _, dbg = await _http(host, port, "GET", "/debug/requests")
                if any(e["trace_id"] == tid for e in dbg["requests"]):
                    break
                await asyncio.sleep(0.02)

            def cli():
                cfg = types.SimpleNamespace(serve_host=host, serve_port=port)
                out = io.StringIO()
                with contextlib.redirect_stdout(out):
                    rc = run_inflight(cfg)
                return rc, out.getvalue()

            rc, out = await asyncio.get_running_loop().run_in_executor(
                None, cli
            )
            session.gate.set()
            await asyncio.wait_for(post, timeout=10.0)
            return rc, out
        finally:
            await app.drain_and_stop()

    rc, out = asyncio.run(scenario())
    assert rc == 0
    assert "1 request(s) in flight" in out
    assert tid in out and "team-a" in out and "dispatched" in out


def test_inflight_cli_unreachable_server():
    from fishnet_tpu.client.app import run_inflight

    with socket.socket() as s:  # a port nothing listens on
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = run_inflight(types.SimpleNamespace(serve_host="127.0.0.1",
                                                serve_port=port))
    assert rc == 1
    assert "cannot reach" in out.getvalue()


# ------------------------------------------- LaneScheduler lifecycle spans


def _analysis_work(depth=3):
    return AnalysisWork(
        id="reqtrace01",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=30.0,
        depth=depth,
        multipv=None,
    )


def _make_chunk(n_positions=3, ctx=None):
    positions = [
        WorkPosition(work=_analysis_work(), position_index=i, url=None,
                     skip=False, root_fen=START, moves=GAME[:i],
                     ctx=dict(ctx) if ctx else None)
        for i in range(n_positions)
    ]
    return Chunk(work=_analysis_work(), deadline=time.monotonic() + 120,
                 variant="standard", flavor=EngineFlavor.TPU,
                 positions=positions)


def _make_refill_engine():
    # same shapes as tests/test_refill.py so the jitted programs are
    # shared in-process; mesh=None pins single-device semantics
    engine = TpuEngine(refill=True, max_depth=3, tt_size_log2=0,
                       helper_lanes=1)
    engine.mesh = None
    engine.n_dev = 1
    return engine


def test_refill_lifecycle_spans_and_bit_identity(recorder):
    """A traced chunk through the refill scheduler leaves the full
    per-position lifecycle on the ring — queued → lane residency →
    delivered, plus segment.residency windows — all under the request's
    trace_id; and the traced results are bit-identical to an untraced
    run of the same chunk."""
    ctx = obs_trace.make_ctx("team-a", "analysis", deadline_ms=30_000)
    tid = ctx["trace_id"]
    traced = asyncio.run(
        _make_refill_engine().go_multiple(_make_chunk(ctx=ctx))
    )
    assert len(traced) == 3

    events = obs_trace.RECORDER.snapshot()
    mine = [e for e in events if (e.get("args") or {}).get("trace_id") == tid]
    by_name = {}
    for e in mine:
        by_name.setdefault(e["name"], []).append(e)
    # one queued + one delivered instant per position, indices intact
    for name in ("position.queued", "position.delivered"):
        evs = by_name.get(name, [])
        assert {e["args"]["position_index"] for e in evs} == {0, 1, 2}, name
        assert all(e["ph"] == "i" for e in evs)
    # one retroactive lane-residency span per position, real duration
    lanes = by_name.get("position.lane", [])
    assert {e["args"]["position_index"] for e in lanes} == {0, 1, 2}
    assert all(e["ph"] == "X" and e["dur"] >= 0.0 for e in lanes)
    assert all(e["args"]["error"] is None for e in lanes)
    # segment residency: which lanes the request occupied per segment
    residency = by_name.get("segment.residency", [])
    assert residency, "no segment.residency spans on the ring"
    assert all("lane" in e["args"] and e["dur"] >= 0.0 for e in residency)
    # the flow chain threads the scheduler hops under the same id
    flows = [e for e in events
             if e["name"] == "request" and e.get("id") == tid]
    assert len(flows) >= 6  # ≥ queued + delivered per position
    # nobody begin()'d this ctx here: the engine's registry updates are
    # harmless no-ops, not phantom entries
    assert not any(e["trace_id"] == tid
                   for e in obs_inflight.REGISTRY.snapshot())

    obs_trace.uninstall()
    plain = asyncio.run(_make_refill_engine().go_multiple(_make_chunk()))
    for w, g in zip(plain, traced):
        assert g.position_index == w.position_index
        assert g.best_move == w.best_move
        assert g.depth == w.depth
        assert g.nodes == w.nodes
        assert g.scores.matrix == w.scores.matrix
        assert g.pvs.matrix == w.pvs.matrix


def test_debug_perf_surface():
    """GET /debug/perf returns the JSON-safe perf snapshot: build info,
    env fingerprint, program/metric tables, and the ledger baseline
    column (None here — no ledger seeded)."""

    async def scenario():
        session = GatedSession()
        app = ServeApp(session, max_inflight=4, max_queue=4,
                       default_timeout_ms=8000, drain_s=5.0)
        host, port = await app.start("127.0.0.1", 0)
        try:
            return await _http(host, port, "GET", "/debug/perf")
        finally:
            await app.drain_and_stop()

    status, snap = asyncio.run(scenario())
    assert status == 200
    json.dumps(snap)  # must be JSON-safe end to end
    assert "git_sha" in snap["build"]
    assert "fingerprint" in snap and "programs" in snap
    assert isinstance(snap["metrics"], dict)
