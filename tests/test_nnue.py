"""NNUE tests: device vs numpy-reference parity, save/load round-trip, and
board768 incremental accumulator correctness along real game playouts."""
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops.board import from_position, make_move, move_piece_changes


@pytest.fixture(scope="module", params=["halfkav2_hm", "board768"])
def params(request):
    return nnue.init_params(
        jax.random.PRNGKey(3), l1=32, h1=8, h2=8, feature_set=request.param
    )


FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 b - - 0 1",
    "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
]


def test_device_matches_reference(params):
    ev = jax.jit(nnue.evaluate)
    for fen in FENS:
        b = from_position(Position.from_fen(fen))
        got = float(ev(params, b.board, b.stm))
        want = nnue.evaluate_reference(params, np.asarray(b.board), int(b.stm))
        assert abs(got - want) < 0.5, fen


def test_bf16_quantization_tolerance(params):
    """bf16-cast weights must evaluate within a few centipawns of the f32
    master (SURVEY §7.2 quantization, validated with int tolerance)."""
    q = nnue.cast_params(params, jnp.bfloat16)
    assert q.ft_w.dtype == jnp.bfloat16
    ev = jax.jit(nnue.evaluate)
    for fen in FENS:
        b = from_position(Position.from_fen(fen))
        f32 = float(ev(params, b.board, b.stm))
        bf16 = float(ev(q, b.board, b.stm))
        assert abs(f32 - bf16) <= 8.0, (fen, f32, bf16)


def test_bf16_search_runs_and_stays_close(params):
    """A depth-2 search under bf16 weights completes and scores within
    quantization tolerance of the f32 search."""
    if not nnue.is_board768(params):
        pytest.skip("search fast path")
    from fishnet_tpu.ops.board import stack_boards
    from fishnet_tpu.ops.search import search_batch_jit

    boards = [from_position(Position.from_fen(f)) for f in FENS]
    roots = stack_boards(boards * 4)  # 16 lanes, the shared test shape
    q = nnue.cast_params(params, jnp.bfloat16)
    a = search_batch_jit(params, roots, 2, 50_000, max_ply=4)
    b = search_batch_jit(q, roots, 2, 50_000, max_ply=4)
    sa = np.asarray(a["score"])[: len(FENS)]
    sb = np.asarray(b["score"])[: len(FENS)]
    # quantization can flip close move choices; scores must stay close
    assert np.all(np.abs(sa - sb) <= 30), (sa, sb)


def test_int8_quantization_tolerance(params):
    """int8 fixed-point evals must stay within quantization error of the
    f32 master (QW=64 weight steps dominate; tolerance sized to that)."""
    if not nnue.is_board768(params):
        pytest.skip("int8 path is board768-only")
    q = nnue.quantize_int8(params)
    assert nnue.is_int8(q) and q.l1_w.dtype == jnp.int8
    ev = jax.jit(nnue.evaluate)
    for fen in FENS:
        b = from_position(Position.from_fen(fen))
        f32 = float(ev(params, b.board, b.stm))
        i8 = float(ev(q, b.board, b.stm))
        assert abs(f32 - i8) <= 25.0, (fen, f32, i8)


def test_int8_incremental_is_exact(params):
    """Integer accumulators make incremental updates EXACTLY equal to a
    refresh — no tolerance (the whole point of the int path)."""
    if not nnue.is_board768(params):
        pytest.skip("int8 path is board768-only")
    import random

    q = nnue.quantize_int8(params)
    upd = jax.jit(
        lambda b, acc, mv: nnue.apply_acc_updates_768(
            q, acc, *move_piece_changes(b, mv)
        )
    )
    refresh = jax.jit(lambda board: nnue.accumulators_768(q, board))
    mk = jax.jit(make_move)
    rng = random.Random(5)
    pos = Position.from_fen(FENS[1])
    b = from_position(pos)
    acc = refresh(b.board)
    for _ in range(12):
        moves = pos.legal_moves()
        if not moves:
            break
        mv = rng.choice(moves)
        enc = mv.from_sq | (mv.to_sq << 6)
        if mv.promotion is not None:
            enc |= {1: 1, 2: 2, 3: 3, 4: 4}[mv.promotion] << 12
        acc = upd(b, acc, enc)
        pos = pos.push(mv)
        b = from_position(pos)
        np.testing.assert_array_equal(
            np.asarray(acc), np.asarray(refresh(b.board))
        )


def test_int8_search_runs(params):
    """A depth-2 search under int8 weights completes with sane scores."""
    if not nnue.is_board768(params):
        pytest.skip("int8 path is board768-only")
    from fishnet_tpu.ops.board import stack_boards
    from fishnet_tpu.ops.search import search_batch_jit

    boards = [from_position(Position.from_fen(f)) for f in FENS]
    roots = stack_boards(boards * 4)  # 16 lanes, the shared test shape
    q = nnue.quantize_int8(params)
    a = search_batch_jit(params, roots, 2, 50_000, max_ply=4)
    b = search_batch_jit(q, roots, 2, 50_000, max_ply=4)
    sa = np.asarray(a["score"])[: len(FENS)]
    sb = np.asarray(b["score"])[: len(FENS)]
    assert np.all(np.abs(sa - sb) <= 60), (sa, sb)


def test_save_load_roundtrip(tmp_path, params):
    path = tmp_path / "net.npz"
    nnue.save_params(params, path)
    loaded = nnue.load_params(path)
    b = from_position(Position.from_fen(FENS[1]))
    a = float(nnue.evaluate(params, b.board, b.stm))
    c = float(nnue.evaluate(loaded, b.board, b.stm))
    assert abs(a - c) < 1e-3


def test_incremental_accumulator_matches_refresh():
    params = nnue.init_params(jax.random.PRNGKey(7), l1=32, feature_set="board768")
    upd = jax.jit(
        lambda b, acc, mv: nnue.apply_acc_updates_768(
            params, acc, *move_piece_changes(b, mv)
        )
    )
    refresh = jax.jit(lambda board: nnue.accumulators_768(params, board))
    mk = jax.jit(make_move)

    rng = random.Random(11)
    for fen in [FENS[0], FENS[1]]:
        pos = Position.from_fen(fen)
        b = from_position(pos)
        acc = refresh(b.board)
        for _ in range(40):
            legal = pos.legal_moves()
            if not legal or pos.outcome() is not None:
                break
            move = rng.choice(legal)
            from test_device_board import encode_host_move

            enc = encode_host_move(move)
            acc = upd(b, acc, jnp.int32(enc))
            b = mk(b, jnp.int32(enc))
            pos = pos.push(move)
            fresh = refresh(b.board)
            err = float(jnp.max(jnp.abs(acc - fresh)))
            assert err < 1e-3, f"acc drift {err} after {move.uci()} in {pos.to_fen()}"
