"""Property tests: device movegen/make_move vs the perft-validated host
rules library over random playouts.

The device generator is pseudo-legal with legality-checked castling — which
is exactly what the host's generate_pseudo_legal + _castling_moves produce,
so the move *sets* must match square-for-square.
"""
import random

import jax
import numpy as np
import pytest

from fishnet_tpu.chess import Move, Position
from fishnet_tpu.chess.position import Chess960Position
from fishnet_tpu.ops import tables as T
from fishnet_tpu.ops.board import Board, from_position, in_check, make_move
from fishnet_tpu.ops.movegen import generate_moves

_PROMO_MAP = {1: T.PROMO_N, 2: T.PROMO_B, 3: T.PROMO_R, 4: T.PROMO_Q}


def encode_host_move(m: Move) -> int:
    promo = _PROMO_MAP[m.promotion] if m.promotion is not None else 0
    return m.from_sq | (m.to_sq << 6) | (promo << 12)


def host_pseudo_set(pos: Position):
    return {encode_host_move(m) for m in pos.generate_pseudo_legal()}


@pytest.fixture(scope="module")
def kernels():
    return jax.jit(generate_moves), jax.jit(make_move), jax.jit(in_check)


def device_move_set(gen, pos: Position):
    moves, count, _noisy = gen(from_position(pos))
    return set(np.asarray(moves)[: int(count)].tolist())


def boards_equal(b1: Board, b2: Board) -> bool:
    return (
        np.array_equal(np.asarray(b1.board), np.asarray(b2.board))
        and int(b1.stm) == int(b2.stm)
        and int(b1.ep) == int(b2.ep)
        and sorted(np.asarray(b1.castling).tolist())
        == sorted(np.asarray(b2.castling).tolist())
        and int(b1.halfmove) == int(b2.halfmove)
    )


def _playout_check(kernels, pos: Position, plies: int, rng: random.Random):
    gen, mk, chk = kernels
    for ply in range(plies):
        legal = pos.legal_moves()
        if not legal:
            break
        host_set = host_pseudo_set(pos)
        dev_set = device_move_set(gen, pos)
        assert dev_set == host_set, (
            f"move set mismatch at ply {ply}\nfen={pos.to_fen()}\n"
            f"host-only={sorted(host_set - dev_set)}\n"
            f"device-only={sorted(dev_set - host_set)}"
        )
        assert bool(chk(from_position(pos))) == pos.is_check()
        move = rng.choice(legal)
        child = pos.push(move)
        dev_child = mk(from_position(pos), encode_host_move(move))
        assert boards_equal(dev_child, from_position(child)), (
            f"make_move mismatch at ply {ply}: {move.uci()}\n"
            f"fen={pos.to_fen()} → {child.to_fen()}"
        )
        pos = child


def test_random_playouts_standard(kernels):
    rng = random.Random(42)
    for game in range(6):
        _playout_check(kernels, Position.initial(), 60, rng)


def test_playouts_tactical_fens(kernels):
    rng = random.Random(7)
    fens = [
        # kiwipete: castling + pins + promos nearby
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        # CPW pos 4: promotions and underpromotions
        "r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1",
        # en-passant rich
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    ]
    for fen in fens:
        for _ in range(3):
            _playout_check(kernels, Position.from_fen(fen), 40, rng)


def test_playouts_chess960(kernels):
    rng = random.Random(3)
    fens = [
        "bqnb1rkr/pp3ppp/3ppn2/2p5/5P2/P2P4/NPP1P1PP/BQ1BNRKR w HFhf - 2 9",
        "b1q1rrkb/pppppppp/3nn3/8/P7/1PPP4/4PPPP/BQNNRKRB w GE - 1 9",
    ]
    for fen in fens:
        for _ in range(3):
            _playout_check(kernels, Chess960Position.from_fen(fen), 40, rng)


def test_castling_move_application(kernels):
    _, mk, _ = kernels
    pos = Position.from_fen("r3k2r/8/8/8/8/8/8/R3K2R w KQkq - 0 1")
    child = pos.push_uci("e1h1")
    dev = mk(from_position(pos), encode_host_move(pos.parse_uci("e1h1")))
    assert boards_equal(dev, from_position(child))
    child_q = pos.push_uci("e1a1")
    dev_q = mk(from_position(pos), encode_host_move(pos.parse_uci("e1a1")))
    assert boards_equal(dev_q, from_position(child_q))


def test_history_ordering_uses_correct_slot_both_colors():
    """Pins the _hist_idx_tables mirror (ops/movegen.py): a history bump
    on one specific quiet move's from|to slot must pull exactly THAT move
    to the front of the quiet tail, for white and for black. A misaligned
    static index table would credit a different candidate slot."""
    import jax.numpy as jnp

    cases = [
        # (fen, uci of a late quiet move expected to jump the quiet tail)
        ("rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", "h2h3"),
        ("rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR b KQkq - 0 1", "h7h6"),
    ]
    gen = jax.jit(
        lambda b, h: generate_moves(b, killers=jnp.asarray([-1, -1]), hist=h)
    )
    for fen, uci in cases:
        pos = Position.from_fen(fen)
        mv = encode_host_move(pos.parse_uci(uci))
        # two INDEPENDENT buffers: jnp.asarray of a numpy array can be
        # zero-copy on CPU and dispatch is async, so mutating the base
        # buffer in place raced the base computation (seen under full
        # suite load: the base run read the already-bumped table)
        hist0 = np.zeros(4096, np.int32)
        hist1 = np.zeros(4096, np.int32)
        hist1[mv & 4095] = 1 << 16
        base_moves, count, noisy = gen(from_position(pos), jnp.asarray(hist0))
        moves, count, noisy = gen(from_position(pos), jnp.asarray(hist1))
        moves = np.asarray(moves)[: int(count)].tolist()
        quiet_tail = moves[int(noisy):]
        # castling (key 900) sorts before history-bumped quiets (911+),
        # so the bumped move must lead the quiet tail modulo castling
        assert mv in quiet_tail
        assert quiet_tail.index(mv) <= 1, (uci, quiet_tail[:4])
        # and without the bump the move must NOT already be first
        base_tail = np.asarray(base_moves)[: int(count)].tolist()[int(noisy):]
        assert base_tail.index(mv) > 1


def test_hist_index_tables_match_candidates():
    """Exhaustive pin of the _hist_idx_tables mirror: for every variant
    table shape and both colors, the static from|to index table must
    equal `cand & 4095` for EVERY candidate slot the traced assembly
    produces (castling slots excepted — they hold 0 in the table and are
    never history-adjusted because their ordering key is 900)."""
    from fishnet_tpu.chess.variants import from_fen as v_from_fen
    from fishnet_tpu.ops.movegen import _candidate_space, _hist_idx_tables

    fens = {
        0: "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        1: "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR b KQkq - 0 1",
    }
    # the three distinct table shapes: standard (4 promos), antichess
    # (5 promos incl. king), crazyhouse (+ drop section)
    for variant in ("standard", "antichess", "crazyhouse"):
        tables = _hist_idx_tables(variant)
        space = jax.jit(lambda b: _candidate_space(b, variant))
        for color in (0, 1):
            pos = (
                Position.from_fen(fens[color]) if variant == "standard"
                else v_from_fen(fens[color], variant)
            )
            _, flat_moves, _, _ = space(from_position(pos))
            cands = np.asarray(flat_moves) & 4095
            table = np.asarray(tables[color])
            assert cands.shape == table.shape, variant
            # locate the 2 castling slots: fixed offset before the drops
            n = cands.shape[0]
            drops = 5 * 64 if variant == "crazyhouse" else 0
            castle_lo = n - drops - 2
            mism = np.nonzero(cands != table)[0]
            allowed = {castle_lo, castle_lo + 1}
            assert set(mism.tolist()) <= allowed, (
                variant, color, mism[:10], cands[mism[:10]], table[mism[:10]]
            )


def test_history_ordering_crazyhouse_drop_slot():
    """Same mirror pin for the drop section of the crazyhouse tables."""
    import jax.numpy as jnp

    from fishnet_tpu.chess.variants import from_fen as v_from_fen
    from fishnet_tpu.ops.movegen import DROP_FLAG

    fen = "rnb1kbnr/ppp1pppp/8/3p4/3P4/8/PPPqPPPP/RNBQKBNR[Nn] w KQkq - 0 4"
    pos = v_from_fen(fen, "crazyhouse")
    gen = jax.jit(
        lambda b, h: generate_moves(
            b, "crazyhouse", killers=jnp.asarray([-1, -1]), hist=h
        )
    )
    to_sq = 16  # a3: drop N@a3
    drop_mv = DROP_FLAG | (1 << 12) | (to_sq << 6) | to_sq
    hist = np.zeros(4096, np.int32)
    hist[((to_sq << 6) | to_sq) & 4095] = 1 << 16
    moves, count, noisy = gen(from_position(pos), jnp.asarray(hist))
    moves = np.asarray(moves)[: int(count)].tolist()
    assert drop_mv in moves
    # drops normally order at 1100 (after board quiets); the bumped drop
    # lands at 1011..1110 - 99 → ahead of every un-bumped drop
    drops = [m for m in moves if m & DROP_FLAG]
    assert drops[0] == drop_mv
