"""Load-generator tests: schedule determinism, traffic shapes, Zipf
tenant skew, record/replay round-trips, report math, and the open-loop
firing engine against a scripted in-process HTTP stub.

Everything runs on the loopback or pure functions — no serve stack, no
subprocesses, no JAX.
"""
import asyncio
import json

from tools.loadgen import (
    KindStats,
    LoadProfile,
    LoadReport,
    PlannedRequest,
    generate_schedule,
    load_schedule,
    rate_at,
    request_body,
    run_load,
    save_schedule,
)

# ----------------------------------------------------------- determinism


def test_same_seed_same_schedule():
    profile = LoadProfile(pattern="flash", duration_s=5.0, base_rps=8.0)
    assert generate_schedule(profile, 7) == generate_schedule(profile, 7)


def test_different_seed_different_schedule():
    profile = LoadProfile(pattern="steady", duration_s=5.0, base_rps=8.0)
    assert generate_schedule(profile, 1) != generate_schedule(profile, 2)


def test_schedule_sorted_and_within_duration():
    profile = LoadProfile(pattern="diurnal", duration_s=6.0, base_rps=10.0)
    schedule = generate_schedule(profile, 3)
    assert schedule, "a 6s run at 10rps must schedule something"
    ats = [r.at for r in schedule]
    assert ats == sorted(ats)
    assert all(0.0 <= at < profile.duration_s for at in ats)


# ------------------------------------------------------------- rate shapes


def test_rate_steady_is_constant():
    profile = LoadProfile(pattern="steady", base_rps=5.0, duration_s=10.0)
    assert {rate_at(profile, t) for t in (0.0, 3.3, 9.9)} == {5.0}


def test_rate_flash_window():
    profile = LoadProfile(pattern="flash", base_rps=2.0, duration_s=10.0,
                          flash_factor=10.0, flash_start=0.4, flash_len=0.2)
    assert rate_at(profile, 3.9) == 2.0  # before the burst
    assert rate_at(profile, 4.0) == 20.0  # burst opens at 40% of the run
    assert rate_at(profile, 5.9) == 20.0
    assert rate_at(profile, 6.0) == 2.0  # burst closes at 60%


def test_rate_diurnal_bounded_by_peak_and_trough():
    profile = LoadProfile(pattern="diurnal", base_rps=4.0,
                          duration_s=10.0, diurnal_period_s=10.0)
    rates = [rate_at(profile, t / 10.0) for t in range(100)]
    assert max(rates) <= 4.0 * 1.75 + 1e-9
    assert min(rates) >= 4.0 * 0.25 - 1e-9
    # one full cycle actually swings: both extremes are approached
    assert max(rates) > 4.0 * 1.6
    assert min(rates) < 4.0 * 0.4


def test_flash_burst_concentrates_arrivals():
    profile = LoadProfile(pattern="flash", duration_s=10.0, base_rps=3.0,
                          flash_factor=10.0, flash_start=0.4, flash_len=0.2)
    schedule = generate_schedule(profile, 11)
    burst = [r for r in schedule if 4.0 <= r.at < 6.0]
    outside = [r for r in schedule if not (4.0 <= r.at < 6.0)]
    # 2s at 30rps vs 8s at 3rps: the burst must dominate the run
    assert len(burst) > len(outside)


# ---------------------------------------------------------- tenants, kinds


def test_zipf_skew_favors_low_ranks():
    profile = LoadProfile(pattern="steady", duration_s=40.0, base_rps=20.0,
                          tenants=4, zipf_s=1.2)
    schedule = generate_schedule(profile, 5)
    counts = {f"t{i}": 0 for i in range(4)}
    for req in schedule:
        counts[req.tenant] += 1
    assert set(counts) == {"t0", "t1", "t2", "t3"}
    assert counts["t0"] > counts["t1"] > counts["t3"]


def test_bestmove_ratio_extremes():
    profile = LoadProfile(pattern="steady", duration_s=5.0, base_rps=10.0,
                          bestmove_ratio=0.0, positions=3)
    schedule = generate_schedule(profile, 1)
    assert all(r.kind == "analysis" and r.positions == 3 for r in schedule)

    profile = LoadProfile(pattern="steady", duration_s=5.0, base_rps=10.0,
                          bestmove_ratio=1.0, positions=3)
    schedule = generate_schedule(profile, 1)
    # bestmove is interactive: always a single position per request
    assert all(r.kind == "bestmove" and r.positions == 1 for r in schedule)


# ---------------------------------------------------------- record/replay


def test_record_replay_round_trip(tmp_path):
    profile = LoadProfile(pattern="flash", duration_s=8.0, base_rps=6.0)
    schedule = generate_schedule(profile, 42)
    path = tmp_path / "run.jsonl"
    save_schedule(str(path), schedule)
    assert load_schedule(str(path)) == schedule


def test_load_schedule_sorts_and_defaults(tmp_path):
    path = tmp_path / "captured.jsonl"
    # a captured production log massaged into the replay shape: out of
    # order, sparse fields, blank lines
    path.write_text(
        json.dumps({"at": 2.5, "kind": "bestmove", "tenant": "bot"})
        + "\n\n"
        + json.dumps({"at": 0.5}) + "\n"
    )
    schedule = load_schedule(str(path))
    assert [r.at for r in schedule] == [0.5, 2.5]
    assert schedule[0].kind == "analysis"
    assert schedule[0].tenant == "t0"
    assert schedule[0].positions == 1
    assert schedule[1].kind == "bestmove"


def test_request_body_pure_and_varied():
    req = PlannedRequest(at=0.0, kind="analysis", tenant="t1",
                         positions=2, depth=3, timeout_ms=4000)
    assert request_body(req, 5) == request_body(req, 5)  # replay = same bytes
    body = request_body(req, 5)
    assert body["tenant"] == "t1" and body["depth"] == 3
    assert len(body["positions"]) == 2
    assert "level" not in body
    # distinct indices give distinct move chains -> distinct fingerprints
    assert request_body(req, 5) != request_body(req, 6)

    bm = PlannedRequest(at=0.0, kind="bestmove", tenant="t0",
                        positions=1, depth=1, timeout_ms=4000)
    assert request_body(bm, 0)["level"] == 5


# ------------------------------------------- fingerprint distribution (zipf)


def test_fingerprint_zipf_deterministic():
    profile = LoadProfile(pattern="steady", duration_s=20.0, base_rps=10.0,
                          fingerprint_dist="zipf", fingerprint_pool=32,
                          fingerprint_zipf_s=1.1)
    assert generate_schedule(profile, 9) == generate_schedule(profile, 9)


def test_fingerprint_zipf_ranks_skew_to_the_head():
    profile = LoadProfile(pattern="steady", duration_s=30.0, base_rps=15.0,
                          fingerprint_dist="zipf", fingerprint_pool=32,
                          fingerprint_zipf_s=1.2)
    schedule = generate_schedule(profile, 9)
    ranks = [r.rank for r in schedule]
    assert all(0 <= rank < 32 for rank in ranks)
    counts = {}
    for rank in ranks:
        counts[rank] = counts.get(rank, 0) + 1
    # rank 0 is the hot head, but the tail is still sampled
    assert counts.get(0, 0) == max(counts.values())
    assert len(counts) > 5


def test_fingerprint_sequential_default_has_no_rank():
    profile = LoadProfile(pattern="steady", duration_s=5.0, base_rps=10.0)
    assert all(r.rank == -1 for r in generate_schedule(profile, 1))


def test_rank_round_trips_through_jsonl(tmp_path):
    profile = LoadProfile(pattern="steady", duration_s=10.0, base_rps=8.0,
                          fingerprint_dist="zipf", fingerprint_pool=16)
    schedule = generate_schedule(profile, 21)
    assert any(r.rank > 0 for r in schedule)
    path = tmp_path / "zipf.jsonl"
    save_schedule(str(path), schedule)
    assert load_schedule(str(path)) == schedule


def test_ranked_request_bodies_repeat_the_pool_position():
    a = PlannedRequest(at=0.0, kind="analysis", tenant="t0", positions=1,
                       depth=2, timeout_ms=4000, rank=3)
    b = PlannedRequest(at=5.0, kind="analysis", tenant="t1", positions=1,
                       depth=2, timeout_ms=4000, rank=3)
    # same rank -> the SAME position regardless of schedule index: that
    # repetition is what gives a cache something to hit
    assert request_body(a, 0)["positions"] == request_body(b, 17)["positions"]
    c = PlannedRequest(at=0.0, kind="analysis", tenant="t0", positions=1,
                       depth=2, timeout_ms=4000, rank=4)
    assert request_body(a, 0)["positions"] != request_body(c, 0)["positions"]


def test_position_for_rank_pool_is_distinct():
    from tools.loadgen import _position_for_rank

    seen = {json.dumps(_position_for_rank(r), sort_keys=True)
            for r in range(40)}
    assert len(seen) == 40  # no aliasing even past the move-line length


# ------------------------------------------------------------- report math


def test_kind_stats_percentiles():
    stats = KindStats(latencies_ms=[float(v) for v in range(1, 101)])
    assert stats.percentile(0.50) == 51.0
    assert stats.percentile(0.99) == 100.0
    assert KindStats().percentile(0.99) == 0.0


def test_report_rates():
    report = LoadReport(duration_s=10.0, scheduled=40, ok=20, shed=10,
                        errors=10)
    assert report.achieved_rps == 2.0
    assert report.shed_rate == 0.25
    d = report.as_dict()
    assert d["scheduled"] == 40 and d["shed_rate"] == 0.25
    assert LoadReport().achieved_rps == 0.0
    assert LoadReport().shed_rate == 0.0


# ------------------------------------------------------------- run_load


class StubServe:
    """Minimal HTTP/1.1 stub that answers each POST with a scripted
    status, recording the bodies it saw."""

    def __init__(self, statuses):
        self.statuses = list(statuses)
        self.bodies = []
        self.server = None

    async def _handle(self, reader, writer):
        raw = await reader.read(65536)
        self.bodies.append(json.loads(raw.partition(b"\r\n\r\n")[2]))
        status = self.statuses.pop(0) if self.statuses else 200
        reason = {200: "OK", 429: "Too Many Requests"}.get(status, "Err")
        body = b"{}"
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n".encode("latin-1") + body
        )
        await writer.drain()
        writer.close()

    async def start(self):
        self.server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0)
        return self.server.sockets[0].getsockname()[:2]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()


def _tiny_schedule():
    return [
        PlannedRequest(at=0.0, kind="analysis", tenant="t0", positions=1,
                       depth=1, timeout_ms=2000),
        PlannedRequest(at=0.01, kind="bestmove", tenant="t1", positions=1,
                       depth=1, timeout_ms=2000),
        PlannedRequest(at=0.02, kind="analysis", tenant="t0", positions=1,
                       depth=1, timeout_ms=2000),
    ]


def test_run_load_counts_every_outcome_exactly_once():
    async def scenario():
        stub = StubServe([200, 429, 500])
        host, port = await stub.start()
        seen = []
        try:
            report = await run_load(
                host, port, _tiny_schedule(), drain_timeout_s=10.0,
                on_result=lambda req, i, status, at: seen.append(
                    (i, status)),
            )
        finally:
            await stub.stop()
        assert (report.ok, report.shed, report.errors) == (1, 1, 1)
        assert report.scheduled == 3
        assert report.duration_s > 0
        assert sorted(i for i, _ in seen) == [0, 1, 2]
        # each kind bucket saw its own outcomes
        assert report.per_kind["analysis"].sent == 2
        assert report.per_kind["bestmove"].sent == 1
        # paths routed by kind: bestmove body carries its level
        assert any("level" in b for b in stub.bodies)

    asyncio.run(scenario())


def test_run_load_transport_error_is_an_error_not_a_shed():
    async def scenario():
        # a server that accepts then slams the connection shut
        async def handle(reader, writer):
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        host, port = server.sockets[0].getsockname()[:2]
        schedule = _tiny_schedule()[:1]
        try:
            report = await run_load(host, port, schedule,
                                    drain_timeout_s=5.0)
        finally:
            server.close()
            await server.wait_closed()
        assert (report.ok, report.shed, report.errors) == (0, 0, 1)

    asyncio.run(scenario())
