"""Transposition table: packing, hashing, probe/store, search integration."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fishnet_tpu.chess import Position
from fishnet_tpu.models import nnue
from fishnet_tpu.ops import tt
from fishnet_tpu.ops.board import from_position, stack_boards
from fishnet_tpu.ops.search import MATE, search_batch_jit


@pytest.fixture(scope="module")
def params():
    return nnue.init_params(
        jax.random.PRNGKey(0), l1=32, h1=8, h2=8, feature_set="board768"
    )


def test_meta_roundtrip():
    for score, depth, flag in ((0, 0, 0), (123, 7, 1), (-30000, 255, 2), (30000, 1, 0)):
        meta = int(tt.pack_meta(jnp.int32(score), jnp.int32(depth), jnp.int32(flag)))
        s, d, f = (int(x) for x in tt.unpack_meta(jnp.int32(meta)))
        assert (s, d, f) == (score, depth, flag)


def test_hash_distinguishes_positions():
    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR b KQkq - 0 1",  # stm
        "rnbqkbnr/pppppppp/8/8/4P3/8/PPPP1PPP/RNBQKBNR w KQkq - 0 1",
        "rnbqkbnr/pppppppp/8/8/4P3/8/PPPP1PPP/RNBQKBNR w Qkq - 0 1",  # castling
        "rnbqkbnr/pp1ppppp/8/2p5/4P3/8/PPPP1PPP/RNBQKBNR w KQkq c6 0 2",
        "rnbqkbnr/pp1ppppp/8/2p5/4P3/8/PPPP1PPP/RNBQKBNR w KQkq - 0 2",  # ep
    ]
    hashes = set()
    for f in fens:
        b = from_position(Position.from_fen(f))
        h1, h2 = tt.hash_board(b.board, b.stm, b.ep, b.castling)
        hashes.add((int(h1), int(h2)))
    assert len(hashes) == len(fens)


def test_hash_ignores_halfmove():
    a = from_position(Position.from_fen("8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1"))
    b = from_position(Position.from_fen("8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 30 1"))
    assert tuple(map(int, tt.hash_board(a.board, a.stm, a.ep, a.castling))) == tuple(
        map(int, tt.hash_board(b.board, b.stm, b.ep, b.castling))
    )


def test_store_probe_roundtrip():
    t = tt.make_table(8)
    h1 = jnp.asarray([7, 300], jnp.uint32)
    h2 = jnp.asarray([11, 13], jnp.uint32)
    t = tt.store(
        t, h1, h2,
        score=jnp.asarray([150, -90], jnp.int32),
        depth=jnp.asarray([3, 2], jnp.int32),
        flag=jnp.asarray([tt.FLAG_EXACT, tt.FLAG_LOWER], jnp.int32),
        move=jnp.asarray([4242, 17], jnp.int32),
        mask=jnp.asarray([True, True]),
    )
    usable, score, move, omove = tt.probe(
        t, h1, h2,
        depth_left=jnp.asarray([3, 2], jnp.int32),
        alpha=jnp.asarray([-100, -100], jnp.int32),
        beta=jnp.asarray([200, -95], jnp.int32),
    )
    assert bool(usable[0]) and int(score[0]) == 150 and int(move[0]) == 4242
    # lower bound -90 >= beta -95 → cutoff usable
    assert bool(usable[1]) and int(score[1]) == -90
    # deeper requirement → miss, but ordering move still available
    usable2, _, _, omove2 = tt.probe(
        t, h1, h2,
        depth_left=jnp.asarray([4, 3], jnp.int32),
        alpha=jnp.asarray([-100, -100], jnp.int32),
        beta=jnp.asarray([200, -95], jnp.int32),
    )
    assert not bool(usable2[0]) and int(omove2[0]) == 4242
    # wrong verification key reads as a miss (torn-write defence)
    usable3, _, _, om3 = tt.probe(
        t, h1, h2 + jnp.uint32(1),
        depth_left=jnp.asarray([0, 0], jnp.int32),
        alpha=jnp.asarray([-100, -100], jnp.int32),
        beta=jnp.asarray([200, 200], jnp.int32),
    )
    assert not bool(usable3[0]) and int(om3[0]) == -1


def _store1(t, depth, score=100, move=42, gen=None, prefer_deep=False,
            h1=5, h2=9):
    """Single-slot store helper for the replacement-policy tests."""
    return tt.store(
        t, jnp.asarray([h1], jnp.uint32), jnp.asarray([h2], jnp.uint32),
        score=jnp.asarray([score], jnp.int32),
        depth=jnp.asarray([depth], jnp.int32),
        flag=jnp.asarray([tt.FLAG_EXACT], jnp.int32),
        move=jnp.asarray([move], jnp.int32),
        mask=jnp.asarray([True]),
        prefer_deep=prefer_deep, gen=gen,
    )


def _row(t, h1=5):
    return np.asarray(t.data[h1 & (t.size - 1)])


def test_prefer_deep_keeps_same_generation_deeper_entry():
    """Helper-lane store policy: within one generation a shallower store
    must not evict a deeper entry (the Lazy-SMP helpers' flood of
    low-depth writes would otherwise wash out the primary's deep path)."""
    t = _store1(tt.make_table(8), depth=5, move=111, gen=3, prefer_deep=True)
    deep = _row(t)
    # shallower same-generation store: dropped
    t2 = _store1(t, depth=2, score=-7, move=222, gen=3, prefer_deep=True)
    np.testing.assert_array_equal(_row(t2), deep)
    # equal-depth same-generation store: replaces (only STRICTLY deeper
    # entries are protected — newer information at the same depth wins)
    t3 = _store1(t, depth=5, score=-40, move=333, gen=3, prefer_deep=True)
    assert int(_row(t3)[2]) == 333


def test_prefer_deep_other_generation_always_replaceable():
    """Entries from any other generation — older chunks' helper stores
    and gen-0 plain stores alike — lose their depth protection, so the
    policy self-heals across chunks without a sweep."""
    t = _store1(tt.make_table(8), depth=7, move=111, gen=3, prefer_deep=True)
    # next chunk's generation: a depth-1 store evicts the old depth-7
    t2 = _store1(t, depth=1, move=222, gen=4, prefer_deep=True)
    assert int(_row(t2)[2]) == 222 and int(_row(t2)[3]) == 4
    # plain always-replace store (gen word 0) ignores the policy entirely
    t3 = _store1(t, depth=0, move=333)
    assert int(_row(t3)[2]) == 333 and int(_row(t3)[3]) == 0
    # and a later prefer_deep store replaces the gen-0 row at any depth
    t4 = _store1(t3, depth=1, move=444, gen=5, prefer_deep=True)
    assert int(_row(t4)[2]) == 444


def test_prefer_deep_gen_none_matches_plain_store():
    """store(..., gen=None) writes bit-identical rows to the pre-helper
    plain store — the K=1 engine path must stay byte-for-byte the same."""
    plain = _store1(tt.make_table(8), depth=3)
    helper_off = _store1(tt.make_table(8), depth=3, prefer_deep=False,
                         gen=None)
    np.testing.assert_array_equal(
        np.asarray(plain.data), np.asarray(helper_off.data)
    )


def test_store_mask_and_mate_filter():
    t = tt.make_table(8)
    t2 = tt.store(
        t,
        jnp.asarray([1, 2], jnp.uint32), jnp.asarray([1, 2], jnp.uint32),
        score=jnp.asarray([100, MATE - 3], jnp.int32),
        depth=jnp.asarray([1, 1], jnp.int32),
        flag=jnp.zeros(2, jnp.int32),
        move=jnp.zeros(2, jnp.int32),
        mask=jnp.asarray([False, True]),
    )
    # lane 0 masked out; lane 1 mate-range filtered: table unchanged
    assert (np.asarray(t2.meta) == np.asarray(t.meta)).all()


B = 16  # shared padded lane shape — one compile for the whole file


def search(params, fens, depth, tt_table, budget=200_000):
    boards = [from_position(Position.from_fen(f)) for f in fens]
    roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
    out = search_batch_jit(
        params, roots, depth, budget, max_ply=4, tt=tt_table
    )
    return {
        k: (v if k == "tt"
            else np.asarray(v)[: len(fens)] if np.ndim(v)
            else np.asarray(v))
        for k, v in out.items()
    }


def test_search_with_tt_matches_plain(params):
    """Same scores with and without the table on these pinned inputs
    (exact-depth probes keep cutoff values true same-depth bounds; see
    ops/tt.py probe for the pruning-era determinism caveat). Node counts
    may grow a LITTLE with the table since round 4: a bound cutoff
    shifts alpha, which flips LMR re-search decisions (reduced score
    vs alpha), occasionally re-searching more than the cutoff saved —
    bounded here; the real cross-lane savings are asserted by
    test_tt_shares_work_across_game_plies."""
    fens = [
        "6k1/5ppp/8/8/8/8/8/4R2K w - - 0 1",  # mate in 1
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
    ]
    plain = search(params, fens, 3, None)
    with_tt = search(params, fens, 3, tt.make_table(16))
    np.testing.assert_array_equal(plain["score"], with_tt["score"])
    assert with_tt["nodes"].sum() <= 1.3 * plain["nodes"].sum()
    assert int(with_tt["score"][0]) == MATE - 1


def test_tt_shares_work_across_game_plies(params):
    """The real fishnet batch shape: one game's consecutive plies as
    lanes. Neighboring plies' subtrees overlap heavily and the lanes run
    out of phase (different tree shapes), so cross-lane TT hits must cut
    total nodes versus the same batch without a table.

    (Identical lanes would NOT share: lockstep sync means every lane
    reaches a node before any lane has stored it.)"""
    game = ["e2e4", "e7e5", "g1f3", "b8c6", "f1c4", "g8f6"]
    pos = Position.initial()
    fens = [pos.to_fen()]
    for uci in game:
        pos = pos.push_uci(uci)
        fens.append(pos.to_fen())
    plain = search(params, fens, 3, None)
    shared = search(params, fens, 3, tt.make_table(18))
    np.testing.assert_array_equal(plain["score"], shared["score"])
    total_plain = int(plain["nodes"].sum())
    total_shared = int(shared["nodes"].sum())
    # shallow (d3) trees transpose little across plies — require soundness
    # and no pathological growth here; the big win is measured by
    # test_tt_persists_across_searches (ID-style reuse, ~2x fewer nodes).
    # A few % of slack: the stored TT move jumps the killer/history order,
    # which at fixed shallow depth occasionally costs a handful of nodes.
    assert total_shared <= total_plain * 1.05, (
        f"TT made the search worse: {total_shared} vs {total_plain}"
    )


def test_tt_persists_across_searches(params):
    """Carrying the table into a repeat search makes it much cheaper."""
    fen = "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3"
    t = tt.make_table(18)
    first = search(params, [fen], 3, t)
    second = search(params, [fen], 3, first["tt"])
    assert int(second["score"][0]) == int(first["score"][0])
    assert int(second["nodes"][0]) < int(first["nodes"][0]) // 2


def test_tt_hit_cannot_override_fifty_move_draw(params):
    """A stored score (hash excludes the halfmove counter) must not
    override a forced fifty-move draw at probe time."""
    root_fen = "7k/8/8/8/8/8/8/K7 b - - 99 50"
    plain = search(params, [root_fen], 1, None)
    assert int(plain["score"][0]) == 0  # all children are halfmove-100 draws

    # poison the table: every child placement gets an EXACT deep entry
    t = tt.make_table(16)
    pos = Position.from_fen(root_fen)
    for mv in pos.legal_moves():
        child = from_position(pos.push(mv))
        h1, h2 = tt.hash_board(child.board, child.stm, child.ep, child.castling)
        t = tt.store(
            t, h1[None], h2[None],
            score=jnp.asarray([-500], jnp.int32),
            depth=jnp.asarray([5], jnp.int32),
            flag=jnp.asarray([tt.FLAG_EXACT], jnp.int32),
            move=jnp.asarray([-1], jnp.int32),
            mask=jnp.asarray([True]),
        )
    poisoned = search(params, [root_fen], 1, t)
    assert int(poisoned["score"][0]) == 0, "TT hit overrode the fifty-move draw"


def test_tt_stores_leaf_evals(params):
    """Static leaf evals (the most numerous node type) must land in the
    table as depth-0 EXACT entries despite folding into their parents
    within a single lockstep step."""
    out = search(
        params,
        ["r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3"],
        2, tt.make_table(18),
    )
    meta = np.asarray(out["tt"].meta)
    depths = [(int(m) >> 2) & 0xFF for m in meta[meta != 0]]
    assert depths, "empty table after a search"
    assert 0 in depths, f"no depth-0 (leaf) entries; histogram: {np.unique(depths)}"
