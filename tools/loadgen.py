"""Open-loop load generator for a live `fishnet-tpu serve` endpoint.

Closed-loop clients (bench.py's serve rows, the chaos scenarios) wait
for each response before sending the next request, so an overloaded
server quietly throttles its own load and the measured latency looks
fine right up to collapse. This tool is **open-loop**: arrival times
are fixed on a pre-generated schedule and every request fires at its
scheduled instant whether or not earlier ones have answered — exactly
the coordinated-omission-free shape the autoscaler
(fishnet_tpu/fleet/autoscaler.py) needs to be tested against.

Traffic shapes (`--pattern`):

  steady    constant `--rps`
  diurnal   sinusoidal rate over `--diurnal-period` seconds (a whole
            day compressed to the run: peak 1.75x base, trough 0.25x)
  flash     constant base with a flash crowd of `--flash-factor` x base
            between `--flash-start` and `--flash-start + --flash-len`
            (fractions of the run)

Per-tenant demand is heavy-tailed: tenants `t0..tN-1` draw Zipf
weights 1/rank^s (`--zipf-s`), so t0 dominates the way one busy bot
dominates a real multi-tenant front-end. A `--bestmove-ratio` slice of
requests hits POST /bestmove (interactive priority); the rest POST
/analyse (batch).

Determinism and record/replay: the schedule is a pure function of the
profile and `--seed` (one `random.Random(seed)`, no wall clock), so
two runs with the same seed submit the identical request sequence.
`--record FILE` writes the schedule as JSONL after the run;
`--replay FILE` re-runs a recorded schedule byte-for-byte instead of
generating one — captured production logs massaged into the same JSONL
shape replay through the identical path.

The report counts every scheduled request exactly once: 200 → ok,
429 → shed (the admission controller refused it; open-loop means we do
NOT retry — a retry loop here would silently convert the tool to
closed-loop), anything else → error. Latency percentiles (p50/p99 per
kind) are computed over answered requests only; achieved RPS and shed
rate are reported against the scheduled total.

Examples:
    python -m tools.loadgen --port 9670 --pattern flash --rps 5 \
        --flash-factor 10 --duration 20 --seed 7
    python -m tools.loadgen --port 9670 --pattern diurnal --record run.jsonl
    python -m tools.loadgen --port 9670 --replay run.jsonl --json

docs/autoscaling.md shows the loadgen + autoscaler + chaos wiring;
bench.py's `autoscale_flash` row and tools/chaos.py's
burst-member-loss scenario drive the programmatic API
(`generate_schedule` / `run_load`) in-process.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import math
import random
import sys
import time
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from fishnet_tpu.client.logger import Logger  # noqa: E402

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"

# thinning-loop safety margin: the acceptance test `rate(t) <= peak`
# must hold everywhere or arrivals silently thin to the wrong rate
_PEAK_PAD = 1.001


@dataclass(frozen=True)
class LoadProfile:
    """Shape of one open-loop run; `generate_schedule` is pure in
    (profile, seed)."""

    pattern: str = "steady"  # steady | diurnal | flash
    duration_s: float = 10.0
    base_rps: float = 5.0
    flash_factor: float = 10.0
    flash_start: float = 0.4  # fraction of duration
    flash_len: float = 0.2  # fraction of duration
    diurnal_period_s: float = 10.0
    tenants: int = 4
    zipf_s: float = 1.2
    bestmove_ratio: float = 0.25
    positions: int = 2  # per analyse request
    depth: int = 1
    timeout_ms: int = 8000
    # which POSITIONS the requests ask about (orthogonal to per-tenant
    # demand): "sequential" walks distinct move-chain prefixes (every
    # request is cold — the exactly-once ledger shape), "zipf" draws
    # each request's position from a fixed pool with 1/rank^s weights —
    # the head repeats constantly, the tail is near-unique, which is
    # the population the analysis cache (fishnet_tpu/cache/) is built
    # for and what the bench `cache_zipf` row replays
    fingerprint_dist: str = "sequential"  # sequential | zipf
    fingerprint_pool: int = 64
    fingerprint_zipf_s: float = 1.1


@dataclass(frozen=True)
class PlannedRequest:
    """One scheduled arrival: fire at `at` seconds after run start."""

    at: float
    kind: str  # "analysis" | "bestmove"
    tenant: str
    positions: int
    depth: int
    timeout_ms: int
    # Zipf position rank (fingerprint_dist="zipf"); -1 keeps the
    # sequential walk. Defaulted so pre-rank JSONL recordings replay.
    rank: int = -1


def rate_at(profile: LoadProfile, t: float) -> float:
    """Instantaneous arrival rate (req/s) at offset t."""
    if profile.pattern == "diurnal":
        phase = 2.0 * math.pi * t / max(profile.diurnal_period_s, 1e-9)
        return profile.base_rps * (1.0 + 0.75 * math.sin(phase))
    if profile.pattern == "flash":
        start = profile.flash_start * profile.duration_s
        end = start + profile.flash_len * profile.duration_s
        if start <= t < end:
            return profile.base_rps * profile.flash_factor
        return profile.base_rps
    return profile.base_rps


def _peak_rate(profile: LoadProfile) -> float:
    if profile.pattern == "diurnal":
        return profile.base_rps * 1.75
    if profile.pattern == "flash":
        return profile.base_rps * max(profile.flash_factor, 1.0)
    return profile.base_rps


def _pick_tenant(rng: random.Random, weights: List[float]) -> int:
    """Zipf draw by inverse CDF over precomputed cumulative weights."""
    x = rng.random() * weights[-1]
    lo, hi = 0, len(weights) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if weights[mid] < x:
            lo = mid + 1
        else:
            hi = mid
    return lo


def generate_schedule(profile: LoadProfile, seed: int) -> List[PlannedRequest]:
    """Poisson arrivals at rate(t) via Lewis-Shedler thinning; pure in
    (profile, seed) — same inputs, same schedule, bit for bit."""
    rng = random.Random(seed)
    peak = _peak_rate(profile) * _PEAK_PAD
    cum = []
    total = 0.0
    for rank in range(max(profile.tenants, 1)):
        total += 1.0 / ((rank + 1) ** profile.zipf_s)
        cum.append(total)
    fcum: Optional[List[float]] = None
    if profile.fingerprint_dist == "zipf":
        fcum = []
        ftotal = 0.0
        for rank in range(max(profile.fingerprint_pool, 1)):
            ftotal += 1.0 / ((rank + 1) ** profile.fingerprint_zipf_s)
            fcum.append(ftotal)
    schedule: List[PlannedRequest] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= profile.duration_s:
            break
        if rng.random() * peak > rate_at(profile, t):
            continue  # thinned: instantaneous rate below peak here
        kind = ("bestmove" if rng.random() < profile.bestmove_ratio
                else "analysis")
        schedule.append(PlannedRequest(
            at=round(t, 6),
            kind=kind,
            tenant=f"t{_pick_tenant(rng, cum)}",
            positions=1 if kind == "bestmove" else profile.positions,
            depth=profile.depth,
            timeout_ms=profile.timeout_ms,
            rank=_pick_tenant(rng, fcum) if fcum is not None else -1,
        ))
    return schedule


def save_schedule(path: str, schedule: List[PlannedRequest]) -> None:
    """One JSONL line per planned request — the replay format."""
    with open(path, "w") as f:
        for req in schedule:
            f.write(json.dumps(asdict(req), sort_keys=True) + "\n")


def load_schedule(path: str) -> List[PlannedRequest]:
    """Read a `save_schedule` file (or a captured request log massaged
    into the same JSONL shape) back into a schedule."""
    schedule: List[PlannedRequest] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            schedule.append(PlannedRequest(
                at=float(row["at"]),
                kind=str(row.get("kind", "analysis")),
                tenant=str(row.get("tenant", "t0")),
                positions=int(row.get("positions", 1)),
                depth=int(row.get("depth", 1)),
                timeout_ms=int(row.get("timeout_ms", 8000)),
                rank=int(row.get("rank", -1)),
            ))
    schedule.sort(key=lambda r: r.at)
    return schedule


# one fixed legal line (closed Ruy Lopez): request_body slices prefixes
# of it so position fingerprints VARY across requests — a single
# repeated fen+moves would alias every request in the exactly-once
# ledger and understate real multi-tenant churn
_LINE = ["e2e4", "e7e5", "g1f3", "b8c6", "f1b5", "a7a6",
         "b5a4", "g8f6", "e1g1", "f8e7", "f1e1", "b7b5"]


def _position_for_rank(rank: int) -> dict:
    """The rank'th distinct position: prefixes of _LINE first, then the
    same prefixes again from a start FEN whose fullmove counter is
    bumped — a legal position with a different content fingerprint, so
    the pool extends past len(_LINE)+1 without aliasing."""
    block, rem = divmod(rank, len(_LINE) + 1)
    fen = START if block == 0 else START.rsplit(" ", 1)[0] + f" {1 + block}"
    return {"fen": fen, "moves": _LINE[:rem]}


def request_body(req: PlannedRequest, index: int) -> dict:
    """The serve/protocol.py JSON body for one planned request.
    Distinct move chains give distinct position fingerprints, so the
    exactly-once ledger sees real entries, and the body is a pure
    function of (req, index) — replay submits identical bytes. A
    Zipf-ranked request (req.rank >= 0) instead asks about its ranked
    pool position, so the hot head of the pool repeats across the run
    the way real opening traffic does."""
    if req.rank >= 0:
        positions = [
            _position_for_rank(req.rank + i) for i in range(req.positions)
        ]
    else:
        positions = [
            {"fen": START, "moves": _LINE[: (index + i) % (len(_LINE) + 1)]}
            for i in range(req.positions)
        ]
    body = {
        "id": f"lg-{index:06d}",
        "tenant": req.tenant,
        "positions": positions,
        "depth": req.depth,
        "timeout_ms": req.timeout_ms,
    }
    if req.kind == "bestmove":
        body["level"] = 5
    return body


@dataclass
class KindStats:
    sent: int = 0
    ok: int = 0
    shed: int = 0
    errors: int = 0
    latencies_ms: List[float] = field(default_factory=list)

    def percentile(self, q: float) -> float:
        if not self.latencies_ms:
            return 0.0
        ordered = sorted(self.latencies_ms)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]


@dataclass
class LoadReport:
    """Outcome of one open-loop run; `as_dict` is the --json shape."""

    duration_s: float = 0.0
    scheduled: int = 0
    ok: int = 0
    shed: int = 0
    errors: int = 0
    per_kind: Dict[str, KindStats] = field(default_factory=dict)

    @property
    def achieved_rps(self) -> float:
        return self.ok / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def shed_rate(self) -> float:
        return self.shed / self.scheduled if self.scheduled else 0.0

    def as_dict(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 3),
            "scheduled": self.scheduled,
            "ok": self.ok,
            "shed": self.shed,
            "errors": self.errors,
            "achieved_rps": round(self.achieved_rps, 3),
            "shed_rate": round(self.shed_rate, 4),
            "per_kind": {
                kind: {
                    "sent": s.sent,
                    "ok": s.ok,
                    "shed": s.shed,
                    "errors": s.errors,
                    "p50_ms": round(s.percentile(0.50), 1),
                    "p99_ms": round(s.percentile(0.99), 1),
                }
                for kind, s in sorted(self.per_kind.items())
            },
        }


async def _http_post(host: str, port: int, path: str, body: dict,
                     timeout_s: float) -> int:
    """One HTTP/1.1 POST over a raw asyncio connection (the serve
    front-end speaks plain stdlib HTTP; no client library). Returns the
    status code; the response body is drained and discarded."""

    async def exchange() -> int:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            payload = json.dumps(body).encode("utf-8")
            head = (
                f"POST {path} HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        header = raw.partition(b"\r\n\r\n")[0]
        return int(header.split(None, 2)[1])

    return await asyncio.wait_for(exchange(), timeout=timeout_s)


async def run_load(host: str, port: int, schedule: List[PlannedRequest],
                   *, logger: Optional[Logger] = None,
                   drain_timeout_s: float = 60.0,
                   on_tick: Optional[Callable[[float], None]] = None,
                   on_result: Optional[
                       Callable[[PlannedRequest, int, Optional[int], float],
                                None]] = None,
                   ) -> LoadReport:
    """Fire the schedule open-loop against host:port and report.

    Every request launches at its scheduled offset regardless of
    earlier requests' fates (one task per arrival — no shared
    connection, no backpressure from slow responses). `on_tick(t)` is
    called once per dispatched arrival with the current offset so a
    caller can interleave chaos actions (kill a member at t=X) without
    a second clock. `on_result(req, index, status, at)` fires as each
    answer lands (status None on transport error, `at` the offset from
    run start) — the chaos gates use it to bound WHEN sheds happened,
    not just how many.
    """
    log = logger or Logger(verbose=0)
    report = LoadReport(scheduled=len(schedule))
    for req in schedule:
        report.per_kind.setdefault(req.kind, KindStats())

    async def fire(req: PlannedRequest, index: int) -> None:
        stats = report.per_kind[req.kind]
        stats.sent += 1
        path = "/analyse" if req.kind == "analysis" else "/bestmove"
        # per-request deadline: the scheduled timeout plus slack for
        # queueing — bounded, never retried (open-loop contract)
        budget_s = req.timeout_ms / 1000.0 + 30.0
        began = time.monotonic()
        try:
            status = await _http_post(
                host, port, path, request_body(req, index), budget_s)
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            stats.errors += 1
            report.errors += 1
            log.debug(f"loadgen: {path} #{index} failed: {e}")
            if on_result is not None:
                on_result(req, index, None, time.monotonic() - run_began)
            return
        elapsed_ms = (time.monotonic() - began) * 1000.0
        if on_result is not None:
            on_result(req, index, status, time.monotonic() - run_began)
        if status == 200:
            stats.ok += 1
            stats.latencies_ms.append(elapsed_ms)
            report.ok += 1
        elif status == 429:
            stats.shed += 1
            report.shed += 1
        else:
            stats.errors += 1
            report.errors += 1
            log.debug(f"loadgen: {path} #{index} answered HTTP {status}")

    run_began = time.monotonic()
    tasks: List[asyncio.Future] = []
    for index, req in enumerate(schedule):
        delay = run_began + req.at - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        if on_tick is not None:
            on_tick(time.monotonic() - run_began)
        tasks.append(asyncio.ensure_future(fire(req, index)))
    if tasks:
        done, pending = await asyncio.wait(tasks, timeout=drain_timeout_s)
        for task in pending:
            task.cancel()
        if pending:
            # a cancelled in-flight request is an error, not a shed
            report.errors += len(pending)
            log.warn(f"loadgen: {len(pending)} request(s) still in "
                     f"flight after the {drain_timeout_s:.0f}s drain "
                     "window; counted as errors")
    report.duration_s = time.monotonic() - run_began
    return report


def profile_from_args(args: argparse.Namespace) -> LoadProfile:
    return LoadProfile(
        pattern=args.pattern,
        duration_s=args.duration,
        base_rps=args.rps,
        flash_factor=args.flash_factor,
        flash_start=args.flash_start,
        flash_len=args.flash_len,
        diurnal_period_s=args.diurnal_period,
        tenants=args.tenants,
        zipf_s=args.zipf_s,
        bestmove_ratio=args.bestmove_ratio,
        positions=args.positions,
        depth=args.depth,
        timeout_ms=args.timeout_ms,
        fingerprint_dist=args.fingerprint_dist,
        fingerprint_pool=args.fingerprint_pool,
        fingerprint_zipf_s=args.fingerprint_zipf_s,
    )


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="loadgen",
        description="open-loop load generator for fishnet-tpu serve",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--pattern", default="steady",
                   choices=["steady", "diurnal", "flash"])
    p.add_argument("--duration", type=float, default=10.0,
                   help="run length in seconds")
    p.add_argument("--rps", type=float, default=5.0,
                   help="base arrival rate, requests/second")
    p.add_argument("--flash-factor", type=float, default=10.0,
                   help="flash pattern: burst multiplier over base rps")
    p.add_argument("--flash-start", type=float, default=0.4,
                   help="flash pattern: burst start, fraction of run")
    p.add_argument("--flash-len", type=float, default=0.2,
                   help="flash pattern: burst length, fraction of run")
    p.add_argument("--diurnal-period", type=float, default=10.0,
                   help="diurnal pattern: one full cycle, seconds")
    p.add_argument("--tenants", type=int, default=4,
                   help="tenant count; demand is Zipf over rank")
    p.add_argument("--zipf-s", type=float, default=1.2,
                   help="Zipf exponent for per-tenant demand")
    p.add_argument("--bestmove-ratio", type=float, default=0.25,
                   help="fraction of requests hitting POST /bestmove")
    p.add_argument("--positions", type=int, default=2,
                   help="positions per analyse request")
    p.add_argument("--depth", type=int, default=1)
    p.add_argument("--timeout-ms", type=int, default=8000)
    p.add_argument("--fingerprint-dist", default="sequential",
                   choices=["sequential", "zipf"],
                   help="position population: sequential (all-cold "
                        "walk) or zipf (requests draw from a ranked "
                        "pool with 1/rank^s weights — the analysis-"
                        "cache workload)")
    p.add_argument("--fingerprint-pool", type=int, default=64,
                   help="zipf fingerprints: distinct positions in the "
                        "ranked pool")
    p.add_argument("--fingerprint-zipf-s", type=float, default=1.1,
                   help="zipf fingerprints: Zipf exponent over the "
                        "position pool")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule seed; same seed, same schedule")
    p.add_argument("--record", metavar="FILE",
                   help="write the executed schedule as JSONL")
    p.add_argument("--replay", metavar="FILE",
                   help="run a recorded JSONL schedule instead of "
                        "generating one (--pattern et al. ignored)")
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   help="seconds to wait for in-flight requests after "
                        "the last arrival")
    p.add_argument("--json", action="store_true",
                   help="print the report as JSON")
    p.add_argument("--verbose", "-v", action="count", default=0)
    args = p.parse_args(argv)

    if args.replay:
        schedule = load_schedule(args.replay)
    else:
        schedule = generate_schedule(profile_from_args(args), args.seed)
    if args.record:
        save_schedule(args.record, schedule)

    logger = Logger(verbose=args.verbose)
    if not args.json:
        logger.headline(
            f"loadgen: {len(schedule)} request(s) over "
            f"{args.duration if not args.replay else 'replay'}"
            f" → http://{args.host}:{args.port}"
        )
    report = asyncio.run(run_load(
        args.host, args.port, schedule,
        logger=logger, drain_timeout_s=args.drain_timeout,
    ))
    if args.json:
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    else:
        d = report.as_dict()
        print(f"scheduled={d['scheduled']} ok={d['ok']} shed={d['shed']} "
              f"errors={d['errors']} achieved_rps={d['achieved_rps']} "
              f"shed_rate={d['shed_rate']}")
        for kind, row in d["per_kind"].items():
            print(f"  {kind}: sent={row['sent']} ok={row['ok']} "
                  f"shed={row['shed']} p50={row['p50_ms']}ms "
                  f"p99={row['p99_ms']}ms")
    return 0 if report.errors == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
