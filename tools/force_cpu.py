"""Import this FIRST to force JAX onto host CPU in ad-hoc scripts.

The image registers a remote-TPU ("axon") PJRT plugin from sitecustomize;
once registered, even JAX_PLATFORMS=cpu still initializes it on first use
(and hangs when the tunnel is down/busy). Deregistering the factory before
any jax operation cleanly forces CPU — same trick as tests/conftest.py.

Usage:  python -c "import tools.force_cpu; ..."   (or set N_DEV env first)
"""
import os

n = os.environ.get("FORCE_CPU_DEVICES", "8")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
