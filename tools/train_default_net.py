"""Regenerate the packaged board768 net (fishnet_tpu/assets/).

Distills the classical handcrafted evaluation (material + PST + mobility,
models/train.py classical_eval_target) into the board768 net the TPU
engine ships with — the reference instead ships externally trained
Stockfish nets (reference: build.rs:8-9); this is the in-framework
bootstrap equivalent.

Usage: python tools/train_default_net.py [--steps N] [--samples N]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser()
    # defaults reproduce the shipped net (docs/strength.md: the r2 net's
    # 4k steps badly underfit — evals compressed to ±200 cp and it LOST
    # to a material searcher; 24k steps/150k positions fits the full
    # material scale and scores 0.94 against the same opponent)
    ap.add_argument("--steps", type=int, default=24_000)
    ap.add_argument("--samples", type=int, default=150_000)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--l1", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax  # noqa: F401  (after env setup)

    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    from fishnet_tpu.assets import ASSET_DIR, DEFAULT_NETS
    from fishnet_tpu.models import nnue
    from fishnet_tpu.models.train import (
        diverse_position_dataset,
        train_material_net,
    )

    print(f"generating {args.samples} positions ...", flush=True)
    dataset = diverse_position_dataset(args.samples, seed=args.seed)
    print("training ...", flush=True)
    params, loss = train_material_net(
        l1=args.l1, steps=args.steps, batch=args.batch, seed=args.seed,
        dataset=dataset, lr=args.lr,
    )
    out = args.out or (ASSET_DIR / DEFAULT_NETS["board768"])
    nnue.save_params(params, out)
    print(f"saved {out} (final loss {loss:.4f})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
