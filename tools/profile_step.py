"""Profile the lockstep search step on the current device.

Times run_segment per-step wall clock at a given shape, then captures a
jax.profiler trace of a short segment and aggregates per-op durations from
the trace so the hot spots are attributable (VERDICT r4 weak #6: perf
claims need a committed artifact — this writes docs/profile-r5 data).

Usage:
  python tools/profile_step.py [B] [depth] [max_ply] [--trace]
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    B = int(args[0]) if len(args) > 0 else 64
    depth = int(args[1]) if len(args) > 1 else 3
    max_ply = int(args[2]) if len(args) > 2 else depth + 1
    do_trace = "--trace" in sys.argv
    use_tt = "--tt" in sys.argv  # shared 2^21-slot table (production config)
    steps = int(os.environ.get("PROFILE_STEPS", "200"))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()
    print(f"devices={jax.devices()} platform={jax.default_backend()}",
          file=sys.stderr)

    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S

    from bench import _roots_for

    roots = _roots_for(B, "standard", "standard")
    params = nnue.init_params(jax.random.PRNGKey(0), l1=64, feature_set="board768")
    depth_arr = jnp.full((B,), depth, jnp.int32)
    budget_arr = jnp.full((B,), 10_000_000, jnp.int32)

    tt_mod = None
    if use_tt:
        from fishnet_tpu.ops import tt as tt_mod

    def fresh_inputs():
        # _run_segment_jit DONATES the state and table (ops/search.py),
        # so every dispatch needs its own copies — rebuilding also keeps
        # the step counts comparable across the timed runs
        st = S._init_state_jit(params, roots, depth_arr, budget_arr,
                               max_ply, "standard")
        t = tt_mod.make_table(21) if use_tt else None
        jax.block_until_ready(st.bt)
        return st, t

    state, tt0 = fresh_inputs()
    t0 = time.perf_counter()
    S._run_segment_jit.lower(params, state, tt0, steps, "standard",
                             False).compile()
    print(f"compile run_segment({steps}): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    # warmup + timed: same fresh state each time so step counts match
    for tag in ("warmup", "timed1", "timed2", "timed3"):
        state, tt0 = fresh_inputs()
        t0 = time.perf_counter()
        out, _, n, _summ = S._run_segment_jit(params, state, tt0, steps,
                                              "standard", False)
        jax.block_until_ready(out.lane)
        dt = time.perf_counter() - t0
        n = int(n)
        nodes = int(np.asarray(out.lane[:, S.LN_NODES]).sum())
        print(f"{tag}: {n} steps in {dt*1e3:.1f}ms -> {dt/max(n,1)*1e6:.0f}"
              f" us/step, {nodes} nodes, {nodes/dt:.0f} nps", file=sys.stderr)

    if not do_trace:
        return

    state, tt0 = fresh_inputs()
    trace_dir = os.environ.get("PROFILE_TRACE_DIR", "/tmp/fishnet-trace")
    with jax.profiler.trace(trace_dir):
        out, _, n, _summ = S._run_segment_jit(params, state, tt0, steps,
                                              "standard", False)
        jax.block_until_ready(out.lane)
    print(f"trace written to {trace_dir}", file=sys.stderr)

    # aggregate per-op durations from the chrome trace
    files = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz")), key=os.path.getmtime)
    if not files:
        print("no trace.json.gz found", file=sys.stderr)
        return
    with gzip.open(files[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # keep only device-lane complete events (ph == 'X') with a duration
    by_name: dict[str, float] = defaultdict(float)
    cnt: dict[str, int] = defaultdict(int)
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
    dev_pids = {p for p, nm in pid_names.items()
                if "TPU" in nm or "/device" in nm.lower() or "XLA" in nm}
    for e in events:
        if e.get("ph") != "X":
            continue
        if dev_pids and e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        by_name[name] += e.get("dur", 0.0)
        cnt[name] += 1
    total = sum(by_name.values())
    print(f"pids seen: {pid_names}", file=sys.stderr)
    print(f"total device-op time: {total/1e3:.1f}ms over {steps} steps")
    for name, dur in sorted(by_name.items(), key=lambda kv: -kv[1])[:40]:
        print(f"{dur/1e3:9.2f}ms {100*dur/max(total,1e-9):5.1f}% "
              f"x{cnt[name]:<6} {name[:110]}")


if __name__ == "__main__":
    main()
