"""Human-oriented fishnet-lint report: findings grouped by rule with
per-family counts, or GitHub workflow annotations.

    python -m tools.lint_report                 # grouped summary
    python -m tools.lint_report --format=github # ::error annotations
    python -m tools.lint_report --sarif out.sarif  # SARIF 2.1.0 for code
                                                   # scanning uploads
    python -m tools.lint_report --all           # include baselined findings

Exit code mirrors `python -m fishnet_tpu.lint`: 1 when active findings
(or stale baseline entries) exist, else 0. The CLI in
fishnet_tpu/lint/__main__.py stays the gate; this tool is the lens —
one line per finding is the right shape for CI logs, but when a rule
fires 30 times locally you want the grouping, not the scroll.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import List, Optional

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from fishnet_tpu.lint import Project, load_baseline, run_lint  # noqa: E402
from fishnet_tpu.lint.__main__ import DEFAULT_BASELINE  # noqa: E402


def _sarif(findings) -> dict:
    """Minimal SARIF 2.1.0 document: one run, one rule object per
    distinct rule id, one result per finding. Columns are 0-based in
    Finding and 1-based in SARIF."""
    rules = {}
    results = []
    for f in findings:
        rules.setdefault(f.rule, {
            "id": f.rule,
            "helpUri": "https://github.com/fishnet-tpu/fishnet-tpu/"
                       "blob/main/docs/lint.md",
        })
        results.append({
            "ruleId": f.rule,
            "level": "note" if f.baselined else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": int(f.line),
                        "startColumn": int(f.col) + 1,
                    },
                },
            }],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "fishnet-lint",
                    "informationUri": "https://github.com/fishnet-tpu/"
                                      "fishnet-tpu/blob/main/docs/lint.md",
                    "rules": sorted(rules.values(),
                                    key=lambda r: r["id"]),
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.lint_report",
        description="fishnet-lint findings grouped by rule.",
    )
    parser.add_argument("--root", type=Path, default=REPO_ROOT)
    parser.add_argument("--format", choices=("text", "github"),
                        default="text")
    parser.add_argument("--all", action="store_true",
                        help="include baselined findings in the report")
    parser.add_argument("--sarif", type=Path, default=None, metavar="PATH",
                        help="also write the shown findings as SARIF 2.1.0 "
                             "(use '-' for stdout)")
    args = parser.parse_args(argv)

    root = args.root.resolve()
    try:
        project = Project.load(root)
    except SyntaxError as e:
        print(f"lint_report: {e}", file=sys.stderr)
        return 2

    baseline: List[str] = []
    baseline_path = root / DEFAULT_BASELINE
    if baseline_path.is_file():
        baseline = load_baseline(baseline_path)
    result = run_lint(project, baseline=baseline)

    shown = result.findings if args.all else result.active

    if args.sarif is not None:
        doc = _sarif(shown)
        blob = json.dumps(doc, indent=2, sort_keys=True)
        if str(args.sarif) == "-":
            print(blob)
        else:
            args.sarif.write_text(blob + "\n", encoding="utf-8")
            print(f"lint_report: wrote {len(shown)} results to "
                  f"{args.sarif}", file=sys.stderr)

    if args.format == "github":
        for f in shown:
            print(f.format_github())
        for entry in result.stale_baseline:
            print(f"::error title=fishnet-lint stale-baseline::stale "
                  f"baseline entry (finding fixed?): {entry}")
        return 1 if (result.failed or result.stale_baseline) else 0

    by_rule = defaultdict(list)
    for f in shown:
        by_rule[f.rule].append(f)

    for rule in sorted(by_rule):
        findings = by_rule[rule]
        print(f"{rule} ({len(findings)})")
        for f in findings:
            tag = " [baselined]" if f.baselined else ""
            print(f"  {f.path}:{f.line}{tag}  {f.source_line.strip()}")
        print()

    families = defaultdict(int)
    for f in shown:
        families[f.rule.split("-", 1)[0]] += 1
    summary = ", ".join(
        f"{name}: {n}" for name, n in sorted(families.items())
    ) or "clean"
    print(f"fishnet-lint summary — {summary}")
    if result.stale_baseline:
        print(f"{len(result.stale_baseline)} stale baseline entries "
              "(finding fixed? regenerate with --write-baseline):")
        for entry in result.stale_baseline:
            print(f"  {entry}")
    return 1 if (result.failed or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
