"""Eval parity vs a real Stockfish `eval` (the reference's evaluator).

VERDICT r3 "what's missing" #3: no eval-parity harness against an actual
Stockfish eval existed. This is it: point --engine at any Stockfish (or
Fairy-Stockfish) binary and it runs the engine's `eval` debug command on
a FEN sweep, parses "Final evaluation", and reports agreement (MAE, sign
agreement, Pearson r) against this framework's evaluator — the shipped
board768 net by default, or an imported real network via --nnue
(models/nnue_import.py).

The image this framework is built in bundles NO engine binaries
(reference build.rs embeds them; we ship weights instead — assets.py),
so without --engine the tool exits 2 with a BLOCKED line: the recorded
attempt the verdict asked for. The moment an operator has a binary, the
same command produces the real table.

Usage:
  python tools/eval_parity.py --engine /path/to/stockfish [--nnue big.nnue]
  python tools/eval_parity.py            # prints BLOCKED status, exit 2
"""
from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# mixed openings / middlegames / endgames, both colors to move
FENS = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    "r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10",
    "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
    "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
    "8/8/8/8/8/4k3/8/R3K3 w Q - 0 1",
    "rnb1kbnr/ppp1pppp/8/3q4/8/8/PPPP1PPP/RNBQKBNR w KQkq - 0 3",
    "r1b1kb1r/2pp1ppp/1np1q3/p3P3/2P5/1P6/PB1NQPPP/R3KB1R b KQkq - 0 1",
    "5rk1/1pp3pp/3p4/4p3/2P1P3/1P1P1q2/1QP2P2/5RK1 w - - 0 1",
]

_FINAL_RE = re.compile(r"Final evaluation\s+([+-]?\d+\.\d+)")
_FINAL_NONE_RE = re.compile(r"Final evaluation:\s*none")


def engine_eval_cp(exe: str, fen: str, timeout: float = 10.0):
    """Stockfish `eval` on one FEN → white-POV centipawns (None: in check
    or unparseable — Stockfish prints 'none' when eval is unavailable)."""
    script = f"position fen {fen}\neval\nquit\n"
    r = subprocess.run(
        [exe], input=script, capture_output=True, text=True, timeout=timeout,
    )
    if r.returncode != 0:
        raise RuntimeError(f"engine exited rc={r.returncode} on `eval`")
    if _FINAL_NONE_RE.search(r.stdout):
        return None  # Stockfish: eval unavailable (side to move in check)
    m = _FINAL_RE.search(r.stdout)
    if m is None:
        # don't silently skip: an unrecognized eval-trace format (e.g. a
        # variant fork printing 'Total evaluation: ...') would otherwise
        # drop every row and masquerade as all-in-check
        tail = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "<empty>"
        raise RuntimeError(f"unparseable eval output (last line: {tail!r})")
    return int(round(float(m.group(1)) * 100))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", default=None,
                    help="path to a Stockfish/Fairy-Stockfish binary")
    ap.add_argument("--nnue", default=None,
                    help="compare an imported real .nnue instead of the "
                         "shipped board768 net")
    ap.add_argument("--net", default="fishnet_tpu/assets/nnue-board768-64.npz")
    args = ap.parse_args()

    import shutil

    if args.engine is not None and not os.path.exists(args.engine):
        resolved = shutil.which(args.engine)  # bare command name on PATH
        if resolved is None:
            print(f"--engine {args.engine!r} not found (neither a file nor "
                  "on PATH)", file=sys.stderr)
            return 1
        args.engine = resolved
    if args.engine is None:
        print(
            "BLOCKED: no engine binary available (this image bundles none; "
            "reference embeds Stockfish via build.rs:8-29). Re-run with "
            "--engine /path/to/stockfish when one exists.",
        )
        return 2

    from tools import force_cpu  # noqa: F401
    import numpy as np

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position

    if args.nnue:
        from fishnet_tpu.models import nnue_import

        params = nnue_import.load_nnue(args.nnue)
        label = os.path.basename(args.nnue)
    else:
        params = nnue.load_params(args.net)
        label = os.path.basename(args.net)

    rows = []
    for fen in FENS:
        try:
            sf = engine_eval_cp(args.engine, fen)
        except (RuntimeError, OSError, subprocess.TimeoutExpired) as e:
            print(f"engine failure on {fen}: {e}", file=sys.stderr)
            return 1
        if sf is None:
            continue
        pos = Position.from_fen(fen)
        b = from_position(pos)
        ours_stm = int(nnue.evaluate(params, b.board, b.stm))
        ours_white = ours_stm if pos.turn == 0 else -ours_stm
        rows.append((fen, sf, ours_white))
        print(f"{fen:64s} sf={sf:+6d} {label}={ours_white:+6d}")

    if not rows:
        print("no comparable positions (all in check?)")
        return 1
    sf = np.array([r[1] for r in rows], np.float64)
    us = np.array([r[2] for r in rows], np.float64)
    mae = float(np.abs(sf - us).mean())
    sign = float(((sf >= 0) == (us >= 0)).mean())
    r = float(np.corrcoef(sf, us)[0, 1]) if len(rows) > 1 else float("nan")
    print(f"n={len(rows)} MAE={mae:.0f}cp sign-agreement={sign:.2f} pearson={r:.3f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
