"""Summarize a trace dump (obs/trace.py Chrome trace-event JSON).

A flight-recorder dump (engine/supervisor.py writes one into
FISHNET_TPU_TRACE_DIR on child death, progress stall, or breaker trip)
or any TraceRecorder.dump() file holds the merged supervisor+host
timeline. This tool turns it into the two summaries the ROADMAP's
measurement items need without opening Perfetto:

- **per-phase time shares**: total duration per span name (warmup,
  search, supervisor.dispatch, queue.acquire, segment, ...) with the
  SyncStats-derived device/host split (`segment.device` /
  `segment.host` child spans) called out as a share of segment time —
  the profiling lever for the ~290 us/step fixed per-segment gap.
- **boundary-gap histogram**: the distribution of gaps between
  consecutive `segment` spans on the host timeline — the fixed
  per-boundary cost itself, bucketed.

Cross-validation: every `segment` span carries its SyncStats snapshot
in args (device_ms/host_ms), and its child spans' durations are those
exact numbers — so `aggregate(args)` and `aggregate(child spans)` must
agree to well under 1%; `--selftest` (and tests/test_trace.py) assert
that.

Request waterfalls: `--request <trace_id>` reconstructs one request's
causal chain from its span links — every span/instant whose args carry
the trace_id (directly or in a dispatch span's `trace_ids` list) plus
the `request` flow hops tying the processes together — and renders it
as a start-ordered waterfall. When the dump holds the serve edge's
`slo.observe` instant for that request, the reconstructed end-to-end
time is cross-checked within 1% against the latency the SLO histogram
actually recorded (same idiom as the SyncStats/segment check).

Usage:
  python tools/trace_report.py TRACE.json
  python tools/trace_report.py TRACE.json --format=github   # CI step
  python tools/trace_report.py TRACE.json --json
  python tools/trace_report.py TRACE.json --request aabbccdd11223344
  python tools/trace_report.py --compare BASE.json CAND.json

`--compare A B` diffs two dumps — per-phase time-share movement and
the boundary-gap distribution shift, with each dump's `buildInfo`
stamp (obs/perf.py) rendered so you know which build produced which
side.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional

# gap buckets in milliseconds (upper bounds; the last is open-ended)
GAP_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 50.0, 250.0)


def load_events(path: str) -> List[dict]:
    """Load and minimally validate a Chrome trace-event file. Raises
    ValueError on anything Perfetto would reject outright."""
    with open(path, "r", encoding="utf-8") as fh:
        obj = json.load(fh)
    if isinstance(obj, list):
        events = obj  # bare-array form is also valid Chrome trace JSON
    elif isinstance(obj, dict) and isinstance(obj.get("traceEvents"), list):
        events = obj["traceEvents"]
    else:
        raise ValueError(f"{path}: not a Chrome trace-event file")
    out = []
    for ev in events:
        if not isinstance(ev, dict) or "ph" not in ev:
            raise ValueError(f"{path}: malformed trace event: {ev!r}")
        out.append(ev)
    return out


def _spans(events: List[dict], name: Optional[str] = None) -> List[dict]:
    return [
        e for e in events
        if e.get("ph") == "X" and (name is None or e.get("name") == name)
    ]


def summarize(events: List[dict]) -> dict:
    """The report dict: phase shares, segment split, boundary gaps."""
    spans = _spans(events)
    per_name: Dict[str, dict] = defaultdict(
        lambda: {"count": 0, "total_ms": 0.0}
    )
    for e in spans:
        row = per_name[str(e.get("name"))]
        row["count"] += 1
        row["total_ms"] += float(e.get("dur", 0.0)) / 1000.0

    # SyncStats cross-validation: args-carried totals vs child-span sums
    seg = _spans(events, "segment")
    args_device = sum(
        float((e.get("args") or {}).get("device_ms", 0.0)) for e in seg
    )
    args_host = sum(
        float((e.get("args") or {}).get("host_ms", 0.0)) for e in seg
    )
    span_device = per_name.get("segment.device", {}).get("total_ms", 0.0)
    span_host = per_name.get("segment.host", {}).get("total_ms", 0.0)

    # boundary gaps: start-to-start minus duration of consecutive
    # segment spans per (pid, tid) track, i.e. time between the end of
    # one boundary window and the start of the next
    gaps_ms: List[float] = []
    by_track: Dict[tuple, List[dict]] = defaultdict(list)
    for e in seg:
        by_track[(e.get("pid"), e.get("tid"))].append(e)
    for track in by_track.values():
        track.sort(key=lambda e: float(e.get("ts", 0.0)))
        for prev, cur in zip(track, track[1:]):
            gap = (
                float(cur.get("ts", 0.0))
                - float(prev.get("ts", 0.0))
                - float(prev.get("dur", 0.0))
            ) / 1000.0
            if gap >= 0.0:
                gaps_ms.append(gap)
    hist = [0] * (len(GAP_BUCKETS_MS) + 1)
    for g in gaps_ms:
        for i, ub in enumerate(GAP_BUCKETS_MS):
            if g <= ub:
                hist[i] += 1
                break
        else:
            hist[-1] += 1

    total_ms = sum(row["total_ms"] for row in per_name.values())
    seg_total = span_device + span_host
    return {
        "events": len(events),
        "spans": len(spans),
        "phases": {
            name: {
                "count": row["count"],
                "total_ms": round(row["total_ms"], 3),
                "share": round(row["total_ms"] / total_ms, 4)
                if total_ms > 0 else 0.0,
            }
            for name, row in sorted(
                per_name.items(), key=lambda kv: -kv[1]["total_ms"]
            )
        },
        "segments": {
            "count": len(seg),
            "device_ms": round(span_device, 3),
            "host_ms": round(span_host, 3),
            "device_share": round(span_device / seg_total, 4)
            if seg_total > 0 else 0.0,
            "host_share": round(span_host / seg_total, 4)
            if seg_total > 0 else 0.0,
            # the args-carried SyncStats totals, for cross-validation
            "args_device_ms": round(args_device, 3),
            "args_host_ms": round(args_host, 3),
        },
        "boundary_gaps": {
            "count": len(gaps_ms),
            "buckets_ms": list(GAP_BUCKETS_MS),
            "histogram": hist,
            "mean_ms": round(sum(gaps_ms) / len(gaps_ms), 3)
            if gaps_ms else 0.0,
            "max_ms": round(max(gaps_ms), 3) if gaps_ms else 0.0,
        },
    }


def load_build_info(path: str) -> dict:
    """The `buildInfo` stamp obs/trace.py export() writes at the dump's
    top level (absent on bare-array dumps and pre-stamp files)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            obj = json.load(fh)
    except (OSError, ValueError, json.JSONDecodeError):
        return {}
    if isinstance(obj, dict) and isinstance(obj.get("buildInfo"), dict):
        return obj["buildInfo"]
    return {}


def compare(a: dict, b: dict) -> dict:
    """Diff two summarize() reports: per-phase time-share movement and
    the boundary-gap distribution shift. `a` is the baseline, `b` the
    candidate; deltas are b - a (so positive share_delta = that phase
    grew). The phase table covers the union of names, with phases
    present on only one side carried at zero on the other — a phase
    appearing or vanishing is itself signal (e.g. a warmup span that
    stopped amortizing)."""
    names = list(
        dict.fromkeys(list(a["phases"].keys()) + list(b["phases"].keys()))
    )
    zero = {"count": 0, "total_ms": 0.0, "share": 0.0}
    phases = {}
    for name in names:
        ra, rb = a["phases"].get(name, zero), b["phases"].get(name, zero)
        phases[name] = {
            "a_total_ms": ra["total_ms"],
            "b_total_ms": rb["total_ms"],
            "a_share": ra["share"],
            "b_share": rb["share"],
            "share_delta": round(rb["share"] - ra["share"], 4),
            "ratio": round(rb["total_ms"] / ra["total_ms"], 3)
            if ra["total_ms"] > 0 else None,
        }
    phases = dict(sorted(
        phases.items(), key=lambda kv: -abs(kv[1]["share_delta"])
    ))
    ga, gb = a["boundary_gaps"], b["boundary_gaps"]
    sa, sb = a["segments"], b["segments"]
    return {
        "phases": phases,
        "segments": {
            "a_count": sa["count"], "b_count": sb["count"],
            "device_share_delta": round(
                sb["device_share"] - sa["device_share"], 4),
            "host_share_delta": round(
                sb["host_share"] - sa["host_share"], 4),
        },
        "boundary_gaps": {
            "a_count": ga["count"], "b_count": gb["count"],
            "a_mean_ms": ga.get("mean_ms", 0.0),
            "b_mean_ms": gb.get("mean_ms", 0.0),
            "mean_delta_ms": round(
                gb.get("mean_ms", 0.0) - ga.get("mean_ms", 0.0), 3),
            "a_max_ms": ga["max_ms"], "b_max_ms": gb["max_ms"],
            "max_delta_ms": round(gb["max_ms"] - ga["max_ms"], 3),
            "buckets_ms": ga["buckets_ms"],
            "a_histogram": ga["histogram"],
            "b_histogram": gb["histogram"],
        },
    }


def render_compare(cmp: dict, label_a: str, label_b: str) -> str:
    lines = [
        f"compare: A={label_a}  B={label_b}  (deltas are B - A)",
        "",
        f"{'phase':<24} {'A share':>8} {'B share':>8} {'delta':>8} "
        f"{'B/A ms':>7}",
    ]
    for name, row in cmp["phases"].items():
        ratio = f"{row['ratio']:>7.2f}" if row["ratio"] is not None \
            else f"{'new':>7}"
        lines.append(
            f"{name:<24} {row['a_share']:>8.1%} {row['b_share']:>8.1%} "
            f"{row['share_delta']:>+8.1%} {ratio}"
        )
    seg = cmp["segments"]
    lines += [
        "",
        f"segments: {seg['a_count']} -> {seg['b_count']}  "
        f"device share {seg['device_share_delta']:+.1%}  "
        f"host share {seg['host_share_delta']:+.1%}",
    ]
    gaps = cmp["boundary_gaps"]
    lines += [
        "",
        f"boundary gaps: {gaps['a_count']} -> {gaps['b_count']}  "
        f"mean {gaps['a_mean_ms']:.3f} -> {gaps['b_mean_ms']:.3f}ms "
        f"({gaps['mean_delta_ms']:+.3f})  "
        f"max {gaps['a_max_ms']:.3f} -> {gaps['b_max_ms']:.3f}ms "
        f"({gaps['max_delta_ms']:+.3f})",
    ]
    edges = ["0"] + [str(b) for b in gaps["buckets_ms"]]
    for i, (na, nb) in enumerate(
            zip(gaps["a_histogram"], gaps["b_histogram"])):
        hi = edges[i + 1] if i < len(gaps["buckets_ms"]) else "inf"
        lines.append(f"  ({edges[i] if i else '0'}, {hi}]: {na} -> {nb}")
    return "\n".join(lines)


def request_events(events: List[dict], trace_id: str) -> List[dict]:
    """Every event on one request's causal chain: spans/instants whose
    args carry the trace_id (their own or in a dispatch span's
    `trace_ids` list) and the `request` flow hops with that id."""
    out = []
    for e in events:
        args = e.get("args") or {}
        if args.get("trace_id") == trace_id:
            out.append(e)
            continue
        tids = args.get("trace_ids")
        if isinstance(tids, list) and trace_id in tids:
            out.append(e)
            continue
        if e.get("ph") in ("s", "t", "f") and str(e.get("id")) == trace_id:
            out.append(e)
    return out


def request_waterfall(events: List[dict], trace_id: str) -> Optional[dict]:
    """One request's start-ordered waterfall, or None if the dump holds
    nothing for that id."""
    evs = request_events(events, trace_id)
    if not evs:
        return None
    spans = [e for e in evs if e.get("ph") == "X"]
    instants = [e for e in evs if e.get("ph") == "i"]
    flows = [e for e in evs if e.get("ph") in ("s", "t", "f")]
    t0 = min(float(e.get("ts", 0.0)) for e in evs)
    rows = []
    for e in sorted(spans + instants,
                    key=lambda e: float(e.get("ts", 0.0))):
        rows.append({
            "name": str(e.get("name")),
            "pid": e.get("pid"),
            "start_ms": round((float(e.get("ts", 0.0)) - t0) / 1000.0, 3),
            "dur_ms": round(float(e.get("dur", 0.0)) / 1000.0, 3)
            if e.get("ph") == "X" else None,
            "args": {
                k: v for k, v in (e.get("args") or {}).items()
                if k not in ("trace_id", "trace_ids")
            },
        })
    http = [e for e in spans if e.get("name") == "http.request"]
    slo = [e for e in instants if e.get("name") == "slo.observe"]
    http_ms = (
        max(float(e.get("dur", 0.0)) for e in http) / 1000.0
        if http else None
    )
    slo_ms = (
        float((slo[0].get("args") or {}).get("total_ms", 0.0))
        if slo else None
    )
    last = max(
        float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
        for e in spans + instants
    )
    return {
        "request": trace_id,
        "events": len(evs),
        "flow_hops": len(flows),
        "processes": sorted({e.get("pid") for e in evs}),
        "span_total_ms": round((last - t0) / 1000.0, 3),
        "http_ms": round(http_ms, 3) if http_ms is not None else None,
        "slo_total_ms": round(slo_ms, 3) if slo_ms is not None else None,
        "rows": rows,
    }


def request_crosscheck(wf: dict, tolerance: float = 0.01) -> List[str]:
    """The <=1% agreement contract between the reconstructed waterfall
    and the SLO histogram observation the serve edge recorded for this
    request. Silently passes when the dump lacks either side (a
    client-chunk trace has no serve edge)."""
    http_ms, slo_ms = wf.get("http_ms"), wf.get("slo_total_ms")
    if http_ms is None or slo_ms is None:
        return []
    ref = max(abs(slo_ms), 1e-9)
    if abs(http_ms - slo_ms) / ref > tolerance:
        return [
            f"request {wf['request']}: http.request span is "
            f"{http_ms:.3f}ms but the SLO histogram observed "
            f"{slo_ms:.3f}ms (>{tolerance:.0%} apart)"
        ]
    return []


def render_waterfall(wf: dict) -> str:
    procs = ", ".join(str(p) for p in wf["processes"])
    lines = [
        f"request {wf['request']}: {wf['events']} events across "
        f"{len(wf['processes'])} process(es) [{procs}], "
        f"{wf['flow_hops']} flow hops, "
        f"{wf['span_total_ms']:.3f}ms end to end",
        "",
        f"{'start_ms':>10} {'dur_ms':>10}  {'pid':>7}  name",
    ]
    for row in wf["rows"]:
        dur = f"{row['dur_ms']:>10.3f}" if row["dur_ms"] is not None \
            else f"{'·':>10}"
        lines.append(
            f"{row['start_ms']:>10.3f} {dur}  {row['pid']!s:>7}  "
            f"{row['name']}"
        )
    if wf["slo_total_ms"] is not None:
        lines += [
            "",
            f"slo observation: {wf['slo_total_ms']:.3f}ms total "
            f"(http span {wf['http_ms']:.3f}ms)"
            if wf["http_ms"] is not None else
            f"slo observation: {wf['slo_total_ms']:.3f}ms total",
        ]
    return "\n".join(lines)


def crosscheck(report: dict, tolerance: float = 0.01) -> List[str]:
    """The <=1% agreement contract between SyncStats args and the child
    spans rendered from them. Returns human-readable violations."""
    seg = report["segments"]
    out = []
    for key in ("device", "host"):
        spans_ms = seg[f"{key}_ms"]
        args_ms = seg[f"args_{key}_ms"]
        ref = max(abs(args_ms), 1e-9)
        if abs(spans_ms - args_ms) / ref > tolerance:
            out.append(
                f"segment.{key} spans sum to {spans_ms:.3f}ms but SyncStats "
                f"args carry {args_ms:.3f}ms (>{tolerance:.0%} apart)"
            )
    return out


def render_text(report: dict) -> str:
    lines = [
        f"trace: {report['events']} events, {report['spans']} spans",
        "",
        f"{'phase':<24} {'count':>7} {'total_ms':>12} {'share':>7}",
    ]
    for name, row in report["phases"].items():
        lines.append(
            f"{name:<24} {row['count']:>7} {row['total_ms']:>12.3f} "
            f"{row['share']:>6.1%}"
        )
    seg = report["segments"]
    if seg["count"]:
        lines += [
            "",
            f"segments: {seg['count']}  device {seg['device_ms']:.3f}ms "
            f"({seg['device_share']:.1%})  host {seg['host_ms']:.3f}ms "
            f"({seg['host_share']:.1%})",
        ]
    gaps = report["boundary_gaps"]
    if gaps["count"]:
        lines += ["", "boundary gaps (ms):"]
        edges = ["0"] + [str(b) for b in gaps["buckets_ms"]]
        for i, n in enumerate(gaps["histogram"]):
            hi = edges[i + 1] if i < len(gaps["buckets_ms"]) else "inf"
            lines.append(f"  ({edges[i] if i else '0'}, {hi}]: {n}")
        lines.append(f"  max: {gaps['max_ms']:.3f}ms")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="trace-report")
    parser.add_argument("trace", nargs="?", default=None,
                        help="Chrome trace-event JSON file")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument(
        "--compare", nargs=2, metavar=("A.json", "B.json"), default=None,
        help="diff two dumps (A = baseline, B = candidate): per-phase "
             "time-share movement and the boundary-gap shift",
    )
    parser.add_argument(
        "--format", choices=["text", "github"], default="text",
        help="github: workflow annotations + step summary lines",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="fail unless SyncStats args and segment child spans agree "
             "within 1%% (the dump's internal cross-validation)",
    )
    parser.add_argument(
        "--request", metavar="TRACE_ID", default=None,
        help="render one request's waterfall from its span links and "
             "cross-check it within 1%% against the serve latency "
             "histogram observation for that request",
    )
    args = parser.parse_args(argv)

    if args.compare is not None:
        path_a, path_b = args.compare
        reports = []
        for path in (path_a, path_b):
            try:
                reports.append(summarize(load_events(path)))
            except (OSError, ValueError, json.JSONDecodeError) as e:
                msg = f"unreadable trace {path}: {e}"
                if args.format == "github":
                    print(f"::error title=trace-report::{msg}")
                else:
                    print(f"trace-report: {msg}", file=sys.stderr)
                return 2
        cmp = compare(reports[0], reports[1])
        for label, path in (("A", path_a), ("B", path_b)):
            info = load_build_info(path)
            if info:
                cmp.setdefault("build_info", {})[label] = info
        if args.json:
            print(json.dumps(cmp, indent=2))
            return 0
        if args.format == "github":
            gaps = cmp["boundary_gaps"]
            print(
                f"::notice title=trace-report compare::"
                f"{path_a} vs {path_b}: boundary gap mean "
                f"{gaps['a_mean_ms']:.3f} -> {gaps['b_mean_ms']:.3f}ms "
                f"({gaps['mean_delta_ms']:+.3f})"
            )
        print(render_compare(cmp, path_a, path_b))
        for label in ("A", "B"):
            info = cmp.get("build_info", {}).get(label)
            if info:
                fields = " ".join(
                    f"{k}={info[k]}" for k in sorted(info))
                print(f"build {label}: {fields}")
        return 0

    if args.trace is None:
        parser.error("trace file required (or use --compare A B)")

    try:
        events = load_events(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        msg = f"unreadable trace {args.trace}: {e}"
        if args.format == "github":
            print(f"::error title=trace-report::{msg}")
        else:
            print(f"trace-report: {msg}", file=sys.stderr)
        return 2

    if args.request is not None:
        wf = request_waterfall(events, args.request)
        if wf is None:
            msg = f"no events for request {args.request} in {args.trace}"
            if args.format == "github":
                print(f"::error title=trace-report::{msg}")
            else:
                print(f"trace-report: {msg}", file=sys.stderr)
            return 2
        violations = request_crosscheck(wf)
        if args.json:
            print(json.dumps(wf, indent=2))
        else:
            if args.format == "github":
                print(
                    f"::notice title=trace-report request::"
                    f"{wf['request']}: {wf['events']} events, "
                    f"{len(wf['processes'])} processes, "
                    f"{wf['span_total_ms']:.3f}ms end to end"
                )
            print(render_waterfall(wf))
        for msg in violations:
            if args.format == "github":
                print(f"::error title=trace-report crosscheck::{msg}")
            else:
                print(f"trace-report: CROSSCHECK FAILED: {msg}",
                      file=sys.stderr)
        return 1 if violations else 0

    report = summarize(events)
    violations = crosscheck(report) if args.selftest else []

    if args.json:
        print(json.dumps(report, indent=2))
    elif args.format == "github":
        seg = report["segments"]
        print(
            f"::notice title=trace-report::{args.trace}: "
            f"{report['events']} events, {report['spans']} spans, "
            f"{seg['count']} segments "
            f"(device {seg['device_share']:.1%} / "
            f"host {seg['host_share']:.1%})"
        )
        print(render_text(report))
    else:
        print(render_text(report))

    for msg in violations:
        if args.format == "github":
            print(f"::error title=trace-report crosscheck::{msg}")
        else:
            print(f"trace-report: CROSSCHECK FAILED: {msg}", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
