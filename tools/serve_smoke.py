"""Acceptance smoke for the serving front-end (fishnet_tpu/serve/).

Boots `python -m fishnet_tpu serve --backend python --serve-port 0`
as a real subprocess, discovers the ephemeral port from the
`serve: listening on host:port` headline, then:

1. exactly-once under concurrency — N client threads (default 16, one
   HTTP connection each) fire mixed /analyse + /bestmove requests with
   unique ids; every id must come back exactly once, HTTP 200, with one
   result per submitted position and a best_move on each;
2. graceful drain — a second wave is launched and SIGTERM lands while
   it is in flight; every already-accepted request must still answer
   200 (the drain finishes in-flight work) and the server must exit 0
   after printing its final stats line.

Pure stdlib (threads + http.client, deliberately *not* asyncio: the
point is independent real connections), CI-friendly:

    python tools/serve_smoke.py
    python tools/serve_smoke.py --clients 16 --format=github
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
LISTEN_PREFIX = "serve: listening on "
BOOT_TIMEOUT_S = 60.0
EXIT_TIMEOUT_S = 30.0


class SmokeFailure(Exception):
    pass


def _start_server():
    """Spawn the serve subprocess; returns (proc, line_queue, host, port)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "fishnet_tpu", "serve",
         "--backend", "python", "--serve-port", "0", "--no-conf"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=str(Path(__file__).resolve().parents[1]),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    lines: "queue.Queue[str]" = queue.Queue()

    def pump():
        assert proc.stdout is not None
        for line in proc.stdout:
            sys.stdout.write(f"  [serve] {line}")
            lines.put(line.rstrip("\n"))
        lines.put("")  # EOF marker

    threading.Thread(target=pump, daemon=True).start()

    deadline = time.monotonic() + BOOT_TIMEOUT_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise SmokeFailure("server never printed its listening line")
        try:
            line = lines.get(timeout=remaining)
        except queue.Empty:
            raise SmokeFailure(
                "server never printed its listening line"
            ) from None
        if not line and proc.poll() is not None:
            raise SmokeFailure(
                f"server exited early with code {proc.returncode}"
            )
        if LISTEN_PREFIX in line:
            hostport = line.split(LISTEN_PREFIX, 1)[1].strip()
            host, _, port = hostport.rpartition(":")
            return proc, lines, host, int(port)


def _body_for(client_id: int, req_id: str, i: int) -> tuple:
    """Alternate analysis and bestmove shapes, varying position count."""
    if (client_id + i) % 2 == 0:
        n_pos = 1 + (i % 3)
        return "/analyse", {
            "id": req_id,
            "tenant": f"smoke-{client_id % 4}",
            "positions": [
                {"fen": START, "moves": ["e2e4", "e7e5"][: (i + k) % 3]}
                for k in range(n_pos)
            ],
            "depth": 2,
            "timeout_ms": 30_000,
        }
    return "/bestmove", {
        "id": req_id,
        "tenant": f"smoke-{client_id % 4}",
        "positions": [{"fen": START, "moves": ["e2e4"][: i % 2]}],
        "level": 1 + (i % 8),
        "timeout_ms": 30_000,
    }


def _post(host: str, port: int, path: str, body: dict) -> tuple:
    conn = http.client.HTTPConnection(host, port, timeout=60.0)
    try:
        conn.request(
            "POST", path, body=json.dumps(body),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        payload = json.loads(resp.read().decode("utf-8"))
        return resp.status, payload
    finally:
        conn.close()


def _client_wave(host, port, clients, per_client, results, errors):
    """Run `clients` threads, each sending `per_client` requests over
    its own connections; record (id -> [payloads]) and errors."""
    lock = threading.Lock()

    def one_client(cid: int):
        for i in range(per_client):
            req_id = f"c{cid:02d}-r{i}"
            path, body = _body_for(cid, req_id, i)
            try:
                status, payload = _post(host, port, path, body)
            except (OSError, ValueError) as e:
                with lock:
                    errors.append(f"{req_id}: transport error: {e}")
                continue
            with lock:
                results.setdefault(req_id, []).append(
                    (status, path, len(body["positions"]), payload)
                )

    threads = [
        threading.Thread(target=one_client, args=(cid,))
        for cid in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
        if t.is_alive():
            errors.append("client thread hung")


def _check_exactly_once(results, errors, expected_ids):
    for req_id in expected_ids:
        got = results.get(req_id, [])
        if len(got) != 1:
            errors.append(
                f"{req_id}: expected exactly one response, got {len(got)}"
            )
            continue
        status, path, n_pos, payload = got[0]
        if status != 200:
            errors.append(f"{req_id}: HTTP {status}: {payload}")
            continue
        if payload.get("id") != req_id:
            errors.append(f"{req_id}: echoed id {payload.get('id')!r}")
            continue
        res = payload.get("results", [])
        if len(res) != n_pos:
            errors.append(
                f"{req_id}: {len(res)} results for {n_pos} positions"
            )
            continue
        if any(not r.get("best_move") for r in res):
            errors.append(f"{req_id}: missing best_move in {path} result")


def run_smoke(clients: int, per_client: int) -> None:
    proc, lines, host, port = _start_server()
    try:
        # ---- wave 1: exactly-once under concurrency ------------------
        print(f"serve-smoke: wave 1 — {clients} clients x {per_client} "
              f"requests against {host}:{port}")
        results: dict = {}
        errors: list = []
        _client_wave(host, port, clients, per_client, results, errors)
        expected = [
            f"c{cid:02d}-r{i}"
            for cid in range(clients) for i in range(per_client)
        ]
        _check_exactly_once(results, errors, expected)
        if errors:
            raise SmokeFailure(
                f"wave 1: {len(errors)} failure(s): " + "; ".join(errors[:5])
            )
        print(f"serve-smoke: wave 1 ok — {len(expected)} requests, "
              "exactly-once, all 200")

        # ---- wave 2: SIGTERM mid-flight must drain -------------------
        print("serve-smoke: wave 2 — SIGTERM mid-flight")
        results2: dict = {}
        errors2: list = []
        wave = threading.Thread(
            target=_client_wave,
            args=(host, port, clients, 2, results2, errors2),
        )
        wave.start()
        time.sleep(0.15)  # let requests get in flight
        proc.send_signal(signal.SIGTERM)
        wave.join(timeout=120.0)
        if wave.is_alive():
            raise SmokeFailure("wave 2: client wave hung after SIGTERM")

        # after SIGTERM, accepted requests must have completed (200) and
        # late ones may be refused (connection error / 503) — but no
        # request may vanish or double-answer
        accepted = {rid: g for rid, g in results2.items() if g}
        for rid, got in accepted.items():
            if len(got) != 1:
                raise SmokeFailure(
                    f"wave 2: {rid} answered {len(got)} times"
                )
            status = got[0][0]
            if status not in (200, 503):
                raise SmokeFailure(f"wave 2: {rid} got HTTP {status}")
        n_ok = sum(1 for g in accepted.values() if g[0][0] == 200)
        if n_ok == 0:
            raise SmokeFailure("wave 2: no request completed through drain")

        try:
            code = proc.wait(timeout=EXIT_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            raise SmokeFailure(
                "server did not exit within the drain window"
            ) from None
        if code != 0:
            raise SmokeFailure(f"server exited {code} after SIGTERM")
        print(f"serve-smoke: wave 2 ok — {n_ok} in-flight request(s) "
              "drained, server exited 0")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=16,
                        help="concurrent client threads (default 16)")
    parser.add_argument("--requests-per-client", type=int, default=3)
    parser.add_argument("--format", choices=["text", "github"],
                        default="text")
    args = parser.parse_args(argv)

    try:
        run_smoke(args.clients, args.requests_per_client)
    except SmokeFailure as e:
        if args.format == "github":
            print(f"::error title=serve smoke::{e}")
        print(f"serve-smoke: FAIL: {e}")
        return 1
    print("serve-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
