"""Regression detector over the perf ledger (fishnet_tpu/obs/perf.py).

Compares the latest ledger run against a rolling baseline built from
prior runs measured under the SAME env fingerprint (the AOT store
fingerprint digest, aot/keys.py) and classifies every metric through
the direction table:

- **direction**: up (throughput — nps, positions/s, positions_per_kstep,
  scaling_x, occupancy fractions, cache warm ratio), down (latency and
  overheads — p50/p99, dt, host_ms, transfers, shed/deadline misses),
  or flat (deterministic totals that must not move at all for a fixed
  workload — nodes, steps, refills, segments, positions done).
- **stability tier**: `counter` metrics are deterministic on a fixed
  workload (search is bit-reproducible), so they gate hard in CI;
  `wallclock` metrics vary with the runner and only ever annotate.

Noise bands come from the baseline history itself (2x the relative
stddev, floored at FISHNET_TPU_PERF_BAND for counters / 15% for wall
clock). Rows are gated only when fingerprints match exactly: a run
with no fingerprint (backfilled artifacts, no-JAX environments) or a
fingerprint unseen in history is compared report-only — never failed —
and a metric with no baseline passes by definition (first run).

Usage:
  python tools/perf_report.py                  # report, text table
  python tools/perf_report.py --check          # exit 1 on regression
  python tools/perf_report.py --check --format=github   # CI perf-gate
  python tools/perf_report.py --json
"""
from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from fishnet_tpu.obs import perf  # noqa: E402

# (pattern matched against the metric's last dotted component,
#  direction, stability tier). First match wins; order matters —
# e.g. positions_per_kstep (up/counter) before the bare positions
# total (flat/counter).
DIRECTION_TABLE: Tuple[Tuple[str, str, str], ...] = (
    (r"^positions_per_kstep$", "up", "counter"),
    (r"^scaling_x$", "up", "counter"),
    (r"^efficiency$", "up", "counter"),
    (r"^(mean_live_frac|mean_live_occupancy|shard_mean_live|occupancy)$",
     "up", "counter"),
    (r"^(hit_ratio|warm_x|speedup|bit_identical|ok)$", "up", "counter"),
    (r"^(transfers|transfers_per_boundary)$", "down", "counter"),
    (r"^(nodes|primary_nodes|steps|steps_per_shard|segments|refills|"
     r"boundaries|positions|positions_done|done|helpers|entries|"
     r"coalesced|rc)$", "flat", "counter"),
    (r"^(nps|positions_per_s|positions_done_per_s|value|vs_baseline|"
     r"rps)$", "up", "wallclock"),
    (r"(^|_)(p50|p90|p99|p999)(_ms)?$", "down", "wallclock"),
    (r"(_ms|_s|_seconds)$", "down", "wallclock"),
    (r"^(dt|shed|deadline_miss|miss_rate|misses)$", "down", "wallclock"),
    (r"^(flops|bytes_accessed|peak_bytes|argument_bytes|output_bytes|"
     r"code_bytes)$", "down", "counter"),
)

_COMPILED_TABLE = [
    (re.compile(pat), direction, tier)
    for pat, direction, tier in DIRECTION_TABLE
]

# minimum relative noise bands per tier (the stddev-derived band can
# only widen these); counters override via FISHNET_TPU_PERF_BAND
DEFAULT_COUNTER_BAND = 0.02
WALLCLOCK_BAND = 0.15


def classify(metric: str) -> Tuple[str, str]:
    """(direction, tier) for one (possibly dotted) metric name;
    unmatched names report-only as ('flat', 'wallclock')."""
    leaf = metric.rsplit(".", 1)[-1]
    for rx, direction, tier in _COMPILED_TABLE:
        if rx.search(leaf):
            return direction, tier
    return "flat", "wallclock"


def counter_band() -> float:
    try:
        from fishnet_tpu.utils import settings

        raw = settings.get_str("FISHNET_TPU_PERF_BAND")
        if raw:
            return max(0.0, float(raw))
    except Exception:
        pass
    return DEFAULT_COUNTER_BAND


def noise_band(history: List[float], tier: str,
               min_counter_band: Optional[float] = None) -> float:
    """Relative band: 2x the baseline's relative stddev, floored at
    the tier minimum — a noisy series earns itself a wide band, a
    perfectly stable counter series keeps the tight floor."""
    floor = (min_counter_band if min_counter_band is not None
             else counter_band()) if tier == "counter" else WALLCLOCK_BAND
    if len(history) < 2:
        return floor
    mean = statistics.fmean(history)
    if mean == 0:
        return floor
    rel = statistics.pstdev(history) / abs(mean)
    return max(floor, 2.0 * rel)


def evaluate(ledger: "perf.PerfLedger", window: int = 5,
             min_counter_band: Optional[float] = None) -> Dict:
    """The full comparison of the latest run vs its rolling baseline.
    Returns {run, rows: [...]}; each row carries status:
      ok / regression / improved / no-baseline / unfingerprinted
    and `gated` (hard-fail eligible: counter tier + matching
    fingerprint + a real baseline)."""
    latest = ledger.latest_run()
    if latest is None:
        return {"run": None, "rows": []}
    fingerprint = latest.get("fingerprint") or ""
    rows: List[Dict] = []
    for bench_row, metrics in sorted(
            ledger.run_metrics(latest["run_id"]).items()):
        for metric, value in sorted(metrics.items()):
            direction, tier = classify(metric)
            entry: Dict = {
                "bench_row": bench_row,
                "metric": metric,
                "value": value,
                "direction": direction,
                "tier": tier,
                "baseline": None,
                "band": None,
                "delta": None,
                "gated": False,
            }
            if not fingerprint:
                entry["status"] = "unfingerprinted"
                rows.append(entry)
                continue
            hist = [
                v for _, v in ledger.history(
                    bench_row, metric, fingerprint=fingerprint,
                    before_seq=latest["seq"], limit=window,
                )
            ]
            if not hist:
                entry["status"] = "no-baseline"
                rows.append(entry)
                continue
            baseline = statistics.fmean(hist)
            band = noise_band(hist, tier, min_counter_band)
            delta = ((value - baseline) / abs(baseline)
                     if baseline != 0 else (0.0 if value == 0 else 1.0))
            entry.update(baseline=round(baseline, 6), band=round(band, 6),
                         delta=round(delta, 6))
            entry["gated"] = tier == "counter"
            if direction == "up":
                worse, better = delta < -band, delta > band
            elif direction == "down":
                worse, better = delta > band, delta < -band
            else:  # flat: any out-of-band move is a regression
                worse, better = abs(delta) > band, False
            entry["status"] = (
                "regression" if worse else "improved" if better else "ok"
            )
            rows.append(entry)
    return {"run": latest, "rows": rows}


def hard_regressions(report: Dict) -> List[Dict]:
    return [
        r for r in report["rows"]
        if r["status"] == "regression" and r["gated"]
    ]


def soft_regressions(report: Dict) -> List[Dict]:
    return [
        r for r in report["rows"]
        if r["status"] == "regression" and not r["gated"]
    ]


def _fmt_delta(row: Dict) -> str:
    return f"{row['delta']:+.1%}" if row["delta"] is not None else "·"


def render_text(report: Dict) -> str:
    run = report["run"]
    if run is None:
        return "perf-report: empty ledger (no runs recorded)"
    lines = [
        f"run {run['run_id']} (seq {run['seq']}, sha "
        f"{run['git_sha'] or '?'}, env {run['fingerprint'] or 'none'}, "
        f"source {run['source']})",
        "",
        f"{'row':<28} {'metric':<34} {'value':>14} {'baseline':>14} "
        f"{'Δ':>8} {'dir':<4} {'tier':<9} status",
    ]
    for r in report["rows"]:
        base = (f"{r['baseline']:>14.4g}" if r["baseline"] is not None
                else f"{'·':>14}")
        lines.append(
            f"{r['bench_row']:<28} {r['metric']:<34} {r['value']:>14.4g} "
            f"{base} {_fmt_delta(r):>8} {r['direction']:<4} "
            f"{r['tier']:<9} {r['status']}"
        )
    hard, soft = hard_regressions(report), soft_regressions(report)
    lines += [
        "",
        f"{len(report['rows'])} metrics: "
        f"{len(hard)} gated regression(s), "
        f"{len(soft)} report-only regression(s)",
    ]
    return "\n".join(lines)


def render_github(report: Dict) -> str:
    run = report["run"]
    out: List[str] = []
    if run is None:
        out.append("::notice title=perf-report::empty ledger, nothing "
                   "to gate")
        return "\n".join(out)
    for r in hard_regressions(report):
        out.append(
            f"::error title=perf-gate {r['bench_row']}.{r['metric']}::"
            f"deterministic {r['direction']}-metric moved "
            f"{_fmt_delta(r)} vs rolling baseline {r['baseline']:g} "
            f"(band ±{r['band']:.1%})"
        )
    for r in soft_regressions(report):
        out.append(
            f"::warning title=perf-drift {r['bench_row']}.{r['metric']}::"
            f"wall-clock metric moved {_fmt_delta(r)} vs baseline "
            f"{r['baseline']:g} (band ±{r['band']:.1%}; report-only)"
        )
    hard, soft = hard_regressions(report), soft_regressions(report)
    out.append(
        f"::notice title=perf-report::run {run['run_id']}: "
        f"{len(report['rows'])} metrics, {len(hard)} gated regressions, "
        f"{len(soft)} wall-clock drifts"
    )
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="perf-report")
    parser.add_argument(
        "--ledger", default=None,
        help="sqlite ledger path (default: FISHNET_TPU_PERF_LEDGER or "
             "perf_ledger.db at the checkout root; created + backfilled "
             "from BENCH_r*/MULTICHIP_r* artifacts when missing)",
    )
    parser.add_argument(
        "--check", action="store_true",
        help="exit 1 when any deterministic counter metric regresses "
             "out of band (wall-clock drift never fails)",
    )
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON")
    parser.add_argument(
        "--format", choices=["text", "github"], default="text",
        help="github: workflow error/warning/notice annotations",
    )
    parser.add_argument(
        "--window", type=int, default=None,
        help="rolling-baseline window in runs "
             "(default FISHNET_TPU_PERF_WINDOW)",
    )
    parser.add_argument(
        "--no-backfill", action="store_true",
        help="do not ingest checked-in BENCH/MULTICHIP artifacts into "
             "a fresh ledger",
    )
    args = parser.parse_args(argv)

    window = args.window
    if window is None:
        try:
            from fishnet_tpu.utils import settings

            window = settings.get_int("FISHNET_TPU_PERF_WINDOW")
        except Exception:
            window = 5
    window = max(1, window)

    path = args.ledger or perf.default_ledger_path()
    fresh = not os.path.exists(path)
    ledger = perf.PerfLedger.open(path)
    try:
        if fresh and not args.no_backfill:
            n = ledger.backfill()
            if n and args.format != "github":
                print(f"perf-report: backfilled {n} metric rows from "
                      "checked-in artifacts", file=sys.stderr)
        report = evaluate(ledger, window=window)
    finally:
        ledger.close()

    if args.json:
        print(json.dumps(report, indent=2))
    elif args.format == "github":
        print(render_github(report))
    else:
        print(render_text(report))

    if args.check and hard_regressions(report):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
