"""Measure time-to-depth and node counts for the production search shape.

VERDICT r3 #2: `tpu_depth` defaults must be backed by a measured
depth × wall-clock × nodes table at the production program shape
(MAX_PLY=32 unless FISHNET_TPU_MAX_PLY trims it), not guesses. Run on
the TPU when the tunnel is up; on CPU the node counts are still exact
(the lockstep program is platform-deterministic) and wall-clock is a
lower-bound sanity check only.

Usage:
  python tools/depth_table.py --depths 4,6,8 --lanes 256
  FISHNET_TPU_NO_PRUNING=1 python tools/depth_table.py ...   # A/B pruning
  python tools/depth_table.py --force-cpu ...                # node counts only
  python tools/depth_table.py --helpers 4 ...                # Lazy-SMP lanes

--helpers K > 1 replicates each root across K-1 extra lanes with perturbed
move ordering (ops/search.py order_jitter), sharing the TT with
depth-preferred stores — the engine's helper-lane configuration. The JSON
then counts primary lanes/nodes separately and reports lockstep steps,
the platform-independent cost proxy (equal widths ⇒ wall ∝ steps).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--depths", default="4,6,8")
    ap.add_argument("--lanes", type=int, default=256)
    ap.add_argument("--budget", type=int, default=5_000_000)
    ap.add_argument("--max-ply", type=int, default=None,
                    help="default: engine MAX_PLY (32 in production)")
    ap.add_argument("--tt-log2", type=int, default=21)
    ap.add_argument("--helpers", type=int, default=1,
                    help="Lazy-SMP lanes per position (1 disables)")
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    if args.force_cpu:
        from tools import force_cpu  # noqa: F401

    import jax
    import numpy as np

    from fishnet_tpu.chess import Position
    from fishnet_tpu.engine.tpu import MAX_PLY
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import tt as tt_mod
    from fishnet_tpu.ops.board import from_position, stack_boards
    from fishnet_tpu.ops.search import _PRUNING, search_batch_resumable
    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()
    max_ply = args.max_ply or MAX_PLY
    platform = jax.default_backend()

    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
        "r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
        "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
    ]
    import jax.numpy as jnp

    B = args.lanes
    K = max(1, args.helpers)
    Bt = B * K
    boards = [from_position(Position.from_fen(fens[i % len(fens)]))
              for i in range(B)]
    # helper layout mirrors bench.py: primaries in rows [0, B), then K-1
    # replica blocks; row h*B + j helps primary j via the shared TT
    roots = stack_boards(boards * K)
    helper_kw = {}
    if K > 1:
        jit_arr = np.zeros(Bt, np.int32)
        for h in range(1, K):
            for j in range(B):
                jit_arr[h * B + j] = j * K + h  # nonzero ⇔ helper lane
        required = np.zeros(Bt, bool)
        required[:B] = True  # a depth is "done" when the primaries are
        helper_kw = dict(
            order_jitter=jnp.asarray(jit_arr),
            group=jnp.asarray(np.arange(Bt, dtype=np.int32) % B),
            required=required, prefer_deep_store=True, tt_gen=1,
        )
    from fishnet_tpu.assets import load_default_params

    params = load_default_params("board768") or nnue.init_params(
        jax.random.PRNGKey(0), l1=64, feature_set="board768"
    )
    tt = tt_mod.make_table(args.tt_log2) if args.tt_log2 else None

    for d in (int(x) for x in args.depths.split(",") if x):
        # fresh TT per depth so depths don't subsidize each other
        tt_d = tt_mod.make_table(args.tt_log2) if args.tt_log2 else None
        # warmup dispatch compiles the (Bt, max_ply) program
        out = search_batch_resumable(
            params, roots, 1, 64, max_ply=max_ply, tt=tt_d, **helper_kw,
        )
        out.pop("tt")
        jax.block_until_ready(out["nodes"])
        tt_d = tt_mod.make_table(args.tt_log2) if args.tt_log2 else None
        t0 = time.perf_counter()
        out = search_batch_resumable(
            params, roots, d, args.budget, max_ply=max_ply, tt=tt_d,
            max_steps=50_000_000, **helper_kw,
        )
        out.pop("tt")
        jax.block_until_ready(out["nodes"])
        wall = time.perf_counter() - t0
        nodes = int(np.asarray(out["nodes"]).sum())
        primary_nodes = int(np.asarray(out["nodes"])[:B].sum())
        print(json.dumps({
            "depth": d, "lanes": B, "helpers": K, "nodes": nodes,
            "primary_nodes": primary_nodes,
            "steps": int(out["steps"]),
            "wall_s": round(wall, 3), "nps": round(nodes / wall),
            "per_pos_nodes": primary_nodes // B,
            "platform": platform, "pruning": _PRUNING,
            "done": bool(np.asarray(out["done"])[:B].all()),
        }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
