"""Acceptance smoke for multi-host mesh lowering (parallel/distributed.py).

Proves the pod-slice contract end to end, in real processes (the whole
point is a Mesh spanning process boundaries — a single-process
forced-device run exercises none of the jax.distributed placement or
the host-level boundary exchange):

1. **single reference** — one process, XLA forced to 2 CPU devices:
   a pipelined `search_stream` chunk over a staggered 4-position
   workload on a 2-device mesh; records scores/moves/nodes/PVs and the
   per-boundary occupancy log.
2. **distributed pair** — two concurrent processes, 1 CPU device each,
   joined via `jax.distributed` (FISHNET_TPU_MESH_HOSTS=2 + coordinator
   settings, exactly the env a `pod:2` fleet member injects): the SAME
   chunk through the SAME registry-derived sharded callables, with the
   boundary summary and finished-lane PV rows assembled through the
   addressable-shard fetches + host exchange.

Gate (any failure exits 1):

* both distributed processes come up (process_count == 2) and finish;
* scores, moves, nodes, PVs and total step counts bit-identical to the
  single-process reference — same global mesh shape (2 devices), same
  shard layout, so the lowering must not change a single bit;
* every no-finish boundary in the distributed run cost exactly ONE
  SyncStats fetch on the reporting host — the pipelined scheduler's
  one-fetch-per-boundary property survives multi-host lowering.

    JAX_PLATFORMS=cpu python tools/mesh_smoke.py
    JAX_PLATFORMS=cpu python tools/mesh_smoke.py --format=github
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

REPO_ROOT = Path(__file__).resolve().parents[1]

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "FISHNET_TPU_MAX_PLY": "8",
    "FISHNET_TPU_HELPERS": "1",
    # the SegmentController adapts on wall-clock; bit-identity needs a
    # pinned boundary cadence
    "FISHNET_TPU_SEGMENT": "150",
    "FISHNET_TPU_PIPELINE": "1",
}
CHILD_TIMEOUT_S = 540.0

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
GAME = ["e2e4", "c7c5", "g1f3"]
# staggered: lanes park at different boundaries on different shards, so
# the finished-lane gather path runs while other lanes are still live
DEPTHS = [1, 3, 2, 3]
WIDTH = 4
BUDGET = 120_000
MAX_PLY = 6
TT_LOG2 = 10


class SmokeFailure(Exception):
    pass


# --------------------------------------------------------------- child


def run_child(role: str, out_path: str) -> int:
    """--role single|dist: run the workload on a 2-device mesh and write
    a JSON report. Both distributed processes drive the identical loop
    (SPMD discipline); only process 0 writes."""
    pid = 0
    if role == "dist":
        # must run before ANY device use: jax.distributed turns the two
        # 1-device processes into one 2-device platform
        from fishnet_tpu.parallel import distributed as dist

        if not dist.ensure_initialized():
            print("  [child] FISHNET_TPU_MESH_HOSTS not set", flush=True)
            return 1
        import jax

        pid = jax.process_index()
        if jax.process_count() != 2:
            print(f"  [child] process_count={jax.process_count()}",
                  flush=True)
            return 1

    import jax
    import numpy as np

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from fishnet_tpu.ops.board import from_position, stack_boards
    from fishnet_tpu.parallel.mesh import make_mesh, make_sharded_table
    from fishnet_tpu.utils.syncstats import SyncStats

    t0 = time.monotonic()
    mesh = make_mesh()
    if mesh.devices.size != 2:
        print(f"  [child] mesh has {mesh.devices.size} device(s), want 2",
              flush=True)
        return 1

    params = nnue.init_params(jax.random.PRNGKey(3), l1=64,
                              feature_set="board768")
    boards, p = [], Position.from_fen(START)
    for uci in [None] + GAME:
        if uci is not None:
            p = p.push(p.parse_uci(uci))
        boards.append(from_position(p))
    roots = stack_boards(boards)

    stats = SyncStats()
    out = S.search_stream(
        params, roots,
        np.asarray(DEPTHS, np.int32),
        np.full(len(DEPTHS), BUDGET, np.int32),
        max_ply=MAX_PLY, width=WIDTH,
        tt=make_sharded_table(mesh, TT_LOG2),
        mesh=mesh, pipeline=True, sync_stats=stats,
    )
    report = {
        "role": role,
        "process_index": pid,
        "process_count": int(jax.process_count()),
        "devices": int(mesh.devices.size),
        "scores": np.asarray(out["score"]).astype(int).tolist(),
        "moves": np.asarray(out["move"]).astype(int).tolist(),
        "nodes": np.asarray(out["nodes"]).astype(int).tolist(),
        "pv": np.asarray(out["pv"]).astype(int).tolist(),
        "pv_len": np.asarray(out["pv_len"]).astype(int).tolist(),
        "done": np.asarray(out["done"]).astype(bool).tolist(),
        "steps": int(np.asarray(out["steps"])),
        "occupancy": [
            {k: r[k] for k in ("segment", "steps", "live", "refilled",
                               "transfers")}
            for r in out["occupancy"]
        ],
        "wall_s": round(time.monotonic() - t0, 2),
    }
    if pid == 0:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh)
    print(f"  [child p{pid}] done in {report['wall_s']}s: "
          f"scores={report['scores']} steps={report['steps']}", flush=True)
    return 0


# -------------------------------------------------------------- parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _load_json(path: Path, what: str) -> dict:
    try:
        return json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        raise SmokeFailure(f"{what} unreadable: {e}") from None


def _drain(tag: str, proc: subprocess.Popen, timeout_s: float) -> None:
    try:
        stdout, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        raise SmokeFailure(f"{tag} timed out after {timeout_s:.0f}s")
    for line in (stdout or "").splitlines():
        print(f"  [{tag}] {line}")
    if proc.returncode != 0:
        raise SmokeFailure(f"{tag} exited {proc.returncode}")


def run_smoke(keep: bool) -> None:
    tmp = Path(tempfile.mkdtemp(prefix="mesh-smoke-"))
    base = {**os.environ, **SMOKE_ENV}
    for k in ("XLA_FLAGS", "FISHNET_TPU_MESH_HOSTS",
              "FISHNET_TPU_MESH_COORDINATOR",
              "FISHNET_TPU_MESH_PROCESS_ID"):
        base.pop(k, None)
    me = str(Path(__file__).resolve())
    try:
        # ---- 1. single-process reference, forced 2 devices -----------
        ref_json = tmp / "ref.json"
        print("mesh-smoke: single-process reference (2 forced devices)",
              flush=True)
        proc = subprocess.Popen(
            [sys.executable, me, "--role", "single", "--out",
             str(ref_json)],
            cwd=str(REPO_ROOT),
            env={**base,
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
            text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        _drain("single", proc, CHILD_TIMEOUT_S)
        ref = _load_json(ref_json, "single-process report")
        if not all(ref["done"]):
            raise SmokeFailure(f"reference left positions unfinished: "
                               f"{ref['done']}")

        # ---- 2. two-process jax.distributed pair ---------------------
        port = _free_port()
        dist_json = tmp / "dist.json"
        print(f"mesh-smoke: distributed pair (coordinator 127.0.0.1:"
              f"{port}, exchange on {port + 1})", flush=True)
        procs = []
        for pid in (0, 1):
            env = {
                **base,
                "FISHNET_TPU_MESH_HOSTS": "2",
                "FISHNET_TPU_MESH_COORDINATOR": f"127.0.0.1:{port}",
                "FISHNET_TPU_MESH_PROCESS_ID": str(pid),
            }
            procs.append(subprocess.Popen(
                [sys.executable, me, "--role", "dist", "--out",
                 str(dist_json)],
                cwd=str(REPO_ROOT), env=env, text=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            ))
        # both must run concurrently — drain sequentially only after
        # both are launched (a worker blocks in initialize() until the
        # coordinator is up, and vice versa for the exchange)
        errs = []
        for pid, proc in enumerate(procs):
            try:
                _drain(f"dist p{pid}", proc, CHILD_TIMEOUT_S)
            except SmokeFailure as e:
                errs.append(str(e))
                for other in procs:
                    if other.poll() is None:
                        other.kill()
        if errs:
            raise SmokeFailure("; ".join(errs))
        dist = _load_json(dist_json, "distributed report")
        if dist["process_count"] != 2:
            raise SmokeFailure(
                f"distributed run spanned {dist['process_count']} "
                "process(es), want 2")

        # ---- 3. bit-identity ----------------------------------------
        for key in ("scores", "moves", "nodes", "pv", "pv_len", "done",
                    "steps"):
            if ref[key] != dist[key]:
                raise SmokeFailure(
                    f"distributed {key} diverged from single-process "
                    f"reference: {dist[key]} vs {ref[key]}")
        print(f"mesh-smoke: bit-identical — scores {ref['scores']}, "
              f"nodes {ref['nodes']}, {ref['steps']} steps")

        # ---- 4. one fetch per no-finish boundary ---------------------
        occ = dist["occupancy"]
        if not occ:
            raise SmokeFailure("distributed run recorded no boundaries")
        nofin = [r for r in occ[:-1] if r["refilled"] == 0]
        if not nofin:
            raise SmokeFailure("no quiet boundaries; shrink the segment")
        costly = [r for r in nofin if r["transfers"] != 1]
        if costly:
            raise SmokeFailure(
                "no-finish boundaries cost more than one fetch on the "
                f"reporting host: {costly}")
        print(f"mesh-smoke: boundary fetches ok — {len(nofin)} quiet "
              f"boundaries, all 1 transfer ({len(occ)} total)")
    finally:
        if not keep:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
        else:
            print(f"mesh-smoke: artifacts kept at {tmp}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--role", choices=["single", "dist"],
                        help=argparse.SUPPRESS)
    parser.add_argument("--out", metavar="OUT_JSON",
                        help=argparse.SUPPRESS)
    parser.add_argument("--keep", action="store_true",
                        help="keep the tempdir (reports)")
    parser.add_argument("--format", choices=["text", "github"],
                        default="text")
    args = parser.parse_args(argv)

    if args.role:
        return run_child(args.role, args.out)

    try:
        run_smoke(args.keep)
    except SmokeFailure as e:
        if args.format == "github":
            print(f"::error title=mesh smoke::{e}")
        print(f"mesh-smoke: FAIL: {e}")
        return 1
    print("mesh-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
