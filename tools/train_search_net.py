"""Train the next board768 net against DEEPER SEARCH labels.

VERDICT r2 #5: the shipped net is distilled from a handcrafted
material+PST+mobility target; the next step is self-distillation from
search — label positions with the device search's depth-d backed-up score
of the CURRENT net (TD-leaf style), and fit a fresh net to those labels.
Search backups see tactics the static eval misses, so the fitted eval
absorbs one tempo of tactics per iteration.

Labeling runs the batched lockstep search itself (lanes are cheap — the
same property the engine exploits), so 30k labels cost ~120 dispatches.

Usage:
  python tools/train_search_net.py --samples 20000 --depth 2 \
      --out /tmp/net-candidate.npz
  python tools/strength_ab.py --net /tmp/net-candidate.npz ...  # then A/B
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", default="fishnet_tpu/assets/nnue-board768-64.npz",
                    help="net whose search produces the labels")
    ap.add_argument("--samples", type=int, default=20_000)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--budget", type=int, default=20_000)
    ap.add_argument("--lanes", type=int, default=256)
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--warm-start", action="store_true",
                    help="initialize from --base instead of fresh")
    ap.add_argument("--out", default="/tmp/net-search-distilled.npz")
    ap.add_argument("--device", action="store_true",
                    help="label AND train on the real accelerator "
                         "(default: force CPU, the historical mode)")
    ap.add_argument("--classical-mix", type=float, default=0.25,
                    help="regularizer weight L: train against "
                         "(search + L*classical)/(1+L) — for MSE this "
                         "IS the sum-of-losses regularizer (identical "
                         "gradients up to scale); docs/strength.md "
                         "recipe (b) against label-noise memorization")
    ap.add_argument("--holdout", type=float, default=0.05,
                    help="fraction of labels held out; training stops "
                         "when held-out loss stops improving "
                         "(docs/strength.md recipe (c))")
    ap.add_argument("--patience", type=int, default=6,
                    help="early-stop after this many 250-step windows "
                         "without a held-out improvement")
    args = ap.parse_args()

    if not args.device:
        from tools import force_cpu  # noqa: F401  (deregisters axon)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from fishnet_tpu.models import nnue
    from fishnet_tpu.models.train import (
        diverse_position_dataset,
        make_train_step,
    )
    from fishnet_tpu.ops.board import Board, stack_boards
    from fishnet_tpu.ops.search import MATE, search_batch_jit
    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()
    base = nnue.load_params(args.base)

    print(f"generating {args.samples} positions ...", flush=True)
    boards, stms, classical = diverse_position_dataset(
        args.samples, seed=args.seed
    )

    print(f"labeling with depth-{args.depth} search of the base net ...",
          flush=True)
    B = args.lanes
    labels = np.zeros(args.samples, np.float32)
    t0 = time.time()
    for off in range(0, args.samples, B):
        sl = slice(off, min(off + B, args.samples))
        n = sl.stop - sl.start
        bb = np.zeros((B, 64), np.int32)
        ss = np.zeros((B,), np.int32)
        bb[:n] = boards[sl]
        ss[:n] = stms[sl]
        roots = Board(
            board=jnp.asarray(bb), stm=jnp.asarray(ss),
            ep=jnp.full((B,), -1, jnp.int32),
            castling=jnp.full((B, 4), -1, jnp.int32),
            halfmove=jnp.zeros((B,), jnp.int32),
            extra=jnp.zeros((B, 12), jnp.int32),
        )
        # max_steps caps the worst batch: random-material monsters (200+
        # moves/node) can spend millions of lockstep steps unwinding
        # after budget exhaustion (a 200k-label run stalled ~40 min on
        # one such batch); lanes cut off report done=False and fall back
        # to their classical target below — sane labels either way
        out = search_batch_jit(
            base, roots, args.depth, args.budget, max_ply=args.depth + 2,
            max_steps=250_000,
        )
        sc = np.asarray(out["score"])[:n].astype(np.float32)
        ok = np.asarray(out["done"])[:n]
        sc = np.where(ok, sc, classical[sl].astype(np.float32))
        # mate-range backups would dominate the regression loss; clamp to
        # the same range the eval itself lives in
        labels[sl] = np.clip(sc, -3000, 3000)
        if (off // B) % 10 == 0:
            done = sl.stop
            rate = done / max(time.time() - t0, 1e-9)
            print(f"  {done}/{args.samples} ({rate:,.0f} pos/s)", flush=True)

    # recipe (b): classical-target regularizer via label blending — for
    # MSE, min over p of (p-s)^2 + L*(p-c)^2 has the same gradients as
    # (1+L) * (p - (s+L*c)/(1+L))^2, so blending IS the regularizer
    lam = args.classical_mix
    labels = (labels + lam * classical.astype(np.float32)) / (1.0 + lam)

    # recipe (c): held-out split, early stop on held-out loss (cap so a
    # tiny --samples smoke run keeps a non-empty training split)
    n_hold = min(
        max(int(args.samples * args.holdout), args.batch),
        args.samples // 2,
    )
    rng = np.random.default_rng(args.seed)
    perm = rng.permutation(args.samples)
    hold, tr = perm[:n_hold], perm[n_hold:]
    hb, hs, hl = (jnp.asarray(boards[hold]), jnp.asarray(stms[hold]),
                  jnp.asarray(labels[hold]))

    print(f"training ({len(tr)} train / {n_hold} held out, "
          f"classical mix {lam}) ...", flush=True)
    if args.warm_start:
        params = base
    else:
        params = nnue.init_params(
            jax.random.PRNGKey(args.seed), l1=base.l1, feature_set="board768"
        )
    # cosine decay: the first self-distillation attempt diverged late on
    # a flat lr (docs/strength.md) — search-backup labels are noisy
    optimizer = optax.adam(
        optax.cosine_decay_schedule(args.lr, args.steps)
    )
    opt_state = optimizer.init(params)
    step = make_train_step(optimizer)
    from fishnet_tpu.models.train import loss_fn

    val_loss = jax.jit(loss_fn)
    loss = None
    best = (float("inf"), params, -1)
    stale = 0
    for i in range(args.steps):
        idx = tr[rng.integers(0, len(tr), size=args.batch)]
        params, opt_state, loss = step(
            params, opt_state,
            jnp.asarray(boards[idx]), jnp.asarray(stms[idx]),
            jnp.asarray(labels[idx]),
        )
        if i % 250 == 0:
            v = float(val_loss(params, hb, hs, hl))
            mark = ""
            if v < best[0] - 1e-4:
                best = (v, params, i)
                stale = 0
                mark = " *"
            else:
                stale += 1
            print(f"  step {i}: loss {float(loss):.4f} "
                  f"held-out {v:.4f}{mark}", flush=True)
            if stale >= args.patience:
                print(f"  early stop at step {i} (best held-out "
                      f"{best[0]:.4f} @ step {best[2]})", flush=True)
                break
    params = best[1]
    nnue.save_params(params, args.out)
    print(f"saved {args.out} (best held-out loss {best[0]:.4f} "
          f"@ step {best[2]})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
