"""Head-to-head strength A/B: a board768 net (device search) vs PyEngine.

VERDICT r1 #8's acceptance check: the shipped net must beat the old one
head-to-head. This harness plays N games of (device search @ depth D)
against PyEngine (material+mobility, depth d) from varied short random
openings, alternating colors, and prints W/D/L + score.

All games play SIMULTANEOUSLY: each move cycle batches every live game
where it is the net's turn into one lockstep search dispatch (the same
lanes-are-cheap property the engine exploits), so N games cost ~one
game's worth of dispatches instead of N.

Usage:
  python tools/strength_ab.py --net fishnet_tpu/assets/nnue-board768-64.npz \
      --games 200 --depth 3
  python tools/strength_ab.py --net old.npz --label old ...   # compare runs
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--net", required=True)
    ap.add_argument("--opponent-net", default=None,
                    help="net-vs-net: the opponent plays device search "
                         "with THIS net (at --py-depth) instead of "
                         "PyEngine")
    ap.add_argument("--games", type=int, default=200)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--py-depth", type=int, default=2)
    ap.add_argument("--max-plies", type=int, default=160)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--label", default="net")
    ap.add_argument("--skill", type=int, default=None,
                    help="lichess move-job skill 1-8 for the NET side: "
                         "root moves become lanes and the engine's "
                         "weakness sampler picks (validates the skill "
                         "model, reference src/api.rs:248-283)")
    ap.add_argument("--opponent-skill", type=int, default=None,
                    help="same, for the opponent side (requires "
                         "--opponent-net for net-vs-net, or uses the "
                         "same net)")
    ap.add_argument("--device", action="store_true",
                    help="run on the real accelerator (default: force "
                         "CPU, the tool's historical mode — device runs "
                         "are ~50x faster per cycle)")
    ap.add_argument("--helpers", type=int, default=1,
                    help="Lazy-SMP helper lanes per game position for the "
                         "full-strength move dispatches (1 disables; "
                         "skill-sampled dispatches already decompose root "
                         "moves into lanes and ignore this)")
    args = ap.parse_args()

    if not args.device:
        from tools import force_cpu  # noqa: F401  (deregisters axon)
    import numpy as np

    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()  # lane-bucket programs persist across runs

    from fishnet_tpu.chess import Position
    from fishnet_tpu.engine.pyengine import MATE_VALUE, PySearch
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards

    params = nnue.load_params(args.net)
    rng = random.Random(args.seed)

    def py_move(pos):
        s = PySearch()
        best, line = s.negamax(
            pos, args.py_depth, -MATE_VALUE * 2, MATE_VALUE * 2, 0
        )
        return line[0] if line else None

    from fishnet_tpu.engine.tpu import _decode_uci as decode_uci

    PAD = 16  # lane bucket granularity: few distinct compiled shapes

    # ONE lane shape for the whole match: per-cycle batch sizes shrink as
    # games finish, and every distinct width is a fresh XLA compile (plus
    # the round-5 narrowing path would compile its own widths per shape —
    # a first run of this tool spent ~an hour compiling instead of
    # playing). Dead lanes re-search boards[0]; a lockstep step costs the
    # same either way, so uniform width trades no real time for one
    # compile per (depth, max_ply).
    from fishnet_tpu.ops.search import search_batch_resumable
    from fishnet_tpu.ops import tt as tt_mod

    B0 = ((args.games + PAD - 1) // PAD) * PAD
    # Lazy-SMP helper lanes (engine/tpu.py layout): primaries in rows
    # [0, B0), then K-1 replica blocks — row h*B0 + r re-searches row r
    # with perturbed ordering through the side's shared TT. Still one
    # compiled shape per match; the picks come from primary rows only.
    K = max(1, args.helpers)
    helper_kw = {}
    if K > 1:
        import jax.numpy as jnp

        jit_arr = np.zeros(B0 * K, np.int32)
        for h in range(1, K):
            for r in range(B0):
                jit_arr[h * B0 + r] = r * K + h  # nonzero ⇔ helper lane
        helper_kw = dict(
            order_jitter=jnp.asarray(jit_arr),
            group=jnp.asarray(np.arange(B0 * K, dtype=np.int32) % B0),
            prefer_deep_store=True,
        )
    # one persistent TT per side, carried across move cycles (the engine
    # keeps one per process too): without it every move re-searches its
    # whole tree and a 160-game match costs ~an hour of device time
    side_tt = {}
    side_gen = {}  # per-side TT generation, bumped per dispatch (engine
    # parity: old-generation entries lose depth-preferred protection)

    def device_moves(positions, p=None, depth=None, side="net"):
        """One batched dispatch: best move per position (None on fail)."""
        if not positions:
            return []
        p = params if p is None else p
        depth = args.depth if depth is None else depth
        boards = [from_position(pos) for pos in positions]
        block = boards + [boards[0]] * (B0 - len(boards))
        roots = stack_boards(block * K)
        if side not in side_tt:
            side_tt[side] = tt_mod.make_table(21)
        kw = dict(helper_kw)
        if K > 1:
            side_gen[side] = (side_gen.get(side, 0) + 1) & 0x3FFFFFFF
            kw["tt_gen"] = side_gen[side]
            req = np.zeros(B0 * K, bool)
            req[: len(boards)] = True  # stop when the real games resolve
            kw["required"] = req
        out = search_batch_resumable(
            p, roots, depth, 500_000, max_ply=depth + 3, narrow=False,
            tt=side_tt[side], **kw,
        )
        side_tt[side] = out.pop("tt")
        ms = np.asarray(out["move"])[: len(boards)]
        return [decode_uci(int(m)) if int(m) >= 0 else None for m in ms]

    def device_moves_skill(positions, skill, p=None, depth=None, tag=""):
        """Move-job-style picks: each position's legal root moves become
        lanes (depth-1 search from the child), ranked, then sampled via
        the engine's skill_pick — the exact weakening path move jobs use
        (engine/tpu.py _move_job)."""
        if not positions:
            return []
        from fishnet_tpu.client.wire import SkillLevel
        from fishnet_tpu.engine.tpu import skill_pick

        p = params if p is None else p
        depth = args.depth if depth is None else depth
        sf_skill = SkillLevel(skill).engine_skill_level
        lane_pos, boards, legals = [], [], []
        for gi, pos in enumerate(positions):
            legal = pos.legal_moves()
            legals.append(legal)
            for m in legal:
                lane_pos.append(gi)
                boards.append(from_position(pos.push(m)))
        # power-of-two buckets (floor 256): root-move lane counts vary
        # every cycle and each distinct width is a fresh XLA compile, so
        # coarse pow2 padding keeps it to 1-2 programs per match; same
        # narrow=False + per-side persistent TT as device_moves
        B = 256
        while B < len(boards):
            B *= 2
        roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
        skey = f"skill-{tag[:3]}-{B}"
        if skey not in side_tt:
            side_tt[skey] = tt_mod.make_table(21)
        out = search_batch_resumable(
            p, roots, max(depth - 1, 0), 500_000, max_ply=depth + 3,
            narrow=False, tt=side_tt[skey],
        )
        side_tt[skey] = out.pop("tt")
        scores = np.asarray(out["score"])
        picks = []
        k = 0
        for gi, legal in enumerate(legals):
            ranked = sorted(
                ((-int(scores[k + j]), j) for j in range(len(legal))),
                key=lambda t: (-t[0], t[1]),
            )
            k += len(legal)
            r = random.Random(f"{args.seed}:{tag}:{gi}:{len(legal)}")
            pick = skill_pick(ranked, sf_skill, r)
            picks.append(legal[pick[1]].uci())
        return picks

    opp_params = (
        nnue.load_params(args.opponent_net) if args.opponent_net else None
    )

    # set up all games, then advance them in lockstep cycles
    games = []
    for g in range(args.games):
        pos = Position.initial()
        for _ in range(rng.randrange(2, 6)):  # varied opening
            moves = pos.legal_moves()
            if not moves:
                break
            pos = pos.push(rng.choice(moves))
        games.append({"pos": pos, "net_color": g % 2, "plies": 0,
                      "result": None, "live": True})

    w = d = l = 0

    def settle(g, outcome):
        nonlocal w, d, l
        g["live"] = False
        g["result"] = outcome
        if outcome is None:
            d += 1
        elif outcome == g["net_color"]:
            w += 1
        else:
            l += 1

    cycle = 0
    while any(g["live"] for g in games):
        cycle += 1
        # terminal checks
        opp_turn = []
        for g in games:
            if not g["live"]:
                continue
            pos = g["pos"]
            oc = pos.outcome()
            if oc is not None:
                settle(g, oc[0])
                continue
            if g["plies"] >= args.max_plies or not pos.legal_moves():
                settle(g, None)
                continue
            if pos.turn != g["net_color"]:
                if opp_params is not None or args.opponent_skill is not None:
                    opp_turn.append(g)
                    continue
                uci = py_move(pos)  # host-side PyEngine reply
                if uci is None:
                    settle(g, None)
                    continue
                g["pos"] = pos.push_uci(uci)
                g["plies"] += 1
        # opponent device replies (net-vs-net / skill-vs-skill modes):
        # one batched dispatch
        if args.opponent_skill is not None:
            opp_ucis = device_moves_skill(
                [g["pos"] for g in opp_turn], args.opponent_skill,
                p=opp_params, depth=args.py_depth, tag=f"opp{cycle}",
            )
        else:
            opp_ucis = device_moves(
                [g["pos"] for g in opp_turn], p=opp_params,
                depth=args.py_depth, side="opp",
            )
        for g, uci in zip(opp_turn, opp_ucis):
            if uci is None:
                settle(g, None)
                continue
            g["pos"] = g["pos"].push_uci(uci)
            g["plies"] += 1
        # net replies: every live game at the net's turn, one dispatch
        net_turn = [
            g for g in games
            if g["live"] and g["pos"].outcome() is None
            and g["pos"].legal_moves() and g["pos"].turn == g["net_color"]
        ]
        if args.skill is not None:
            ucis = device_moves_skill(
                [g["pos"] for g in net_turn], args.skill, tag=f"net{cycle}",
            )
        else:
            ucis = device_moves([g["pos"] for g in net_turn], side="net")
        for g, uci in zip(net_turn, ucis):
            if uci is None:
                settle(g, None)
                continue
            g["pos"] = g["pos"].push_uci(uci)
            g["plies"] += 1
        if cycle % 5 == 0 or cycle <= 3:
            done = sum(1 for g in games if not g["live"])
            print(
                f"[{args.label}] cycle {cycle}: {done}/{args.games} games "
                f"done, +{w} ={d} -{l}",
                flush=True,
            )
    n = max(args.games, 1)
    score = (w + 0.5 * d) / n
    # Wilson 95% interval on the score fraction (draws as half-wins):
    # the standard interval for match results at these game counts
    z = 1.96
    mid = (score + z * z / (2 * n)) / (1 + z * z / n)
    half = (
        z * ((score * (1 - score) + z * z / (4 * n)) / n) ** 0.5
        / (1 + z * z / n)
    )
    print(
        f"[{args.label}] final: +{w} ={d} -{l} over {args.games} games, "
        f"score {score:.3f} (95% CI {mid - half:.3f}-{mid + half:.3f})"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
