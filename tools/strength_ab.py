"""Head-to-head strength A/B: a board768 net (device search) vs PyEngine.

VERDICT r1 #8's acceptance check: the shipped net must beat the old one
head-to-head. This harness plays N games of (device search @ depth D)
against PyEngine (material+mobility, depth d) from varied short random
openings, alternating colors, and prints W/D/L + score.

Usage:
  python tools/strength_ab.py --net fishnet_tpu/assets/nnue-board768-64.npz \
      --games 200 --depth 3
  python tools/strength_ab.py --net old.npz --label old ...   # compare runs
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--net", required=True)
    ap.add_argument("--games", type=int, default=200)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--py-depth", type=int, default=2)
    ap.add_argument("--max-plies", type=int, default=160)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--label", default="net")
    args = ap.parse_args()

    import jax

    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import numpy as np

    from fishnet_tpu.chess import Position
    from fishnet_tpu.engine.pyengine import MATE_VALUE, PySearch
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards
    from fishnet_tpu.ops.search import search_batch_jit

    params = nnue.load_params(args.net)
    rng = random.Random(args.seed)

    def py_move(pos):
        s = PySearch()
        best, line = s.negamax(
            pos, args.py_depth, -MATE_VALUE * 2, MATE_VALUE * 2, 0
        )
        return line[0] if line else None

    def device_move(pos):
        roots = stack_boards([from_position(pos)])
        out = search_batch_jit(
            params, roots, args.depth, 500_000, max_ply=args.depth + 3
        )
        m = int(np.asarray(out["move"])[0])
        if m < 0:
            return None
        frm, to, promo = m & 63, (m >> 6) & 63, (m >> 12) & 7
        uci = (
            "abcdefgh"[frm & 7] + str((frm >> 3) + 1)
            + "abcdefgh"[to & 7] + str((to >> 3) + 1)
        )
        if promo:
            uci += " nbrq"[promo]
        return uci

    w = d = l = 0
    for game in range(args.games):
        pos = Position.initial()
        for _ in range(rng.randrange(2, 6)):  # varied opening
            moves = pos.legal_moves()
            if not moves:
                break
            pos = pos.push(rng.choice(moves))
        net_color = game % 2
        plies = 0
        outcome = None
        while plies < args.max_plies:
            oc = pos.outcome()
            if oc is not None:
                outcome = oc[0]
                break
            if not pos.legal_moves():
                outcome = None
                break
            if pos.turn == net_color:
                uci = device_move(pos)
                if uci is None:
                    break
                pos = pos.push_uci(uci)
            else:
                uci = py_move(pos)
                if uci is None:
                    break
                pos = pos.push_uci(uci)
            plies += 1
        if outcome is None:
            d += 1
        elif outcome == net_color:
            w += 1
        else:
            l += 1
        if (game + 1) % 10 == 0:
            print(
                f"[{args.label}] {game + 1}/{args.games}: +{w} ={d} -{l} "
                f"score {(w + 0.5 * d) / (game + 1):.3f}",
                flush=True,
            )
    print(
        f"[{args.label}] final: +{w} ={d} -{l} over {args.games} games, "
        f"score {(w + 0.5 * d) / max(args.games, 1):.3f}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
