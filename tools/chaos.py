"""Replay named fault scripts against a live engine supervisor.

Manual soak/chaos harness for the supervisor (engine/supervisor.py):
spins up a SupervisedEngine over the scriptable fake host
(engine/fakehost.py), feeds it synthetic analysis chunks, and prints
per-chunk outcomes plus the final SupervisorStats. The same scripts run
in tier-1 (tests/test_supervisor.py); this tool is for watching the
watchdog work in real time and for soak-testing timing knobs.

Examples:
    python -m tools.chaos --script flap --chunks 6 --breaker-threshold 2 \
        --probe-interval 2
    python -m tools.chaos --script hang --chunk-ttl 3
    python -m tools.chaos --script '{"chunks": ["stall", "ok"]}' --chunks 3
    python -m tools.chaos --list
    python -m tools.chaos --scenario --format=github   # CI acceptance run
    python -m tools.chaos --scenario fleet-member-loss # fleet CI gate

`--scenario` (default `ladder`) runs the round-9 session-recovery
acceptance ladder end-to-end (kill-mid-chunk replay, hang-at-segment
progress kill, crash-on-fingerprint quarantine) and exits non-zero on
any lost or duplicated PositionResponse, on a full-chunk re-search
after a partial kill, or on quarantine routing the wrong position.

`--scenario fleet-member-loss` is the fleet acceptance gate (ISSUE 12):
3 fakehost-backed members, one SIGKILLed mid-chunk — every position
must answer exactly once on the engine path, the re-dispatched set must
be a strict subset of the dead member's in-flight positions (acked work
is harvested, not re-searched), exactly one loss event must be
recorded, and the merged flight-recorder dump must carry spans from all
three member processes on one clock-synced timeline despite their
deliberately skewed clocks.

`--scenario fleet-flap` and `--scenario fleet-straggler-hedge` are the
self-healing acceptance gates (ISSUE 15). fleet-flap puts a remote
member behind a FlakyProxy: a connection-refused window shorter than
the in-dispatch retry budget must cost ZERO loss events, a longer one
exactly ONE, and the member must readmit through the probation
gauntlet (healthz + canary) once the proxy recovers — all with
bit-identical answers. fleet-straggler-hedge runs a 3-member fleet
with one 400ms straggler, hedge off then on: hedging must cut p99
chunk latency, keep every position exactly-once, count its wins in
fleet_hedges_total/fleet_hedge_wins_total, and stay bit-identical.

`--scenario request-trace` is the request-tracing acceptance gate
(ISSUE 14): a request POSTed to /analyse on a ServeApp fronting that
same 3-member dying fleet must leave ONE merged Chrome trace linking
the HTTP edge through admission, chunk dispatch, the member loss and
the re-dispatch into the surviving member's process; /debug/requests
must show the request's stage while it is in flight; and the results
must be bit-identical with tracing off. The ladder's kill-mid-chunk
(--trace-smoke) and fleet-member-loss runs additionally stamp their
chunks with a request context and assert the id survives supervisor
respawn replay and fleet re-dispatch in the merged dumps.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from fishnet_tpu.client.backoff import RandomizedBackoff  # noqa: E402
from fishnet_tpu.client.ipc import (  # noqa: E402
    Chunk,
    WorkPosition,
    position_fingerprint,
)
from fishnet_tpu.client.logger import Logger  # noqa: E402
from fishnet_tpu.client.wire import (  # noqa: E402
    AnalysisWork,
    EngineFlavor,
    NodeLimit,
)
from fishnet_tpu.engine.base import EngineError  # noqa: E402
from fishnet_tpu.engine.fakehost import FAKE_CP, NAMED_SCRIPTS  # noqa: E402
from fishnet_tpu.engine.supervisor import SupervisedEngine  # noqa: E402
from fishnet_tpu.obs import trace as obs_trace  # noqa: E402

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def make_chunk(index: int, ttl: float, n_positions: int,
               trace_id: str = "") -> Chunk:
    work = AnalysisWork(
        id=f"chaos{index:03d}",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=1, multipv=None,
    )
    # a trace_id stamps every position with a request context, so the
    # continuity scenarios can follow one request id through respawn
    # replay and fleet re-dispatch
    ctx = (obs_trace.make_ctx("chaos", "analysis",
                              deadline_ms=int(ttl * 1000),
                              trace_id=trace_id)
           if trace_id else None)
    return Chunk(
        work=work, deadline=time.monotonic() + ttl, variant="standard",
        flavor=EngineFlavor.TPU,
        positions=[
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=[],
                         ctx=dict(ctx) if ctx else None)
            for i in range(n_positions)
        ],
    )


async def replay(args) -> int:
    state = tempfile.NamedTemporaryFile(
        prefix="chaos-state-", suffix=".json", delete=False
    )
    state.close()
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", args.script,
        "--state", state.name,
        "--hb-interval", str(args.hb_interval),
    ]
    sup = SupervisedEngine(
        host_cmd,
        logger=Logger(verbose=2),
        hb_interval=args.hb_interval,
        hb_timeout=args.hb_timeout,
        breaker_threshold=args.breaker_threshold,
        probe_interval=args.probe_interval,
    )
    failures = 0
    try:
        for i in range(args.chunks):
            chunk = make_chunk(i, args.chunk_ttl, args.positions)
            t0 = time.monotonic()
            try:
                responses = await sup.go_multiple(chunk)
            except EngineError as e:
                failures += 1
                print(f"chunk {i}: ChunkFailed after "
                      f"{time.monotonic() - t0:.2f}s — {e}")
            else:
                cp = responses[0].scores.best()
                src = ("fake host" if cp is not None and cp.value == 777
                       else "cpu fallback")
                print(f"chunk {i}: ok in {time.monotonic() - t0:.2f}s "
                      f"({len(responses)} responses via {src})")
            if args.pause:
                await asyncio.sleep(args.pause)
    finally:
        await sup.close()
        Path(state.name).unlink(missing_ok=True)
    print_stats(sup.stats)
    print(f"chunks: {args.chunks - failures} served, {failures} failed")
    return 0


def print_stats(s) -> None:
    print(
        f"\nstats: spawns={s.spawns} deaths={s.deaths} kills={s.kills} "
        f"hb_stalls={s.hb_stalls} deadline_kills={s.deadline_kills} "
        f"protocol_errors={s.protocol_errors} breaker_trips={s.breaker_trips} "
        f"breaker_resets={s.breaker_resets} probes={s.probes} "
        f"fallback_chunks={s.fallback_chunks} chunks_ok={s.chunks_ok}"
    )
    print(
        f"recovery: partials={s.partials} "
        f"duplicate_partials={s.duplicate_partials} replays={s.replays} "
        f"replayed_positions={s.replayed_positions} "
        f"bisections={s.bisections} quarantined={s.quarantined} "
        f"quarantine_routed={s.quarantine_routed} "
        f"progress_stalls={s.progress_stalls}"
    )


# ------------------------------------------------ scripted acceptance run


def _scenario_supervisor(script: str, state_name: str, **kw):
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", script,
        "--state", state_name,
        "--hb-interval", "0.05",
    ]
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 1.0)
    kw.setdefault("backoff", RandomizedBackoff(max_s=0.05))
    kw.setdefault("logger", Logger(verbose=0))
    return SupervisedEngine(host_cmd, **kw)


def _check_exactly_once(responses, n, problems, phase) -> None:
    indices = [r.position_index for r in responses]
    if sorted(indices) != list(range(n)):
        problems.append(
            f"{phase}: lost/duplicated PositionResponse — indices {indices}"
        )


async def scenario(args) -> int:
    """The round-9 acceptance ladder, one phase per rung."""
    problems = []
    n = 4
    with tempfile.TemporaryDirectory(prefix="chaos-scenario-") as tmp:
        # ---- phase 1: kill-mid-chunk — replay resumes the suffix
        print("== phase 1: kill after 2 partials (replay) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s1.json",
        )
        try:
            responses = await sup.go_multiple(make_chunk(1, 30.0, n))
            _check_exactly_once(responses, n, problems, "kill-mid-chunk")
            re_searched = n - sup.stats.replayed_positions
            if not (0 < re_searched < n):
                problems.append(
                    "kill-mid-chunk: expected strictly fewer re-searched "
                    f"positions than chunk size, got {re_searched} of {n} "
                    f"(replayed={sup.stats.replayed_positions})"
                )
        except EngineError as e:
            problems.append(f"kill-mid-chunk: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 2: hang-at-segment — progress watchdog + replay
        print("\n== phase 2: hang after 1 partial (progress stall) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["hang-at:1", "partial-ok"]}),
            f"{tmp}/s2.json",
            progress_timeout=0.5,
        )
        try:
            responses = await sup.go_multiple(make_chunk(2, 30.0, n))
            _check_exactly_once(responses, n, problems, "hang-at-segment")
            if sup.stats.progress_stalls < 1:
                problems.append(
                    "hang-at-segment: the stalled partial stream was not "
                    "killed by progress_timeout"
                )
            if sup.stats.deadline_kills:
                problems.append(
                    "hang-at-segment: hit the chunk deadline instead of "
                    "the progress watchdog"
                )
        except EngineError as e:
            problems.append(f"hang-at-segment: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 3: crash-on-fingerprint — quarantine exactly the poison
        print("\n== phase 3: crash on one fingerprint (quarantine) ==")
        ref = _scenario_supervisor(
            json.dumps({"chunks": ["partial-ok"]}), f"{tmp}/ref.json"
        )
        try:
            fault_free = await ref.go_multiple(make_chunk(3, 30.0, n))
        finally:
            await ref.close()
        chunk = make_chunk(3, 60.0, n)
        poison_index = 2
        poison = position_fingerprint(chunk.positions[poison_index])
        sup = _scenario_supervisor(
            json.dumps({"chunks": [f"crash-on-fp:{poison}"]}),
            f"{tmp}/s3.json",
        )
        try:
            responses = await sup.go_multiple(chunk)
            _check_exactly_once(responses, n, problems, "crash-on-fp")
            if sup.stats.quarantined != 1:
                problems.append(
                    f"crash-on-fp: quarantined={sup.stats.quarantined}, "
                    "expected exactly the one poison position"
                )
            for i, (got, want) in enumerate(zip(responses, fault_free)):
                got_cp = got.scores.best().value
                if i == poison_index:
                    if got_cp == FAKE_CP:
                        problems.append(
                            "crash-on-fp: poison position answered by the "
                            "engine path, not the CPU fallback"
                        )
                elif (got_cp, got.best_move, got.depth, got.nodes) != (
                    want.scores.best().value, want.best_move,
                    want.depth, want.nodes,
                ):
                    problems.append(
                        f"crash-on-fp: position {i} not bit-identical to "
                        "the fault-free run"
                    )
            if sup.stats.breaker_trips:
                problems.append(
                    "crash-on-fp: the recovery ladder tripped the "
                    "whole-engine breaker"
                )
        except EngineError as e:
            problems.append(f"crash-on-fp: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos scenario: all phases passed "
          "(replay, progress-stall, quarantine)")
    return 0


async def fleet_scenario(args) -> int:
    """Fleet member-loss acceptance gate (ISSUE 12). Three local
    fakehost members with deliberately skewed child clocks; member m0
    dies after acking 1 of its positions mid-chunk. Verifies the
    exactly-once ledger (harvest acks, re-dispatch only the un-acked
    remainder to survivors), the one-loss-event contract, and that the
    merged flight dump holds all three members' spans on the parent
    timeline."""
    import os

    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from tools import trace_report

    problems = []
    n = 6
    # fixed request id every position carries: the continuity checks
    # follow it from the dispatch spans through the member loss into the
    # survivor's re-dispatched search
    tid = "ab1ef1ee7ab1ef1ee7ab1ef1"
    with tempfile.TemporaryDirectory(prefix="chaos-fleet-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before any member constructs: SupervisedEngine.__init__
        # reads the registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir

        def member(name, script, skew):
            # distinct non-zero skews: if the per-member ClockSync were
            # broken, these spans would land seconds off the timeline
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                    "--trace-skew", str(skew),
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        print("== fleet scenario: 3 members, m0 dies after 1 ack ==")
        members = [
            member("m0", {"chunks": ["die-after:1", "ok"]}, 5.0),
            member("m1", {"chunks": ["ok"]}, 0.0),
            member("m2", {"chunks": ["ok"]}, 2.5),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=2),
            redispatch_max=3, loss_window=0.2,
        )
        t0_us = obs_trace.now_us()
        try:
            await coord.start()
            responses = await coord.go_multiple(
                make_chunk(1, 30.0, n, trace_id=tid)
            )
            _check_exactly_once(responses, n, problems, "fleet-member-loss")
            if any(r.scores.best().value != FAKE_CP for r in responses):
                problems.append(
                    "fleet-member-loss: a position was answered off the "
                    "engine path (fallback leaked into the fleet)"
                )
            if coord.stats.losses != 1 or len(coord.loss_log) != 1:
                problems.append(
                    f"fleet-member-loss: expected exactly one loss event, "
                    f"got losses={coord.stats.losses} "
                    f"log={len(coord.loss_log)}"
                )
            if coord.loss_log:
                ev = coord.loss_log[0]
                redisp = set(ev.redispatched_fps)
                inflight = set(ev.inflight_fps)
                unacked = inflight - set(ev.acked_fps)
                if not redisp:
                    problems.append(
                        "fleet-member-loss: nothing re-dispatched — the "
                        "dead member's un-acked work was dropped"
                    )
                if redisp != unacked:
                    problems.append(
                        "fleet-member-loss: re-dispatched set != the dead "
                        f"member's un-acked in-flight set ({redisp} vs "
                        f"{unacked})"
                    )
                if not redisp < inflight:
                    problems.append(
                        "fleet-member-loss: re-dispatched set is not a "
                        "strict subset of the member's in-flight set — "
                        "acked work was re-searched"
                    )
                if len(redisp) >= n:
                    problems.append(
                        "fleet-member-loss: re-dispatched as much as a "
                        "full chunk resubmit"
                    )
        except EngineError as e:
            problems.append(f"fleet-member-loss: chunk failed outright: {e}")
        finally:
            print(f"fleet stats: {coord.stats}")
            rec = obs_trace.RECORDER
            if rec is not None:
                # final merged dump with every member's absorbed spans
                # (the member-loss dump is written mid-flight and may
                # race the survivors' trace frames)
                rec.flight_dump(trace_dir, "fleet-scenario")
            await coord.close()
        t1_us = obs_trace.now_us()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        loss_dumps = sorted(Path(trace_dir).glob("trace-member-loss-*.json"))
        if not loss_dumps:
            problems.append(
                "fleet-member-loss: the loss left no member-loss flight "
                f"dump in {trace_dir}"
            )
        dumps = sorted(Path(trace_dir).glob("trace-fleet-scenario-*.json"))
        if not dumps:
            problems.append(
                f"fleet-member-loss: no merged fleet dump in {trace_dir}"
            )
        else:
            print(f"\nmerged dump: {dumps[-1].name}")
            events = trace_report.load_events(str(dumps[-1]))
            searches = [e for e in events if e.get("name") == "fake.search"]
            pids = {e.get("pid") for e in searches}
            if len(pids) < 3:
                problems.append(
                    "fleet-member-loss: merged dump has fake.search spans "
                    f"from {len(pids)} member process(es), expected 3"
                )
            # clock-sync: with 5.0s/2.5s child skews, an unsynced span
            # would sit seconds outside the parent's monotonic window
            slack_us = 1_000_000
            for e in searches:
                if not (t0_us - slack_us <= e["ts"] <= t1_us + slack_us):
                    problems.append(
                        "fleet-member-loss: a member span (pid "
                        f"{e.get('pid')}) landed {e['ts']} outside the "
                        f"parent window [{t0_us}, {t1_us}] — clock sync "
                        "failed"
                    )
                    break
            names = {e.get("name") for e in events}
            for expected in ("fleet.dispatch", "fleet.member-loss"):
                if expected not in names:
                    problems.append(
                        f"fleet-member-loss: merged dump is missing the "
                        f"coordinator's {expected!r} marker"
                    )
            # ctx continuity: the request id stamped on the chunk must
            # ride the loss into the re-dispatched sub-chunk — the loss
            # instant names it, and a FOURTH fake.search span (3 initial
            # dispatches + the survivor's re-dispatch) carries it
            req = trace_report.request_events(events, tid)
            req_names = {e.get("name") for e in req}
            if "fleet.member-loss" not in req_names:
                problems.append(
                    "fleet-member-loss: the loss instant does not name "
                    "the request's trace id — re-dispatch dropped ctx"
                )
            searches_tid = [
                e for e in req if e.get("name") == "fake.search"
            ]
            if len(searches_tid) < 4:
                problems.append(
                    "fleet-member-loss: expected the re-dispatched "
                    "sub-chunk to add a fourth fake.search span carrying "
                    f"the request id, got {len(searches_tid)}"
                )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet scenario: exactly-once under member loss, merged "
          "3-member timeline verified")
    return 0


async def fleet_flap_scenario(args) -> int:
    """Self-healing acceptance gate (ISSUE 15), flap half. A remote
    member sits behind a FlakyProxy TCP shim:

    - a refusal window SHORTER than the in-dispatch retry budget must
      produce ZERO loss events (the taxonomy calls connect-refused
      transient; the bounded backoff rides it out);
    - a refusal window LONGER than the budget must cost exactly ONE
      loss event, with the stranded positions rerouted to the survivor;
    - once the proxy recovers, the member must readmit through the
      probation gauntlet (healthz + one canary chunk) and serve again;
    - every chunk's answers must be bit-identical to the same chunks
      run directly on the member engine (PyEngine)."""
    from fishnet_tpu.client.ipc import response_to_wire
    from fishnet_tpu.engine.fakehost import FlakyProxy
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator, FleetMember
    from fishnet_tpu.fleet.remote import HttpEngine
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp

    problems = []
    n = 4

    def flap_chunk(i):
        work = AnalysisWork(
            id=f"flap{i:03d}",
            nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
            timeout_s=20.0, depth=2, multipv=None,
        )
        return Chunk(
            work=work, deadline=time.monotonic() + 20.0,
            variant="standard", flavor=EngineFlavor.OFFICIAL,
            positions=[
                WorkPosition(work=work, position_index=i, url=None,
                             skip=False, root_fen=START, moves=[])
                for i in range(n)
            ],
        )

    def comparable(res):
        wire = response_to_wire(res)
        return {k: wire[k]
                for k in ("scores", "pvs", "best_move", "depth", "nodes")}

    # ground truth: the same three chunks straight through the engine
    direct = []
    for i in range(3):
        direct.append([
            comparable(r)
            for r in await PyEngine(max_depth=2).go_multiple(flap_chunk(i))
        ])

    app = ServeApp(
        EngineSession(PyEngine(max_depth=2), flavor=EngineFlavor.OFFICIAL),
        registry=MetricsRegistry(), logger=Logger(verbose=0),
    )
    host, port = await app.start("127.0.0.1", 0)
    proxy = FlakyProxy(host, port)
    phost, pport = await proxy.start()
    remote = FleetMember(
        name="proxy",
        engine=HttpEngine(f"http://{phost}:{pport}", retry_max=4),
        kind="remote",
    )
    coord = FleetCoordinator(
        [remote, FleetMember(name="cpu0", engine=PyEngine(max_depth=2))],
        logger=Logger(verbose=2), registry=MetricsRegistry(),
        loss_window=0.3, redispatch_max=3,
    )
    fleet_runs = []
    try:
        print("== flap phase 1: refusal shorter than the retry budget ==")
        await proxy.set_fault("refuse-for:0.2")
        responses = await coord.go_multiple(flap_chunk(0))
        _check_exactly_once(responses, n, problems, "flap-transient")
        fleet_runs.append([comparable(r) for r in responses])
        if coord.stats.losses != 0:
            problems.append(
                "flap-transient: a refusal shorter than the retry budget "
                f"became {coord.stats.losses} loss event(s) — the "
                "taxonomy must retry connect-phase faults in-dispatch"
            )
        if remote.engine.retries < 1:
            problems.append(
                "flap-transient: the dispatch never retried "
                "(retries=0) — the refusal window was not exercised"
            )

        print("== flap phase 2: refusal longer than the retry budget ==")
        await proxy.wait_recovered()
        await proxy.set_fault("refuse-for:1.5")
        responses = await coord.go_multiple(flap_chunk(1))
        _check_exactly_once(responses, n, problems, "flap-loss")
        fleet_runs.append([comparable(r) for r in responses])
        if coord.stats.losses != 1 or len(coord.loss_log) != 1:
            problems.append(
                "flap-loss: expected exactly one loss event, got "
                f"losses={coord.stats.losses} log={len(coord.loss_log)}"
            )
        if coord.loss_log and coord.loss_log[0].member != "proxy":
            problems.append(
                f"flap-loss: the loss names {coord.loss_log[0].member!r},"
                " expected the proxied member"
            )
        if not remote.probation:
            problems.append(
                "flap-loss: the lost member skipped probation — "
                "readmission must pass through the gauntlet"
            )

        print("== flap phase 3: probed readmission (healthz + canary) ==")
        await proxy.wait_recovered()
        await asyncio.sleep(0.4)  # sit out the escalated cooldown
        served_before = remote.dispatched_positions
        await coord.probe_members()
        if coord.stats.readmissions != 1 or coord.stats.canaries_ok != 1:
            problems.append(
                "flap-readmit: expected 1 readmission through 1 canary, "
                f"got readmissions={coord.stats.readmissions} "
                f"canaries_ok={coord.stats.canaries_ok} "
                f"probe_failures={coord.stats.probe_failures}"
            )
        if not remote.available() or remote.probation:
            problems.append(
                f"flap-readmit: member state {remote.state()!r} after a "
                "successful probe — expected eligible"
            )
        responses = await coord.go_multiple(flap_chunk(2))
        _check_exactly_once(responses, n, problems, "flap-readmit")
        fleet_runs.append([comparable(r) for r in responses])
        if remote.dispatched_positions <= served_before:
            problems.append(
                "flap-readmit: the readmitted member was never planned "
                "work again"
            )
        if coord.stats.losses != 1:
            problems.append(
                "flap-readmit: losses moved after readmission "
                f"({coord.stats.losses}) — the canary/chunk flapped"
            )
        for phase, (got, want) in enumerate(zip(fleet_runs, direct)):
            if got != want:
                problems.append(
                    f"flap phase {phase + 1}: answers are not "
                    "bit-identical to the direct engine run"
                )
    except EngineError as e:
        problems.append(f"fleet-flap: chunk failed outright: {e}")
    finally:
        print(f"fleet stats: {coord.stats}")
        await coord.close()
        await proxy.close()
        await app.drain_and_stop()

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet-flap::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet-flap: zero-loss transient retry, one-loss flap, "
          "probed readmission, bit-identical answers verified")
    return 0


async def fleet_hedge_scenario(args) -> int:
    """Self-healing acceptance gate (ISSUE 15), hedging half. Three
    fakehost members, one a 400ms straggler. With FISHNET_TPU_FLEET_HEDGE
    semantics on, the straggler's position is duplicated to a free
    member once deadline slack runs low and the first answer wins:
    tail latency must drop measurably vs the hedge-off run, every
    position must answer exactly once, the hedge counters must tie out
    in the metrics registry, and the answers must be bit-identical
    with hedging on or off."""
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs.metrics import MetricsRegistry

    problems = []
    n, rounds = 3, 5

    async def run(hedge, tmp):
        def member(name, extra=()):
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps({"chunks": ["ok"]}),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                ] + list(extra),
                logger=Logger(verbose=0),
                hb_interval=0.05, hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        registry = MetricsRegistry()
        coord = FleetCoordinator(
            [
                member("straggler", extra=["--latency-ms", "400"]),
                member("f1"),
                member("f2"),
            ],
            logger=Logger(verbose=0), registry=registry,
            loss_window=5.0, hedge=hedge, hedge_slack_ms=1800,
        )
        latencies, answers = [], []
        try:
            await coord.start()
            # warm round: absorb process spawn cost outside the timing
            # (ttl 10 puts the hedge trigger far past completion)
            await coord.go_multiple(make_chunk(900, 10.0, n))
            for i in range(rounds):
                chunk = make_chunk(901 + i, 2.0, n)
                t0 = time.monotonic()
                responses = await coord.go_multiple(chunk)
                latencies.append(time.monotonic() - t0)
                _check_exactly_once(
                    responses, n, problems,
                    f"straggler-hedge[hedge={hedge}] round {i}",
                )
                answers.append([
                    (r.position_index, r.scores.best().value)
                    for r in responses
                ])
            snap = registry.snapshot()
        finally:
            await coord.close()
        return latencies, answers, coord.stats, snap

    with tempfile.TemporaryDirectory(prefix="chaos-hedge-") as tmp:
        print("== straggler fleet, hedge OFF ==")
        lat_off, ans_off, stats_off, _ = await run(False, tmp)
        print(f"   per-chunk latency: "
              f"{' '.join(f'{v * 1000:.0f}ms' for v in lat_off)}")
        print("== straggler fleet, hedge ON ==")
        lat_on, ans_on, stats_on, snap_on = await run(True, tmp)
        print(f"   per-chunk latency: "
              f"{' '.join(f'{v * 1000:.0f}ms' for v in lat_on)}")

    p99_off, p99_on = max(lat_off), max(lat_on)
    print(f"\np99: off={p99_off * 1000:.0f}ms on={p99_on * 1000:.0f}ms  "
          f"hedges={stats_on.hedges} wins={stats_on.hedge_wins}")
    if ans_on != ans_off:
        problems.append(
            "straggler-hedge: answers differ between hedge on and off — "
            "hedging must be bit-identical"
        )
    if stats_off.hedges != 0:
        problems.append(
            f"straggler-hedge: hedge-off run hedged {stats_off.hedges} "
            "position(s)"
        )
    if stats_on.hedges < 1 or stats_on.hedge_wins < 1:
        problems.append(
            "straggler-hedge: expected at least one hedge and one hedge "
            f"win, got hedges={stats_on.hedges} "
            f"wins={stats_on.hedge_wins}"
        )
    if stats_on.losses or stats_off.losses:
        problems.append(
            "straggler-hedge: a slow member was treated as dead "
            f"(losses on={stats_on.losses} off={stats_off.losses})"
        )
    if snap_on.get("fleet_hedges_total") != stats_on.hedges or \
            snap_on.get("fleet_hedge_wins_total") != stats_on.hedge_wins:
        problems.append(
            "straggler-hedge: fleet_hedges_total/fleet_hedge_wins_total "
            "do not tie out with the coordinator ledger"
        )
    if not p99_on < p99_off:
        problems.append(
            f"straggler-hedge: hedging did not cut p99 chunk latency "
            f"({p99_on * 1000:.0f}ms vs {p99_off * 1000:.0f}ms)"
        )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet-straggler-hedge::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet-straggler-hedge: first-answer-wins hedging cut the "
          "tail, exactly-once and bit-identity verified")
    return 0


async def trace_smoke(args) -> int:
    """CI flight-recorder smoke (ISSUE 10): a chaos-induced child death
    with tracing on must leave a merged supervisor+host dump that loads
    as valid Chrome trace JSON and passes trace_report's internal
    cross-validation. Fails the step when no dump appears or the dump
    does not parse."""
    import os

    from tools import trace_report

    problems = []
    # fixed request id: the continuity checks follow it across the kill
    # into the respawned incarnation's replay
    tid = "c0ffeec0ffeec0ffeec0ffee"
    with tempfile.TemporaryDirectory(prefix="chaos-trace-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before the supervisor constructs: its __init__ reads the
        # settings registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir
        print("== trace smoke: kill after 2 partials, tracing on ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s.json",
        )
        # --trace-skew 0.0 opts the fake host into streaming a synthetic
        # child trace ring, so the dump exercises the cross-process merge
        sup.host_cmd += ["--trace-skew", "0.0"]
        try:
            responses = await sup.go_multiple(
                make_chunk(1, 30.0, 4, trace_id=tid)
            )
            _check_exactly_once(responses, 4, problems, "trace-smoke")
        except EngineError as e:
            problems.append(f"trace-smoke: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            rec = obs_trace.RECORDER
            if rec is not None:
                # second dump AFTER recovery: the child-death dump above
                # is written mid-replay, this one holds the respawned
                # incarnation's spans for the ctx-continuity checks
                rec.flight_dump(trace_dir, "smoke-final")
            await sup.close()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        dumps = sorted(Path(trace_dir).glob("trace-child-death-*.json"))
        if not dumps:
            problems.append(
                "trace-smoke: child death left no flight dump in "
                f"{trace_dir}"
            )
        else:
            print(f"\nflight dump: {dumps[-1].name}")
            rc = trace_report.main(
                [str(dumps[-1]), "--selftest", f"--format={args.format}"]
            )
            if rc != 0:
                problems.append(
                    f"trace-smoke: trace_report exited {rc} on the dump"
                )
            else:
                events = trace_report.load_events(str(dumps[-1]))
                names = {e.get("name") for e in events}
                # supervisor-side markers (spawn, the dump's own ladder
                # instant) AND the child's streamed span must both be in
                # the merged ring — the dump is written mid-recovery, so
                # the still-open dispatch span is legitimately absent
                for expected in ("spawn", "flight-dump", "fake.search"):
                    if expected not in names:
                        problems.append(
                            f"trace-smoke: merged dump is missing "
                            f"{expected!r} — supervisor and host "
                            "timelines did not both land"
                        )

        # ctx continuity (kill-mid-chunk): in the post-recovery dump the
        # request id must link the journaled pre-death partials to the
        # respawned incarnation's replay — the chain spans BOTH host
        # incarnations (two child pids) plus the supervisor's flow hops
        finals = sorted(Path(trace_dir).glob("trace-smoke-final-*.json"))
        if not finals:
            problems.append(
                f"trace-smoke: no post-recovery dump in {trace_dir}"
            )
        else:
            events = trace_report.load_events(str(finals[-1]))
            req = trace_report.request_events(events, tid)
            req_names = {e.get("name") for e in req}
            if "position.journaled" not in req_names:
                problems.append(
                    "trace-smoke: no position.journaled instant carries "
                    "the request id — the journal dropped ctx across "
                    "the kill"
                )
            search_pids = {e.get("pid") for e in req
                           if e.get("name") == "fake.search"}
            if len(search_pids) < 2:
                problems.append(
                    "trace-smoke: the request chain does not span both "
                    "host incarnations (fake.search pids: "
                    f"{sorted(search_pids)}) — replay lost the context"
                )
            flow_pids = {e.get("pid") for e in req
                         if e.get("ph") in ("s", "t", "f")}
            if len(flow_pids) < 2:
                problems.append(
                    "trace-smoke: request flow hops come from fewer "
                    "than two processes — the cross-process link is gone"
                )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos trace smoke::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos trace smoke: flight dump written, merged, and parsed")
    return 0


async def request_trace_scenario(args) -> int:
    """Request-tracing acceptance gate (ISSUE 14). One request POSTed
    to /analyse on a ServeApp fronting a 3-member fakehost fleet, with
    member m0 killed mid-chunk, must leave ONE merged Chrome trace whose
    spans link the HTTP edge to every process that touched the request —
    including the survivor that absorbed the re-dispatch — while
    `GET /debug/requests` shows the request's stage in flight; and the
    search results must be bit-identical with tracing on vs off."""
    import os

    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs import metrics as obs_metrics
    from fishnet_tpu.serve.server import ServeApp
    from tools import trace_report

    problems = []
    # fixed request id so the traced and untraced phases submit
    # byte-identical bodies
    tid = "feedc0defeedc0defeedc0defeedc0de"

    async def http(host, port, method, path, body=None):
        """One HTTP/1.1 exchange over a raw asyncio connection (the
        serve front-end speaks plain stdlib HTTP; no client library)."""
        reader, writer = await asyncio.open_connection(host, port)
        try:
            payload = (b"" if body is None
                       else json.dumps(body).encode("utf-8"))
            head = (
                f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        header, _, body_bytes = raw.partition(b"\r\n\r\n")
        status = int(header.split(None, 2)[1])
        return status, (json.loads(body_bytes) if body_bytes else {})

    async def run_once(tmp: str, tag: str):
        """One POST /analyse against a fresh 3-member fleet behind the
        serve front-end; m0 dies after acking 1 position. Polls
        /debug/requests while the request is in flight. Returns
        (status, payload, stages_seen, coordinator)."""

        def member(name, script, skew):
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{tag}-{name}.json",
                    "--hb-interval", "0.05",
                    "--trace-skew", str(skew),
                    # widen the in-flight window so the /debug/requests
                    # poll reliably catches the request mid-stage
                    "--latency-ms", "250",
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        members = [
            member("m0", {"chunks": ["die-after:1", "ok"]}, 5.0),
            member("m1", {"chunks": ["ok"]}, 0.0),
            member("m2", {"chunks": ["ok"]}, 2.5),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
            redispatch_max=3, loss_window=0.2,
        )
        app = ServeApp(
            EngineSession(coord, flavor=EngineFlavor.TPU),
            logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
        )
        stages = []
        try:
            await coord.start()
            host, port = await app.start("127.0.0.1", 0)
            body = {
                "id": f"reqtrace-{tag}",
                "tenant": "chaos",
                "trace_id": tid,
                # distinct move chains → distinct position fingerprints,
                # so the exactly-once ledger tracks 6 real entries
                "positions": [
                    {"fen": START, "moves": ["e2e4"] * i}
                    for i in range(6)
                ],
                "depth": 1,
                "timeout_ms": 8000,
            }
            post = asyncio.ensure_future(
                http(host, port, "POST", "/analyse", body)
            )
            poll_deadline = time.monotonic() + 30.0
            while not post.done() and time.monotonic() < poll_deadline:
                st, dbg = await http(host, port, "GET", "/debug/requests")
                if st == 200:
                    for r in dbg.get("requests", []):
                        if r.get("trace_id") == tid:
                            stages.append(r.get("stage"))
                await asyncio.sleep(0.02)
            status, payload = await asyncio.wait_for(post, timeout=30.0)
        finally:
            await app.drain_and_stop()
            await coord.close()
        return status, payload, stages, coord

    with tempfile.TemporaryDirectory(prefix="chaos-reqtrace-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before any member constructs: SupervisedEngine.__init__
        # reads the registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir
        print("== request-trace: tracing ON, m0 dies after 1 ack ==")
        try:
            status, payload, stages, coord = await run_once(tmp, "on")
        finally:
            rec = obs_trace.RECORDER
            if rec is not None:
                rec.flight_dump(trace_dir, "request-trace")
            obs_trace.uninstall()
            del os.environ["FISHNET_TPU_TRACE_DIR"]

        if status != 200:
            problems.append(
                f"request-trace: POST /analyse answered {status}: {payload}"
            )
        if coord.stats.losses != 1:
            problems.append(
                "request-trace: expected exactly one member loss, got "
                f"{coord.stats.losses}"
            )
        results = payload.get("results", [])
        if len(results) != 6:
            problems.append(
                f"request-trace: {len(results)} results for 6 positions"
            )
        if not stages:
            problems.append(
                "request-trace: /debug/requests never showed the request "
                "while it was in flight"
            )
        elif "dispatched" not in stages:
            problems.append(
                "request-trace: /debug/requests never showed the "
                f"'dispatched' stage (saw {sorted(set(stages))})"
            )

        dumps = sorted(Path(trace_dir).glob("trace-request-trace-*.json"))
        if not dumps:
            problems.append(
                f"request-trace: no merged flight dump in {trace_dir}"
            )
        else:
            print(f"\nmerged dump: {dumps[-1].name}")
            events = trace_report.load_events(str(dumps[-1]))
            req = trace_report.request_events(events, tid)
            names = {e.get("name") for e in req}
            # the full causal chain, HTTP edge → lane-level hand-offs:
            # each name is one hop that must carry the request id
            for expected in ("http.request", "serve.admission",
                             "serve.chunk", "fleet.dispatch",
                             "supervisor.dispatch", "position.journaled",
                             "slo.observe", "fake.search"):
                if expected not in names:
                    problems.append(
                        "request-trace: the request's causal chain is "
                        f"missing {expected!r} in the merged dump"
                    )
            flow_pids = {e.get("pid") for e in req
                         if e.get("ph") in ("s", "t", "f")}
            if len(flow_pids) < 3:
                problems.append(
                    "request-trace: request flow hops span "
                    f"{len(flow_pids)} process(es), expected the serve "
                    "process plus at least two member children"
                )
            searches = [e for e in req if e.get("name") == "fake.search"]
            if len(searches) < 4:
                problems.append(
                    "request-trace: expected the re-dispatch to add a "
                    "fourth fake.search span carrying the request id, "
                    f"got {len(searches)}"
                )
            if "fleet.member-loss" not in names:
                problems.append(
                    "request-trace: the member-loss instant does not "
                    "name the request's trace id"
                )
            wf = trace_report.request_waterfall(events, tid)
            if wf is None:
                problems.append(
                    "request-trace: request_waterfall found nothing for "
                    "the request id"
                )
            else:
                print(trace_report.render_waterfall(wf))
                problems.extend(
                    f"request-trace: {p}"
                    for p in trace_report.request_crosscheck(wf)
                )

        # ---- tracing OFF: same fault schedule, results must not move
        print("\n== request-trace: tracing OFF, same fault schedule ==")
        status_off, payload_off, _stages, _coord = await run_once(tmp, "off")
        if status_off != 200:
            problems.append(
                "request-trace: untraced POST /analyse answered "
                f"{status_off}: {payload_off}"
            )
        elif payload.get("results") != payload_off.get("results"):
            problems.append(
                "request-trace: search results differ with tracing on "
                "vs off — instrumentation perturbed the search"
            )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos request trace::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos request trace: one merged edge-to-member timeline, live "
          "stage introspection, results identical with tracing off")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--script", default="flap",
                   help="named script, inline JSON, or @path "
                        "(see --list; default: flap)")
    p.add_argument("--list", action="store_true",
                   help="list named fault scripts and exit")
    p.add_argument("--chunks", type=int, default=4,
                   help="number of chunks to feed (default 4)")
    p.add_argument("--positions", type=int, default=2,
                   help="positions per chunk (default 2)")
    p.add_argument("--chunk-ttl", type=float, default=10.0,
                   help="per-chunk deadline in seconds (default 10)")
    p.add_argument("--pause", type=float, default=0.0,
                   help="seconds to sleep between chunks (default 0)")
    p.add_argument("--hb-interval", type=float, default=0.25)
    p.add_argument("--hb-timeout", type=float, default=2.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    p.add_argument("--probe-interval", type=float, default=5.0)
    p.add_argument("--scenario", nargs="?", const="ladder", default=None,
                   choices=["ladder", "fleet-member-loss", "request-trace",
                            "fleet-flap", "fleet-straggler-hedge"],
                   help="run an acceptance scenario and exit non-zero on "
                        "any delivery violation: `ladder` (default when "
                        "the flag is bare) is the session-recovery "
                        "ladder, `fleet-member-loss` kills one of 3 "
                        "fleet members mid-chunk, `request-trace` POSTs "
                        "a traced request to /analyse over that same "
                        "dying fleet and checks the merged edge-to-"
                        "member timeline")
    p.add_argument("--trace-smoke", action="store_true",
                   help="kill a child mid-chunk with tracing on and "
                        "verify the merged flight dump parses")
    p.add_argument("--format", choices=["text", "github"], default="text",
                   help="github emits ::error annotations for CI")
    args = p.parse_args(argv)
    if args.list:
        for name, script in NAMED_SCRIPTS.items():
            print(f"{name:14s} {json.dumps(script)}")
        return 0
    if args.scenario == "ladder":
        return asyncio.run(scenario(args))
    if args.scenario == "fleet-member-loss":
        return asyncio.run(fleet_scenario(args))
    if args.scenario == "fleet-flap":
        return asyncio.run(fleet_flap_scenario(args))
    if args.scenario == "fleet-straggler-hedge":
        return asyncio.run(fleet_hedge_scenario(args))
    if args.scenario == "request-trace":
        return asyncio.run(request_trace_scenario(args))
    if args.trace_smoke:
        return asyncio.run(trace_smoke(args))
    return asyncio.run(replay(args))


if __name__ == "__main__":
    sys.exit(main())
