"""Replay named fault scripts against a live engine supervisor.

Manual soak/chaos harness for the supervisor (engine/supervisor.py):
spins up a SupervisedEngine over the scriptable fake host
(engine/fakehost.py), feeds it synthetic analysis chunks, and prints
per-chunk outcomes plus the final SupervisorStats. The same scripts run
in tier-1 (tests/test_supervisor.py); this tool is for watching the
watchdog work in real time and for soak-testing timing knobs.

Examples:
    python -m tools.chaos --script flap --chunks 6 --breaker-threshold 2 \
        --probe-interval 2
    python -m tools.chaos --script hang --chunk-ttl 3
    python -m tools.chaos --script '{"chunks": ["stall", "ok"]}' --chunks 3
    python -m tools.chaos --list
    python -m tools.chaos --scenario --format=github   # CI acceptance run
    python -m tools.chaos --scenario fleet-member-loss # fleet CI gate

`--scenario` (default `ladder`) runs the round-9 session-recovery
acceptance ladder end-to-end (kill-mid-chunk replay, hang-at-segment
progress kill, crash-on-fingerprint quarantine) and exits non-zero on
any lost or duplicated PositionResponse, on a full-chunk re-search
after a partial kill, or on quarantine routing the wrong position.

`--scenario fleet-member-loss` is the fleet acceptance gate (ISSUE 12):
3 fakehost-backed members, one SIGKILLed mid-chunk — every position
must answer exactly once on the engine path, the re-dispatched set must
be a strict subset of the dead member's in-flight positions (acked work
is harvested, not re-searched), exactly one loss event must be
recorded, and the merged flight-recorder dump must carry spans from all
three member processes on one clock-synced timeline despite their
deliberately skewed clocks.

`--scenario fleet-flap` and `--scenario fleet-straggler-hedge` are the
self-healing acceptance gates (ISSUE 15). fleet-flap puts a remote
member behind a FlakyProxy: a connection-refused window shorter than
the in-dispatch retry budget must cost ZERO loss events, a longer one
exactly ONE, and the member must readmit through the probation
gauntlet (healthz + canary) once the proxy recovers — all with
bit-identical answers. fleet-straggler-hedge runs a 3-member fleet
with one 400ms straggler, hedge off then on: hedging must cut p99
chunk latency, keep every position exactly-once, count its wins in
fishnet_fleet_hedges_total/fishnet_fleet_hedge_wins_total, and stay bit-identical.

`--scenario burst-member-loss` and `--scenario flap-under-load` are
the elastic-capacity gates (ISSUE 16) — chaos UNDER load.
burst-member-loss fires an open-loop 10x flash crowd
(tools/loadgen.py) against a two-member-floor fleet with the
autoscaler on, one floor member dying mid-burst: zero lost requests
(every arrival answers or sheds), sheds bounded to the burst window,
exactly one loss event, no scale-down inside the post-loss cooldown,
and the member count must return to the floor once the burst passes.
flap-under-load streams steady open-loop traffic while a FlakyProxy
member refuses connections twice — a window inside the retry budget
(zero losses) and one past it (losses naming only the proxied member)
— and every scheduled request must still answer 200.

`--scenario cache-poison` is the analysis-memoization gate (ISSUE 17):
a corrupt persisted cache entry (fishnet_tpu/cache/store.py) must be
quarantined exactly once — `.bad` rename, one warning, index row
dropped — while every response stays bit-identical to a cache-off
run; the fallback search must then re-fill the entry so the next
replay is all-hit.

`--scenario request-trace` is the request-tracing acceptance gate
(ISSUE 14): a request POSTed to /analyse on a ServeApp fronting that
same 3-member dying fleet must leave ONE merged Chrome trace linking
the HTTP edge through admission, chunk dispatch, the member loss and
the re-dispatch into the surviving member's process; /debug/requests
must show the request's stage while it is in flight; and the results
must be bit-identical with tracing off. The ladder's kill-mid-chunk
(--trace-smoke) and fleet-member-loss runs additionally stamp their
chunks with a request context and assert the id survives supervisor
respawn replay and fleet re-dispatch in the merged dumps.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from fishnet_tpu.client.backoff import RandomizedBackoff  # noqa: E402
from fishnet_tpu.client.ipc import (  # noqa: E402
    Chunk,
    WorkPosition,
    position_fingerprint,
)
from fishnet_tpu.client.logger import Logger  # noqa: E402
from fishnet_tpu.client.wire import (  # noqa: E402
    AnalysisWork,
    EngineFlavor,
    NodeLimit,
)
from fishnet_tpu.engine.base import EngineError  # noqa: E402
from fishnet_tpu.engine.fakehost import FAKE_CP, NAMED_SCRIPTS  # noqa: E402
from fishnet_tpu.engine.supervisor import SupervisedEngine  # noqa: E402
from fishnet_tpu.obs import trace as obs_trace  # noqa: E402

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def make_chunk(index: int, ttl: float, n_positions: int,
               trace_id: str = "") -> Chunk:
    work = AnalysisWork(
        id=f"chaos{index:03d}",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=1, multipv=None,
    )
    # a trace_id stamps every position with a request context, so the
    # continuity scenarios can follow one request id through respawn
    # replay and fleet re-dispatch
    ctx = (obs_trace.make_ctx("chaos", "analysis",
                              deadline_ms=int(ttl * 1000),
                              trace_id=trace_id)
           if trace_id else None)
    return Chunk(
        work=work, deadline=time.monotonic() + ttl, variant="standard",
        flavor=EngineFlavor.TPU,
        positions=[
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=[],
                         ctx=dict(ctx) if ctx else None)
            for i in range(n_positions)
        ],
    )


async def replay(args) -> int:
    state = tempfile.NamedTemporaryFile(
        prefix="chaos-state-", suffix=".json", delete=False
    )
    state.close()
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", args.script,
        "--state", state.name,
        "--hb-interval", str(args.hb_interval),
    ]
    sup = SupervisedEngine(
        host_cmd,
        logger=Logger(verbose=2),
        hb_interval=args.hb_interval,
        hb_timeout=args.hb_timeout,
        breaker_threshold=args.breaker_threshold,
        probe_interval=args.probe_interval,
    )
    failures = 0
    try:
        for i in range(args.chunks):
            chunk = make_chunk(i, args.chunk_ttl, args.positions)
            t0 = time.monotonic()
            try:
                responses = await sup.go_multiple(chunk)
            except EngineError as e:
                failures += 1
                print(f"chunk {i}: ChunkFailed after "
                      f"{time.monotonic() - t0:.2f}s — {e}")
            else:
                cp = responses[0].scores.best()
                src = ("fake host" if cp is not None and cp.value == 777
                       else "cpu fallback")
                print(f"chunk {i}: ok in {time.monotonic() - t0:.2f}s "
                      f"({len(responses)} responses via {src})")
            if args.pause:
                await asyncio.sleep(args.pause)
    finally:
        await sup.close()
        Path(state.name).unlink(missing_ok=True)
    print_stats(sup.stats)
    print(f"chunks: {args.chunks - failures} served, {failures} failed")
    return 0


def print_stats(s) -> None:
    print(
        f"\nstats: spawns={s.spawns} deaths={s.deaths} kills={s.kills} "
        f"hb_stalls={s.hb_stalls} deadline_kills={s.deadline_kills} "
        f"protocol_errors={s.protocol_errors} breaker_trips={s.breaker_trips} "
        f"breaker_resets={s.breaker_resets} probes={s.probes} "
        f"fallback_chunks={s.fallback_chunks} chunks_ok={s.chunks_ok}"
    )
    print(
        f"recovery: partials={s.partials} "
        f"duplicate_partials={s.duplicate_partials} replays={s.replays} "
        f"replayed_positions={s.replayed_positions} "
        f"bisections={s.bisections} quarantined={s.quarantined} "
        f"quarantine_routed={s.quarantine_routed} "
        f"progress_stalls={s.progress_stalls}"
    )


# ------------------------------------------------ scripted acceptance run


def _scenario_supervisor(script: str, state_name: str, **kw):
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", script,
        "--state", state_name,
        "--hb-interval", "0.05",
    ]
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 1.0)
    kw.setdefault("backoff", RandomizedBackoff(max_s=0.05))
    kw.setdefault("logger", Logger(verbose=0))
    return SupervisedEngine(host_cmd, **kw)


def _check_exactly_once(responses, n, problems, phase) -> None:
    indices = [r.position_index for r in responses]
    if sorted(indices) != list(range(n)):
        problems.append(
            f"{phase}: lost/duplicated PositionResponse — indices {indices}"
        )


async def scenario(args) -> int:
    """The round-9 acceptance ladder, one phase per rung."""
    problems = []
    n = 4
    with tempfile.TemporaryDirectory(prefix="chaos-scenario-") as tmp:
        # ---- phase 1: kill-mid-chunk — replay resumes the suffix
        print("== phase 1: kill after 2 partials (replay) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s1.json",
        )
        try:
            responses = await sup.go_multiple(make_chunk(1, 30.0, n))
            _check_exactly_once(responses, n, problems, "kill-mid-chunk")
            re_searched = n - sup.stats.replayed_positions
            if not (0 < re_searched < n):
                problems.append(
                    "kill-mid-chunk: expected strictly fewer re-searched "
                    f"positions than chunk size, got {re_searched} of {n} "
                    f"(replayed={sup.stats.replayed_positions})"
                )
        except EngineError as e:
            problems.append(f"kill-mid-chunk: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 2: hang-at-segment — progress watchdog + replay
        print("\n== phase 2: hang after 1 partial (progress stall) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["hang-at:1", "partial-ok"]}),
            f"{tmp}/s2.json",
            progress_timeout=0.5,
        )
        try:
            responses = await sup.go_multiple(make_chunk(2, 30.0, n))
            _check_exactly_once(responses, n, problems, "hang-at-segment")
            if sup.stats.progress_stalls < 1:
                problems.append(
                    "hang-at-segment: the stalled partial stream was not "
                    "killed by progress_timeout"
                )
            if sup.stats.deadline_kills:
                problems.append(
                    "hang-at-segment: hit the chunk deadline instead of "
                    "the progress watchdog"
                )
        except EngineError as e:
            problems.append(f"hang-at-segment: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 3: crash-on-fingerprint — quarantine exactly the poison
        print("\n== phase 3: crash on one fingerprint (quarantine) ==")
        ref = _scenario_supervisor(
            json.dumps({"chunks": ["partial-ok"]}), f"{tmp}/ref.json"
        )
        try:
            fault_free = await ref.go_multiple(make_chunk(3, 30.0, n))
        finally:
            await ref.close()
        chunk = make_chunk(3, 60.0, n)
        poison_index = 2
        poison = position_fingerprint(chunk.positions[poison_index])
        sup = _scenario_supervisor(
            json.dumps({"chunks": [f"crash-on-fp:{poison}"]}),
            f"{tmp}/s3.json",
        )
        try:
            responses = await sup.go_multiple(chunk)
            _check_exactly_once(responses, n, problems, "crash-on-fp")
            if sup.stats.quarantined != 1:
                problems.append(
                    f"crash-on-fp: quarantined={sup.stats.quarantined}, "
                    "expected exactly the one poison position"
                )
            for i, (got, want) in enumerate(zip(responses, fault_free)):
                got_cp = got.scores.best().value
                if i == poison_index:
                    if got_cp == FAKE_CP:
                        problems.append(
                            "crash-on-fp: poison position answered by the "
                            "engine path, not the CPU fallback"
                        )
                elif (got_cp, got.best_move, got.depth, got.nodes) != (
                    want.scores.best().value, want.best_move,
                    want.depth, want.nodes,
                ):
                    problems.append(
                        f"crash-on-fp: position {i} not bit-identical to "
                        "the fault-free run"
                    )
            if sup.stats.breaker_trips:
                problems.append(
                    "crash-on-fp: the recovery ladder tripped the "
                    "whole-engine breaker"
                )
        except EngineError as e:
            problems.append(f"crash-on-fp: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos scenario: all phases passed "
          "(replay, progress-stall, quarantine)")
    return 0


async def fleet_scenario(args) -> int:
    """Fleet member-loss acceptance gate (ISSUE 12). Three local
    fakehost members with deliberately skewed child clocks; member m0
    dies after acking 1 of its positions mid-chunk. Verifies the
    exactly-once ledger (harvest acks, re-dispatch only the un-acked
    remainder to survivors), the one-loss-event contract, and that the
    merged flight dump holds all three members' spans on the parent
    timeline."""
    import os

    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from tools import trace_report

    problems = []
    n = 6
    # fixed request id every position carries: the continuity checks
    # follow it from the dispatch spans through the member loss into the
    # survivor's re-dispatched search
    tid = "ab1ef1ee7ab1ef1ee7ab1ef1"
    with tempfile.TemporaryDirectory(prefix="chaos-fleet-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before any member constructs: SupervisedEngine.__init__
        # reads the registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir

        def member(name, script, skew):
            # distinct non-zero skews: if the per-member ClockSync were
            # broken, these spans would land seconds off the timeline
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                    "--trace-skew", str(skew),
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        print("== fleet scenario: 3 members, m0 dies after 1 ack ==")
        members = [
            member("m0", {"chunks": ["die-after:1", "ok"]}, 5.0),
            member("m1", {"chunks": ["ok"]}, 0.0),
            member("m2", {"chunks": ["ok"]}, 2.5),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=2),
            redispatch_max=3, loss_window=0.2,
        )
        t0_us = obs_trace.now_us()
        try:
            await coord.start()
            responses = await coord.go_multiple(
                make_chunk(1, 30.0, n, trace_id=tid)
            )
            _check_exactly_once(responses, n, problems, "fleet-member-loss")
            if any(r.scores.best().value != FAKE_CP for r in responses):
                problems.append(
                    "fleet-member-loss: a position was answered off the "
                    "engine path (fallback leaked into the fleet)"
                )
            if coord.stats.losses != 1 or len(coord.loss_log) != 1:
                problems.append(
                    f"fleet-member-loss: expected exactly one loss event, "
                    f"got losses={coord.stats.losses} "
                    f"log={len(coord.loss_log)}"
                )
            if coord.loss_log:
                ev = coord.loss_log[0]
                redisp = set(ev.redispatched_fps)
                inflight = set(ev.inflight_fps)
                unacked = inflight - set(ev.acked_fps)
                if not redisp:
                    problems.append(
                        "fleet-member-loss: nothing re-dispatched — the "
                        "dead member's un-acked work was dropped"
                    )
                if redisp != unacked:
                    problems.append(
                        "fleet-member-loss: re-dispatched set != the dead "
                        f"member's un-acked in-flight set ({redisp} vs "
                        f"{unacked})"
                    )
                if not redisp < inflight:
                    problems.append(
                        "fleet-member-loss: re-dispatched set is not a "
                        "strict subset of the member's in-flight set — "
                        "acked work was re-searched"
                    )
                if len(redisp) >= n:
                    problems.append(
                        "fleet-member-loss: re-dispatched as much as a "
                        "full chunk resubmit"
                    )
        except EngineError as e:
            problems.append(f"fleet-member-loss: chunk failed outright: {e}")
        finally:
            print(f"fleet stats: {coord.stats}")
            rec = obs_trace.RECORDER
            if rec is not None:
                # final merged dump with every member's absorbed spans
                # (the member-loss dump is written mid-flight and may
                # race the survivors' trace frames)
                rec.flight_dump(trace_dir, "fleet-scenario")
            await coord.close()
        t1_us = obs_trace.now_us()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        loss_dumps = sorted(Path(trace_dir).glob("trace-member-loss-*.json"))
        if not loss_dumps:
            problems.append(
                "fleet-member-loss: the loss left no member-loss flight "
                f"dump in {trace_dir}"
            )
        dumps = sorted(Path(trace_dir).glob("trace-fleet-scenario-*.json"))
        if not dumps:
            problems.append(
                f"fleet-member-loss: no merged fleet dump in {trace_dir}"
            )
        else:
            print(f"\nmerged dump: {dumps[-1].name}")
            events = trace_report.load_events(str(dumps[-1]))
            searches = [e for e in events if e.get("name") == "fake.search"]
            pids = {e.get("pid") for e in searches}
            if len(pids) < 3:
                problems.append(
                    "fleet-member-loss: merged dump has fake.search spans "
                    f"from {len(pids)} member process(es), expected 3"
                )
            # clock-sync: with 5.0s/2.5s child skews, an unsynced span
            # would sit seconds outside the parent's monotonic window
            slack_us = 1_000_000
            for e in searches:
                if not (t0_us - slack_us <= e["ts"] <= t1_us + slack_us):
                    problems.append(
                        "fleet-member-loss: a member span (pid "
                        f"{e.get('pid')}) landed {e['ts']} outside the "
                        f"parent window [{t0_us}, {t1_us}] — clock sync "
                        "failed"
                    )
                    break
            names = {e.get("name") for e in events}
            for expected in ("fleet.dispatch", "fleet.member-loss"):
                if expected not in names:
                    problems.append(
                        f"fleet-member-loss: merged dump is missing the "
                        f"coordinator's {expected!r} marker"
                    )
            # ctx continuity: the request id stamped on the chunk must
            # ride the loss into the re-dispatched sub-chunk — the loss
            # instant names it, and a FOURTH fake.search span (3 initial
            # dispatches + the survivor's re-dispatch) carries it
            req = trace_report.request_events(events, tid)
            req_names = {e.get("name") for e in req}
            if "fleet.member-loss" not in req_names:
                problems.append(
                    "fleet-member-loss: the loss instant does not name "
                    "the request's trace id — re-dispatch dropped ctx"
                )
            searches_tid = [
                e for e in req if e.get("name") == "fake.search"
            ]
            if len(searches_tid) < 4:
                problems.append(
                    "fleet-member-loss: expected the re-dispatched "
                    "sub-chunk to add a fourth fake.search span carrying "
                    f"the request id, got {len(searches_tid)}"
                )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet scenario: exactly-once under member loss, merged "
          "3-member timeline verified")
    return 0


async def fleet_flap_scenario(args) -> int:
    """Self-healing acceptance gate (ISSUE 15), flap half. A remote
    member sits behind a FlakyProxy TCP shim:

    - a refusal window SHORTER than the in-dispatch retry budget must
      produce ZERO loss events (the taxonomy calls connect-refused
      transient; the bounded backoff rides it out);
    - a refusal window LONGER than the budget must cost exactly ONE
      loss event, with the stranded positions rerouted to the survivor;
    - once the proxy recovers, the member must readmit through the
      probation gauntlet (healthz + one canary chunk) and serve again;
    - every chunk's answers must be bit-identical to the same chunks
      run directly on the member engine (PyEngine)."""
    from fishnet_tpu.client.ipc import response_to_wire
    from fishnet_tpu.engine.fakehost import FlakyProxy
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator, FleetMember
    from fishnet_tpu.fleet.remote import HttpEngine
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp

    problems = []
    n = 4

    def flap_chunk(i):
        work = AnalysisWork(
            id=f"flap{i:03d}",
            nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
            timeout_s=20.0, depth=2, multipv=None,
        )
        return Chunk(
            work=work, deadline=time.monotonic() + 20.0,
            variant="standard", flavor=EngineFlavor.OFFICIAL,
            positions=[
                WorkPosition(work=work, position_index=i, url=None,
                             skip=False, root_fen=START, moves=[])
                for i in range(n)
            ],
        )

    def comparable(res):
        wire = response_to_wire(res)
        return {k: wire[k]
                for k in ("scores", "pvs", "best_move", "depth", "nodes")}

    # ground truth: the same three chunks straight through the engine
    direct = []
    for i in range(3):
        direct.append([
            comparable(r)
            for r in await PyEngine(max_depth=2).go_multiple(flap_chunk(i))
        ])

    app = ServeApp(
        EngineSession(PyEngine(max_depth=2), flavor=EngineFlavor.OFFICIAL),
        registry=MetricsRegistry(), logger=Logger(verbose=0),
    )
    host, port = await app.start("127.0.0.1", 0)
    proxy = FlakyProxy(host, port)
    phost, pport = await proxy.start()
    remote = FleetMember(
        name="proxy",
        engine=HttpEngine(f"http://{phost}:{pport}", retry_max=4),
        kind="remote",
    )
    coord = FleetCoordinator(
        [remote, FleetMember(name="cpu0", engine=PyEngine(max_depth=2))],
        logger=Logger(verbose=2), registry=MetricsRegistry(),
        loss_window=0.3, redispatch_max=3,
    )
    fleet_runs = []
    try:
        print("== flap phase 1: refusal shorter than the retry budget ==")
        await proxy.set_fault("refuse-for:0.2")
        responses = await coord.go_multiple(flap_chunk(0))
        _check_exactly_once(responses, n, problems, "flap-transient")
        fleet_runs.append([comparable(r) for r in responses])
        if coord.stats.losses != 0:
            problems.append(
                "flap-transient: a refusal shorter than the retry budget "
                f"became {coord.stats.losses} loss event(s) — the "
                "taxonomy must retry connect-phase faults in-dispatch"
            )
        if remote.engine.retries < 1:
            problems.append(
                "flap-transient: the dispatch never retried "
                "(retries=0) — the refusal window was not exercised"
            )

        print("== flap phase 2: refusal longer than the retry budget ==")
        await proxy.wait_recovered()
        await proxy.set_fault("refuse-for:1.5")
        responses = await coord.go_multiple(flap_chunk(1))
        _check_exactly_once(responses, n, problems, "flap-loss")
        fleet_runs.append([comparable(r) for r in responses])
        if coord.stats.losses != 1 or len(coord.loss_log) != 1:
            problems.append(
                "flap-loss: expected exactly one loss event, got "
                f"losses={coord.stats.losses} log={len(coord.loss_log)}"
            )
        if coord.loss_log and coord.loss_log[0].member != "proxy":
            problems.append(
                f"flap-loss: the loss names {coord.loss_log[0].member!r},"
                " expected the proxied member"
            )
        if not remote.probation:
            problems.append(
                "flap-loss: the lost member skipped probation — "
                "readmission must pass through the gauntlet"
            )

        print("== flap phase 3: probed readmission (healthz + canary) ==")
        await proxy.wait_recovered()
        await asyncio.sleep(0.4)  # sit out the escalated cooldown
        served_before = remote.dispatched_positions
        await coord.probe_members()
        if coord.stats.readmissions != 1 or coord.stats.canaries_ok != 1:
            problems.append(
                "flap-readmit: expected 1 readmission through 1 canary, "
                f"got readmissions={coord.stats.readmissions} "
                f"canaries_ok={coord.stats.canaries_ok} "
                f"probe_failures={coord.stats.probe_failures}"
            )
        if not remote.available() or remote.probation:
            problems.append(
                f"flap-readmit: member state {remote.state()!r} after a "
                "successful probe — expected eligible"
            )
        responses = await coord.go_multiple(flap_chunk(2))
        _check_exactly_once(responses, n, problems, "flap-readmit")
        fleet_runs.append([comparable(r) for r in responses])
        if remote.dispatched_positions <= served_before:
            problems.append(
                "flap-readmit: the readmitted member was never planned "
                "work again"
            )
        if coord.stats.losses != 1:
            problems.append(
                "flap-readmit: losses moved after readmission "
                f"({coord.stats.losses}) — the canary/chunk flapped"
            )
        for phase, (got, want) in enumerate(zip(fleet_runs, direct)):
            if got != want:
                problems.append(
                    f"flap phase {phase + 1}: answers are not "
                    "bit-identical to the direct engine run"
                )
    except EngineError as e:
        problems.append(f"fleet-flap: chunk failed outright: {e}")
    finally:
        print(f"fleet stats: {coord.stats}")
        await coord.close()
        await proxy.close()
        await app.drain_and_stop()

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet-flap::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet-flap: zero-loss transient retry, one-loss flap, "
          "probed readmission, bit-identical answers verified")
    return 0


async def fleet_hedge_scenario(args) -> int:
    """Self-healing acceptance gate (ISSUE 15), hedging half. Three
    fakehost members, one a 400ms straggler. With FISHNET_TPU_FLEET_HEDGE
    semantics on, the straggler's position is duplicated to a free
    member once deadline slack runs low and the first answer wins:
    tail latency must drop measurably vs the hedge-off run, every
    position must answer exactly once, the hedge counters must tie out
    in the metrics registry, and the answers must be bit-identical
    with hedging on or off."""
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs.metrics import MetricsRegistry

    problems = []
    n, rounds = 3, 5

    async def run(hedge, tmp):
        def member(name, extra=()):
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps({"chunks": ["ok"]}),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                ] + list(extra),
                logger=Logger(verbose=0),
                hb_interval=0.05, hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        registry = MetricsRegistry()
        coord = FleetCoordinator(
            [
                member("straggler", extra=["--latency-ms", "400"]),
                member("f1"),
                member("f2"),
            ],
            logger=Logger(verbose=0), registry=registry,
            loss_window=5.0, hedge=hedge, hedge_slack_ms=1800,
        )
        latencies, answers = [], []
        try:
            await coord.start()
            # warm round: absorb process spawn cost outside the timing
            # (ttl 10 puts the hedge trigger far past completion)
            await coord.go_multiple(make_chunk(900, 10.0, n))
            for i in range(rounds):
                chunk = make_chunk(901 + i, 2.0, n)
                t0 = time.monotonic()
                responses = await coord.go_multiple(chunk)
                latencies.append(time.monotonic() - t0)
                _check_exactly_once(
                    responses, n, problems,
                    f"straggler-hedge[hedge={hedge}] round {i}",
                )
                answers.append([
                    (r.position_index, r.scores.best().value)
                    for r in responses
                ])
            snap = registry.snapshot()
        finally:
            await coord.close()
        return latencies, answers, coord.stats, snap

    with tempfile.TemporaryDirectory(prefix="chaos-hedge-") as tmp:
        print("== straggler fleet, hedge OFF ==")
        lat_off, ans_off, stats_off, _ = await run(False, tmp)
        print(f"   per-chunk latency: "
              f"{' '.join(f'{v * 1000:.0f}ms' for v in lat_off)}")
        print("== straggler fleet, hedge ON ==")
        lat_on, ans_on, stats_on, snap_on = await run(True, tmp)
        print(f"   per-chunk latency: "
              f"{' '.join(f'{v * 1000:.0f}ms' for v in lat_on)}")

    p99_off, p99_on = max(lat_off), max(lat_on)
    print(f"\np99: off={p99_off * 1000:.0f}ms on={p99_on * 1000:.0f}ms  "
          f"hedges={stats_on.hedges} wins={stats_on.hedge_wins}")
    if ans_on != ans_off:
        problems.append(
            "straggler-hedge: answers differ between hedge on and off — "
            "hedging must be bit-identical"
        )
    if stats_off.hedges != 0:
        problems.append(
            f"straggler-hedge: hedge-off run hedged {stats_off.hedges} "
            "position(s)"
        )
    if stats_on.hedges < 1 or stats_on.hedge_wins < 1:
        problems.append(
            "straggler-hedge: expected at least one hedge and one hedge "
            f"win, got hedges={stats_on.hedges} "
            f"wins={stats_on.hedge_wins}"
        )
    if stats_on.losses or stats_off.losses:
        problems.append(
            "straggler-hedge: a slow member was treated as dead "
            f"(losses on={stats_on.losses} off={stats_off.losses})"
        )
    if snap_on.get("fishnet_fleet_hedges_total") != stats_on.hedges or \
            snap_on.get("fishnet_fleet_hedge_wins_total") != stats_on.hedge_wins:
        problems.append(
            "straggler-hedge: fishnet_fleet_hedges_total/fishnet_fleet_hedge_wins_total "
            "do not tie out with the coordinator ledger"
        )
    if not p99_on < p99_off:
        problems.append(
            f"straggler-hedge: hedging did not cut p99 chunk latency "
            f"({p99_on * 1000:.0f}ms vs {p99_off * 1000:.0f}ms)"
        )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet-straggler-hedge::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet-straggler-hedge: first-answer-wins hedging cut the "
          "tail, exactly-once and bit-identity verified")
    return 0


async def trace_smoke(args) -> int:
    """CI flight-recorder smoke (ISSUE 10): a chaos-induced child death
    with tracing on must leave a merged supervisor+host dump that loads
    as valid Chrome trace JSON and passes trace_report's internal
    cross-validation. Fails the step when no dump appears or the dump
    does not parse."""
    import os

    from tools import trace_report

    problems = []
    # fixed request id: the continuity checks follow it across the kill
    # into the respawned incarnation's replay
    tid = "c0ffeec0ffeec0ffeec0ffee"
    with tempfile.TemporaryDirectory(prefix="chaos-trace-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before the supervisor constructs: its __init__ reads the
        # settings registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir
        print("== trace smoke: kill after 2 partials, tracing on ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s.json",
        )
        # --trace-skew 0.0 opts the fake host into streaming a synthetic
        # child trace ring, so the dump exercises the cross-process merge
        sup.host_cmd += ["--trace-skew", "0.0"]
        try:
            responses = await sup.go_multiple(
                make_chunk(1, 30.0, 4, trace_id=tid)
            )
            _check_exactly_once(responses, 4, problems, "trace-smoke")
        except EngineError as e:
            problems.append(f"trace-smoke: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            rec = obs_trace.RECORDER
            if rec is not None:
                # second dump AFTER recovery: the child-death dump above
                # is written mid-replay, this one holds the respawned
                # incarnation's spans for the ctx-continuity checks
                rec.flight_dump(trace_dir, "smoke-final")
            await sup.close()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        dumps = sorted(Path(trace_dir).glob("trace-child-death-*.json"))
        if not dumps:
            problems.append(
                "trace-smoke: child death left no flight dump in "
                f"{trace_dir}"
            )
        else:
            print(f"\nflight dump: {dumps[-1].name}")
            rc = trace_report.main(
                [str(dumps[-1]), "--selftest", f"--format={args.format}"]
            )
            if rc != 0:
                problems.append(
                    f"trace-smoke: trace_report exited {rc} on the dump"
                )
            else:
                events = trace_report.load_events(str(dumps[-1]))
                names = {e.get("name") for e in events}
                # supervisor-side markers (spawn, the dump's own ladder
                # instant) AND the child's streamed span must both be in
                # the merged ring — the dump is written mid-recovery, so
                # the still-open dispatch span is legitimately absent
                for expected in ("spawn", "flight-dump", "fake.search"):
                    if expected not in names:
                        problems.append(
                            f"trace-smoke: merged dump is missing "
                            f"{expected!r} — supervisor and host "
                            "timelines did not both land"
                        )

        # ctx continuity (kill-mid-chunk): in the post-recovery dump the
        # request id must link the journaled pre-death partials to the
        # respawned incarnation's replay — the chain spans BOTH host
        # incarnations (two child pids) plus the supervisor's flow hops
        finals = sorted(Path(trace_dir).glob("trace-smoke-final-*.json"))
        if not finals:
            problems.append(
                f"trace-smoke: no post-recovery dump in {trace_dir}"
            )
        else:
            events = trace_report.load_events(str(finals[-1]))
            req = trace_report.request_events(events, tid)
            req_names = {e.get("name") for e in req}
            if "position.journaled" not in req_names:
                problems.append(
                    "trace-smoke: no position.journaled instant carries "
                    "the request id — the journal dropped ctx across "
                    "the kill"
                )
            search_pids = {e.get("pid") for e in req
                           if e.get("name") == "fake.search"}
            if len(search_pids) < 2:
                problems.append(
                    "trace-smoke: the request chain does not span both "
                    "host incarnations (fake.search pids: "
                    f"{sorted(search_pids)}) — replay lost the context"
                )
            flow_pids = {e.get("pid") for e in req
                         if e.get("ph") in ("s", "t", "f")}
            if len(flow_pids) < 2:
                problems.append(
                    "trace-smoke: request flow hops come from fewer "
                    "than two processes — the cross-process link is gone"
                )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos trace smoke::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos trace smoke: flight dump written, merged, and parsed")
    return 0


async def request_trace_scenario(args) -> int:
    """Request-tracing acceptance gate (ISSUE 14). One request POSTed
    to /analyse on a ServeApp fronting a 3-member fakehost fleet, with
    member m0 killed mid-chunk, must leave ONE merged Chrome trace whose
    spans link the HTTP edge to every process that touched the request —
    including the survivor that absorbed the re-dispatch — while
    `GET /debug/requests` shows the request's stage in flight; and the
    search results must be bit-identical with tracing on vs off."""
    import os

    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs import metrics as obs_metrics
    from fishnet_tpu.serve.server import ServeApp
    from tools import trace_report

    problems = []
    # fixed request id so the traced and untraced phases submit
    # byte-identical bodies
    tid = "feedc0defeedc0defeedc0defeedc0de"

    async def http(host, port, method, path, body=None):
        """One HTTP/1.1 exchange over a raw asyncio connection (the
        serve front-end speaks plain stdlib HTTP; no client library)."""
        reader, writer = await asyncio.open_connection(host, port)
        try:
            payload = (b"" if body is None
                       else json.dumps(body).encode("utf-8"))
            head = (
                f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        header, _, body_bytes = raw.partition(b"\r\n\r\n")
        status = int(header.split(None, 2)[1])
        return status, (json.loads(body_bytes) if body_bytes else {})

    async def run_once(tmp: str, tag: str):
        """One POST /analyse against a fresh 3-member fleet behind the
        serve front-end; m0 dies after acking 1 position. Polls
        /debug/requests while the request is in flight. Returns
        (status, payload, stages_seen, coordinator)."""

        def member(name, script, skew):
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{tag}-{name}.json",
                    "--hb-interval", "0.05",
                    "--trace-skew", str(skew),
                    # widen the in-flight window so the /debug/requests
                    # poll reliably catches the request mid-stage
                    "--latency-ms", "250",
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        members = [
            member("m0", {"chunks": ["die-after:1", "ok"]}, 5.0),
            member("m1", {"chunks": ["ok"]}, 0.0),
            member("m2", {"chunks": ["ok"]}, 2.5),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
            redispatch_max=3, loss_window=0.2,
        )
        app = ServeApp(
            EngineSession(coord, flavor=EngineFlavor.TPU),
            logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
        )
        stages = []
        try:
            await coord.start()
            host, port = await app.start("127.0.0.1", 0)
            body = {
                "id": f"reqtrace-{tag}",
                "tenant": "chaos",
                "trace_id": tid,
                # distinct move chains → distinct position fingerprints,
                # so the exactly-once ledger tracks 6 real entries
                "positions": [
                    {"fen": START, "moves": ["e2e4"] * i}
                    for i in range(6)
                ],
                "depth": 1,
                "timeout_ms": 8000,
            }
            post = asyncio.ensure_future(
                http(host, port, "POST", "/analyse", body)
            )
            poll_deadline = time.monotonic() + 30.0
            while not post.done() and time.monotonic() < poll_deadline:
                st, dbg = await http(host, port, "GET", "/debug/requests")
                if st == 200:
                    for r in dbg.get("requests", []):
                        if r.get("trace_id") == tid:
                            stages.append(r.get("stage"))
                await asyncio.sleep(0.02)
            status, payload = await asyncio.wait_for(post, timeout=30.0)
        finally:
            await app.drain_and_stop()
            await coord.close()
        return status, payload, stages, coord

    with tempfile.TemporaryDirectory(prefix="chaos-reqtrace-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before any member constructs: SupervisedEngine.__init__
        # reads the registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir
        print("== request-trace: tracing ON, m0 dies after 1 ack ==")
        try:
            status, payload, stages, coord = await run_once(tmp, "on")
        finally:
            rec = obs_trace.RECORDER
            if rec is not None:
                rec.flight_dump(trace_dir, "request-trace")
            obs_trace.uninstall()
            del os.environ["FISHNET_TPU_TRACE_DIR"]

        if status != 200:
            problems.append(
                f"request-trace: POST /analyse answered {status}: {payload}"
            )
        if coord.stats.losses != 1:
            problems.append(
                "request-trace: expected exactly one member loss, got "
                f"{coord.stats.losses}"
            )
        results = payload.get("results", [])
        if len(results) != 6:
            problems.append(
                f"request-trace: {len(results)} results for 6 positions"
            )
        if not stages:
            problems.append(
                "request-trace: /debug/requests never showed the request "
                "while it was in flight"
            )
        elif "dispatched" not in stages:
            problems.append(
                "request-trace: /debug/requests never showed the "
                f"'dispatched' stage (saw {sorted(set(stages))})"
            )

        dumps = sorted(Path(trace_dir).glob("trace-request-trace-*.json"))
        if not dumps:
            problems.append(
                f"request-trace: no merged flight dump in {trace_dir}"
            )
        else:
            print(f"\nmerged dump: {dumps[-1].name}")
            events = trace_report.load_events(str(dumps[-1]))
            req = trace_report.request_events(events, tid)
            names = {e.get("name") for e in req}
            # the full causal chain, HTTP edge → lane-level hand-offs:
            # each name is one hop that must carry the request id
            for expected in ("http.request", "serve.admission",
                             "serve.chunk", "fleet.dispatch",
                             "supervisor.dispatch", "position.journaled",
                             "slo.observe", "fake.search"):
                if expected not in names:
                    problems.append(
                        "request-trace: the request's causal chain is "
                        f"missing {expected!r} in the merged dump"
                    )
            flow_pids = {e.get("pid") for e in req
                         if e.get("ph") in ("s", "t", "f")}
            if len(flow_pids) < 3:
                problems.append(
                    "request-trace: request flow hops span "
                    f"{len(flow_pids)} process(es), expected the serve "
                    "process plus at least two member children"
                )
            searches = [e for e in req if e.get("name") == "fake.search"]
            if len(searches) < 4:
                problems.append(
                    "request-trace: expected the re-dispatch to add a "
                    "fourth fake.search span carrying the request id, "
                    f"got {len(searches)}"
                )
            if "fleet.member-loss" not in names:
                problems.append(
                    "request-trace: the member-loss instant does not "
                    "name the request's trace id"
                )
            wf = trace_report.request_waterfall(events, tid)
            if wf is None:
                problems.append(
                    "request-trace: request_waterfall found nothing for "
                    "the request id"
                )
            else:
                print(trace_report.render_waterfall(wf))
                problems.extend(
                    f"request-trace: {p}"
                    for p in trace_report.request_crosscheck(wf)
                )

        # ---- tracing OFF: same fault schedule, results must not move
        print("\n== request-trace: tracing OFF, same fault schedule ==")
        status_off, payload_off, _stages, _coord = await run_once(tmp, "off")
        if status_off != 200:
            problems.append(
                "request-trace: untraced POST /analyse answered "
                f"{status_off}: {payload_off}"
            )
        elif payload.get("results") != payload_off.get("results"):
            problems.append(
                "request-trace: search results differ with tracing on "
                "vs off — instrumentation perturbed the search"
            )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos request trace::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos request trace: one merged edge-to-member timeline, live "
          "stage introspection, results identical with tracing off")
    return 0


async def burst_member_loss_scenario(args) -> int:
    """Elastic-capacity chaos gate (ISSUE 16): chaos UNDER load. An
    open-loop flash crowd (tools/loadgen.py, 10x base rate) hits a
    ServeApp whose fleet starts at its two-member floor with the
    autoscaler running; floor member m0 dies mid-burst. The gate
    demands the properties docs/autoscaling.md promises:

    - zero lost requests: every scheduled arrival answers 200 or is
      shed with a 429 — nothing hangs, nothing errors;
    - bounded shed window: any shed lands inside the flash crowd (plus
      drain slack), never after the autoscaler has caught up;
    - exactly one loss event for the one death;
    - no scale-DOWN decision inside the post-loss cooldown window (the
      recovery-ladder veto — capacity never shrinks mid-ladder);
    - the member count returns to the floor once the burst passes, so
      the scale-up is hysteretic, not a ratchet.
    """
    import os

    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.autoscaler import AutoscaleConfig, Autoscaler
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs import metrics as obs_metrics
    from fishnet_tpu.serve.server import ServeApp
    from tools.loadgen import LoadProfile, generate_schedule, run_load

    problems = []
    with tempfile.TemporaryDirectory(prefix="chaos-burst-") as tmp:

        def member(name, script):
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                    # steady per-chunk service time: the flash crowd
                    # must actually queue for the autoscaler to see it
                    "--latency-ms", "30",
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        print("== burst-member-loss: flash crowd, floor member dies "
              "mid-burst, autoscaler on ==")
        # a 2-member floor: m0 dies once mid-chunk (its respawn
        # incarnation is clean) and m1 absorbs the re-dispatch — a
        # 1-member floor would strand in-flight work in the dead
        # window, which is a deployment error, not a chaos finding.
        # Every autoscaled member is clean
        coord = FleetCoordinator(
            [
                member("m0", {"chunks": ["die-after:1", "ok"]}),
                member("m1", {"chunks": ["ok"]}),
            ],
            logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
            redispatch_max=3, loss_window=0.2,
            local_factory=lambda name: member(name, {"chunks": ["ok"]}),
        )
        app = ServeApp(
            EngineSession(coord, flavor=EngineFlavor.TPU),
            # a tiny admission section so the burst visibly queues:
            # queued>0 is the autoscaler's up-pressure signal.
            # max_inflight/max_queue count POSITIONS — inflight must fit
            # at least one whole 4-position request or nothing admits
            max_inflight=4, max_queue=64,
            logger=Logger(verbose=0),
            registry=obs_metrics.MetricsRegistry(),
        )
        as_cfg = AutoscaleConfig(
            min_members=2, max_members=4, interval_s=0.15,
            up_queue=1, up_ticks=2, down_ticks=5,
            loss_cooldown_s=2.0, drain_timeout_s=20.0,
        )
        autoscaler = Autoscaler(
            coord, app.admission, config=as_cfg,
            registry=app.registry, logger=Logger(verbose=0),
        )
        # 4 positions per request: the coordinator splits a request
        # across members, so m0's share of its first dispatch is >= 2
        # positions and "die-after:1" lands MID-sub-chunk — a real
        # member-loss event, not an idle death the supervisor absorbs
        profile = LoadProfile(
            pattern="flash", duration_s=8.0, base_rps=2.0,
            flash_factor=10.0, flash_start=0.125, flash_len=0.375,
            tenants=3, bestmove_ratio=0.0, positions=4, depth=1,
            timeout_ms=20000,
        )
        schedule = generate_schedule(profile, seed=16)
        flash_t0 = profile.flash_start * profile.duration_s
        flash_t1 = flash_t0 + profile.flash_len * profile.duration_s
        shed_offsets = []
        loss_seen_at = [None]
        run_began = [0.0]

        def on_tick(t):
            # first observation of the loss, on the loadgen clock
            if loss_seen_at[0] is None and coord.stats.losses > 0:
                loss_seen_at[0] = time.monotonic()

        def on_result(req, index, status, at):
            if status == 429:
                shed_offsets.append(at)

        try:
            await coord.start()
            host, port = await app.start("127.0.0.1", 0)
            autoscaler.start()
            run_began[0] = time.monotonic()
            report = await run_load(
                host, port, schedule, logger=Logger(verbose=0),
                drain_timeout_s=60.0, on_tick=on_tick,
                on_result=on_result,
            )
            # post-burst: wait for the loop to drain back to the floor
            # (down_ticks idle ticks per step + one drain per member)
            floor_deadline = time.monotonic() + 30.0
            while time.monotonic() < floor_deadline:
                snap = autoscaler.snapshot()
                if (snap["members"] == as_cfg.min_members
                        and snap["draining"] is None):
                    break
                await asyncio.sleep(0.1)
            snap = autoscaler.snapshot()
        finally:
            await autoscaler.stop()
            await app.drain_and_stop()
            await coord.close()

        d = report.as_dict()
        print(f"load: {d['scheduled']} scheduled, {d['ok']} ok, "
              f"{d['shed']} shed, {d['errors']} errors; "
              f"p99={d['per_kind'].get('analysis', {}).get('p99_ms', 0)}ms")
        print(f"autoscale: ups={snap['ups']} downs={snap['downs']} "
              f"blocked={snap['downs_blocked']} members={snap['members']} "
              f"member_seconds={snap['member_seconds']}")
        print(f"fleet: losses={coord.stats.losses}")

        if report.errors:
            problems.append(
                f"burst-member-loss: {report.errors} request(s) lost "
                "(neither answered nor shed) — chaos under load dropped "
                "work"
            )
        if report.ok == 0:
            problems.append("burst-member-loss: no request succeeded")
        if coord.stats.losses != 1:
            problems.append(
                "burst-member-loss: expected exactly one loss event, "
                f"got {coord.stats.losses}"
            )
        if shed_offsets:
            # sheds may only happen while the flash crowd outruns
            # capacity: inside the burst plus a catch-up slack
            first, last = min(shed_offsets), max(shed_offsets)
            slack = 2.0
            if first < flash_t0 - 0.1 or last > flash_t1 + slack:
                problems.append(
                    "burst-member-loss: shed window "
                    f"[{first:.2f}, {last:.2f}]s escaped the flash "
                    f"window [{flash_t0:.2f}, {flash_t1:.2f}]s (+"
                    f"{slack:.0f}s slack) — capacity never caught up"
                )
        if snap["ups"] < 1:
            problems.append(
                "burst-member-loss: the autoscaler never scaled up "
                "under a 10x flash crowd"
            )
        if snap["members"] != as_cfg.min_members or snap["owned"]:
            problems.append(
                "burst-member-loss: member count did not return to the "
                f"floor after the burst (members={snap['members']}, "
                f"owned={snap['owned']})"
            )
        if loss_seen_at[0] is not None:
            veto_until = (loss_seen_at[0] - run_began[0]
                          + as_cfg.loss_cooldown_s)
            early_downs = [
                dec for dec in autoscaler.decisions
                if dec.action == "down"
                and dec.at - run_began[0] < veto_until
            ]
            if early_downs:
                problems.append(
                    "burst-member-loss: a scale-down fired inside the "
                    "post-loss cooldown window — the recovery-ladder "
                    "veto failed"
                )
        else:
            problems.append(
                "burst-member-loss: the scripted member death was "
                "never observed during the run"
            )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos burst member loss::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos burst member loss: flash crowd survived a mid-burst "
          "member death — zero lost requests, bounded shed window, "
          "scale-up then return to floor, no scale-down mid-ladder")
    return 0


async def flap_under_load_scenario(args) -> int:
    """Elastic-capacity chaos gate (ISSUE 16), flap half: the
    fault-taxonomy guarantees of `fleet-flap` re-proven UNDER sustained
    open-loop load instead of one chunk at a time. A steady loadgen
    stream hits a ServeApp whose fleet is one PyEngine member plus one
    remote member behind a FlakyProxy; mid-run the proxy refuses
    connections twice:

    - a refusal window SHORTER than the in-dispatch retry budget must
      cost ZERO loss events — the bounded backoff rides it out while
      traffic keeps flowing;
    - a refusal window LONGER than the budget must surface as loss
      events naming ONLY the proxied member, with the stranded
      positions rerouted to the survivor;
    - through both: every scheduled request answers 200 — zero errors,
      zero sheds. Clients never see the flap; that is the graceful-
      degradation contract docs/autoscaling.md and docs/fleet.md make.
    """
    from fishnet_tpu.engine.fakehost import FlakyProxy
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator, FleetMember
    from fishnet_tpu.fleet.remote import HttpEngine
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp
    from tools.loadgen import LoadProfile, generate_schedule, run_load

    problems = []

    print("== flap-under-load: sustained open-loop stream, proxy "
          "refuses twice (short, then long) ==")
    # the proxied member's target: a plain serve front-end over PyEngine
    backend = ServeApp(
        EngineSession(PyEngine(max_depth=2), flavor=EngineFlavor.OFFICIAL),
        registry=MetricsRegistry(), logger=Logger(verbose=0),
    )
    bhost, bport = await backend.start("127.0.0.1", 0)
    proxy = FlakyProxy(bhost, bport)
    phost, pport = await proxy.start()
    remote = FleetMember(
        name="proxy",
        engine=HttpEngine(f"http://{phost}:{pport}", retry_max=4),
        kind="remote",
    )
    coord = FleetCoordinator(
        [remote, FleetMember(name="cpu0", engine=PyEngine(max_depth=2))],
        logger=Logger(verbose=0), registry=MetricsRegistry(),
        loss_window=0.3, redispatch_max=3,
    )
    app = ServeApp(
        EngineSession(coord, flavor=EngineFlavor.OFFICIAL),
        max_inflight=8, max_queue=64,
        registry=MetricsRegistry(), logger=Logger(verbose=0),
    )
    host, port = await app.start("127.0.0.1", 0)

    # steady 2 rps for 8s of single-position depth-1 requests: light
    # enough that the 8s serve deadline cap is never the constraint —
    # the flap, not the search, must be the only stressor
    profile = LoadProfile(
        pattern="steady", duration_s=8.0, base_rps=2.0, tenants=2,
        bestmove_ratio=0.0, positions=1, depth=1, timeout_ms=8000,
    )
    schedule = generate_schedule(profile, seed=16)

    # anchor each refusal window just ahead of a real scheduled
    # arrival: the schedule is pure in (profile, seed), and an idle
    # fleet's least-backlog tie-break dispatches to the FIRST member
    # (the proxy), so a window that covers an arrival deterministically
    # puts a connect attempt inside it
    def arrival_after(t: float) -> float:
        return next((p.at for p in schedule if p.at >= t), t)

    short_at = max(arrival_after(1.5) - 0.1, 0.1)
    long_at = arrival_after(short_at + 1.8) - 0.1

    losses_after_short = [None]

    async def inject():
        # short refusal: inside the retry budget (min time-to-exhaust
        # for retry_max=4 is ~0.38s of backoff, so 0.25s always rides)
        await asyncio.sleep(short_at)
        await proxy.set_fault("refuse-for:0.25")
        await asyncio.sleep(1.4)
        losses_after_short[0] = coord.stats.losses
        # long refusal: past the budget (worst-case total backoff is
        # ~1.1s, so a 1.5s window always exhausts) — a real loss
        await asyncio.sleep(max(long_at - short_at - 1.4, 0.0))
        await proxy.set_fault("refuse-for:1.5")

    try:
        injector = asyncio.ensure_future(inject())
        report = await run_load(host, port, schedule,
                                logger=Logger(verbose=0),
                                drain_timeout_s=40.0)
        await injector
    finally:
        await app.drain_and_stop()
        await coord.close()
        await proxy.close()
        await backend.drain_and_stop()

    print(f"load: scheduled={report.scheduled} ok={report.ok} "
          f"shed={report.shed} errors={report.errors}")
    print(f"fleet: losses={coord.stats.losses} "
          f"retries={remote.engine.retries}")

    if report.errors or report.shed or report.ok != report.scheduled:
        problems.append(
            "flap-under-load: the flap leaked to clients — "
            f"ok={report.ok}/{report.scheduled} shed={report.shed} "
            f"errors={report.errors} (all must answer 200)"
        )
    if losses_after_short[0] is None or losses_after_short[0] != 0:
        problems.append(
            "flap-under-load: a refusal shorter than the retry budget "
            f"cost {losses_after_short[0]} loss event(s) — transient "
            "connect faults must be ridden out in-dispatch"
        )
    if remote.engine.retries < 1:
        problems.append(
            "flap-under-load: the dispatch never retried (retries=0) — "
            "the short refusal window was not exercised"
        )
    if coord.stats.losses < 1:
        problems.append(
            "flap-under-load: the long refusal never surfaced as a "
            "loss event — the gate did not exercise re-dispatch"
        )
    wrong = [ev.member for ev in coord.loss_log if ev.member != "proxy"]
    if wrong:
        problems.append(
            f"flap-under-load: loss events name {wrong!r} — only the "
            "proxied member may be lost"
        )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos flap under load::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos flap under load: sustained stream rode out a short "
          "refusal with zero losses, absorbed the long one as proxied-"
          "member loss events, and every request answered 200")
    return 0


async def cache_poison_scenario(args) -> int:
    """Analysis-cache poison gate (ISSUE 17): a corrupt persisted cache
    entry must cost exactly ONE quarantine (`.bad` rename + one warning
    + its index row dropped) and nothing else — every response, served
    from the surviving entries or re-searched as fallback, must be
    bit-identical to a cache-off run. Three phases over one cache dir:

    1. reference: the request served with the cache OFF;
    2. cold fill: same request through a persisted cache — the body
       must already be bit-identical (the cold path IS the engine
       path) and every position must persist;
    3. poison + restart: one payload file is corrupted on disk, a new
       process (fresh AnalysisCache over the same directory) serves
       the same request — `X-Fishnet-Cache: partial`, one quarantine,
       identical body; a follow-up request must be all-hit again (the
       fallback search re-fills the poisoned entry).
    """
    from fishnet_tpu.cache.keys import engine_identity
    from fishnet_tpu.cache.store import AnalysisCache
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp

    problems = []
    n = 4
    moves = ["e2e4", "e7e5", "g1f3"]
    body = {
        "id": "cache-poison", "tenant": "chaos",
        "positions": [{"fen": START, "moves": moves[:i]} for i in range(n)],
        "depth": 2, "timeout_ms": 8000,
    }

    class _WarnLog(Logger):
        def __init__(self):
            super().__init__(verbose=0)
            self.warnings = []

        def warn(self, text: str) -> None:
            self.warnings.append(text)
            super().warn(text)

    async def http_post(host, port, payload_obj):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            payload = json.dumps(payload_obj).encode("utf-8")
            head = (
                f"POST /analyse HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        header, _, body_bytes = raw.partition(b"\r\n\r\n")
        lines = header.decode("latin-1").split("\r\n")
        status = int(lines[0].split(None, 2)[1])
        headers = {}
        for line in lines[1:]:
            name, _, value = line.partition(":")
            headers[name.strip().lower()] = value.strip()
        return status, headers, (json.loads(body_bytes) if body_bytes else {})

    def comparable(resp_body):
        """The search-determined payload: wall-clock fields (time_s,
        nps, request latency) legitimately differ between a cached
        entry — which carries the ORIGINAL search's timings — and a
        fresh run; bit-identity is over what the search decided."""
        return [
            {k: r.get(k)
             for k in ("scores", "pvs", "best_move", "depth", "nodes")}
            for r in resp_body.get("results", [])
        ]

    async def ask(cache):
        """One request through a fresh serve front-end (each phase is
        its own 'process'; only the cache directory is shared)."""
        app = ServeApp(
            EngineSession(PyEngine(max_depth=2), flavor=EngineFlavor.OFFICIAL),
            cache=cache, registry=MetricsRegistry(), logger=Logger(verbose=0),
        )
        host, port = await app.start("127.0.0.1", 0)
        try:
            return await http_post(host, port, body)
        finally:
            await app.drain_and_stop()

    # one identity fingerprint across every phase: same engine, same
    # flavor — a restart must NOT read as a netswap
    ident = engine_identity(PyEngine(max_depth=2), EngineFlavor.OFFICIAL)

    with tempfile.TemporaryDirectory(prefix="chaos-cache-") as tmp:
        entries = Path(tmp) / "entries"

        print("== phase 1: reference run, cache off ==")
        status, headers, ref = await ask(None)
        if status != 200:
            problems.append(f"reference: status {status}, expected 200")
        if "x-fishnet-cache" in headers:
            problems.append(
                "reference: X-Fishnet-Cache header present with the "
                "cache off"
            )

        print("== phase 2: cold fill through a persisted cache ==")
        wl1 = _WarnLog()
        cache1 = AnalysisCache(ident, directory=tmp, logger=wl1)
        status, headers, cold = await ask(cache1)
        if status != 200:
            problems.append(f"cold fill: status {status}, expected 200")
        if headers.get("x-fishnet-cache") != "miss":
            problems.append(
                "cold fill: X-Fishnet-Cache="
                f"{headers.get('x-fishnet-cache')!r}, expected 'miss'"
            )
        if comparable(cold) != comparable(ref):
            problems.append(
                "cold fill: response differs from the cache-off run — "
                "cold positions must be bit-identical"
            )
        if cache1.stats.fills != n:
            problems.append(
                f"cold fill: fills={cache1.stats.fills}, expected {n}"
            )
        payloads = sorted(p.name for p in entries.glob("*.json"))
        if len(payloads) != n:
            problems.append(
                f"cold fill: {len(payloads)} persisted payloads, "
                f"expected {n}"
            )

        print("== phase 3: corrupt one payload, restart, replay ==")
        poisoned = payloads[0] if payloads else ""
        if poisoned:
            path = entries / poisoned
            path.write_bytes(path.read_bytes()[:-4] + b"ruin")
        wl2 = _WarnLog()
        cache2 = AnalysisCache(ident, directory=tmp, logger=wl2)
        if cache2.counters()["disk_entries"] != n:
            problems.append(
                "restart: persisted index did not survive — "
                f"disk_entries={cache2.counters()['disk_entries']}, "
                f"expected {n}"
            )
        if cache2.stats.invalidated:
            problems.append(
                "restart: a plain restart invalidated entries — the "
                "identity fingerprint must be stable"
            )
        status, headers, warm = await ask(cache2)
        if status != 200:
            problems.append(f"poisoned replay: status {status}")
        if comparable(warm) != comparable(ref):
            problems.append(
                "poisoned replay: response differs from the cache-off "
                "run — the fallback search must be bit-identical"
            )
        if headers.get("x-fishnet-cache") != "partial":
            problems.append(
                "poisoned replay: X-Fishnet-Cache="
                f"{headers.get('x-fishnet-cache')!r}, expected 'partial' "
                f"({n - 1} hits + 1 quarantined fallback)"
            )
        c = cache2.counters()
        if c["quarantined"] != 1:
            problems.append(
                f"poisoned replay: quarantined={c['quarantined']}, "
                "expected exactly the one corrupted entry"
            )
        if c["disk_hits"] != n - 1:
            problems.append(
                f"poisoned replay: disk_hits={c['disk_hits']}, expected "
                f"{n - 1} — the other entries must keep serving"
            )
        bad = sorted(p.name for p in entries.glob("*.bad"))
        if bad != [poisoned + ".bad"]:
            problems.append(
                f"poisoned replay: quarantine files {bad!r}, expected "
                f"exactly [{poisoned + '.bad'!r}]"
            )
        quarantine_warns = [
            w for w in wl2.warnings if "integrity check failed" in w
        ]
        if len(quarantine_warns) != 1:
            problems.append(
                f"poisoned replay: {len(quarantine_warns)} quarantine "
                "warnings, expected exactly one"
            )

        # the fallback search must have re-filled the poisoned entry:
        # the same request again is all-hit, still bit-identical
        status, headers, again = await ask(cache2)
        if headers.get("x-fishnet-cache") != "hit" \
                or comparable(again) != comparable(ref):
            problems.append(
                "re-fill: second replay after the quarantine is "
                f"X-Fishnet-Cache={headers.get('x-fishnet-cache')!r} "
                "(expected 'hit' — the fallback result must repair the "
                "cache) or not bit-identical"
            )
        if cache2.stats.quarantined != 1:
            problems.append(
                "re-fill: a second quarantine happened on the replay — "
                "corruption must cost exactly one"
            )
        print(f"cache: {cache2.counters()}")

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos cache poison::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos cache poison: one corrupt payload cost exactly one "
          "quarantine (.bad + one warning), every response stayed "
          "bit-identical to cache-off, and the fallback re-filled the "
          "entry")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--script", default="flap",
                   help="named script, inline JSON, or @path "
                        "(see --list; default: flap)")
    p.add_argument("--list", action="store_true",
                   help="list named fault scripts and exit")
    p.add_argument("--chunks", type=int, default=4,
                   help="number of chunks to feed (default 4)")
    p.add_argument("--positions", type=int, default=2,
                   help="positions per chunk (default 2)")
    p.add_argument("--chunk-ttl", type=float, default=10.0,
                   help="per-chunk deadline in seconds (default 10)")
    p.add_argument("--pause", type=float, default=0.0,
                   help="seconds to sleep between chunks (default 0)")
    p.add_argument("--hb-interval", type=float, default=0.25)
    p.add_argument("--hb-timeout", type=float, default=2.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    p.add_argument("--probe-interval", type=float, default=5.0)
    p.add_argument("--scenario", nargs="?", const="ladder", default=None,
                   choices=["ladder", "fleet-member-loss", "request-trace",
                            "fleet-flap", "fleet-straggler-hedge",
                            "burst-member-loss", "flap-under-load",
                            "cache-poison"],
                   help="run an acceptance scenario and exit non-zero on "
                        "any delivery violation: `ladder` (default when "
                        "the flag is bare) is the session-recovery "
                        "ladder, `fleet-member-loss` kills one of 3 "
                        "fleet members mid-chunk, `request-trace` POSTs "
                        "a traced request to /analyse over that same "
                        "dying fleet and checks the merged edge-to-"
                        "member timeline")
    p.add_argument("--trace-smoke", action="store_true",
                   help="kill a child mid-chunk with tracing on and "
                        "verify the merged flight dump parses")
    p.add_argument("--format", choices=["text", "github"], default="text",
                   help="github emits ::error annotations for CI")
    args = p.parse_args(argv)
    if args.list:
        for name, script in NAMED_SCRIPTS.items():
            print(f"{name:14s} {json.dumps(script)}")
        return 0
    if args.scenario == "ladder":
        return asyncio.run(scenario(args))
    if args.scenario == "fleet-member-loss":
        return asyncio.run(fleet_scenario(args))
    if args.scenario == "fleet-flap":
        return asyncio.run(fleet_flap_scenario(args))
    if args.scenario == "fleet-straggler-hedge":
        return asyncio.run(fleet_hedge_scenario(args))
    if args.scenario == "request-trace":
        return asyncio.run(request_trace_scenario(args))
    if args.scenario == "burst-member-loss":
        return asyncio.run(burst_member_loss_scenario(args))
    if args.scenario == "flap-under-load":
        return asyncio.run(flap_under_load_scenario(args))
    if args.scenario == "cache-poison":
        return asyncio.run(cache_poison_scenario(args))
    if args.trace_smoke:
        return asyncio.run(trace_smoke(args))
    return asyncio.run(replay(args))


if __name__ == "__main__":
    sys.exit(main())
