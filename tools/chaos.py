"""Replay named fault scripts against a live engine supervisor.

Manual soak/chaos harness for the supervisor (engine/supervisor.py):
spins up a SupervisedEngine over the scriptable fake host
(engine/fakehost.py), feeds it synthetic analysis chunks, and prints
per-chunk outcomes plus the final SupervisorStats. The same scripts run
in tier-1 (tests/test_supervisor.py); this tool is for watching the
watchdog work in real time and for soak-testing timing knobs.

Examples:
    python -m tools.chaos --script flap --chunks 6 --breaker-threshold 2 \
        --probe-interval 2
    python -m tools.chaos --script hang --chunk-ttl 3
    python -m tools.chaos --script '{"chunks": ["stall", "ok"]}' --chunks 3
    python -m tools.chaos --list
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from fishnet_tpu.client.ipc import Chunk, WorkPosition  # noqa: E402
from fishnet_tpu.client.logger import Logger  # noqa: E402
from fishnet_tpu.client.wire import (  # noqa: E402
    AnalysisWork,
    EngineFlavor,
    NodeLimit,
)
from fishnet_tpu.engine.base import EngineError  # noqa: E402
from fishnet_tpu.engine.fakehost import NAMED_SCRIPTS  # noqa: E402
from fishnet_tpu.engine.supervisor import SupervisedEngine  # noqa: E402

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def make_chunk(index: int, ttl: float, n_positions: int) -> Chunk:
    work = AnalysisWork(
        id=f"chaos{index:03d}",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=1, multipv=None,
    )
    return Chunk(
        work=work, deadline=time.monotonic() + ttl, variant="standard",
        flavor=EngineFlavor.TPU,
        positions=[
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=[])
            for i in range(n_positions)
        ],
    )


async def replay(args) -> int:
    state = tempfile.NamedTemporaryFile(
        prefix="chaos-state-", suffix=".json", delete=False
    )
    state.close()
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", args.script,
        "--state", state.name,
        "--hb-interval", str(args.hb_interval),
    ]
    sup = SupervisedEngine(
        host_cmd,
        logger=Logger(verbose=2),
        hb_interval=args.hb_interval,
        hb_timeout=args.hb_timeout,
        breaker_threshold=args.breaker_threshold,
        probe_interval=args.probe_interval,
    )
    failures = 0
    try:
        for i in range(args.chunks):
            chunk = make_chunk(i, args.chunk_ttl, args.positions)
            t0 = time.monotonic()
            try:
                responses = await sup.go_multiple(chunk)
            except EngineError as e:
                failures += 1
                print(f"chunk {i}: ChunkFailed after "
                      f"{time.monotonic() - t0:.2f}s — {e}")
            else:
                cp = responses[0].scores.best()
                src = ("fake host" if cp is not None and cp.value == 777
                       else "cpu fallback")
                print(f"chunk {i}: ok in {time.monotonic() - t0:.2f}s "
                      f"({len(responses)} responses via {src})")
            if args.pause:
                await asyncio.sleep(args.pause)
    finally:
        await sup.close()
        Path(state.name).unlink(missing_ok=True)
    s = sup.stats
    print(
        f"\nstats: spawns={s.spawns} deaths={s.deaths} kills={s.kills} "
        f"hb_stalls={s.hb_stalls} deadline_kills={s.deadline_kills} "
        f"protocol_errors={s.protocol_errors} breaker_trips={s.breaker_trips} "
        f"breaker_resets={s.breaker_resets} probes={s.probes} "
        f"fallback_chunks={s.fallback_chunks} chunks_ok={s.chunks_ok}"
    )
    print(f"chunks: {args.chunks - failures} served, {failures} failed")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--script", default="flap",
                   help="named script, inline JSON, or @path "
                        "(see --list; default: flap)")
    p.add_argument("--list", action="store_true",
                   help="list named fault scripts and exit")
    p.add_argument("--chunks", type=int, default=4,
                   help="number of chunks to feed (default 4)")
    p.add_argument("--positions", type=int, default=2,
                   help="positions per chunk (default 2)")
    p.add_argument("--chunk-ttl", type=float, default=10.0,
                   help="per-chunk deadline in seconds (default 10)")
    p.add_argument("--pause", type=float, default=0.0,
                   help="seconds to sleep between chunks (default 0)")
    p.add_argument("--hb-interval", type=float, default=0.25)
    p.add_argument("--hb-timeout", type=float, default=2.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    p.add_argument("--probe-interval", type=float, default=5.0)
    args = p.parse_args(argv)
    if args.list:
        for name, script in NAMED_SCRIPTS.items():
            print(f"{name:12s} {json.dumps(script)}")
        return 0
    return asyncio.run(replay(args))


if __name__ == "__main__":
    sys.exit(main())
