"""Replay named fault scripts against a live engine supervisor.

Manual soak/chaos harness for the supervisor (engine/supervisor.py):
spins up a SupervisedEngine over the scriptable fake host
(engine/fakehost.py), feeds it synthetic analysis chunks, and prints
per-chunk outcomes plus the final SupervisorStats. The same scripts run
in tier-1 (tests/test_supervisor.py); this tool is for watching the
watchdog work in real time and for soak-testing timing knobs.

Examples:
    python -m tools.chaos --script flap --chunks 6 --breaker-threshold 2 \
        --probe-interval 2
    python -m tools.chaos --script hang --chunk-ttl 3
    python -m tools.chaos --script '{"chunks": ["stall", "ok"]}' --chunks 3
    python -m tools.chaos --list
    python -m tools.chaos --scenario --format=github   # CI acceptance run
    python -m tools.chaos --scenario fleet-member-loss # fleet CI gate

`--scenario` (default `ladder`) runs the round-9 session-recovery
acceptance ladder end-to-end (kill-mid-chunk replay, hang-at-segment
progress kill, crash-on-fingerprint quarantine) and exits non-zero on
any lost or duplicated PositionResponse, on a full-chunk re-search
after a partial kill, or on quarantine routing the wrong position.

`--scenario fleet-member-loss` is the fleet acceptance gate (ISSUE 12):
3 fakehost-backed members, one SIGKILLed mid-chunk — every position
must answer exactly once on the engine path, the re-dispatched set must
be a strict subset of the dead member's in-flight positions (acked work
is harvested, not re-searched), exactly one loss event must be
recorded, and the merged flight-recorder dump must carry spans from all
three member processes on one clock-synced timeline despite their
deliberately skewed clocks.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from fishnet_tpu.client.backoff import RandomizedBackoff  # noqa: E402
from fishnet_tpu.client.ipc import (  # noqa: E402
    Chunk,
    WorkPosition,
    position_fingerprint,
)
from fishnet_tpu.client.logger import Logger  # noqa: E402
from fishnet_tpu.client.wire import (  # noqa: E402
    AnalysisWork,
    EngineFlavor,
    NodeLimit,
)
from fishnet_tpu.engine.base import EngineError  # noqa: E402
from fishnet_tpu.engine.fakehost import FAKE_CP, NAMED_SCRIPTS  # noqa: E402
from fishnet_tpu.engine.supervisor import SupervisedEngine  # noqa: E402

START = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


def make_chunk(index: int, ttl: float, n_positions: int) -> Chunk:
    work = AnalysisWork(
        id=f"chaos{index:03d}",
        nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
        timeout_s=ttl, depth=1, multipv=None,
    )
    return Chunk(
        work=work, deadline=time.monotonic() + ttl, variant="standard",
        flavor=EngineFlavor.TPU,
        positions=[
            WorkPosition(work=work, position_index=i, url=None, skip=False,
                         root_fen=START, moves=[])
            for i in range(n_positions)
        ],
    )


async def replay(args) -> int:
    state = tempfile.NamedTemporaryFile(
        prefix="chaos-state-", suffix=".json", delete=False
    )
    state.close()
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", args.script,
        "--state", state.name,
        "--hb-interval", str(args.hb_interval),
    ]
    sup = SupervisedEngine(
        host_cmd,
        logger=Logger(verbose=2),
        hb_interval=args.hb_interval,
        hb_timeout=args.hb_timeout,
        breaker_threshold=args.breaker_threshold,
        probe_interval=args.probe_interval,
    )
    failures = 0
    try:
        for i in range(args.chunks):
            chunk = make_chunk(i, args.chunk_ttl, args.positions)
            t0 = time.monotonic()
            try:
                responses = await sup.go_multiple(chunk)
            except EngineError as e:
                failures += 1
                print(f"chunk {i}: ChunkFailed after "
                      f"{time.monotonic() - t0:.2f}s — {e}")
            else:
                cp = responses[0].scores.best()
                src = ("fake host" if cp is not None and cp.value == 777
                       else "cpu fallback")
                print(f"chunk {i}: ok in {time.monotonic() - t0:.2f}s "
                      f"({len(responses)} responses via {src})")
            if args.pause:
                await asyncio.sleep(args.pause)
    finally:
        await sup.close()
        Path(state.name).unlink(missing_ok=True)
    print_stats(sup.stats)
    print(f"chunks: {args.chunks - failures} served, {failures} failed")
    return 0


def print_stats(s) -> None:
    print(
        f"\nstats: spawns={s.spawns} deaths={s.deaths} kills={s.kills} "
        f"hb_stalls={s.hb_stalls} deadline_kills={s.deadline_kills} "
        f"protocol_errors={s.protocol_errors} breaker_trips={s.breaker_trips} "
        f"breaker_resets={s.breaker_resets} probes={s.probes} "
        f"fallback_chunks={s.fallback_chunks} chunks_ok={s.chunks_ok}"
    )
    print(
        f"recovery: partials={s.partials} "
        f"duplicate_partials={s.duplicate_partials} replays={s.replays} "
        f"replayed_positions={s.replayed_positions} "
        f"bisections={s.bisections} quarantined={s.quarantined} "
        f"quarantine_routed={s.quarantine_routed} "
        f"progress_stalls={s.progress_stalls}"
    )


# ------------------------------------------------ scripted acceptance run


def _scenario_supervisor(script: str, state_name: str, **kw):
    host_cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.fakehost",
        "--script", script,
        "--state", state_name,
        "--hb-interval", "0.05",
    ]
    kw.setdefault("hb_interval", 0.05)
    kw.setdefault("hb_timeout", 1.0)
    kw.setdefault("backoff", RandomizedBackoff(max_s=0.05))
    kw.setdefault("logger", Logger(verbose=0))
    return SupervisedEngine(host_cmd, **kw)


def _check_exactly_once(responses, n, problems, phase) -> None:
    indices = [r.position_index for r in responses]
    if sorted(indices) != list(range(n)):
        problems.append(
            f"{phase}: lost/duplicated PositionResponse — indices {indices}"
        )


async def scenario(args) -> int:
    """The round-9 acceptance ladder, one phase per rung."""
    problems = []
    n = 4
    with tempfile.TemporaryDirectory(prefix="chaos-scenario-") as tmp:
        # ---- phase 1: kill-mid-chunk — replay resumes the suffix
        print("== phase 1: kill after 2 partials (replay) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s1.json",
        )
        try:
            responses = await sup.go_multiple(make_chunk(1, 30.0, n))
            _check_exactly_once(responses, n, problems, "kill-mid-chunk")
            re_searched = n - sup.stats.replayed_positions
            if not (0 < re_searched < n):
                problems.append(
                    "kill-mid-chunk: expected strictly fewer re-searched "
                    f"positions than chunk size, got {re_searched} of {n} "
                    f"(replayed={sup.stats.replayed_positions})"
                )
        except EngineError as e:
            problems.append(f"kill-mid-chunk: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 2: hang-at-segment — progress watchdog + replay
        print("\n== phase 2: hang after 1 partial (progress stall) ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["hang-at:1", "partial-ok"]}),
            f"{tmp}/s2.json",
            progress_timeout=0.5,
        )
        try:
            responses = await sup.go_multiple(make_chunk(2, 30.0, n))
            _check_exactly_once(responses, n, problems, "hang-at-segment")
            if sup.stats.progress_stalls < 1:
                problems.append(
                    "hang-at-segment: the stalled partial stream was not "
                    "killed by progress_timeout"
                )
            if sup.stats.deadline_kills:
                problems.append(
                    "hang-at-segment: hit the chunk deadline instead of "
                    "the progress watchdog"
                )
        except EngineError as e:
            problems.append(f"hang-at-segment: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

        # ---- phase 3: crash-on-fingerprint — quarantine exactly the poison
        print("\n== phase 3: crash on one fingerprint (quarantine) ==")
        ref = _scenario_supervisor(
            json.dumps({"chunks": ["partial-ok"]}), f"{tmp}/ref.json"
        )
        try:
            fault_free = await ref.go_multiple(make_chunk(3, 30.0, n))
        finally:
            await ref.close()
        chunk = make_chunk(3, 60.0, n)
        poison_index = 2
        poison = position_fingerprint(chunk.positions[poison_index])
        sup = _scenario_supervisor(
            json.dumps({"chunks": [f"crash-on-fp:{poison}"]}),
            f"{tmp}/s3.json",
        )
        try:
            responses = await sup.go_multiple(chunk)
            _check_exactly_once(responses, n, problems, "crash-on-fp")
            if sup.stats.quarantined != 1:
                problems.append(
                    f"crash-on-fp: quarantined={sup.stats.quarantined}, "
                    "expected exactly the one poison position"
                )
            for i, (got, want) in enumerate(zip(responses, fault_free)):
                got_cp = got.scores.best().value
                if i == poison_index:
                    if got_cp == FAKE_CP:
                        problems.append(
                            "crash-on-fp: poison position answered by the "
                            "engine path, not the CPU fallback"
                        )
                elif (got_cp, got.best_move, got.depth, got.nodes) != (
                    want.scores.best().value, want.best_move,
                    want.depth, want.nodes,
                ):
                    problems.append(
                        f"crash-on-fp: position {i} not bit-identical to "
                        "the fault-free run"
                    )
            if sup.stats.breaker_trips:
                problems.append(
                    "crash-on-fp: the recovery ladder tripped the "
                    "whole-engine breaker"
                )
        except EngineError as e:
            problems.append(f"crash-on-fp: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos scenario: all phases passed "
          "(replay, progress-stall, quarantine)")
    return 0


async def fleet_scenario(args) -> int:
    """Fleet member-loss acceptance gate (ISSUE 12). Three local
    fakehost members with deliberately skewed child clocks; member m0
    dies after acking 1 of its positions mid-chunk. Verifies the
    exactly-once ledger (harvest acks, re-dispatch only the un-acked
    remainder to survivors), the one-loss-event contract, and that the
    merged flight dump holds all three members' spans on the parent
    timeline."""
    import os

    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs import trace as obs_trace
    from tools import trace_report

    problems = []
    n = 6
    with tempfile.TemporaryDirectory(prefix="chaos-fleet-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before any member constructs: SupervisedEngine.__init__
        # reads the registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir

        def member(name, script, skew):
            # distinct non-zero skews: if the per-member ClockSync were
            # broken, these spans would land seconds off the timeline
            return make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", json.dumps(script),
                    "--state", f"{tmp}/{name}.json",
                    "--hb-interval", "0.05",
                    "--trace-skew", str(skew),
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05,
                hb_timeout=1.0,
                backoff=RandomizedBackoff(max_s=0.05),
            )

        print("== fleet scenario: 3 members, m0 dies after 1 ack ==")
        members = [
            member("m0", {"chunks": ["die-after:1", "ok"]}, 5.0),
            member("m1", {"chunks": ["ok"]}, 0.0),
            member("m2", {"chunks": ["ok"]}, 2.5),
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=2),
            redispatch_max=3, loss_window=0.2,
        )
        t0_us = obs_trace.now_us()
        try:
            await coord.start()
            responses = await coord.go_multiple(make_chunk(1, 30.0, n))
            _check_exactly_once(responses, n, problems, "fleet-member-loss")
            if any(r.scores.best().value != FAKE_CP for r in responses):
                problems.append(
                    "fleet-member-loss: a position was answered off the "
                    "engine path (fallback leaked into the fleet)"
                )
            if coord.stats.losses != 1 or len(coord.loss_log) != 1:
                problems.append(
                    f"fleet-member-loss: expected exactly one loss event, "
                    f"got losses={coord.stats.losses} "
                    f"log={len(coord.loss_log)}"
                )
            if coord.loss_log:
                ev = coord.loss_log[0]
                redisp = set(ev.redispatched_fps)
                inflight = set(ev.inflight_fps)
                unacked = inflight - set(ev.acked_fps)
                if not redisp:
                    problems.append(
                        "fleet-member-loss: nothing re-dispatched — the "
                        "dead member's un-acked work was dropped"
                    )
                if redisp != unacked:
                    problems.append(
                        "fleet-member-loss: re-dispatched set != the dead "
                        f"member's un-acked in-flight set ({redisp} vs "
                        f"{unacked})"
                    )
                if not redisp < inflight:
                    problems.append(
                        "fleet-member-loss: re-dispatched set is not a "
                        "strict subset of the member's in-flight set — "
                        "acked work was re-searched"
                    )
                if len(redisp) >= n:
                    problems.append(
                        "fleet-member-loss: re-dispatched as much as a "
                        "full chunk resubmit"
                    )
        except EngineError as e:
            problems.append(f"fleet-member-loss: chunk failed outright: {e}")
        finally:
            print(f"fleet stats: {coord.stats}")
            rec = obs_trace.RECORDER
            if rec is not None:
                # final merged dump with every member's absorbed spans
                # (the member-loss dump is written mid-flight and may
                # race the survivors' trace frames)
                rec.flight_dump(trace_dir, "fleet-scenario")
            await coord.close()
        t1_us = obs_trace.now_us()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        loss_dumps = sorted(Path(trace_dir).glob("trace-member-loss-*.json"))
        if not loss_dumps:
            problems.append(
                "fleet-member-loss: the loss left no member-loss flight "
                f"dump in {trace_dir}"
            )
        dumps = sorted(Path(trace_dir).glob("trace-fleet-scenario-*.json"))
        if not dumps:
            problems.append(
                f"fleet-member-loss: no merged fleet dump in {trace_dir}"
            )
        else:
            print(f"\nmerged dump: {dumps[-1].name}")
            events = trace_report.load_events(str(dumps[-1]))
            searches = [e for e in events if e.get("name") == "fake.search"]
            pids = {e.get("pid") for e in searches}
            if len(pids) < 3:
                problems.append(
                    "fleet-member-loss: merged dump has fake.search spans "
                    f"from {len(pids)} member process(es), expected 3"
                )
            # clock-sync: with 5.0s/2.5s child skews, an unsynced span
            # would sit seconds outside the parent's monotonic window
            slack_us = 1_000_000
            for e in searches:
                if not (t0_us - slack_us <= e["ts"] <= t1_us + slack_us):
                    problems.append(
                        "fleet-member-loss: a member span (pid "
                        f"{e.get('pid')}) landed {e['ts']} outside the "
                        f"parent window [{t0_us}, {t1_us}] — clock sync "
                        "failed"
                    )
                    break
            names = {e.get("name") for e in events}
            for expected in ("fleet.dispatch", "fleet.member-loss"):
                if expected not in names:
                    problems.append(
                        f"fleet-member-loss: merged dump is missing the "
                        f"coordinator's {expected!r} marker"
                    )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos fleet scenario::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos fleet scenario: exactly-once under member loss, merged "
          "3-member timeline verified")
    return 0


async def trace_smoke(args) -> int:
    """CI flight-recorder smoke (ISSUE 10): a chaos-induced child death
    with tracing on must leave a merged supervisor+host dump that loads
    as valid Chrome trace JSON and passes trace_report's internal
    cross-validation. Fails the step when no dump appears or the dump
    does not parse."""
    import os

    from fishnet_tpu.obs import trace as obs_trace
    from tools import trace_report

    problems = []
    with tempfile.TemporaryDirectory(prefix="chaos-trace-") as tmp:
        trace_dir = f"{tmp}/traces"
        # set before the supervisor constructs: its __init__ reads the
        # settings registry and installs the process-global recorder
        os.environ["FISHNET_TPU_TRACE_DIR"] = trace_dir
        print("== trace smoke: kill after 2 partials, tracing on ==")
        sup = _scenario_supervisor(
            json.dumps({"chunks": ["die-after:2", "partial-ok"]}),
            f"{tmp}/s.json",
        )
        # --trace-skew 0.0 opts the fake host into streaming a synthetic
        # child trace ring, so the dump exercises the cross-process merge
        sup.host_cmd += ["--trace-skew", "0.0"]
        try:
            responses = await sup.go_multiple(make_chunk(1, 30.0, 4))
            _check_exactly_once(responses, 4, problems, "trace-smoke")
        except EngineError as e:
            problems.append(f"trace-smoke: chunk failed outright: {e}")
        finally:
            print_stats(sup.stats)
            await sup.close()
        obs_trace.uninstall()
        del os.environ["FISHNET_TPU_TRACE_DIR"]

        dumps = sorted(Path(trace_dir).glob("trace-child-death-*.json"))
        if not dumps:
            problems.append(
                "trace-smoke: child death left no flight dump in "
                f"{trace_dir}"
            )
        else:
            print(f"\nflight dump: {dumps[-1].name}")
            rc = trace_report.main(
                [str(dumps[-1]), "--selftest", f"--format={args.format}"]
            )
            if rc != 0:
                problems.append(
                    f"trace-smoke: trace_report exited {rc} on the dump"
                )
            else:
                events = trace_report.load_events(str(dumps[-1]))
                names = {e.get("name") for e in events}
                # supervisor-side markers (spawn, the dump's own ladder
                # instant) AND the child's streamed span must both be in
                # the merged ring — the dump is written mid-recovery, so
                # the still-open dispatch span is legitimately absent
                for expected in ("spawn", "flight-dump", "fake.search"):
                    if expected not in names:
                        problems.append(
                            f"trace-smoke: merged dump is missing "
                            f"{expected!r} — supervisor and host "
                            "timelines did not both land"
                        )

    print()
    for msg in problems:
        if args.format == "github":
            print(f"::error title=chaos trace smoke::{msg}")
        else:
            print(f"FAIL: {msg}")
    if problems:
        return 1
    print("chaos trace smoke: flight dump written, merged, and parsed")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--script", default="flap",
                   help="named script, inline JSON, or @path "
                        "(see --list; default: flap)")
    p.add_argument("--list", action="store_true",
                   help="list named fault scripts and exit")
    p.add_argument("--chunks", type=int, default=4,
                   help="number of chunks to feed (default 4)")
    p.add_argument("--positions", type=int, default=2,
                   help="positions per chunk (default 2)")
    p.add_argument("--chunk-ttl", type=float, default=10.0,
                   help="per-chunk deadline in seconds (default 10)")
    p.add_argument("--pause", type=float, default=0.0,
                   help="seconds to sleep between chunks (default 0)")
    p.add_argument("--hb-interval", type=float, default=0.25)
    p.add_argument("--hb-timeout", type=float, default=2.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    p.add_argument("--probe-interval", type=float, default=5.0)
    p.add_argument("--scenario", nargs="?", const="ladder", default=None,
                   choices=["ladder", "fleet-member-loss"],
                   help="run an acceptance scenario and exit non-zero on "
                        "any delivery violation: `ladder` (default when "
                        "the flag is bare) is the session-recovery "
                        "ladder, `fleet-member-loss` kills one of 3 "
                        "fleet members mid-chunk")
    p.add_argument("--trace-smoke", action="store_true",
                   help="kill a child mid-chunk with tracing on and "
                        "verify the merged flight dump parses")
    p.add_argument("--format", choices=["text", "github"], default="text",
                   help="github emits ::error annotations for CI")
    args = p.parse_args(argv)
    if args.list:
        for name, script in NAMED_SCRIPTS.items():
            print(f"{name:14s} {json.dumps(script)}")
        return 0
    if args.scenario == "ladder":
        return asyncio.run(scenario(args))
    if args.scenario == "fleet-member-loss":
        return asyncio.run(fleet_scenario(args))
    if args.trace_smoke:
        return asyncio.run(trace_smoke(args))
    return asyncio.run(replay(args))


if __name__ == "__main__":
    sys.exit(main())
