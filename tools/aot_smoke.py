"""Acceptance smoke for AOT program assets (fishnet_tpu/aot/).

Proves the warmup-free-boot contract end to end, in real subprocesses
(the whole point is surviving a process boundary — an in-process
round-trip would share jit caches and prove nothing):

1. **pack** — `python -m fishnet_tpu pack --aot-bundle <store>` in a
   fresh process compiles every hot search program and serializes the
   bundle.
2. **reference child** — FISHNET_TPU_AOT=0: plain JIT boot + a 16-lane
   depth-1 search of the initial position; records scores/nodes.
3. **warm child** — FISHNET_TPU_AOT=1 + FISHNET_TPU_AOT_DIR=<store> +
   FISHNET_TPU_TRACE_DIR: the same boot and search against the bundle,
   then dumps its trace timeline.

Gate (any failure exits 1):

* warm child's registry stats: 0 misses, 0 errors, >= 1 disk load;
* warm child's trace: >= 1 ``aot.load`` instant, zero ``aot.miss``
  instants, and zero ``xla_backend_compile`` spans at or above the
  program threshold (0.5 s — eager host-callback compiles are
  milliseconds, a search-program compile is tens of seconds);
* scores and node counts bit-identical between the two children.

Both children and the pack run share one tiny CPU config
(MAX_PLY=8, WARMUP_BUCKETS=16, HELPERS=1) and disable the persistent
XLA cache so neither side can warm-start around the thing under test.

    JAX_PLATFORMS=cpu python tools/aot_smoke.py
    JAX_PLATFORMS=cpu python tools/aot_smoke.py --format=github
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

REPO_ROOT = Path(__file__).resolve().parents[1]

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "FISHNET_TPU_MAX_PLY": "8",
    "FISHNET_TPU_WARMUP_BUCKETS": "16",
    "FISHNET_TPU_HELPERS": "1",
    "FISHNET_TPU_NO_COMPILE_CACHE": "1",
}
PACK_TIMEOUT_S = 540.0
CHILD_TIMEOUT_S = 420.0
# a real search-program compile is tens of seconds even on the CPU
# backend at these knobs; eager host-callback compiles are ~10 ms
BIG_COMPILE_US = 0.5e6
LANES = 16


class SmokeFailure(Exception):
    pass


# --------------------------------------------------------------- child


def run_child(out_path: str, trace_path: str) -> int:
    """--child mode: boot an engine under the env the parent prepared,
    search, and write a JSON report (plus a trace dump when tracing)."""
    import numpy as np

    from fishnet_tpu.obs import trace

    trace.install_from_settings("aot-smoke")  # no-op without TRACE_DIR

    t0 = time.monotonic()
    from fishnet_tpu.aot import registry
    from fishnet_tpu.chess.position import Position
    from fishnet_tpu.engine.tpu import TpuEngine
    from fishnet_tpu.ops.board import from_position, stack_boards

    eng = TpuEngine()
    eng.warmup(None, lambda m: print(f"  [child] {m}", flush=True))
    roots = stack_boards([from_position(Position.initial())] * LANES)
    out = eng._search(
        roots,
        np.ones(LANES, np.int32),
        np.full(LANES, 64, np.int32),
    )
    scores = np.asarray(out["score"]).astype(int).tolist()
    nodes = int(np.asarray(out["nodes"]).sum())

    reg = registry.REGISTRY
    rec = trace.RECORDER
    if trace_path and rec is not None:
        rec.dump(trace_path)
    report = {
        "scores": scores,
        "nodes": nodes,
        "stats": dict(reg.stats) if reg is not None else {},
        "aot": registry.boot_report(),
        "compiles": registry.compile_count(),
        "wall_s": round(time.monotonic() - t0, 2),
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh)
    print(f"  [child] done in {report['wall_s']}s: "
          f"nodes={nodes} aot={report['aot']}", flush=True)
    return 0


# -------------------------------------------------------------- parent


def _run(tag: str, argv: list, env: dict, timeout_s: float) -> None:
    print(f"aot-smoke: {tag}: {' '.join(argv[2:] or argv)}", flush=True)
    proc = subprocess.run(
        argv, cwd=str(REPO_ROOT), env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout_s,
    )
    for line in (proc.stdout or "").splitlines():
        print(f"  [{tag}] {line}")
    if proc.returncode != 0:
        raise SmokeFailure(f"{tag} exited {proc.returncode}")


def _load_json(path: Path, what: str) -> dict:
    try:
        return json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        raise SmokeFailure(f"{what} unreadable: {e}") from None


def _check_trace(trace_path: Path) -> None:
    doc = _load_json(trace_path, "warm child trace")
    events = doc.get("traceEvents", [])
    names = [e.get("name", "") for e in events]
    misses = names.count("aot.miss")
    loads = names.count("aot.load")
    big = [
        e for e in events
        if e.get("name") == "xla_backend_compile"
        and float(e.get("dur", 0.0)) >= BIG_COMPILE_US
    ]
    if misses:
        raise SmokeFailure(f"warm trace has {misses} aot.miss instant(s)")
    if not loads:
        raise SmokeFailure("warm trace has no aot.load instant")
    if big:
        worst = max(float(e.get("dur", 0.0)) for e in big) / 1e6
        raise SmokeFailure(
            f"warm trace has {len(big)} compile span(s) >= "
            f"{BIG_COMPILE_US / 1e6:.1f}s (worst {worst:.1f}s) — "
            "the bundle did not preempt compilation"
        )
    print(f"aot-smoke: trace ok — {loads} load(s), 0 misses, "
          f"0 program-scale compile spans ({len(events)} events)")


def run_smoke(keep: bool) -> None:
    tmp = Path(tempfile.mkdtemp(prefix="aot-smoke-"))
    store = tmp / "store"
    base = {**os.environ, **SMOKE_ENV}
    base.pop("FISHNET_TPU_TRACE_DIR", None)
    me = str(Path(__file__).resolve())
    try:
        # ---- 1. pack a bundle through the real CLI -------------------
        _run(
            "pack",
            [sys.executable, "-m", "fishnet_tpu", "pack",
             "--aot-bundle", str(store), "--no-conf"],
            {**base, "FISHNET_TPU_AOT": "0"},
            PACK_TIMEOUT_S,
        )
        manifests = list(store.glob("*/manifest.json"))
        if len(manifests) != 1:
            raise SmokeFailure(
                f"pack left {len(manifests)} manifest(s) under {store}"
            )
        man = _load_json(manifests[0], "bundle manifest")
        n_prog = len(man.get("programs", {}))
        if not n_prog:
            raise SmokeFailure("pack produced an empty bundle")
        print(f"aot-smoke: packed {n_prog} program(s), "
              f"covers={man.get('covers')}")

        # ---- 2. plain-JIT reference --------------------------------
        ref_json = tmp / "ref.json"
        _run(
            "jit-ref",
            [sys.executable, me, "--child", str(ref_json)],
            {**base, "FISHNET_TPU_AOT": "0"},
            CHILD_TIMEOUT_S,
        )
        ref = _load_json(ref_json, "reference report")
        if ref["nodes"] <= 0:
            raise SmokeFailure("reference search visited no nodes")
        if ref["aot"].get("enabled"):
            raise SmokeFailure("reference child had AOT enabled")

        # ---- 3. warm boot against the bundle ------------------------
        warm_json = tmp / "warm.json"
        warm_trace = tmp / "warm-trace.json"
        _run(
            "warm",
            [sys.executable, me, "--child", str(warm_json),
             "--trace", str(warm_trace)],
            {**base,
             "FISHNET_TPU_AOT": "1",
             "FISHNET_TPU_AOT_DIR": str(store),
             "FISHNET_TPU_TRACE_DIR": str(tmp)},
            CHILD_TIMEOUT_S,
        )
        warm = _load_json(warm_json, "warm report")
        stats = warm.get("stats", {})
        if not warm["aot"].get("enabled"):
            raise SmokeFailure(
                f"warm child never activated the bundle: {warm['aot']}"
            )
        if stats.get("misses", 1) != 0 or stats.get("errors", 1) != 0:
            raise SmokeFailure(f"warm child registry stats: {stats}")
        if stats.get("loads", 0) < 1:
            raise SmokeFailure(f"warm child loaded nothing: {stats}")
        _check_trace(warm_trace)

        # ---- 4. bit-identity ----------------------------------------
        if warm["scores"] != ref["scores"] or warm["nodes"] != ref["nodes"]:
            raise SmokeFailure(
                "warm result diverged from JIT reference: "
                f"scores {warm['scores']} vs {ref['scores']}, "
                f"nodes {warm['nodes']} vs {ref['nodes']}"
            )
        print(f"aot-smoke: bit-identical — scores {ref['scores'][:4]}..., "
              f"nodes {ref['nodes']}; warm boot {warm['wall_s']}s vs "
              f"JIT {ref['wall_s']}s")
    finally:
        if not keep:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
        else:
            print(f"aot-smoke: artifacts kept at {tmp}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--child", metavar="OUT_JSON",
                        help=argparse.SUPPRESS)
    parser.add_argument("--trace", metavar="TRACE_JSON", default="",
                        help=argparse.SUPPRESS)
    parser.add_argument("--keep", action="store_true",
                        help="keep the tempdir (bundle, reports, trace)")
    parser.add_argument("--format", choices=["text", "github"],
                        default="text")
    args = parser.parse_args(argv)

    if args.child:
        return run_child(args.child, args.trace)

    try:
        run_smoke(args.keep)
    except (SmokeFailure, subprocess.TimeoutExpired) as e:
        if args.format == "github":
            print(f"::error title=aot smoke::{e}")
        print(f"aot-smoke: FAIL: {e}")
        return 1
    print("aot-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
