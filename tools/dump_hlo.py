"""Dump the optimized HLO of run_segment and summarize named fusions.

Companion to profile_step.py: the profiler trace names ops `fusion.N` /
`sort.N`; this prints each requested computation's root + operand shapes so
trace lines map back to source-level work.

Usage: python tools/dump_hlo.py [B] [depth] [max_ply] fusion.803 sort.59 ...
       python tools/dump_hlo.py [B] [depth] [max_ply] --full > /tmp/hlo.txt
"""
from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--") and "." not in a]
    names = [a for a in sys.argv[1:] if "." in a]
    B = int(args[0]) if len(args) > 0 else 64
    depth = int(args[1]) if len(args) > 1 else 3
    max_ply = int(args[2]) if len(args) > 2 else depth + 1

    import jax
    import jax.numpy as jnp

    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from bench import _roots_for

    roots = _roots_for(B, "standard", "standard")
    params = nnue.init_params(jax.random.PRNGKey(0), l1=64, feature_set="board768")
    depth_arr = jnp.full((B,), depth, jnp.int32)
    budget_arr = jnp.full((B,), 10_000_000, jnp.int32)
    state = S._init_state_jit(params, roots, depth_arr, budget_arr, max_ply,
                              "standard")
    compiled = S._run_segment_jit.lower(
        params, state, None, 200, "standard", False).compile()
    txt = compiled.as_text()
    if "--full" in sys.argv:
        print(txt)
        return

    # index computations by name
    comps: dict[str, str] = {}
    cur = None
    buf: list[str] = []
    for line in txt.splitlines():
        m = re.match(r"^(%?[\w\.\-]+)\s.*{\s*(//.*)?$", line)
        if line.startswith("ENTRY") or (m and not line.startswith(" ")):
            if cur:
                comps[cur] = "\n".join(buf)
            cur = (m.group(1).lstrip("%") if m else "ENTRY")
            buf = [line]
        else:
            buf.append(line)
    if cur:
        comps[cur] = "\n".join(buf)

    # fusion instruction lines live inside other computations; find them
    fusion_defs: dict[str, str] = {}
    for line in txt.splitlines():
        m = re.search(r"%?([\w\.\-]+)\s*=\s*\S+\s+fusion\(", line)
        if m:
            fusion_defs[m.group(1)] = line.strip()
        m = re.search(r"%?([\w\.\-]+)\s*=\s*\S+\s+sort\(", line)
        if m:
            fusion_defs[m.group(1)] = line.strip()

    for name in names:
        print(f"===== {name} =====")
        d = fusion_defs.get(name)
        if d:
            print(d[:2000])
            # print the called computation too
            m = re.search(r"calls=%?([\w\.\-]+)", d)
            if m and m.group(1) in comps:
                body = comps[m.group(1)]
                lines = body.splitlines()
                print(f"  --- computation {m.group(1)} "
                      f"({len(lines)} lines) ---")
                for ln in lines[:80]:
                    print("  " + ln[:160])
        else:
            print("  (not found as fusion/sort instruction)")
        print()


if __name__ == "__main__":
    main()
