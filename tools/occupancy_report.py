"""Replay a bench-like stage and report per-segment lane occupancy.

Continuous lane refill (ops/search.py search_stream, round 7) keeps the
compiled lockstep step at full width by resplicing DONE lanes with queued
positions at segment boundaries. This tool makes that claim inspectable:
it streams a multipv-style workload (more positions than lanes) through
search_stream and prints a per-segment table of live / helper / idle lane
counts plus the aggregate live-lane fraction — the same counters the
engine's LaneScheduler logs per session (engine/tpu.py occupancy_totals).

Usage:
  python tools/occupancy_report.py --lanes 192 --depth 6 --tt-log2 21
  python tools/occupancy_report.py --smoke            # fast CPU shape
  python tools/occupancy_report.py --format=github    # ::warning below threshold

--format=github emits a workflow warning annotation when the mean live
fraction falls below --threshold (default 0.5): sustained low occupancy
means the refill queue drained long before the stragglers finished, i.e.
the stage is paying full-width step cost for mostly-idle lanes.

Round 8 (segment pipeline): every row also shows the boundary's host
transfer count and host/device wall-clock split (utils/syncstats.py via
search_stream), and the summary line reports the aggregate boundary
share host_ms/(host_ms+device_ms). --host-share-threshold warns (a
::warning annotation under --format=github) when that share exceeds the
bound — the pipeline exists precisely to keep it small. --pipeline-ab
runs the stage twice (FISHNET_TPU_PIPELINE off, then on) and FAILS on
any per-position result divergence: the pipelined loop must be
bit-identical to the round-7 synchronous loop.

Round 10 (mesh parity): --mesh-ab runs the stage single-device and then
sharded over every local device (search_stream(mesh=make_mesh())) and
FAILS on any per-position result divergence — shard-local refill and the
stacked boundary summary must be bit-identical to the flat stream. The
TT is disabled for both passes when set (a sharded table hashes into
per-device shards, which legitimately changes move ordering). Sharded
rows grow a per-shard live-lane column and the JSON summary a per-shard
mean live fraction list.

Round 9 (session recovery): --stats-db PATH reads the client's sqlite
stats store and prepends the latest SupervisorStats snapshot (replay /
bisection / quarantine counters, exported by the client's summary loop)
plus the persisted quarantine list — one line per poison fingerprint.
--stats-only prints that report and exits without importing JAX or
running the occupancy stage, so it works on a machine with no
accelerator at all.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _recovery_report(db_path: str, emit_json: bool) -> int:
    """Print the latest persisted SupervisorStats + quarantine list."""
    import sqlite3

    if not os.path.exists(db_path):
        print(f"recovery: no stats db at {db_path}")
        return 1
    con = sqlite3.connect(db_path)
    try:
        try:
            row = con.execute(
                "SELECT timestamp, counters FROM supervisor_stats "
                "ORDER BY id DESC LIMIT 1"
            ).fetchone()
            quarantine = con.execute(
                "SELECT timestamp, fingerprint, batch_id, position_index "
                "FROM supervisor_quarantine ORDER BY id"
            ).fetchall()
        except sqlite3.Error as e:
            print(f"recovery: stats db has no supervisor tables ({e})")
            return 1
    finally:
        con.close()

    if row is None:
        print("recovery: no SupervisorStats snapshot recorded yet")
        counters = {}
    else:
        counters = json.loads(row[1])
        print(f"recovery: SupervisorStats at {row[0]}")
        for key in sorted(counters):
            print(f"  {key:>20} {counters[key]}")
    print(f"quarantine: {len(quarantine)} poison position(s)")
    for ts, fp, batch, idx in quarantine:
        print(f"  {fp}  batch={batch} index={idx}  at {ts}")
    if emit_json:
        print("RECOVERY " + json.dumps({
            "counters": counters,
            "quarantine": [
                {"fingerprint": fp, "batch_id": batch, "position_index": idx}
                for _, fp, batch, idx in quarantine
            ],
        }))
    return 0


def _boards(lanes: int, variant: str, cap: int | None = None):
    """Every root-move board of the standard 8-FEN set (the production
    multipv workload, 229 boards), tiled up if --lanes exceeds it —
    the report needs MORE positions than lanes to exercise refill.
    `cap` (the --smoke path) truncates the queue so CI pays for a
    handful of refills, not the full production drain."""
    from bench import FENS_STANDARD
    from fishnet_tpu.chess import Position
    from fishnet_tpu.ops.board import from_position, stack_boards

    boards = []
    for fen in FENS_STANDARD:
        p = Position.from_fen(fen)
        for m in p.legal_moves():
            boards.append(from_position(p.push(m)))
    floor = lanes + max(lanes // 4, 2)
    while len(boards) < floor:
        boards.append(boards[len(boards) % 229])
    if cap is not None:
        boards = boards[: max(cap, floor)]
    return stack_boards(boards), len(boards)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=192)
    ap.add_argument("--depth", type=int, default=6)
    ap.add_argument("--budget", type=int, default=5_000_000)
    ap.add_argument("--segment", type=int, default=None,
                    help="segment steps (default: FISHNET_TPU_SEGMENT)")
    ap.add_argument("--max-ply", type=int, default=32)
    ap.add_argument("--tt-log2", type=int, default=21)
    ap.add_argument("--net", choices=("random", "default"), default="default")
    ap.add_argument("--threshold", type=float, default=0.5,
                    help="annotate when mean live fraction is below this")
    ap.add_argument("--host-share-threshold", type=float, default=0.25,
                    help="annotate when the boundary host share "
                         "host_ms/(host_ms+device_ms) exceeds this")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="run the stage with the segment pipeline off "
                         "then on; FAIL on any result divergence")
    ap.add_argument("--mesh-ab", action="store_true",
                    help="run the stage single-device then sharded over "
                         "all local devices (TT disabled for both); FAIL "
                         "on any result divergence")
    ap.add_argument("--format", choices=("text", "github"), default="text")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable summary line")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU shape for CI (8 lanes, depth 2, toy net)")
    ap.add_argument("--stats-db", default=None, metavar="PATH",
                    help="prepend the latest SupervisorStats snapshot and "
                         "quarantine list from this client stats sqlite db")
    ap.add_argument("--stats-only", action="store_true",
                    help="with --stats-db: print the recovery report and "
                         "exit without running the occupancy stage")
    args = ap.parse_args()

    if args.stats_db is not None:
        rc = _recovery_report(args.stats_db, args.json)
        if args.stats_only:
            return rc

    if args.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        args.lanes, args.depth, args.max_ply = 8, 2, 6
        args.budget, args.tt_log2, args.net = 50_000, 0, "random"
        # segments must be shorter than a single toy search or every
        # position finishes inside segment 1 and the live fraction reads
        # as pure idle — 48 steps gives the smoke a real refill cadence.
        # The straggler drain tail dominates a 10-position queue, so the
        # production threshold would warn on every smoke run; the smoke
        # gate is completion + accounting, not toy-shape occupancy
        args.segment = args.segment or 48
        args.threshold = min(args.threshold, 0.3)

    import jax
    import numpy as np

    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()
    if args.net == "default":
        from fishnet_tpu.assets import load_default_params

        params = load_default_params("board768")
        if params is None:
            raise RuntimeError("packaged net missing; use --net=random")
    else:
        params = nnue.init_params(
            jax.random.PRNGKey(0), l1=64, feature_set="board768")

    roots, n = _boards(args.lanes, "standard",
                       cap=(args.lanes + max(args.lanes // 4, 2)
                            if args.smoke else None))
    depth = np.full(n, args.depth, np.int32)
    budget = np.full(n, args.budget, np.int32)

    mesh = None
    if args.mesh_ab:
        from fishnet_tpu.parallel.mesh import make_mesh

        ndev = jax.device_count()
        if args.lanes % ndev:
            print(f"ERROR: --mesh-ab needs --lanes divisible by the "
                  f"{ndev} local devices")
            return 1
        mesh = make_mesh()
        if args.tt_log2:
            # a sharded table hashes into per-device shards; that
            # legitimately reorders moves, so the A/B drops the TT
            print("mesh A/B: TT disabled for both passes "
                  "(sharded vs flat tables hash differently)")

    def run(pipeline=None, on_mesh=None):
        # the table (and the running state) are DONATED into the segment
        # jits, so every pass gets its own fresh table
        tt = None
        if args.tt_log2 and not args.mesh_ab:
            from fishnet_tpu.ops import tt as tt_mod

            tt = tt_mod.make_table(args.tt_log2)
        t0 = time.perf_counter()
        out = S.search_stream(
            params, roots, depth, budget, max_ply=args.max_ply,
            width=args.lanes, segment_steps=args.segment, tt=tt,
            mesh=on_mesh, pipeline=pipeline,
        )
        jax.block_until_ready(out["nodes"])
        return out, time.perf_counter() - t0

    legacy = None
    if args.pipeline_ab:
        legacy = run(pipeline=False, on_mesh=mesh)
        out, wall = run(pipeline=True, on_mesh=mesh)
    else:
        out, wall = run(on_mesh=mesh)
    flat_base = run(pipeline=False) if args.mesh_ab else None

    # ops-level rows: {segment, steps, live, refilled, idle, queue} plus
    # the round-8 syncstats columns {transfers, host_ms, device_ms}
    # (the engine's LaneScheduler adds helper counts on top of these)
    occ = out["occupancy"]
    lane_steps = sum(o["steps"] * args.lanes for o in occ) or 1
    live_steps = sum(o["steps"] * (o["live"] + o["refilled"]) for o in occ)
    mean_live = live_steps / lane_steps
    host_ms = sum(o["host_ms"] for o in occ)
    device_ms = sum(o["device_ms"] for o in occ)
    boundary_share = host_ms / max(host_ms + device_ms, 1e-9)
    transfers = sum(o["transfers"] for o in occ)
    done = int(np.asarray(out["done"]).sum())

    has_shard = bool(occ) and "shard_live" in occ[0]
    shard_hdr = f" {'shard live':>18}" if has_shard else ""
    print(f"{'seg':>4} {'steps':>6} {'live':>5} {'idle':>5} "
          f"{'refill':>6} {'queue':>5} {'xfers':>5} {'host_ms':>8} "
          f"{'dev_ms':>8} {'share':>6}{shard_hdr}")
    for o in occ:
        tot = o["host_ms"] + o["device_ms"]
        share = o["host_ms"] / tot if tot > 0 else 0.0
        shard_col = ""
        if has_shard:
            shard_col = " " + ",".join(str(x) for x in o["shard_live"])
        print(f"{o['segment']:>4} {o['steps']:>6} {o['live']:>5} "
              f"{o['idle']:>5} {o['refilled']:>6} {o['queue']:>5} "
              f"{o['transfers']:>5} {o['host_ms']:>8.2f} "
              f"{o['device_ms']:>8.2f} {share:>6.3f}{shard_col}")
    print(f"positions {done}/{n} done, width {args.lanes}, "
          f"{len(occ)} segments, {out['refills']} refills, "
          f"mean live fraction {mean_live:.3f}, "
          f"boundary share {boundary_share:.3f} "
          f"({transfers} transfers), wall {wall:.2f}s")
    if args.json:
        summary = {
            "lanes": args.lanes, "positions": n, "done": done,
            "segments": len(occ), "refills": out["refills"],
            "mean_live_frac": round(mean_live, 4),
            "host_ms": round(host_ms, 1),
            "device_ms": round(device_ms, 1),
            "boundary_share": round(boundary_share, 4),
            "transfers": transfers,
            "wall_s": round(wall, 3),
        }
        if has_shard:
            ndev = len(occ[0]["shard_live"])
            local = args.lanes // ndev
            denom = sum(o["steps"] * local for o in occ) or 1
            summary["ndev"] = ndev
            summary["shard_mean_live"] = [
                round(sum(o["steps"] * o["shard_live"][s] for o in occ)
                      / denom, 4)
                for s in range(ndev)
            ]
        print("OCCUPANCY " + json.dumps(summary))

    if legacy is not None:
        lout, lwall = legacy
        diverged = []
        for key in ("score", "move", "nodes", "pv_len", "pv", "done"):
            if not np.array_equal(np.asarray(lout[key]),
                                  np.asarray(out[key])):
                diverged.append(key)
        lx = sum(o["transfers"] for o in lout["occupancy"])
        print(f"pipeline A/B: legacy {lwall:.2f}s / pipelined {wall:.2f}s "
              f"({lwall / max(wall, 1e-9):.2f}x), transfers {lx} -> "
              f"{transfers}")
        if diverged:
            msg = (f"pipelined results diverge from the synchronous loop "
                   f"on: {', '.join(diverged)} — the segment pipeline "
                   "must be bit-identical")
            if args.format == "github":
                print(f"::error title=pipeline-ab divergence::{msg}")
            else:
                print(f"ERROR: {msg}")
            return 1

    if flat_base is not None:
        fout, fwall = flat_base
        diverged = []
        for key in ("score", "move", "nodes", "pv_len", "pv", "done"):
            if not np.array_equal(np.asarray(fout[key]),
                                  np.asarray(out[key])):
                diverged.append(key)
        print(f"mesh A/B: single-device {fwall:.2f}s / sharded "
              f"{wall:.2f}s over {mesh.devices.size} devices")
        if diverged:
            msg = (f"sharded results diverge from the single-device "
                   f"stream on: {', '.join(diverged)} — shard-local "
                   "refill must be bit-identical")
            if args.format == "github":
                print(f"::error title=mesh-ab divergence::{msg}")
            else:
                print(f"ERROR: {msg}")
            return 1

    if done < n:
        msg = (f"only {done}/{n} positions finished — raise --budget or "
               f"lower --depth")
        if args.format == "github":
            print(f"::error title=occupancy-report incomplete::{msg}")
        else:
            print(f"ERROR: {msg}")
        return 1
    if mean_live < args.threshold:
        msg = (f"mean live lane fraction {mean_live:.3f} below threshold "
               f"{args.threshold} — the refill queue drained long before "
               f"the stragglers finished")
        if args.format == "github":
            print(f"::warning title=occupancy-report::{msg}")
        else:
            print(f"WARNING: {msg}")
    if boundary_share > args.host_share_threshold:
        msg = (f"boundary host share {boundary_share:.3f} exceeds "
               f"{args.host_share_threshold} — the host is stalling the "
               "device at segment boundaries; shrink the boundary work "
               "or raise FISHNET_TPU_SEGMENT (=auto retunes it)")
        if args.format == "github":
            print(f"::warning title=occupancy-report host-share::{msg}")
        else:
            print(f"WARNING: {msg}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
