"""NNUE training: supervised regression on (position, score) pairs.

The reference consumes externally-trained Stockfish nets; this framework
can train its own. The step shards over a 2-D ("dp", "tp") mesh: batch over
dp, the feature-transform width (L1) over tp — the gather-heavy FT is the
bulk of the FLOPs, and splitting its output dim keeps each chip's HBM
traffic local until the (tiny) layer stack, where an all_gather over tp
assembles the accumulator. Gradients psum over dp. XLA inserts both
collectives from the shardings; nothing is hand-written.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from ..ops.board import piece_color, piece_type  # noqa: F401 (re-export context)
from ..parallel import partition as _partition
from . import nnue


def batched_forward(params: nnue.NnueParams, boards: jnp.ndarray,
                    stms: jnp.ndarray) -> jnp.ndarray:
    """(B, 64) boards, (B,) stms → (B,) centipawn scores.

    Plain XLA: the eval stack is a few small matmuls + clipped ReLUs that
    XLA fuses on its own. A hand-written Pallas fusion of this stack
    lived here for rounds 2-3 but never reached hardware (the TPU tunnel
    was down whenever it was ready) and only ever ran interpreted in
    training — retired per the round-3 verdict ("measure on hardware or
    delete"); see git history (ops/pallas_nnue.py) to resurrect it if a
    measured win ever justifies it."""
    return jax.vmap(nnue.evaluate, in_axes=(None, 0, 0))(params, boards, stms)


def loss_fn(params, boards, stms, targets):
    pred = batched_forward(params, boards, stms)
    # scale to pawns so the loss is O(1)
    return jnp.mean(((pred - targets) / 100.0) ** 2)


def make_train_step(optimizer):
    @jax.jit
    def train_step(params, opt_state, boards, stms, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, boards, stms, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def param_shardings(mesh: Mesh) -> nnue.NnueParams:
    """TP over the feature-transform width; the small stack is
    replicated. Derived from the partition-rule registry
    (parallel/partition.py PARAM_RULES_TP) — the training layout and the
    search engine's replicated layout live in ONE table."""
    return jax.tree_util.tree_map(
        lambda spec: _partition.named_sharding(mesh, spec),
        _partition.param_specs(tp=True),
    )


def make_sharded_train_step(mesh: Mesh, optimizer):
    """Training step with dp×tp shardings; collectives inserted by XLA."""
    p_shard = param_shardings(mesh)
    batch_shard = _partition.named_sharding(
        mesh, _partition.batch_spec(1))
    board_shard = _partition.named_sharding(
        mesh, _partition.batch_spec(2))

    @partial(
        jax.jit,
        in_shardings=(p_shard, None, board_shard, batch_shard, batch_shard),
        out_shardings=(p_shard, None, None),
    )
    def train_step(params, opt_state, boards, stms, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, boards, stms, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


# --------------------------------------------------- training data synthesis


def material_mobility_target(pos) -> float:
    """Cheap supervised target: material + mobility in centipawns, from the
    side to move's perspective (mirrors engine/pyengine.py's evaluation)."""
    from ..chess.types import BISHOP, KNIGHT, PAWN, QUEEN, ROOK

    vals = {PAWN: 100, KNIGHT: 300, BISHOP: 315, ROOK: 500, QUEEN: 900}
    us = pos.turn
    score = 0
    for ptype, val in vals.items():
        score += val * (
            bin(pos.bbs[us][ptype]).count("1")
            - bin(pos.bbs[us ^ 1][ptype]).count("1")
        )
    score += 2 * len(pos.legal_moves())
    return float(score)


def random_position_dataset(n: int, seed: int = 0, max_plies: int = 60):
    """Generate positions by random playouts with material targets."""
    import random as _random

    from ..chess import Position
    from ..ops.board import board_array

    rng = _random.Random(seed)
    boards = np.zeros((n, 64), np.int32)
    stms = np.zeros((n,), np.int32)
    targets = np.zeros((n,), np.float32)
    pos = Position.initial()
    plies = 0
    for i in range(n):
        legal = pos.legal_moves()
        if not legal or plies > max_plies or pos.outcome() is not None:
            pos = Position.initial()
            plies = 0
            legal = pos.legal_moves()
        pos = pos.push(rng.choice(legal))
        plies += 1
        boards[i] = board_array(pos)  # numpy: no per-position device put
        stms[i] = int(pos.turn)
        targets[i] = material_mobility_target(pos)
    return boards, stms, targets


def train_material_net(
    l1: int = 64,
    steps: int = 200,
    batch: int = 256,
    seed: int = 0,
    dataset: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    lr: float = 1e-3,
    feature_set: str = "board768",
):
    """Train a small net against the material+mobility oracle. Returns
    (params, final_loss). Gives the TPU engine sane (if modest) play
    without external weights."""
    params = nnue.init_params(jax.random.PRNGKey(seed), l1=l1, feature_set=feature_set)
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step = make_train_step(optimizer)
    if dataset is None:
        dataset = random_position_dataset(batch * 8, seed=seed)
    boards, stms, targets = dataset
    n = boards.shape[0]
    rng = np.random.default_rng(seed)
    loss = None
    for i in range(steps):
        idx = rng.integers(0, n, size=batch)
        params, opt_state, loss = step(
            params, opt_state,
            jnp.asarray(boards[idx]), jnp.asarray(stms[idx]),
            jnp.asarray(targets[idx]),
        )
    return params, float(loss)


# ------------------------------------------ classical target + diverse data
#
# The packaged board768 net is distilled from a classical handcrafted
# evaluation (material + piece-square + mobility), the same bootstrap real
# NNUE lineages used before self-play data existed. The r1 net trained on
# random-playout positions only — near-balanced material throughout — so it
# extrapolated garbage on imbalanced/sparse positions (a bare
# queen-vs-king board eval'd ~0). The dataset below mixes playouts with
# synthetic random-material positions precisely to pin the material axis.

_PST_PAWN = np.array([
    0, 0, 0, 0, 0, 0, 0, 0,
    5, 10, 10, -20, -20, 10, 10, 5,
    5, -5, -10, 0, 0, -10, -5, 5,
    0, 0, 0, 20, 20, 0, 0, 0,
    5, 5, 10, 25, 25, 10, 5, 5,
    10, 10, 20, 30, 30, 20, 10, 10,
    50, 50, 50, 50, 50, 50, 50, 50,
    0, 0, 0, 0, 0, 0, 0, 0,
], np.int32)
_PST_KNIGHT = np.array([
    -50, -40, -30, -30, -30, -30, -40, -50,
    -40, -20, 0, 5, 5, 0, -20, -40,
    -30, 5, 10, 15, 15, 10, 5, -30,
    -30, 0, 15, 20, 20, 15, 0, -30,
    -30, 5, 15, 20, 20, 15, 5, -30,
    -30, 0, 10, 15, 15, 10, 0, -30,
    -40, -20, 0, 0, 0, 0, -20, -40,
    -50, -40, -30, -30, -30, -30, -40, -50,
], np.int32)
_PST_BISHOP = np.array([
    -20, -10, -10, -10, -10, -10, -10, -20,
    -10, 5, 0, 0, 0, 0, 5, -10,
    -10, 10, 10, 10, 10, 10, 10, -10,
    -10, 0, 10, 10, 10, 10, 0, -10,
    -10, 5, 5, 10, 10, 5, 5, -10,
    -10, 0, 5, 10, 10, 5, 0, -10,
    -10, 0, 0, 0, 0, 0, 0, -10,
    -20, -10, -10, -10, -10, -10, -10, -20,
], np.int32)
_PST_ROOK = np.array([
    0, 0, 0, 5, 5, 0, 0, 0,
    -5, 0, 0, 0, 0, 0, 0, -5,
    -5, 0, 0, 0, 0, 0, 0, -5,
    -5, 0, 0, 0, 0, 0, 0, -5,
    -5, 0, 0, 0, 0, 0, 0, -5,
    -5, 0, 0, 0, 0, 0, 0, -5,
    5, 10, 10, 10, 10, 10, 10, 5,
    0, 0, 0, 0, 0, 0, 0, 0,
], np.int32)
_PST_QUEEN = np.array([
    -20, -10, -10, -5, -5, -10, -10, -20,
    -10, 0, 5, 0, 0, 0, 0, -10,
    -10, 5, 5, 5, 5, 5, 0, -10,
    0, 0, 5, 5, 5, 5, 0, -5,
    -5, 0, 5, 5, 5, 5, 0, -5,
    -10, 0, 5, 5, 5, 5, 0, -10,
    -10, 0, 0, 0, 0, 0, 0, -10,
    -20, -10, -10, -5, -5, -10, -10, -20,
], np.int32)
_PST_KING = np.array([
    20, 30, 10, 0, 0, 10, 30, 20,
    20, 20, 0, 0, 0, 0, 20, 20,
    -10, -20, -20, -20, -20, -20, -20, -10,
    -20, -30, -30, -40, -40, -30, -30, -20,
    -30, -40, -40, -50, -50, -40, -40, -30,
    -30, -40, -40, -50, -50, -40, -40, -30,
    -30, -40, -40, -50, -50, -40, -40, -30,
    -30, -40, -40, -50, -50, -40, -40, -30,
], np.int32)
_PSTS = [_PST_PAWN, _PST_KNIGHT, _PST_BISHOP, _PST_ROOK, _PST_QUEEN, _PST_KING]
_PIECE_VALUES = [100, 300, 315, 500, 900, 0]


def classical_eval_target(pos) -> float:
    """Material + piece-square + mobility in cp from the side to move."""
    from ..chess.types import scan

    score = 0
    for color in (0, 1):
        sign = 1 if color == pos.turn else -1
        for ptype in range(6):
            for sq in scan(pos.bbs[color][ptype]):
                o_sq = sq if color == 0 else sq ^ 56
                score += sign * (_PIECE_VALUES[ptype] + int(_PSTS[ptype][o_sq]))
    score += 2 * len(pos.legal_moves())
    return float(np.clip(score, -3000, 3000))


def _random_material_position(rng) -> Optional[object]:
    """A synthetic legal-ish position with random (often lopsided)
    material — the axis random playouts never cover."""
    from ..chess import Position

    board = [""] * 64
    squares = list(range(64))
    rng.shuffle(squares)
    it = iter(squares)
    wk, bk = next(it), next(it)
    while max(abs((wk & 7) - (bk & 7)), abs((wk >> 3) - (bk >> 3))) <= 1:
        bk = next(it)
    board[wk], board[bk] = "K", "k"
    for color, syms in ((0, "PNBRQ"), (1, "pnbrq")):
        counts = [
            rng.randint(0, 8), rng.randint(0, 2), rng.randint(0, 2),
            rng.randint(0, 2), rng.randint(0, 1),
        ]
        for ptype, cnt in enumerate(counts):
            for _ in range(cnt):
                sq = next(it, None)
                if sq is None:
                    break
                if syms[ptype] in "Pp" and (sq < 8 or sq >= 56):
                    continue
                board[sq] = syms[ptype]
    rows = []
    for rank in range(7, -1, -1):
        row, empty = "", 0
        for f in range(8):
            c = board[rank * 8 + f]
            if c:
                row += (str(empty) if empty else "") + c
                empty = 0
            else:
                empty += 1
        rows.append(row + (str(empty) if empty else ""))
    fen = "/".join(rows) + (" w - - 0 1" if rng.random() < 0.5 else " b - - 0 1")
    try:
        return Position.from_fen(fen)
    except Exception:
        return None


def diverse_position_dataset(n: int, seed: int = 0):
    """50% random-playout positions (structure), 50% synthetic
    random-material positions (material axis); classical targets."""
    import random as _random

    from ..chess import Position
    from ..ops.board import board_array

    rng = _random.Random(seed)
    boards = np.zeros((n, 64), np.int32)
    stms = np.zeros((n,), np.int32)
    targets = np.zeros((n,), np.float32)
    pos = Position.initial()
    plies = 0
    i = 0
    while i < n:
        if i % 2 == 0:
            legal = pos.legal_moves()
            if not legal or plies > 80 or pos.outcome() is not None:
                pos = Position.initial()
                plies = 0
                legal = pos.legal_moves()
            pos = pos.push(rng.choice(legal))
            plies += 1
            sample = pos
        else:
            sample = _random_material_position(rng)
            if sample is None or sample.outcome() is not None:
                continue
        # numpy end to end: per-position jnp conversion costs a device
        # put (through the remote tunnel, ~ms each) — at 200k positions
        # the round-5 run spent 30+ min "generating" before the fix
        boards[i] = board_array(sample)
        stms[i] = int(sample.turn)
        targets[i] = classical_eval_target(sample)
        i += 1
    return boards, stms, targets
