"""NNUE training: supervised regression on (position, score) pairs.

The reference consumes externally-trained Stockfish nets; this framework
can train its own. The step shards over a 2-D ("dp", "tp") mesh: batch over
dp, the feature-transform width (L1) over tp — the gather-heavy FT is the
bulk of the FLOPs, and splitting its output dim keeps each chip's HBM
traffic local until the (tiny) layer stack, where an all_gather over tp
assembles the accumulator. Gradients psum over dp. XLA inserts both
collectives from the shardings; nothing is hand-written.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.board import piece_color, piece_type  # noqa: F401 (re-export context)
from . import nnue


def batched_forward(params: nnue.NnueParams, boards: jnp.ndarray,
                    stms: jnp.ndarray) -> jnp.ndarray:
    """(B, 64) boards, (B,) stms → (B,) centipawn scores.

    FISHNET_TPU_PALLAS=1 routes board768 nets through the fused Pallas
    kernel (ops/pallas_nnue.py); default is the XLA path."""
    from ..ops import pallas_nnue

    if pallas_nnue.is_enabled() and nnue.is_board768(params):
        return pallas_nnue.evaluate_batch_trainable(params, boards, stms)
    return jax.vmap(nnue.evaluate, in_axes=(None, 0, 0))(params, boards, stms)


def loss_fn(params, boards, stms, targets):
    pred = batched_forward(params, boards, stms)
    # scale to pawns so the loss is O(1)
    return jnp.mean(((pred - targets) / 100.0) ** 2)


def make_train_step(optimizer):
    @jax.jit
    def train_step(params, opt_state, boards, stms, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, boards, stms, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def param_shardings(mesh: Mesh) -> nnue.NnueParams:
    """TP over the feature-transform width; the small stack is replicated."""
    return nnue.NnueParams(
        ft_w=NamedSharding(mesh, P(None, "tp")),
        ft_b=NamedSharding(mesh, P("tp")),
        l1_w=NamedSharding(mesh, P()),
        l1_b=NamedSharding(mesh, P()),
        l2_w=NamedSharding(mesh, P()),
        l2_b=NamedSharding(mesh, P()),
        out_w=NamedSharding(mesh, P()),
        out_b=NamedSharding(mesh, P()),
    )


def make_sharded_train_step(mesh: Mesh, optimizer):
    """Training step with dp×tp shardings; collectives inserted by XLA."""
    p_shard = param_shardings(mesh)
    batch_shard = NamedSharding(mesh, P("dp"))
    board_shard = NamedSharding(mesh, P("dp", None))

    @partial(
        jax.jit,
        in_shardings=(p_shard, None, board_shard, batch_shard, batch_shard),
        out_shardings=(p_shard, None, None),
    )
    def train_step(params, opt_state, boards, stms, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, boards, stms, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


# --------------------------------------------------- training data synthesis


def material_mobility_target(pos) -> float:
    """Cheap supervised target: material + mobility in centipawns, from the
    side to move's perspective (mirrors engine/pyengine.py's evaluation)."""
    from ..chess.types import BISHOP, KNIGHT, PAWN, QUEEN, ROOK

    vals = {PAWN: 100, KNIGHT: 300, BISHOP: 315, ROOK: 500, QUEEN: 900}
    us = pos.turn
    score = 0
    for ptype, val in vals.items():
        score += val * (
            bin(pos.bbs[us][ptype]).count("1")
            - bin(pos.bbs[us ^ 1][ptype]).count("1")
        )
    score += 2 * len(pos.legal_moves())
    return float(score)


def random_position_dataset(n: int, seed: int = 0, max_plies: int = 60):
    """Generate positions by random playouts with material targets."""
    import random as _random

    from ..chess import Position
    from ..ops.board import from_position

    rng = _random.Random(seed)
    boards = np.zeros((n, 64), np.int32)
    stms = np.zeros((n,), np.int32)
    targets = np.zeros((n,), np.float32)
    pos = Position.initial()
    plies = 0
    for i in range(n):
        legal = pos.legal_moves()
        if not legal or plies > max_plies or pos.outcome() is not None:
            pos = Position.initial()
            plies = 0
            legal = pos.legal_moves()
        pos = pos.push(rng.choice(legal))
        plies += 1
        b = from_position(pos)
        boards[i] = np.asarray(b.board)
        stms[i] = int(b.stm)
        targets[i] = material_mobility_target(pos)
    return boards, stms, targets


def train_material_net(
    l1: int = 64,
    steps: int = 200,
    batch: int = 256,
    seed: int = 0,
    dataset: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    lr: float = 1e-3,
    feature_set: str = "board768",
):
    """Train a small net against the material+mobility oracle. Returns
    (params, final_loss). Gives the TPU engine sane (if modest) play
    without external weights."""
    params = nnue.init_params(jax.random.PRNGKey(seed), l1=l1, feature_set=feature_set)
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)
    step = make_train_step(optimizer)
    if dataset is None:
        dataset = random_position_dataset(batch * 8, seed=seed)
    boards, stms, targets = dataset
    n = boards.shape[0]
    rng = np.random.default_rng(seed)
    loss = None
    for i in range(steps):
        idx = rng.integers(0, n, size=batch)
        params, opt_state, loss = step(
            params, opt_state,
            jnp.asarray(boards[idx]), jnp.asarray(stms[idx]),
            jnp.asarray(targets[idx]),
        )
    return params, float(loss)
