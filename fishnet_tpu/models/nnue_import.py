"""Importer for Stockfish `.nnue` network files (HalfKAv2_hm).

The reference embeds two Stockfish nets as opaque binaries and lets the
C++ engine evaluate them (reference: build.rs:8-9 embeds
nn-1c0000000000.nnue + nn-37f18f62d772.nnue; src/assets.rs:15 ships them
inside the asset archive). Here the file format itself is parsed on the
host and the network becomes device-resident arrays evaluated by XLA —
the "ship weights, not binaries" design (SURVEY.md §7.2).

Supported layout — the SFNNv5-era HalfKAv2_hm serialization as written by
the public nnue-pytorch trainer and read by Stockfish 15/16:

    uint32 version | uint32 net_hash | uint32 len | len×u8 description
    FeatureTransformer:
        uint32 ft_hash
        int16 biases[L1]
        int16 weights[22528 × L1]          (row-major, feature-major)
        int32 psqt_weights[22528 × 8]      (8 PSQT output buckets)
    Network (8 layer stacks, stored bucket-by-bucket):
        uint32 hash
        per bucket b in 0..8:
            fc_0: int32 biases[16],  int8 weights[16 × L1]
            fc_1: int32 biases[32],  int8 weights[32 × 30]
            fc_2: int32 biases[1],   int8 weights[1 × 32]

    * FT activation is pairwise "squared clipped ReLU": each perspective's
      L1 accumulator is split in halves, clamp(x,0,QA) of the two halves
      multiplied elementwise → L1/2 values per perspective, concatenated
      (side to move first) → L1 inputs to fc_0.
    * fc_0 has 16 rows; row 15 is the *skip connection* added directly to
      the output (nnue-pytorch docs), rows 0..15 feed a clipped ReLU.
      fc_1 consumes 30 inputs: 15 clipped + 15 squared-clipped values.
    * Any int16/int8/int32 array section may instead be stored LEB128-
      compressed: magic b"COMPRESSED_LEB128" + uint32 byte_count + stream.
    * Quantization scales: FT 127 (QA), hidden weights 64 (QB),
      output scale 16; dequantized here to float32.

SCOPE — eval-parity tooling, not the search path. Imported HalfKAv2_hm
nets evaluate positions (engine compat path, eval A/Bs, label
generation) but pay a full accumulator refresh per search step, because
"incremental" HalfKAv2_hm cannot win inside a lockstep vmapped step: a
king move forces a full per-perspective refresh, a vmapped `cond`
compiles to a select that EXECUTES both branches, so every step would
pay the masked 64-gather refresh anyway — exactly what the full-refresh
path already costs. board768 (no king buckets, every move a ≤4-feature
delta) is the search feature set by design; see README "Evaluation".

Anything that doesn't match this layout (different sizes, unknown
section lengths) raises UnsupportedNnueFormat rather than misparsing.
There are no real `.nnue` files in this build environment, so the parser
is validated by synthetic round-trip against its own writer
(tests/test_nnue_import.py); the layout constants above are the public
ones and size checks are strict enough to fail loudly on mismatch.
"""
from __future__ import annotations

import dataclasses
import struct
from functools import partial
from pathlib import Path

import jax
import numpy as np

from . import nnue

LEB_MAGIC = b"COMPRESSED_LEB128"
NUM_FEATURES = nnue.NUM_FEATURES  # 22528 (32 buckets × 11 kinds × 64 sq)
NUM_PSQT_BUCKETS = 8
NUM_STACKS = 8
FC0_OUT = 16  # 15 hidden + 1 skip row
FC1_IN = 30  # 15 clipped + 15 squared-clipped
FC1_OUT = 32

QA = 127.0  # feature-transformer scale (activations 0..127 ≡ 0..1)
QB = 64.0  # hidden-layer weight scale
OUTPUT_SCALE = 16.0  # FV_SCALE: quantized net output / 16 = centipawns
NNUE2SCORE = 600.0  # float-model output ±1 ≡ ±600 cp (nnue-pytorch)
# quantized storage scales (nnue-pytorch serializer):
#   ft w,b              × QA
#   fc0/fc1 w           × QB          fc0/fc1 b × QA·QB
#   fc2 w               × NNUE2SCORE·OUTPUT_SCALE/QA
#   fc2 b, psqt w       × NNUE2SCORE·OUTPUT_SCALE


class UnsupportedNnueFormat(ValueError):
    pass


_ARRAY_FIELDS = (
    "ft_w", "ft_b", "psqt_w",
    "fc0_w", "fc0_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b",
)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=list(_ARRAY_FIELDS),
    meta_fields=["version", "net_hash", "description"],
)
@dataclasses.dataclass(frozen=True)
class StockfishNet:
    """Dequantized HalfKAv2_hm net; array fields are float32.

    A pytree whose metadata is static, so a net passes straight through
    jit (e.g. as the `params` of ops.search.search_batch_jit)."""

    ft_w: np.ndarray  # (NUM_FEATURES, L1)
    ft_b: np.ndarray  # (L1,)
    psqt_w: np.ndarray  # (NUM_FEATURES, 8) pawn-value units
    fc0_w: np.ndarray  # (8, 16, L1)
    fc0_b: np.ndarray  # (8, 16)
    fc1_w: np.ndarray  # (8, 32, 30)
    fc1_b: np.ndarray  # (8, 32)
    fc2_w: np.ndarray  # (8, 1, 32)
    fc2_b: np.ndarray  # (8, 1)
    version: int = 0
    net_hash: int = 0
    description: bytes = b""

    @property
    def l1(self) -> int:
        return self.ft_w.shape[1]

    def as_device(self) -> "StockfishNet":
        import jax.numpy as jnp

        return dataclasses.replace(
            self, **{f: jnp.asarray(getattr(self, f)) for f in _ARRAY_FIELDS}
        )


# ------------------------------------------------------------------ LEB128


def _leb128_decode(buf: memoryview, count: int) -> tuple[np.ndarray, int]:
    """Decode `count` signed LEB128 integers; returns (values, bytes_used)."""
    out = np.empty(count, dtype=np.int64)
    pos = 0
    end = len(buf)
    for i in range(count):
        result = 0
        shift = 0
        while True:
            if pos >= end:
                raise UnsupportedNnueFormat("truncated LEB128 stream")
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                if b & 0x40:  # sign-extend
                    result |= -(1 << shift)
                break
        out[i] = result
    return out, pos


def _leb128_encode(values: np.ndarray) -> bytes:
    out = bytearray()
    for v in map(int, values):
        while True:
            b = v & 0x7F
            v >>= 7
            if (v == 0 and not b & 0x40) or (v == -1 and b & 0x40):
                out.append(b)
                break
            out.append(b | 0x80)
    return bytes(out)


class _Reader:
    def __init__(self, data: bytes):
        self.data = memoryview(data)
        self.pos = 0

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        return v

    def bytes(self, n: int) -> bytes:
        b = bytes(self.data[self.pos : self.pos + n])
        if len(b) != n:
            raise UnsupportedNnueFormat("truncated file")
        self.pos += n
        return b

    def array(self, dtype, count: int) -> np.ndarray:
        """Read `count` values, either raw little-endian or LEB128-block."""
        magic_len = len(LEB_MAGIC)
        if bytes(self.data[self.pos : self.pos + magic_len]) == LEB_MAGIC:
            self.pos += magic_len
            nbytes = self.u32()
            values, used = _leb128_decode(self.data[self.pos :], count)
            if used != nbytes:
                raise UnsupportedNnueFormat(
                    f"LEB128 block length mismatch: header {nbytes}, used {used}"
                )
            self.pos += used
            info = np.iinfo(dtype)
            if values.min() < info.min or values.max() > info.max:
                raise UnsupportedNnueFormat("LEB128 value out of dtype range")
            return values.astype(dtype)
        itemsize = np.dtype(dtype).itemsize
        raw = self.bytes(count * itemsize)
        return np.frombuffer(raw, dtype=np.dtype(dtype).newbyteorder("<")).astype(dtype)

    def eof(self) -> bool:
        return self.pos == len(self.data)


# ------------------------------------------------------------------- parse


def _infer_l1(total: int, header_end: int) -> int:
    """Solve file size for L1 given the fixed layout (raw, uncompressed)."""
    # size = ft_hash(4) + 2*L1 + 2*NF*L1 + 4*NF*8 + net_hash(4)
    #        + 8 * (4*16 + 16*L1 + 4*32 + 32*30 + 4 + 32)
    body = total - header_end
    for l1 in (64, 128, 256, 512, 1024, 1536, 2048, 2560, 3072):
        ft = 4 + 2 * l1 + 2 * NUM_FEATURES * l1 + 4 * NUM_FEATURES * NUM_PSQT_BUCKETS
        stacks = 4 + NUM_STACKS * (
            4 * FC0_OUT + FC0_OUT * l1 + 4 * FC1_OUT + FC1_OUT * FC1_IN + 4 + FC1_OUT
        )
        if ft + stacks == body:
            return l1
    raise UnsupportedNnueFormat(
        f"cannot infer L1 from file size {total} (compressed files carry "
        "explicit block lengths; raw files must match a known L1)"
    )


def load_nnue(path: str | Path, l1: int | None = None) -> StockfishNet:
    """Parse a `.nnue` file into dequantized float32 arrays."""
    data = Path(path).read_bytes()
    r = _Reader(data)
    version = r.u32()
    net_hash = r.u32()
    desc_len = r.u32()
    if desc_len > 4096:
        raise UnsupportedNnueFormat(f"implausible description length {desc_len}")
    description = r.bytes(desc_len)

    ft_hash = r.u32()  # noqa: F841 — validated only by downstream size checks
    if l1 is None:
        try:
            l1 = _infer_l1(len(data), r.pos - 4)
        except UnsupportedNnueFormat:
            if LEB_MAGIC in data:  # compressed sections shrink the file
                raise UnsupportedNnueFormat(
                    "pass l1= explicitly for compressed files"
                ) from None
            raise
    if l1 % 2:
        raise UnsupportedNnueFormat("L1 must be even (pairwise activation)")

    ft_b = r.array(np.int16, l1)
    ft_w = r.array(np.int16, NUM_FEATURES * l1).reshape(NUM_FEATURES, l1)
    psqt = r.array(np.int32, NUM_FEATURES * NUM_PSQT_BUCKETS).reshape(
        NUM_FEATURES, NUM_PSQT_BUCKETS
    )

    _net_hash2 = r.u32()
    fc0_w = np.empty((NUM_STACKS, FC0_OUT, l1), np.float32)
    fc0_b = np.empty((NUM_STACKS, FC0_OUT), np.float32)
    fc1_w = np.empty((NUM_STACKS, FC1_OUT, FC1_IN), np.float32)
    fc1_b = np.empty((NUM_STACKS, FC1_OUT), np.float32)
    fc2_w = np.empty((NUM_STACKS, 1, FC1_OUT), np.float32)
    fc2_b = np.empty((NUM_STACKS, 1), np.float32)
    for b in range(NUM_STACKS):
        fc0_b[b] = r.array(np.int32, FC0_OUT) / (QA * QB)
        fc0_w[b] = r.array(np.int8, FC0_OUT * l1).reshape(FC0_OUT, l1) / QB
        fc1_b[b] = r.array(np.int32, FC1_OUT) / (QA * QB)
        fc1_w[b] = r.array(np.int8, FC1_OUT * FC1_IN).reshape(FC1_OUT, FC1_IN) / QB
        fc2_b[b] = r.array(np.int32, 1) / (NNUE2SCORE * OUTPUT_SCALE)
        fc2_w[b] = r.array(np.int8, FC1_OUT).reshape(1, FC1_OUT) / (
            NNUE2SCORE * OUTPUT_SCALE / QA
        )
    if not r.eof():
        raise UnsupportedNnueFormat(
            f"{len(data) - r.pos} trailing bytes after last layer stack"
        )

    return StockfishNet(
        ft_w=(ft_w / QA).astype(np.float32),
        ft_b=(ft_b / QA).astype(np.float32),
        psqt_w=(psqt / (NNUE2SCORE * OUTPUT_SCALE)).astype(np.float32),
        fc0_w=fc0_w, fc0_b=fc0_b, fc1_w=fc1_w, fc1_b=fc1_b,
        fc2_w=fc2_w, fc2_b=fc2_b,
        version=version, net_hash=net_hash, description=description,
    )


# ------------------------------------------------------------------ forward


def evaluate_sf(net: StockfishNet, board64, stm):
    """Centipawn-ish score for one position, SFNNv5 semantics, in jax.

    Full-refresh evaluation (the engine's HalfKAv2_hm compat path; the
    board768 fast path keeps its incremental accumulators instead)."""
    import jax.numpy as jnp

    l1 = net.ft_w.shape[1]
    half = l1 // 2

    from ..ops.board import king_square

    def persp_acc(perspective):
        ksq = king_square(board64, perspective)
        idx = nnue.feature_indices(board64, perspective, jnp.maximum(ksq, 0))
        rows = jnp.asarray(net.ft_w)[jnp.clip(idx, 0)]
        rows = jnp.where((idx >= 0)[:, None], rows, 0)
        psqt_rows = jnp.asarray(net.psqt_w)[jnp.clip(idx, 0)]
        psqt_rows = jnp.where((idx >= 0)[:, None], psqt_rows, 0)
        return jnp.asarray(net.ft_b) + rows.sum(0), psqt_rows.sum(0)

    acc_w, psqt_w_ = persp_acc(jnp.int32(0))
    acc_b, psqt_b_ = persp_acc(jnp.int32(1))
    acc_own = jnp.where(stm == 0, acc_w, acc_b)
    acc_opp = jnp.where(stm == 0, acc_b, acc_w)

    def pairwise(acc):
        c = jnp.clip(acc, 0.0, 1.0)
        return c[:half] * c[half:]

    x = jnp.concatenate([pairwise(acc_own), pairwise(acc_opp)])  # (L1,)

    bucket = nnue.output_bucket(board64)
    h0 = jnp.asarray(net.fc0_w)[bucket] @ x + jnp.asarray(net.fc0_b)[bucket]
    skip = h0[15]
    h = jnp.clip(h0[:15], 0.0, 1.0)
    h1_in = jnp.concatenate([h, jnp.square(h)])  # (30,)
    h1 = jnp.clip(
        jnp.asarray(net.fc1_w)[bucket] @ h1_in + jnp.asarray(net.fc1_b)[bucket],
        0.0, 1.0,
    )
    out = (jnp.asarray(net.fc2_w)[bucket] @ h1)[0] + jnp.asarray(net.fc2_b)[bucket][0]

    psqt = jnp.where(stm == 0, psqt_w_ - psqt_b_, psqt_b_ - psqt_w_)[bucket] / 2.0
    return (out + skip + psqt) * NNUE2SCORE


def evaluate_sf_reference(net: StockfishNet, board64: np.ndarray, stm: int) -> float:
    """Pure-numpy mirror of evaluate_sf for parity tests."""
    l1 = net.ft_w.shape[1]
    half = l1 // 2
    accs, psqts = [], []
    for persp in (0, 1):
        king_code = 6 if persp == 0 else 12
        ksq = int(np.argmax(board64 == king_code))
        flip = 56 if persp == 1 else 0
        o_ksq = ksq ^ flip
        mirror = 7 if (o_ksq & 7) > 3 else 0
        o_ksq ^= mirror
        bucket = nnue.KING_BUCKET[o_ksq]
        acc = net.ft_b.astype(np.float64).copy()
        ps = np.zeros(NUM_PSQT_BUCKETS)
        for sq in range(64):
            code = int(board64[sq])
            if code == 0:
                continue
            pt = (code - 1) % 6
            col = 0 if code <= 6 else 1
            kind = 10 if pt == 5 else (pt if col == persp else 5 + pt)
            o_sq = (sq ^ flip) ^ mirror
            idx = bucket * (11 * 64) + kind * 64 + o_sq
            acc += net.ft_w[idx]
            ps += net.psqt_w[idx]
        accs.append(acc)
        psqts.append(ps)
    own, opp = (0, 1) if stm == 0 else (1, 0)

    def pairwise(a):
        c = np.clip(a, 0.0, 1.0)
        return c[:half] * c[half:]

    x = np.concatenate([pairwise(accs[own]), pairwise(accs[opp])])
    ob = min((int(np.sum(board64 > 0)) - 1) // 4, NUM_PSQT_BUCKETS - 1)
    h0 = net.fc0_w[ob] @ x + net.fc0_b[ob]
    skip = h0[15]
    h = np.clip(h0[:15], 0.0, 1.0)
    h1 = np.clip(net.fc1_w[ob] @ np.concatenate([h, h * h]) + net.fc1_b[ob], 0.0, 1.0)
    out = float((net.fc2_w[ob] @ h1 + net.fc2_b[ob])[0])
    psqt = (psqts[own][ob] - psqts[opp][ob]) / 2.0
    return (out + skip + psqt) * NNUE2SCORE


# ---------------------------------------------------- synthetic writer (tests)


def write_nnue(path: str | Path, net_q: dict, compress_ft: bool = False) -> None:
    """Serialize quantized arrays into the `.nnue` layout (test fixture).

    net_q keys: ft_b int16[L1], ft_w int16[NF,L1], psqt int32[NF,8],
    and per-stack lists fc0_b/fc0_w/fc1_b/fc1_w/fc2_b/fc2_w."""
    l1 = net_q["ft_b"].shape[0]
    out = bytearray()
    out += struct.pack("<I", net_q.get("version", 0x7AF32F20))
    out += struct.pack("<I", net_q.get("net_hash", 0x1337))
    desc = net_q.get("description", b"fishnet-tpu synthetic test net")
    out += struct.pack("<I", len(desc)) + desc

    def emit(arr: np.ndarray, compress: bool = False):
        nonlocal out
        flat = arr.reshape(-1)
        if compress:
            payload = _leb128_encode(flat)
            out += LEB_MAGIC + struct.pack("<I", len(payload)) + payload
        else:
            out += flat.astype(flat.dtype.newbyteorder("<")).tobytes()

    out += struct.pack("<I", net_q.get("ft_hash", 0x5D69D5B8))
    emit(net_q["ft_b"].astype(np.int16))
    emit(net_q["ft_w"].astype(np.int16).reshape(-1), compress=compress_ft)
    emit(net_q["psqt"].astype(np.int32))
    out += struct.pack("<I", net_q.get("stack_hash", 0x63337156))
    for b in range(NUM_STACKS):
        emit(net_q["fc0_b"][b].astype(np.int32))
        emit(net_q["fc0_w"][b].astype(np.int8))
        emit(net_q["fc1_b"][b].astype(np.int32))
        emit(net_q["fc1_w"][b].astype(np.int8))
        emit(net_q["fc2_b"][b].astype(np.int32))
        emit(net_q["fc2_w"][b].astype(np.int8))
    Path(path).write_bytes(bytes(out))
