"""Model zoo: NNUE evaluation networks (the framework's flagship model)."""
