"""NNUE evaluation network (HalfKAv2_hm feature set) in JAX.

The reference ships Stockfish's nets as opaque binaries inside the engine
(reference: build.rs:8-9 embeds nn-1c0000000000.nnue + nn-37f18f62d772.nnue;
the engines evaluate them in C++). Here the network is a first-class model:
HalfKAv2_hm features (32 horizontally-mirrored king buckets × 11 piece
kinds × 64 squares = 22528 inputs per perspective), a perspective-shared
feature transform, and a bucketed layer stack selected by piece count —
resident in HBM as arrays, evaluated by XLA, and trainable in-framework
(fishnet_tpu.models.train).

Weights are float (bf16/f32) rather than Stockfish's int8/int16: the MXU
natively prefers bf16, and quantization is a later optimization, not a
architectural requirement as it is on CPU.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import tables as T
from ..ops.board import king_square, piece_color, piece_type

NUM_KING_BUCKETS = 32
NUM_PIECE_KINDS = 11  # our P N B R Q, their P N B R Q, kings (shared plane)
NUM_SQUARES = 64
NUM_FEATURES = NUM_KING_BUCKETS * NUM_PIECE_KINDS * NUM_SQUARES  # 22528
# board768: the TPU fast-path feature set — 12 piece kinds × 64 squares per
# perspective, no king buckets. King-bucketed sets force a full accumulator
# refresh whenever a king moves; under lockstep masked execution that
# refresh branch would run every step for every lane, so the fast path uses
# a set whose updates are *always* incremental (≤4 changed features/move).
NUM_FEATURES_768 = 12 * 64
NUM_OUTPUT_BUCKETS = 8
OUTPUT_SCALE = 600.0  # network output [-1,1]-ish → centipawns

# king bucket: files a-d (after mirroring) × 8 ranks
_KING_BUCKET = np.full(64, -1, dtype=np.int32)
for _sq in range(64):
    _f, _r = _sq & 7, _sq >> 3
    if _f < 4:
        _KING_BUCKET[_sq] = _r * 4 + _f
KING_BUCKET = _KING_BUCKET


class NnueParams(NamedTuple):
    ft_w: jnp.ndarray  # (NUM_FEATURES, L1)
    ft_b: jnp.ndarray  # (L1,)
    l1_w: jnp.ndarray  # (NUM_OUTPUT_BUCKETS, 2*L1, H1)
    l1_b: jnp.ndarray  # (NUM_OUTPUT_BUCKETS, H1)
    l2_w: jnp.ndarray  # (NUM_OUTPUT_BUCKETS, H1, H2)
    l2_b: jnp.ndarray  # (NUM_OUTPUT_BUCKETS, H2)
    out_w: jnp.ndarray  # (NUM_OUTPUT_BUCKETS, H2)
    out_b: jnp.ndarray  # (NUM_OUTPUT_BUCKETS,)

    @property
    def l1(self) -> int:
        return self.ft_w.shape[1]


def init_params(
    key, l1: int = 256, h1: int = 16, h2: int = 32, dtype=jnp.float32,
    feature_set: str = "halfkav2_hm",
) -> NnueParams:
    num_features = {
        "halfkav2_hm": NUM_FEATURES,
        "board768": NUM_FEATURES_768,
    }[feature_set]
    k = jax.random.split(key, 4)
    return NnueParams(
        ft_w=(jax.random.normal(k[0], (num_features, l1)) * 0.02).astype(dtype),
        ft_b=jnp.full((l1,), 0.5, dtype),
        l1_w=(jax.random.normal(k[1], (NUM_OUTPUT_BUCKETS, 2 * l1, h1))
              * (1.0 / np.sqrt(2 * l1))).astype(dtype),
        l1_b=jnp.zeros((NUM_OUTPUT_BUCKETS, h1), dtype),
        l2_w=(jax.random.normal(k[2], (NUM_OUTPUT_BUCKETS, h1, h2))
              * (1.0 / np.sqrt(h1))).astype(dtype),
        l2_b=jnp.zeros((NUM_OUTPUT_BUCKETS, h2), dtype),
        out_w=(jax.random.normal(k[3], (NUM_OUTPUT_BUCKETS, h2))
               * (1.0 / np.sqrt(h2))).astype(dtype),
        out_b=jnp.zeros((NUM_OUTPUT_BUCKETS,), dtype),
    )


# ------------------------------------------------------------------ features


def feature_indices(board64: jnp.ndarray, perspective: jnp.ndarray,
                    ksq: jnp.ndarray) -> jnp.ndarray:
    """(64,) feature index per square for one perspective; -1 where empty.

    Orientation: flip ranks for black's perspective, then mirror files so
    the king lands on files a-d (the _hm halving).
    """
    sq = jnp.arange(64, dtype=jnp.int32)
    flip = jnp.where(perspective == 1, 56, 0)
    o_sq = sq ^ flip
    o_ksq = ksq ^ flip
    mirror = jnp.where((o_ksq & 7) > 3, 7, 0)
    o_sq = o_sq ^ mirror
    o_ksq = o_ksq ^ mirror
    bucket = jnp.asarray(KING_BUCKET)[o_ksq]

    code = board64
    pt = piece_type(code)  # -1 empty, 0..5
    col = piece_color(code)
    kind = jnp.where(pt == 5, 10, jnp.where(col == perspective, pt, 5 + pt))
    idx = bucket * (NUM_PIECE_KINDS * NUM_SQUARES) + kind * NUM_SQUARES + o_sq
    return jnp.where(code > 0, idx, -1)


def refresh_accumulator(params: NnueParams, board64: jnp.ndarray,
                        perspective: jnp.ndarray) -> jnp.ndarray:
    """(L1,) accumulator for one perspective, recomputed from scratch."""
    ksq = king_square(board64, perspective)
    idx = feature_indices(board64, perspective, jnp.maximum(ksq, 0))
    rows = params.ft_w[jnp.clip(idx, 0)]  # (64, L1)
    rows = jnp.where((idx >= 0)[:, None], rows, 0)
    return params.ft_b + jnp.sum(rows, axis=0, dtype=acc_dtype(params))


def accumulators(params: NnueParams, board64: jnp.ndarray) -> jnp.ndarray:
    """(2, L1): white and black perspective accumulators."""
    return jnp.stack(
        [
            refresh_accumulator(params, board64, jnp.int32(0)),
            refresh_accumulator(params, board64, jnp.int32(1)),
        ]
    )


def feature_index_768(code: jnp.ndarray, sq: jnp.ndarray,
                      perspective: jnp.ndarray) -> jnp.ndarray:
    """board768 feature row for one piece; -1 when code==0 (empty)."""
    pt = piece_type(code)
    col = piece_color(code)
    kind = jnp.where(col == perspective, pt, 6 + pt)
    o_sq = sq ^ jnp.where(perspective == 1, 56, 0)
    return jnp.where(code > 0, kind * 64 + o_sq, -1)


def feature_indices_768(board64: jnp.ndarray, perspective: jnp.ndarray) -> jnp.ndarray:
    sq = jnp.arange(64, dtype=jnp.int32)
    return feature_index_768(board64, sq, perspective)


def refresh_accumulator_768(params: NnueParams, board64: jnp.ndarray,
                            perspective: jnp.ndarray) -> jnp.ndarray:
    idx = feature_indices_768(board64, perspective)
    rows = params.ft_w[jnp.clip(idx, 0)]
    rows = jnp.where((idx >= 0)[:, None], rows, 0)
    return params.ft_b + jnp.sum(rows, axis=0, dtype=acc_dtype(params))


def accumulators_768(params: NnueParams, board64: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack(
        [
            refresh_accumulator_768(params, board64, jnp.int32(0)),
            refresh_accumulator_768(params, board64, jnp.int32(1)),
        ]
    )


def apply_acc_updates_768(params: NnueParams, acc: jnp.ndarray,
                          codes: jnp.ndarray, sqs: jnp.ndarray,
                          signs: jnp.ndarray) -> jnp.ndarray:
    """Incrementally update a (2, L1) accumulator pair.

    codes/sqs/signs: (K,) piece changes (code 0 → no-op). Cost: 2K gathers
    of an (L1,) row — this is the whole point of board768.
    """
    # The per-slot rows are never needed individually — only their signed
    # SUM. Build a (NUM_FEATURES,) weight vector W with <= K nonzero
    # entries in {-1, +1} (slot one-hots scaled by sign; idx -1 matches
    # nothing) and contract it against ft_w once. ~16x less work than
    # gathering/selecting K rows (round-5 device profile: the row-select
    # form cost 180 us/step at B=256), and exact: int paths are integer
    # sums; float paths multiply rows by +-1 (exact) and add zeros, with
    # one fixed reduction order shared by the device step and the host
    # oracle (both call this function).
    nf = params.ft_w.shape[0]
    feat = jnp.arange(nf, dtype=jnp.int32)
    for persp in (0, 1):
        idx = feature_index_768(codes, sqs, jnp.int32(persp))  # (K,)
        w = jnp.sum(
            jnp.where(idx[:, None] == feat[None, :], signs[:, None], 0),
            axis=0,
        )  # (NF,) int32 in {-1, 0, +1}
        delta = jnp.sum(
            params.ft_w * w[:, None].astype(params.ft_w.dtype), axis=0,
            dtype=acc_dtype(params),
        )
        acc = acc.at[persp].add(delta)
    return acc


def cast_params(params: NnueParams, dtype=jnp.bfloat16) -> NnueParams:
    """Quantize the network weights (bf16 by default — the MXU's native
    input type; SURVEY §7.2). Search accumulators stay f32 (init_state
    allocates acc in f32 regardless), so incremental updates keep their
    precision; matmuls run bf16×f32→f32 which XLA maps onto the MXU.
    Evaluations may drift a few centipawns vs f32 — use the f32 master
    weights for training and parity tests."""
    return NnueParams(*[jnp.asarray(a).astype(dtype) for a in params])


# int8 quantization scales (Stockfish-style fixed-point ladder):
# activations live in [0, QA] (int), weights are rounded to 1/QW steps;
# a matmul accumulates at scale QA*QW and the >>QW_SHIFT rescales back.
QA = 127  # activation quant — fits int8 for the MXU's int8 dot path
QW = 64
QW_SHIFT = 6


def quantize_int8(params: NnueParams) -> NnueParams:
    """f32 master weights → int fixed-point (SURVEY §7.2's int8 path).

    ft_w is int16 (the accumulator sums ≤33 rows, far within int32);
    hidden/output weights are int8, biases pre-scaled int32. Incremental
    accumulator updates become EXACT integer adds (no f32 drift down the
    search stack), and the hidden matmuls run int8×int8→int32 — the
    MXU's highest-throughput mode. Same NnueParams container: the
    integer dtype is the dispatch flag (is_int8)."""
    f = lambda a: np.asarray(a, np.float64)  # noqa: E731
    return NnueParams(
        ft_w=jnp.asarray(np.round(f(params.ft_w) * QA), jnp.int16),
        ft_b=jnp.asarray(np.round(f(params.ft_b) * QA), jnp.int32),
        l1_w=jnp.asarray(
            np.clip(np.round(f(params.l1_w) * QW), -127, 127), jnp.int8
        ),
        l1_b=jnp.asarray(np.round(f(params.l1_b) * QA * QW), jnp.int32),
        l2_w=jnp.asarray(
            np.clip(np.round(f(params.l2_w) * QW), -127, 127), jnp.int8
        ),
        l2_b=jnp.asarray(np.round(f(params.l2_b) * QA * QW), jnp.int32),
        out_w=jnp.asarray(
            np.clip(np.round(f(params.out_w) * QW), -127, 127), jnp.int8
        ),
        out_b=jnp.asarray(np.round(f(params.out_b) * QA * QW), jnp.int32),
    )


def is_int8(params) -> bool:
    return (
        isinstance(params, NnueParams)
        and jnp.issubdtype(jnp.asarray(params.ft_w).dtype, jnp.integer)
    )


def acc_dtype(params) -> jnp.dtype:
    """Search accumulator dtype for a params set (int32 under int8
    quantization — integer adds are exact; f32 otherwise)."""
    return jnp.int32 if is_int8(params) else jnp.float32


def is_board768(params) -> bool:
    return (
        isinstance(params, NnueParams)
        and params.ft_w.shape[0] == NUM_FEATURES_768
    )


# ------------------------------------------------------------------- forward


def _crelu(x):
    return jnp.clip(x, 0.0, 1.0)


def output_bucket(board64: jnp.ndarray) -> jnp.ndarray:
    count = jnp.sum(board64 > 0)
    return jnp.clip((count - 1) // 4, 0, NUM_OUTPUT_BUCKETS - 1)


def _bucket_weights(params: NnueParams, bucket: jnp.ndarray):
    """Layer-stack weights for one output bucket, selected by an 8-way
    where-chain instead of `w[bucket]` — the data-dependent gather lowers
    to a serialized per-lane fusion on TPU (round-5 device profile) while
    the select chain is vectorized; the selected values (and downstream
    matmul shapes, hence float bits) are identical."""
    picked = None
    for n in range(NUM_OUTPUT_BUCKETS):
        cur = (params.l1_w[n], params.l1_b[n], params.l2_w[n],
               params.l2_b[n], params.out_w[n], params.out_b[n])
        if picked is None:
            picked = cur
        else:
            picked = tuple(
                jnp.where(bucket == n, c, p) for c, p in zip(cur, picked)
            )
    return picked


def forward_from_acc(params: NnueParams, acc: jnp.ndarray, stm: jnp.ndarray,
                     bucket: jnp.ndarray) -> jnp.ndarray:
    """Centipawn score from the side to move's perspective (scalar f32)."""
    own = jnp.where(stm == 0, acc[0], acc[1])
    opp = jnp.where(stm == 0, acc[1], acc[0])
    if is_int8(params):
        # fixed-point ladder: activations [0,QA] int8, weights 1/QW
        # steps, int8×int8→int32 dots (the MXU's fastest mode), >>6
        # rescale between layers; exact integer arithmetic throughout
        w1, b1, w2, b2, ow, ob = _bucket_weights(params, bucket)
        x = jnp.clip(jnp.concatenate([own, opp]), 0, QA).astype(jnp.int8)
        h = jnp.matmul(x, w1, preferred_element_type=jnp.int32) + b1
        h = jnp.clip(h >> QW_SHIFT, 0, QA).astype(jnp.int8)
        h = jnp.matmul(h, w2, preferred_element_type=jnp.int32) + b2
        h = jnp.clip(h >> QW_SHIFT, 0, QA).astype(jnp.int8)
        out = jnp.matmul(h, ow, preferred_element_type=jnp.int32) + ob
        return out.astype(jnp.float32) * (OUTPUT_SCALE / (QA * QW))
    x = jnp.concatenate([_crelu(own), _crelu(opp)])  # (2*L1,)
    w1, b1, w2, b2, ow, ob = _bucket_weights(params, bucket)
    h = _crelu(x @ w1 + b1)
    h = _crelu(h @ w2 + b2)
    out = h @ ow + ob
    return out * OUTPUT_SCALE


def evaluate(params, board64: jnp.ndarray, stm: jnp.ndarray) -> jnp.ndarray:
    """Full evaluation of one lane (refresh + forward); dispatches on the
    feature set statically (by table shape / params type). Accepts either
    our NnueParams or an imported Stockfish net (models/nnue_import.py)."""
    if not isinstance(params, NnueParams):
        from . import nnue_import

        return nnue_import.evaluate_sf(params, board64, stm)
    if is_board768(params):
        acc = accumulators_768(params, board64)
    else:
        acc = accumulators(params, board64)
    return forward_from_acc(params, acc, stm, output_bucket(board64))


v_evaluate = jax.vmap(evaluate, in_axes=(None, 0, 0))


# ------------------------------------------------- host reference (numpy)


def evaluate_reference(params: NnueParams, board64: np.ndarray, stm: int) -> float:
    """Pure-numpy reference implementation for parity tests."""
    p = jax.tree_util.tree_map(np.asarray, params)
    accs = []
    if p.ft_w.shape[0] == NUM_FEATURES_768:
        for persp in (0, 1):
            acc = p.ft_b.astype(np.float64).copy()
            for sq in range(64):
                code = int(board64[sq])
                if code == 0:
                    continue
                pt = (code - 1) % 6
                col = 0 if code <= 6 else 1
                kind = pt if col == persp else 6 + pt
                o_sq = sq ^ (56 if persp == 1 else 0)
                acc += p.ft_w[kind * 64 + o_sq]
            accs.append(acc)
        own, opp = (accs[0], accs[1]) if stm == 0 else (accs[1], accs[0])
        x = np.concatenate([np.clip(own, 0, 1), np.clip(opp, 0, 1)])
        ob = min((int(np.sum(board64 > 0)) - 1) // 4, NUM_OUTPUT_BUCKETS - 1)
        h = np.clip(x @ p.l1_w[ob] + p.l1_b[ob], 0, 1)
        h = np.clip(h @ p.l2_w[ob] + p.l2_b[ob], 0, 1)
        return float((h @ p.out_w[ob] + p.out_b[ob]) * OUTPUT_SCALE)
    for persp in (0, 1):
        king_code = 6 if persp == 0 else 12
        ksq = int(np.argmax(board64 == king_code))
        flip = 56 if persp == 1 else 0
        o_ksq = ksq ^ flip
        mirror = 7 if (o_ksq & 7) > 3 else 0
        o_ksq ^= mirror
        bucket = KING_BUCKET[o_ksq]
        acc = p.ft_b.astype(np.float64).copy()
        for sq in range(64):
            code = int(board64[sq])
            if code == 0:
                continue
            pt = (code - 1) % 6
            col = 0 if code <= 6 else 1
            kind = 10 if pt == 5 else (pt if col == persp else 5 + pt)
            o_sq = (sq ^ flip) ^ mirror
            idx = bucket * (NUM_PIECE_KINDS * NUM_SQUARES) + kind * NUM_SQUARES + o_sq
            acc += p.ft_w[idx]
        accs.append(acc)
    own, opp = (accs[0], accs[1]) if stm == 0 else (accs[1], accs[0])
    x = np.concatenate([np.clip(own, 0, 1), np.clip(opp, 0, 1)])
    ob = min((int(np.sum(board64 > 0)) - 1) // 4, NUM_OUTPUT_BUCKETS - 1)
    h = np.clip(x @ p.l1_w[ob] + p.l1_b[ob], 0, 1)
    h = np.clip(h @ p.l2_w[ob] + p.l2_b[ob], 0, 1)
    return float((h @ p.out_w[ob] + p.out_b[ob]) * OUTPUT_SCALE)


# -------------------------------------------------------------- persistence


def save_params(params: NnueParams, path: str | Path) -> None:
    path = Path(path)
    meta = {
        "format": "fishnet-tpu-nnue-v1",
        "feature_set": (
            "board768" if params.ft_w.shape[0] == NUM_FEATURES_768 else "HalfKAv2_hm"
        ),
        "l1": int(params.ft_w.shape[1]),
        "h1": int(params.l1_w.shape[2]),
        "h2": int(params.l2_w.shape[2]),
        "output_buckets": NUM_OUTPUT_BUCKETS,
        "output_scale": OUTPUT_SCALE,
    }
    arrays = {f: np.asarray(getattr(params, f)) for f in NnueParams._fields}
    np.savez_compressed(path, __meta__=json.dumps(meta), **arrays)


def load_params(path: str | Path) -> NnueParams:
    with np.load(Path(path), allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta.get("format") != "fishnet-tpu-nnue-v1":
            raise ValueError(f"unknown nnue format: {meta.get('format')!r}")
        return NnueParams(**{f: jnp.asarray(z[f]) for f in NnueParams._fields})
