"""Content-addressed store of serialized compiled search programs.

The executable artifact is ``jax.experimental.serialize_executable``
output — a pickled (payload, in_tree, out_tree) triple, zlib-compressed
— because deserialize_and_load restores a *Compiled* object that runs
with zero recompilation. (jax.export round-trips StableHLO, which
recompiles on first call — useless for warmup-free boot.)

Store layout (one directory per store fingerprint, so incompatible
jax/config combinations never collide)::

    <root>/<fingerprint12>/manifest.json
    <root>/<fingerprint12>/blobs/<program-key>.bin

The fallback ladder, in order, for every wrapped call:

1. in-memory compiled executable → call it (zero host overhead after
   first load);
2. on-disk artifact → sha256-verify, deserialize, cache, call;
3. corrupted/unloadable artifact → quarantine (rename ``.bad``), warn,
   fall through;
4. miss → **plain JIT**, with a one-time warning per program key and an
   ``aot.miss`` trace instant. A miss is never an error: the engine
   degrades to exactly the pre-AOT behaviour.

In export mode (``pack``, or FISHNET_TPU_AOT_EXPORT=1 for background
re-export on a live host) a miss additionally lowers + compiles through
the wrapper and serializes the executable to the store from a
background thread, so the next boot hits.

All serialize/deserialize calls live in THIS module — fishnet-lint's
``aot-unkeyed-export`` rule rejects them anywhere else, which is what
keeps every artifact behind the fingerprint key.

Security note: artifacts are pickles and a bundle is trusted exactly
like the code that loads it — ship bundles over the same channel as the
wheel/zipapp, never from untrusted input.
"""

from __future__ import annotations

import hashlib
import inspect
import json
import os
import pickle
import sys
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..obs import trace
from ..utils import settings
from . import keys

try:  # pragma: no cover - exercised implicitly on every import
    from jax.experimental import serialize_executable as _serialize_executable
except Exception:  # pragma: no cover - jax builds without the module
    _serialize_executable = None

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"

# Sentinel cached after a key already missed: later calls skip the disk
# probe and go straight to jit (whose own executable cache is warm by
# then — the JIT fallback pays the compile exactly once).
_MISS = object()

REGISTRY: Optional["Registry"] = None

_install_lock = threading.Lock()
_monitoring_installed = False
_compile_count = 0
_compile_current = threading.local()


def _on_compile_duration(event: str, duration: float, **kw: Any) -> None:
    # jax.monitoring fires this for every XLA backend compile, including
    # ~10ms eager-op compiles; mirror each one into the trace timeline
    # (retroactively — the compile just ended) so tools/aot_smoke.py can
    # assert a warmed boot ran no big compiles.
    if "backend_compile" not in event:
        return
    global _compile_count
    _compile_count += 1
    try:
        # the perf layer's compile-duration stream (docs/perf.md):
        # count + cumulative seconds, next to the trace mirror below
        from ..obs.metrics import REGISTRY

        REGISTRY.counter(
            "fishnet_compiles_total",
            "XLA backend compiles observed via jax.monitoring",
        ).inc()
        REGISTRY.counter(
            "fishnet_compile_seconds_total",
            "Cumulative XLA backend compile wall time",
        ).inc(float(duration))
    except (ImportError, TypeError, ValueError):
        pass  # metrics are best-effort; the trace mirror still runs
    rec = trace.RECORDER
    if rec is not None:
        dur_us = float(duration) * 1e6
        rec.complete(
            "xla_backend_compile",
            trace.now_us() - dur_us,
            dur_us,
            cat="compile",
            args={
                "event": event,
                "program": getattr(_compile_current, "program", ""),
            },
        )


def _install_monitoring() -> None:
    global _monitoring_installed
    with _install_lock:
        if _monitoring_installed:
            return
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                _on_compile_duration
            )
            _monitoring_installed = True
        except Exception:
            _monitoring_installed = True  # no monitoring API: stay quiet


def compile_count() -> int:
    """Backend compiles observed process-wide since install."""
    return _compile_count


def default_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".cache", "fishnet-tpu", "aot"
    )


class Registry:
    """One process's view of an on-disk program store."""

    def __init__(self, root: str, export: bool = False,
                 logger: Optional[Callable[[str], None]] = None):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.export = bool(export)
        self._log = logger
        if self.export:
            # serialize() of an executable that was LOADED from the XLA
            # persistent compile cache yields an incomplete payload that
            # fails at deserialize ("Symbols not found", observed on
            # XLA:CPU) — an exporter must compile for real, so the
            # tier-2 cache goes off for this whole process
            from ..utils.compile_cache import disable_compile_cache

            disable_compile_cache()
        self.fingerprint = keys.store_fingerprint()
        self.digest = keys.fingerprint_digest(self.fingerprint)
        self.dir = os.path.join(self.root, self.digest[:12])
        self.blob_dir = os.path.join(self.dir, "blobs")
        self._lock = threading.Lock()
        self._warned: set = set()
        self._pending: List[threading.Thread] = []
        self.stats = {
            "hits": 0, "misses": 0, "loads": 0,
            "errors": 0, "exports": 0,
        }
        self.manifest = self._read_manifest()
        # A registry over an empty store in read-only mode has nothing
        # to offer: deactivate so the wrappers are pure passthrough.
        self.active = self.export or bool(self.manifest["programs"])
        if not self.manifest["programs"] and not self.export:
            self._note_rejections()

    # -- store I/O ---------------------------------------------------

    def _read_manifest(self) -> Dict[str, Any]:
        path = os.path.join(self.dir, MANIFEST_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                man = json.load(f)
            if man.get("version") != MANIFEST_VERSION:
                self._warn(
                    f"aot: manifest version {man.get('version')!r} != "
                    f"{MANIFEST_VERSION}; ignoring store {self.dir}"
                )
                raise ValueError("version skew")
            man.setdefault("programs", {})
            man.setdefault("covers", [])
            return man
        except (OSError, ValueError, KeyError):
            return {
                "version": MANIFEST_VERSION,
                "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "fingerprint": self.fingerprint,
                "covers": [],
                "programs": {},
            }

    def _note_rejections(self) -> None:
        # The explicit compat-rejection path: name WHY sibling stores
        # (other fingerprints under the same root) don't apply here,
        # instead of silently booting cold.
        try:
            entries = os.listdir(self.root)
        except OSError:
            return
        for d in entries:
            if d == self.digest[:12]:
                continue
            mpath = os.path.join(self.root, d, MANIFEST_NAME)
            if not os.path.isfile(mpath):
                continue
            try:
                with open(mpath, "r", encoding="utf-8") as f:
                    theirs = json.load(f).get("fingerprint") or {}
            except (OSError, ValueError, AttributeError):
                continue
            diff = keys.diff_fingerprints(self.fingerprint, theirs)
            self._warn(
                f"aot: store {d} is incompatible with this process "
                f"({'; '.join(diff) or 'fingerprint digest mismatch'}) "
                f"— booting cold (JIT)"
            )

    def _write_manifest_locked(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def set_covers(self, covers: List[str]) -> None:
        with self._lock:
            self.manifest["covers"] = sorted(set(covers))
            self._write_manifest_locked()

    def flush(self) -> None:
        """Join pending export threads (pack calls this before exit)."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                t = self._pending.pop()
            # serialization of one executable is seconds; a wedged
            # thread must not hang pack (the bundle just won't cover
            # that program — the boot-side ladder degrades to JIT)
            t.join(timeout=120.0)
            if t.is_alive():
                self._warn(f"aot: export thread {t.name} still running "
                           f"after 120s; leaving it behind")

    # -- logging -----------------------------------------------------

    def _warn(self, msg: str, once_key: Optional[str] = None) -> None:
        if once_key is not None:
            with self._lock:
                if once_key in self._warned:
                    return
                self._warned.add(once_key)
        if self._log is not None:
            try:
                self._log(msg)
                return
            except Exception:
                # broken logger sink: fall through to stderr so the
                # warning is never swallowed
                print(f"W: {msg}", file=sys.stderr, flush=True)
                return
        print(f"W: {msg}", file=sys.stderr, flush=True)

    # -- call path ---------------------------------------------------

    def call(self, prog: "AotProgram", args: tuple, kwargs: dict) -> Any:
        try:
            bound = prog.signature.bind(*args, **kwargs)
            bound.apply_defaults()
            ordered = list(bound.arguments.items())
            statics = {n: v for n, v in ordered if n in prog.static_names}
            dynamics = tuple(
                v for n, v in ordered if n not in prog.static_names
            )
            key, meta = keys.program_key(
                prog.name, statics, prog.extra_static, dynamics
            )
        except Exception as e:
            self._warn(
                f"aot: {prog.name}: cannot canonicalize call ({e!r}); "
                f"falling back to JIT", once_key=f"canon:{prog.name}",
            )
            return self._jit_call(prog, args, kwargs)

        cached = prog.cache.get(key)
        if cached is _MISS:
            return self._jit_call(prog, args, kwargs)
        if cached is not None:
            try:
                out = cached(*dynamics)
                self.stats["hits"] += 1
                return out
            except Exception as e:
                # Never let a stale artifact break a dispatch: evict and
                # degrade this key to JIT for the rest of the process.
                self.stats["errors"] += 1
                prog.cache[key] = _MISS
                self._warn(
                    f"aot: {prog.name}: preloaded executable rejected the "
                    f"call ({e!r}); evicted, falling back to JIT",
                    once_key=f"callerr:{key}",
                )
                return self._jit_call(prog, args, kwargs)

        entry = self.manifest["programs"].get(key)
        if entry is not None:
            compiled = self._load(key, entry)
            if compiled is not None:
                prog.cache[key] = compiled
                self.stats["loads"] += 1
                trace.instant(
                    "aot.load", "aot", program=prog.name, key=key[:12]
                )
                try:
                    out = compiled(*dynamics)
                    self.stats["hits"] += 1
                    return out
                except Exception as e:
                    self.stats["errors"] += 1
                    prog.cache[key] = _MISS
                    self._warn(
                        f"aot: {prog.name}: loaded executable rejected the "
                        f"call ({e!r}); falling back to JIT",
                        once_key=f"callerr:{key}",
                    )
                    return self._jit_call(prog, args, kwargs)

        return self._miss(prog, key, meta, ordered, dynamics, args, kwargs)

    def _jit_call(self, prog: "AotProgram", args: tuple,
                  kwargs: dict) -> Any:
        _compile_current.program = prog.name
        try:
            return prog.jit(*args, **kwargs)
        finally:
            _compile_current.program = ""

    def _miss(self, prog: "AotProgram", key: str, meta: Dict[str, str],
              ordered: List[Tuple[str, Any]], dynamics: tuple,
              args: tuple, kwargs: dict) -> Any:
        self.stats["misses"] += 1
        trace.instant("aot.miss", "aot", program=prog.name, key=key[:12])
        self._warn(
            f"aot: miss for {prog.name} [{key[:12]}] "
            f"(statics {meta['statics']}); compiling via JIT",
            once_key=f"miss:{key}",
        )
        if not (self.export and _serialize_executable is not None):
            prog.cache[key] = _MISS
            return self._jit_call(prog, args, kwargs)
        # Export mode: compile through lower() so we hold the Compiled
        # object to serialize, then answer the call with it.
        _compile_current.program = prog.name
        try:
            compiled = prog.jit.lower(*[v for _, v in ordered]).compile()
        except Exception as e:
            self._warn(
                f"aot: {prog.name}: lower/compile for export failed "
                f"({e!r}); serving the call via plain JIT",
                once_key=f"lower:{key}",
            )
            prog.cache[key] = _MISS
            return self._jit_call(prog, args, kwargs)
        finally:
            _compile_current.program = ""
        prog.cache[key] = compiled
        # program cost accounting (obs/perf.py): pack time is the one
        # moment every search jit and mesh callable passes through here
        # as a Compiled object, so the FLOPs/bytes/memory read is free
        try:
            if settings.get_bool("FISHNET_TPU_PERF_PROGRAMS"):
                from ..obs import perf as _perf

                _perf.record_program_cost(prog.name, compiled)
        except (ImportError, TypeError, ValueError):
            pass  # accounting is best-effort; the export still runs
        t = threading.Thread(
            target=self._export_one, args=(prog.name, key, meta, compiled),
            daemon=True, name=f"aot-export-{key[:8]}",
        )
        with self._lock:
            self._pending.append(t)
        t.start()
        return compiled(*dynamics)

    # -- artifacts ---------------------------------------------------

    def _load(self, key: str, entry: Dict[str, Any]) -> Optional[Any]:
        path = os.path.join(self.blob_dir, key + ".bin")
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            self._warn(
                f"aot: artifact {key[:12]} listed in manifest but "
                f"unreadable ({e!r})", once_key=f"noblob:{key}",
            )
            return None
        if hashlib.sha256(blob).hexdigest() != entry.get("sha256"):
            self._quarantine(path, key, "sha256 mismatch")
            return None
        try:
            payload, in_tree, out_tree = pickle.loads(zlib.decompress(blob))
            with trace.span("aot.deserialize", "aot",
                            program=entry.get("entry", "?"), key=key[:12]):
                return _serialize_executable.deserialize_and_load(
                    payload, in_tree, out_tree
                )
        except Exception as e:
            self._quarantine(path, key, repr(e))
            return None

    def _quarantine(self, path: str, key: str, why: str) -> None:
        self.stats["errors"] += 1
        try:
            os.replace(path, path + ".bad")
        except OSError:
            pass
        self._warn(
            f"aot: artifact {key[:12]} corrupt ({why}); quarantined as "
            f"{os.path.basename(path)}.bad, falling back to JIT",
            once_key=f"quarantine:{key}",
        )

    def _export_one(self, name: str, key: str, meta: Dict[str, str],
                    compiled: Any) -> None:
        try:
            payload, in_tree, out_tree = _serialize_executable.serialize(
                compiled
            )
            blob = zlib.compress(
                pickle.dumps((payload, in_tree, out_tree)), 6
            )
        except Exception as e:
            # shard_map/unsupported executables may refuse serialization;
            # the program still runs (compiled is cached in memory).
            self._warn(
                f"aot: {name} [{key[:12]}] is not serializable ({e!r}); "
                f"bundle will not cover it", once_key=f"ser:{key}",
            )
            return
        try:
            os.makedirs(self.blob_dir, exist_ok=True)
            path = os.path.join(self.blob_dir, key + ".bin")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            with self._lock:
                self.manifest["programs"][key] = dict(
                    meta,
                    sha256=hashlib.sha256(blob).hexdigest(),
                    size=len(blob),
                )
                self._write_manifest_locked()
            self.stats["exports"] += 1
            trace.instant("aot.export", "aot", program=name, key=key[:12])
        except Exception as e:
            self._warn(f"aot: export of {name} [{key[:12]}] failed ({e!r})")

    # -- reporting ---------------------------------------------------

    def covers(self) -> set:
        return set(self.manifest.get("covers") or [])

    def report(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "export": self.export,
            "fingerprint": self.digest[:12],
            "dir": self.dir,
            "programs": len(self.manifest["programs"]),
            "covers": sorted(self.covers()),
            **self.stats,
        }


class AotProgram:
    """Transparent wrapper around one jitted entry point.

    Callable exactly like the jit it wraps (same signature, donation and
    static handling included). With no active registry it IS the jit
    plus one global check; with one, calls route through the fallback
    ladder above. Keep the module-level variable names of wrapped jits
    unchanged (`_run_segment_jit` etc.) — fishnet-lint's conc-host-sync
    device-producer list matches on those names.
    """

    __slots__ = ("name", "jit", "signature", "static_names",
                 "extra_static", "cache", "_plain")

    def __init__(self, name: str, jit_fn: Any, fun: Callable,
                 static_names: tuple = (),
                 extra_static: Optional[Dict[str, Any]] = None):
        self.name = name
        self.jit = jit_fn
        self.signature = inspect.signature(fun)
        self.static_names = frozenset(static_names)
        self.extra_static = dict(extra_static or {})
        self.cache: Dict[str, Any] = {}
        # *args/**kwargs signatures cannot be canonicalized to a stable
        # positional form — such programs stay plain JIT forever.
        self._plain = any(
            p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
            for p in self.signature.parameters.values()
        )

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        reg = REGISTRY
        if reg is None or not reg.active or self._plain:
            return self.jit(*args, **kwargs)
        return reg.call(self, args, kwargs)

    def lower(self, *args: Any, **kwargs: Any) -> Any:
        return self.jit.lower(*args, **kwargs)


def wrap(name: str, jit_fn: Any, fun: Callable, static_names: tuple = (),
         extra_static: Optional[Dict[str, Any]] = None) -> AotProgram:
    """Wrap a jitted entry point for AOT load/export."""
    return AotProgram(name, jit_fn, fun, static_names, extra_static)


def install(root: str, export: bool = False,
            logger: Optional[Callable[[str], None]] = None) -> Registry:
    """Install a registry at an explicit root (pack / tests)."""
    global REGISTRY
    _install_monitoring()
    with _install_lock:
        REGISTRY = Registry(root, export=export, logger=logger)
        return REGISTRY


def uninstall() -> None:
    global REGISTRY
    with _install_lock:
        REGISTRY = None


def install_from_settings(
    logger: Optional[Callable[[str], None]] = None,
) -> Optional[Registry]:
    """Install the process registry from FISHNET_TPU_AOT* settings.

    Idempotent; called from the TpuEngine constructor so every
    deployment shape (host child, in-process client, serve, fleet,
    bench) gets the same behaviour. Returns None when AOT is disabled
    or the serialize API is unavailable.
    """
    global REGISTRY
    _install_monitoring()
    with _install_lock:
        if REGISTRY is not None:
            return REGISTRY
        if _serialize_executable is None:
            return None
        if not settings.get_bool("FISHNET_TPU_AOT"):
            return None
        root = settings.get_str("FISHNET_TPU_AOT_DIR") or default_dir()
        export = settings.get_bool("FISHNET_TPU_AOT_EXPORT")
        REGISTRY = Registry(root, export=export, logger=logger)
        return REGISTRY


def boot_report() -> Dict[str, Any]:
    """Small JSON-safe summary for ready frames and logs."""
    reg = REGISTRY
    if reg is None or not reg.active:
        return {"enabled": False, "programs": 0, "covers": []}
    return reg.report()


def warm_covers(*need: str) -> bool:
    """True iff a non-exporting registry's bundle covers `need`.

    The warmup early-outs key on this: an exporting registry must never
    skip warmup (pack IS the warmup), and an empty store covers nothing.
    """
    reg = REGISTRY
    if reg is None or not reg.active or reg.export:
        return False
    if not reg.manifest["programs"]:
        return False
    return set(need) <= reg.covers()
