"""AOT program assets: pack, ship, and preload compiled search programs.

The reference ships ready-to-run engine *binaries* in an archive
unpacked at startup (assets.rs); our executable is the XLA program.
This package inverts the same trick for programs: `pack` runs the real
warmup/stream paths under an exporting registry and serializes every
compiled executable (jax.experimental.serialize_executable) into a
content-addressed bundle; `warm` installs a bundle on a host; a booted
replica then reaches its first segment dispatch with zero XLA
compilations, loading executables from disk instead of compiling.

Layout:
  keys.py     — canonical store fingerprint + per-program keys, and the
                explicit compat-rejection diff.
  registry.py — the on-disk store, the AotProgram wrapper around the
                hot jits, load→deserialize→call plumbing, JIT fallback.
  pack.py     — bundle build (`python -m fishnet_tpu pack`) and install
                (`python -m fishnet_tpu warm`).

See docs/aot.md for the bundle format and the fallback ladder.
"""

from . import keys, registry  # noqa: F401
