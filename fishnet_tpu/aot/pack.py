"""Build and install AOT program bundles.

`pack` is warmup-as-a-build-step: it installs an *exporting* registry,
builds a real TpuEngine and runs the exact code paths a booted replica
runs — bucket warmup, the deep move-job program, variant warmup, and a
small refill stream — so every program key in the bundle matches the
runtime call forms bit-for-bit (same arg trees, same weak types, same
statics). The resulting directory is the bundle: manifest.json plus
content-addressed compressed executables, mirroring assets.py's
packaged-weights story but for programs.

`warm` installs a bundle on a host: fingerprint-checks it against the
local process (explicit field-by-field rejection on skew), re-verifies
every artifact hash, and copies it into the live AOT directory.

CLI (dispatched from client/app.py main):

    python -m fishnet_tpu pack  [--aot-bundle OUT]  # default: live dir
    python -m fishnet_tpu warm  --aot-bundle SRC [--aot-dir DEST]

Run `pack` under the same environment the replica boots with (same
FISHNET_TPU_* knobs, same jax, same device topology) — the fingerprint
enforces it at load time anyway; matching up front avoids building a
bundle no replica can use.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils import settings
from . import keys, registry


def _log_to(logger: Optional[Callable[[str], None]]):
    import sys

    if logger is not None:
        return logger
    return lambda msg: print(msg, file=sys.stderr, flush=True)


def _stream_warmup(engine, log: Callable[[str], None]) -> bool:
    """Compile the stream-form programs warmup never touches.

    The LaneScheduler's refill path calls the SAME jits with different
    aval shapes than chunk-serial warmup: a (B,) tt_gen array on the
    segment, the merge-splice program, and the full-array init form.
    Stream a few positions through the smallest bucket with N > width so
    a refill boundary actually fires; the program keys this exports are
    exactly what a refill-enabled boot dispatches first.
    """
    if not engine.refill:
        return False
    if engine.mesh is not None and not engine.mesh_refill:
        return False
    import jax.numpy as jnp

    from ..chess.position import Position
    from ..engine.tpu import LANE_BUCKETS, MAX_PLY
    from ..ops import search as search_ops
    from ..ops.board import from_position, stack_boards

    width = engine._pad(min(LANE_BUCKETS))
    n = width + 2  # > width: forces at least one refill + merge
    roots = stack_boards([from_position(Position.initial())] * n)
    out = search_ops.search_stream(
        engine.params, roots,
        np.ones(n, np.int32), np.full(n, 64, np.int32),
        max_ply=MAX_PLY, width=width,
        tt=engine._scratch_tt(), mesh=engine.mesh,
        prefer_deep_store=engine.helper_lanes > 1,
    )
    done = int(np.asarray(out["done"]).sum()) if "done" in out else n
    log(f"pack: stream programs exported (width {width}, {done}/{n} done)")
    return True


def pack(out_dir: Optional[str] = None,
         logger: Optional[Callable[[str], None]] = None,
         engine_kwargs: Optional[Dict] = None) -> Dict:
    """Build a bundle at out_dir (default: the live AOT directory)."""
    log = _log_to(logger)
    root = (
        out_dir
        or settings.get_str("FISHNET_TPU_AOT_DIR")
        or registry.default_dir()
    )
    if registry.REGISTRY is not None and not registry.REGISTRY.export:
        # a read-only registry from an earlier engine in this process
        # would shadow the exporter — replace it explicitly
        registry.uninstall()
    reg = registry.install(root, export=True, logger=log)
    log(
        f"pack: exporting into {reg.dir} "
        f"(fingerprint {reg.digest[:12]}, backend "
        f"{reg.fingerprint['backend']}/{reg.fingerprint['device_kind']})"
    )

    from ..engine.tpu import TpuEngine

    engine = TpuEngine(**(engine_kwargs or {}))
    covers: List[str] = list(engine.warmup(None, log) or [])
    if engine.warmup_variants(log):
        covers.append("variants")
    if _stream_warmup(engine, log):
        covers.append("stream")
    reg.flush()
    reg.set_covers(covers)
    rep = reg.report()
    log(
        f"pack: bundle ready — {rep['programs']} programs, covers "
        f"{','.join(rep['covers']) or 'nothing'}, {reg.dir}"
    )
    return rep


def verify_bundle(bundle_dir: str) -> Dict:
    """Load + integrity-check a bundle directory; returns its manifest.

    Raises ValueError naming the failure: missing manifest, version
    skew, fingerprint mismatch against this process (field-by-field),
    or an artifact whose sha256 does not match its manifest entry.
    """
    man_path = os.path.join(bundle_dir, registry.MANIFEST_NAME)
    try:
        with open(man_path, "r", encoding="utf-8") as f:
            man = json.load(f)
    except OSError as e:
        raise ValueError(f"bundle has no readable manifest: {e}") from e
    if man.get("version") != registry.MANIFEST_VERSION:
        raise ValueError(
            f"bundle manifest version {man.get('version')!r} != "
            f"{registry.MANIFEST_VERSION}"
        )
    ours = keys.store_fingerprint()
    diff = keys.diff_fingerprints(ours, man.get("fingerprint"))
    if diff:
        raise ValueError(
            "bundle fingerprint is incompatible with this process: "
            + "; ".join(diff)
        )
    for key, entry in (man.get("programs") or {}).items():
        path = os.path.join(bundle_dir, "blobs", key + ".bin")
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise ValueError(f"artifact {key[:12]} unreadable: {e}") from e
        if hashlib.sha256(blob).hexdigest() != entry.get("sha256"):
            raise ValueError(f"artifact {key[:12]} fails its sha256 check")
    return man


def warm(bundle_dir: str, dest_root: Optional[str] = None,
         logger: Optional[Callable[[str], None]] = None) -> Dict:
    """Verify a bundle and install it under the live AOT directory."""
    log = _log_to(logger)
    bundle_dir = os.path.abspath(os.path.expanduser(bundle_dir))
    # accept either a fingerprint directory or a store root holding one
    if not os.path.isfile(os.path.join(bundle_dir, registry.MANIFEST_NAME)):
        ours12 = keys.fingerprint_digest(keys.store_fingerprint())[:12]
        nested = os.path.join(bundle_dir, ours12)
        if os.path.isfile(os.path.join(nested, registry.MANIFEST_NAME)):
            bundle_dir = nested
    man = verify_bundle(bundle_dir)
    root = (
        dest_root
        or settings.get_str("FISHNET_TPU_AOT_DIR")
        or registry.default_dir()
    )
    digest12 = keys.fingerprint_digest(man["fingerprint"])[:12]
    dest = os.path.join(os.path.abspath(os.path.expanduser(root)), digest12)
    if os.path.abspath(bundle_dir) != dest:
        if os.path.isdir(dest):
            shutil.rmtree(dest)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copytree(bundle_dir, dest)
    n = len(man.get("programs") or {})
    log(
        f"warm: installed {n} programs (covers "
        f"{','.join(man.get('covers') or []) or 'nothing'}) into {dest}"
    )
    return {"programs": n, "covers": man.get("covers") or [], "dir": dest}


def main_pack(cfg) -> int:
    """`python -m fishnet_tpu pack` entry (cfg: client/configure.py)."""
    try:
        pack(getattr(cfg, "aot_bundle", None))
        return 0
    except Exception as e:
        print(f"pack failed: {e}", flush=True)
        return 1


def main_warm(cfg) -> int:
    """`python -m fishnet_tpu warm` entry (cfg: client/configure.py)."""
    bundle = getattr(cfg, "aot_bundle", None)
    if not bundle:
        print("warm: --aot-bundle BUNDLE_DIR is required", flush=True)
        return 2
    try:
        warm(bundle, getattr(cfg, "aot_dir", None))
        return 0
    except Exception as e:
        print(f"warm failed: {e}", flush=True)
        return 1
