"""Canonical fingerprints for AOT program assets.

Two layers of keying:

* **Store fingerprint** — everything that invalidates *every* artifact
  at once: jax/jaxlib version, backend + device kind + device count,
  and the raw value of every search-visible settings knob
  (AOT_KEY_SETTINGS). A bundle packed under one fingerprint is never
  loaded under another; `diff_fingerprints` names the exact fields that
  diverged so the rejection is explicit, not a silent cache miss.

* **Program key** — one compiled executable: entry-point name, the
  static (compile-time) arguments, and the abstract signature of the
  dynamic arguments (shape/dtype/weak_type per leaf plus the pytree
  structure). Width buckets, variants, mesh shapes and scalar
  weak-typing all land in this layer naturally, because they change
  either a static argument or a leaf aval.

Everything here is pure computation over strings/avals — no I/O, no
serialization. registry.py owns the store.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

import jax

from ..utils import settings

# Settings whose raw values key the store fingerprint: every knob that
# changes the traced search program or its numerics. Adding a
# search-visible setting without listing it here means stale bundles
# keep loading after the knob flips — list liberally.
AOT_KEY_SETTINGS = (
    "FISHNET_TPU_MAX_PLY",
    "FISHNET_TPU_ASPIRATION",
    "FISHNET_TPU_SELECT_UPDATES",
    "FISHNET_TPU_NO_PRUNING",
    "FISHNET_TPU_DTYPE",
    "FISHNET_TPU_EXPERIMENTAL_INT8",
)


def _jaxlib_version() -> str:
    try:
        import jaxlib

        return str(getattr(jaxlib, "__version__", ""))
    except ImportError:
        return ""


def store_fingerprint() -> Dict[str, Any]:
    """The compatibility envelope of this process's compiled programs.

    Includes the mesh topology (shape, axis names, process count from
    parallel.partition.default_topology): a sharded executable bakes its
    mesh into the compiled program, so a bundle packed on a 1-host mesh
    must be rejected-with-named-diff on a 2-host mesh — loading it would
    deserialize garbage (or deadlock the pod) at dispatch time."""
    try:
        devs = jax.devices()
    except Exception:
        devs = []
    from ..parallel.partition import default_topology

    fp: Dict[str, Any] = {
        "jax": jax.__version__,
        "jaxlib": _jaxlib_version(),
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "",
        "device_count": len(devs),
        "settings": {
            name: settings.raw(name) or "" for name in AOT_KEY_SETTINGS
        },
    }
    fp.update(default_topology())
    return fp


def fingerprint_digest(fp: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True).encode("utf-8")
    ).hexdigest()


def diff_fingerprints(ours: Optional[Dict[str, Any]],
                      theirs: Optional[Dict[str, Any]]) -> List[str]:
    """Field-by-field mismatch list — the explicit compat-rejection path.

    Empty list means compatible. Each entry reads
    ``field: ours=... bundle=...`` so a rejected bundle is diagnosable
    from one log line.
    """

    def flat(fp: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        fp = fp or {}
        out = {k: v for k, v in fp.items() if k != "settings"}
        for k, v in (fp.get("settings") or {}).items():
            out[f"settings.{k}"] = v
        return out

    a, b = flat(ours), flat(theirs)
    return [
        f"{k}: ours={a.get(k)!r} bundle={b.get(k)!r}"
        for k in sorted(set(a) | set(b))
        if a.get(k) != b.get(k)
    ]


def _leaf_sig(x: Any) -> List[Any]:
    try:
        from jax.api_util import shaped_abstractify

        a = shaped_abstractify(x)
        return [
            [int(d) for d in a.shape],
            a.dtype.name,
            bool(getattr(a, "weak_type", False)),
        ]
    except Exception:
        # Non-abstractifiable leaf (opaque host object): key on its type
        # so distinct kinds never alias; such programs simply never
        # share an artifact across leaf types.
        return ["opaque", type(x).__name__]


def abstract_signature(dynamics: Any) -> str:
    """JSON aval signature of a dynamic-argument pytree.

    shape + dtype + weak_type per leaf and the stringified treedef —
    exactly what jit keys its own executable cache on, so two calls
    share an artifact iff jit would have shared a compilation.
    """
    leaves, treedef = jax.tree_util.tree_flatten(dynamics)
    return json.dumps(
        {"tree": str(treedef), "leaves": [_leaf_sig(x) for x in leaves]},
        sort_keys=True,
    )


def static_signature(statics: Dict[str, Any],
                     extra_static: Optional[Dict[str, Any]]) -> str:
    items = {name: repr(v) for name, v in statics.items()}
    for name, v in (extra_static or {}).items():
        items[f"~{name}"] = repr(v)
    return json.dumps(items, sort_keys=True)


def program_key(entry: str, statics: Dict[str, Any],
                extra_static: Optional[Dict[str, Any]],
                dynamics: Any) -> Tuple[str, Dict[str, str]]:
    """(sha256 hex key, manifest metadata) for one executable."""
    stat = static_signature(statics, extra_static)
    avals = abstract_signature(dynamics)
    h = hashlib.sha256()
    for part in (entry, stat, avals):
        h.update(part.encode("utf-8"))
        h.update(b"\x00")
    return h.hexdigest(), {"entry": entry, "statics": stat, "avals": avals}
