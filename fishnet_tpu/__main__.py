"""`python -m fishnet_tpu` entry point."""
import sys

from .client.app import main

if __name__ == "__main__":
    sys.exit(main())
