"""The asyncio HTTP/1.1 front-end: many tenants, one lane pool.

Stdlib only — `asyncio.start_server` plus a hand-rolled HTTP/1.1 layer
(request line, headers, Content-Length bodies, keep-alive). Endpoints:

    POST /analyse    batch analysis  (protocol.py body shape)
    POST /bestmove   play-speed move requests
    GET  /healthz    JSON liveness/occupancy summary
    GET  /fleet/members   fleet health table   (fleet front-ends only)
    POST /fleet/members   runtime membership: add / drain / remove

Every accepted request is stamped with a deadline (its own timeout_ms
clamped by FISHNET_TPU_SERVE_TIMEOUT_MS), passes the admission
controller (429 + Retry-After on saturation, admission.py), and is
expanded into `PositionRequest`s submitted through one shared
`EngineSession` — against the TPU engine all tenants' positions merge
into the LaneScheduler's hardest-deadline-first pending queue.

Graceful drain: SIGTERM/SIGINT closes the listener, in-flight requests
finish (bounded by FISHNET_TPU_SERVE_DRAIN_S), per-tenant totals are
flushed to the log and the metrics registry snapshot, then the process
exits. New requests during the drain get 503 + Connection: close.
"""
from __future__ import annotations

import asyncio
import json
import signal
import time
from typing import Dict, Optional, Tuple

from ..cache.keys import keys_for_requests
from ..cache.store import AnalysisCache
from ..client.ipc import response_to_wire
from ..client.logger import Logger
from ..client.wire import EngineFlavor
from ..engine.base import EngineError
from ..engine.session import EngineSession
from ..obs import inflight as obs_inflight
from ..obs import metrics as obs_metrics
from ..obs import perf as obs_perf
from ..obs import trace as obs_trace
from ..utils import settings
from .admission import AdmissionController, Shed
from .protocol import (
    ProtocolError,
    parse_request,
    results_to_json,
    shed_to_json,
    to_position_requests,
)

# HTTP header carrying an upstream trace id into the serve edge (the
# body field "trace_id" wins when both are present).
TRACE_HEADER = "x-fishnet-trace"

MAX_HEADER_BYTES = 32768
MAX_BODY_BYTES = 4 * 1024 * 1024
# keep-alive idle cutoff: a silent client must not pin a connection
# handler forever
IDLE_TIMEOUT_S = 75.0

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

_ENDPOINTS = {"/analyse": "analysis", "/bestmove": "bestmove"}


class _BadRequest(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class ServeApp:
    """One server instance: listener + admission + shared session."""

    def __init__(
        self,
        session: EngineSession,
        max_inflight: Optional[int] = None,
        max_queue: Optional[int] = None,
        default_timeout_ms: Optional[int] = None,
        drain_s: Optional[float] = None,
        logger: Optional[Logger] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        fleet=None,
        cache: Optional[AnalysisCache] = None,
    ):
        self.session = session
        # the FleetCoordinator behind this front-end, when there is one:
        # enables the /fleet/members runtime-membership admin surface
        self.fleet = fleet
        # the analysis-result cache (fishnet_tpu/cache/), consulted
        # BEFORE admission: a hit costs microseconds and sheds no
        # capacity; only cold positions pay for an admission ticket
        self.cache = cache
        self.logger = logger or Logger()
        if max_inflight is None:
            max_inflight = settings.get_int("FISHNET_TPU_SERVE_MAX_INFLIGHT")
        if max_queue is None:
            max_queue = settings.get_int("FISHNET_TPU_SERVE_MAX_QUEUE")
        if default_timeout_ms is None:
            default_timeout_ms = settings.get_int("FISHNET_TPU_SERVE_TIMEOUT_MS")
        if drain_s is None:
            drain_s = float(settings.get_int("FISHNET_TPU_SERVE_DRAIN_S"))
        self.default_timeout_ms = default_timeout_ms
        self.drain_s = drain_s
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self.admission = AdmissionController(
            max_inflight, max_queue, registry=self.registry
        )
        self.slo = obs_metrics.SloRecorder(self.registry)
        self.inflight = obs_inflight.REGISTRY
        self._server: Optional[asyncio.AbstractServer] = None
        self._draining = False
        self._open_requests = 0
        self._drained = asyncio.Event()

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str, port: int) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_conn, host=host, port=port
        )
        bound = self._server.sockets[0].getsockname()
        return bound[0], bound[1]

    def begin_drain(self) -> None:
        """Stop accepting; in-flight requests run to completion."""
        if self._draining:
            return
        self._draining = True
        self.logger.headline("serve: draining (no new requests)")
        if self._server is not None:
            self._server.close()
        if self._open_requests == 0:
            self._drained.set()

    async def drain_and_stop(self) -> None:
        """Wait for in-flight work (bounded by drain_s), then stop."""
        self.begin_drain()
        try:
            await asyncio.wait_for(self._drained.wait(), timeout=self.drain_s)
        except asyncio.TimeoutError:
            self.logger.warn(
                f"serve: drain grace period ({self.drain_s:.0f}s) expired "
                f"with {self._open_requests} request(s) still open"
            )
        if self._server is not None:
            await self._server.wait_closed()
        self._flush_stats()

    def _flush_stats(self) -> None:
        snap = self.registry.snapshot()
        served = {
            k: v for k, v in sorted(snap.items())
            if k.startswith("fishnet_serve_") and not k.endswith("_sum")
        }
        parts = ", ".join(f"{k.removeprefix('fishnet_serve_')}={int(v)}"
                          for k, v in served.items())
        self.logger.headline(f"serve: final stats: {parts or 'no requests'}")

    # ------------------------------------------------------------ transport

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    request = await asyncio.wait_for(
                        self._read_request(reader), timeout=IDLE_TIMEOUT_S
                    )
                except asyncio.TimeoutError:
                    break
                if request is None:  # clean EOF between requests
                    break
                method, path, headers, body = request
                want_close = (
                    headers.get("connection", "").lower() == "close"
                    or self._draining
                )
                status, payload, extra = await self._dispatch(
                    method, path, headers, body
                )
                await self._write_response(
                    writer, status, payload, extra, close=want_close
                )
                if want_close:
                    break
        except _BadRequest as e:
            # malformed transport framing: answer once and hang up
            try:
                await self._write_response(
                    writer, e.status, {"error": e.message}, {}, close=True
                )
            except (ConnectionError, OSError):
                pass  # peer already gone; nothing to answer
        except (ConnectionError, asyncio.IncompleteReadError, OSError) as e:
            self.logger.debug(f"serve: connection dropped: {e}")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass  # close raced the peer's reset; already closed

    async def _read_request(
        self, reader: asyncio.StreamReader
    ) -> Optional[Tuple[str, str, Dict[str, str], bytes]]:
        line = await reader.readline()
        if not line:
            return None
        try:
            parts = line.decode("latin-1").split()
            method, target, _version = parts[0], parts[1], parts[2]
        except (IndexError, UnicodeDecodeError):
            raise _BadRequest(400, "malformed request line") from None
        headers: Dict[str, str] = {}
        total = len(line)
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            total += len(h)
            if total > MAX_HEADER_BYTES:
                raise _BadRequest(400, "headers too large")
            name, sep, value = h.decode("latin-1").partition(":")
            if not sep:
                raise _BadRequest(400, "malformed header")
            headers[name.strip().lower()] = value.strip()
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            raise _BadRequest(400, "bad Content-Length") from None
        if length > MAX_BODY_BYTES:
            raise _BadRequest(413, "body too large")
        body = await reader.readexactly(length) if length > 0 else b""
        return method, target.split("?", 1)[0], headers, body

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        payload: dict,
        extra_headers: Dict[str, str],
        close: bool,
    ) -> None:
        body = json.dumps(payload).encode("utf-8")
        lines = [
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}",
            "Content-Type: application/json",
            f"Content-Length: {len(body)}",
            f"Connection: {'close' if close else 'keep-alive'}",
        ]
        lines.extend(f"{k}: {v}" for k, v in extra_headers.items())
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body)
        await writer.drain()

    # ------------------------------------------------------------ handlers

    async def _dispatch(
        self, method: str, path: str, headers: Dict[str, str], body: bytes
    ) -> Tuple[int, dict, Dict[str, str]]:
        if path == "/healthz":
            if method != "GET":
                return 405, {"error": "use GET"}, {}
            inflight, queued = self.admission.occupancy()
            return 200, {
                "status": "draining" if self._draining else "ok",
                "inflight": inflight,
                "queued": queued,
                "drain_rate_pos_per_s": round(self.admission.drain_rate(), 3),
                "cache": (
                    self.cache.counters() if self.cache is not None else None
                ),
            }, {}
        if path == "/debug/requests":
            if method != "GET":
                return 405, {"error": "use GET"}, {}
            reqs = self.inflight.snapshot()
            return 200, {"inflight": len(reqs), "requests": reqs}, {}
        if path == "/debug/perf":
            if method != "GET":
                return 405, {"error": "use GET"}, {}
            # current perf snapshot next to the last ledger baseline
            # (obs/perf.py, docs/perf.md); `python -m fishnet_tpu perf`
            # renders this payload as a table
            from ..obs import perf as obs_perf

            return 200, obs_perf.live_snapshot(), {}
        if path == "/fleet/members":
            return await self._fleet_members(method, body)
        kind = _ENDPOINTS.get(path)
        if kind is None:
            return 404, {"error": f"no such endpoint {path}"}, {}
        if method != "POST":
            return 405, {"error": "use POST"}, {}
        if self._draining:
            return 503, {"error": "draining"}, {"Retry-After": "5"}
        try:
            obj = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return 400, {"error": "body is not valid JSON"}, {}
        try:
            sreq = parse_request(kind, obj)
        except ProtocolError as e:
            return 400, {"error": str(e)}, {}
        return await self._serve_request(
            sreq, upstream_trace=headers.get(TRACE_HEADER, "")
        )

    async def _fleet_members(
        self, method: str, body: bytes
    ) -> Tuple[int, dict, Dict[str, str]]:
        """Runtime membership (docs/fleet.md rolling restarts): GET is
        the coordinator's health table; POST takes {"action": "add",
        "spec": ...} | {"action": "drain"|"remove", "member": ...}.
        State conflicts (undrained removal, duplicate add) answer 409."""
        if self.fleet is None:
            return 404, {"error": "not a fleet front-end"}, {}
        if method == "GET":
            return 200, self.fleet.health(), {}
        if method != "POST":
            return 405, {"error": "use GET or POST"}, {}
        try:
            obj = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return 400, {"error": "body is not valid JSON"}, {}
        if not isinstance(obj, dict):
            return 400, {"error": "body must be a JSON object"}, {}
        action = obj.get("action")
        try:
            if action == "add":
                row = await self.fleet.add_member(
                    str(obj.get("spec") or "")
                )
                return 200, {"ok": True, "member": row}, {}
            if action == "drain":
                out = self.fleet.drain_member(
                    str(obj.get("member") or "")
                )
                return 200, {"ok": True, **out}, {}
            if action == "remove":
                row = await self.fleet.remove_member(
                    str(obj.get("member") or ""),
                    force=bool(obj.get("force")),
                )
                return 200, {"ok": True, "member": row}, {}
        except EngineError as e:
            return 409, {"error": str(e)}, {}
        return 400, {
            "error": f"unknown action {action!r} "
                     "(use add / drain / remove)"
        }, {}

    async def _serve_request(
        self, sreq, upstream_trace: str = ""
    ) -> Tuple[int, dict, Dict[str, str]]:
        timeout_ms = min(
            sreq.timeout_ms or self.default_timeout_ms, self.default_timeout_ms
        )
        t0 = time.monotonic()
        deadline = t0 + timeout_ms / 1000.0
        # The edge stamp: every request gets a context (the in-flight
        # registry and SLO accounting key on it even with tracing off);
        # spans/flow links are additionally gated on the recorder and
        # the deterministic sampling verdict for this trace_id.
        ctx = obs_trace.make_ctx(
            sreq.tenant, sreq.kind, deadline_ms=timeout_ms,
            trace_id=sreq.trace_id or upstream_trace or None,
        )
        tid = ctx["trace_id"]
        rec = obs_trace.RECORDER
        traced = rec is not None and obs_trace.sampled(tid)
        self.inflight.begin(
            tid, sreq.id, sreq.tenant, sreq.kind,
            deadline_mono_s=deadline, n_positions=len(sreq.positions),
        )
        self._open_requests += 1
        try:
            with (rec.span("http.request", "serve",
                           **obs_trace.ctx_args(ctx, id=sreq.id,
                                                n=len(sreq.positions)))
                  if traced else obs_trace.NULL_SPAN):
                if traced:
                    rec.flow("request", tid, "s")
                preqs = to_position_requests(sreq, deadline, ctx=ctx)
                n = len(preqs)
                cache = self.cache
                # cache consult (docs/caching.md): classify every
                # position as hit (served from store), join (an
                # identical search is already in flight — one search,
                # N deliveries) or lead (cold; we search and fill)
                hydrated: Dict[int, object] = {}
                joins: Dict[int, "asyncio.Future"] = {}
                leases: Dict[int, object] = {}
                keys = None
                if cache is not None:
                    flavor = getattr(self.session, "flavor", EngineFlavor.TPU)
                    keys = keys_for_requests(preqs, cache.net, flavor=flavor)
                    for i, (key, depth) in enumerate(keys):
                        state, val = cache.lease(key, depth)
                        if state == "hit":
                            hydrated[i] = AnalysisCache.hydrate(val, i)
                        elif state == "join":
                            joins[i] = val
                        else:
                            leases[i] = val
                        if traced:
                            rec.instant(
                                "cache.hit" if state != "lead"
                                else "cache.miss",
                                "serve",
                                **obs_trace.ctx_args(
                                    ctx, position_index=i,
                                    coalesced=state == "join",
                                ))
                cold = sorted(leases) if cache is not None else list(range(n))
                fallback: list = []
                try:
                    ticket = None
                    if cold or cache is None:
                        # only cold positions pay for admission: an
                        # all-hit request never touches the waiting room
                        try:
                            with (rec.span("serve.admission", "serve",
                                           **obs_trace.ctx_args(ctx))
                                  if traced else obs_trace.NULL_SPAN):
                                ticket = await self.admission.admit(
                                    sreq.tenant,
                                    len(cold) if cache is not None else n,
                                    deadline, sreq.priority,
                                )
                        except Shed as e:
                            self.slo.shed(sreq.tenant, sreq.kind)
                            return 429, shed_to_json(
                                e.retry_after, e.reason
                            ), {"Retry-After": str(e.retry_after)}
                    self.inflight.stage(tid, "admitted")
                    queue_ms = (time.monotonic() - t0) * 1000.0
                    ok = False
                    try:
                        self.inflight.stage(tid, "dispatched")
                        searched = (
                            await self.session.submit_many(
                                [preqs[i] for i in cold]
                            ) if cold else []
                        )
                        ok = True
                    except EngineError as e:
                        self.logger.error(f"serve: engine error: {e}")
                        return 500, {"error": f"engine error: {e}"}, {}
                    finally:
                        if ticket is not None:
                            self.admission.release(ticket, ok=ok)
                    for i, resp in zip(cold, searched):
                        hydrated[i] = resp
                        if keys is not None:
                            # fill + settle: followers coalesced onto
                            # this search get the same wire result
                            # (store() is idempotent — the engine-side
                            # delivery hook may have filled already)
                            wire = response_to_wire(resp)
                            key, depth = keys[i]
                            cache.store(key, depth, wire)
                            leases[i].settle(dict(wire))
                    for i, fut in joins.items():
                        try:
                            wire = await asyncio.wait_for(
                                asyncio.shield(fut),
                                timeout=max(
                                    0.0, deadline - time.monotonic()
                                ),
                            )
                        except (asyncio.TimeoutError,
                                asyncio.CancelledError):
                            wire = None
                        if wire is None:
                            # the leader's search failed or outran our
                            # deadline: fall back to our own search
                            fallback.append(i)
                        else:
                            hydrated[i] = AnalysisCache.hydrate(wire, i)
                    if fallback:
                        try:
                            fb = await self.session.submit_many(
                                [preqs[i] for i in fallback]
                            )
                        except EngineError as e:
                            self.logger.error(f"serve: engine error: {e}")
                            return 500, {"error": f"engine error: {e}"}, {}
                        for i, resp in zip(fallback, fb):
                            hydrated[i] = resp
                finally:
                    if cache is not None:
                        for lease in leases.values():
                            # no-op for settled leases; an error path
                            # resolves followers to None (search-your-
                            # own) instead of wedging them
                            lease.settle(None)
                responses = [hydrated[i] for i in range(n)]
                now = time.monotonic()
                total_ms = (now - t0) * 1000.0
                device_ms = max(
                    (r.time_s for r in responses), default=0.0
                ) * 1000.0
                self.slo.observe(
                    sreq.tenant, sreq.kind, total_ms,
                    queue_ms=queue_ms,
                    device_ms=device_ms,
                    deadline_missed=now > deadline,
                )
                if traced:
                    # the histogram observation rides the dump so
                    # trace_report --request can crosscheck the
                    # reconstructed waterfall against what the SLO
                    # accounting actually recorded (same idiom as the
                    # segment spans carrying their SyncStats args)
                    rec.instant(
                        "slo.observe", "serve",
                        **obs_trace.ctx_args(
                            ctx, total_ms=total_ms, queue_ms=queue_ms,
                            device_ms=device_ms,
                            deadline_missed=now > deadline,
                        ))
                    rec.flow("request", tid, "f")
                extra: Dict[str, str] = {}
                if cache is not None:
                    served = n - len(cold) - len(fallback)
                    extra["X-Fishnet-Cache"] = (
                        "hit" if n and served == n
                        else "partial" if served else "miss"
                    )
                    cache.observe_request(sreq.tenant, served, n)
                    cache.export_metrics()
                return 200, results_to_json(sreq, responses, now - t0), extra
        finally:
            self.inflight.end(tid)
            self._open_requests -= 1
            if self._draining and self._open_requests == 0:
                self._drained.set()


async def run_serve(cfg) -> int:
    """`python -m fishnet_tpu serve` entry: build the engine for the
    configured backend, share it through one EngineSession, serve until
    SIGTERM/SIGINT, drain, exit."""
    from ..client.app import make_engine_factory
    from ..client.wire import EngineFlavor

    logger = Logger(verbose=cfg.verbose)
    if obs_trace.RECORDER is None:
        # serve is its own trace edge: the request-scoped http/admission
        # spans and the flow chain start here (no-op without TRACE_DIR)
        obs_trace.install_from_settings("serve")
    host = cfg.serve_host or settings.get_str("FISHNET_TPU_SERVE_HOST")
    port = (
        cfg.serve_port
        if cfg.serve_port is not None
        else settings.get_int("FISHNET_TPU_SERVE_PORT")
    )

    factory = make_engine_factory(cfg, logger)
    flavor = (
        EngineFlavor.TPU if cfg.backend == "tpu" else EngineFlavor.OFFICIAL
    )
    engine = factory(flavor)
    if getattr(cfg, "fleet", False):
        # fleet front door: the coordinator spawns its local members
        # (remote ones need no warmup) before the listener opens
        logger.info("serve: starting fleet members ...")
        await engine.start()
        logger.info("serve: fleet coordinator ready.")
    elif cfg.backend == "tpu":
        logger.info("serve: warming up TPU engine (compiling search program) ...")
        if cfg.supervisor:
            await engine.start()
            logger.info("serve: supervised TPU engine host ready.")
        else:
            await asyncio.to_thread(engine.warmup, None, logger.info)
            logger.info("serve: TPU engine ready.")
        # autoscaling cold-start signal (docs/aot.md): a replica booted
        # from an AOT bundle reached this point without compiling, so
        # it can accept traffic the moment the listener opens
        from ..aot import registry as aot_registry

        rep = getattr(engine, "aot_report", None) or aot_registry.boot_report()
        if rep.get("enabled"):
            logger.info(
                f"serve: AOT assets — {rep.get('programs', 0)} programs "
                f"(bundle {rep.get('fingerprint', '?')}, covers "
                f"{','.join(rep.get('covers') or []) or 'none'})"
            )

    session = EngineSession(engine, flavor=flavor)
    cache = None
    if getattr(cfg, "cache", True):
        from ..cache import attach_ttwarm, cache_from_settings
        from ..cache import attach_engine as cache_attach_engine

        if getattr(cfg, "fleet", False):
            # the coordinator object carries no net of its own: pin the
            # identity inputs from the config its members are built with
            # so the fingerprint tracks netswaps (cache/keys.py)
            if getattr(engine, "weights_path", None) is None:
                engine.weights_path = cfg.tpu_weights
            if getattr(engine, "max_depth", None) is None:
                engine.max_depth = cfg.tpu_depth
        cache = cache_from_settings(
            engine, flavor, logger=logger,
            directory=getattr(cfg, "cache_dir", None),
        )
    if cache is not None:
        logger.info(
            f"serve: analysis cache on (identity {cache.net}, "
            f"{'persisted' if cache.recorder is not None else 'memory-only'})"
        )
        if getattr(cfg, "fleet", False):
            # fleet: consult + fill at the coordinator so N members
            # share one hit set (exactly-once via the ack journal path)
            engine.attach_cache(cache)
        else:
            # direct engine: fill from the exactly-once delivery hook
            cache_attach_engine(engine, cache)
            if attach_ttwarm(engine, logger=logger) is not None:
                logger.info(
                    "serve: TT warm slices on "
                    f"(prefix {engine.tt_warm_prefix} plies)"
                )
    app = ServeApp(
        session, logger=logger,
        fleet=engine if getattr(cfg, "fleet", False) else None,
        cache=cache,
    )
    bound_host, bound_port = await app.start(host, port)
    # the smoke client and bench parse this exact line to find an
    # ephemeral port (FISHNET_TPU_SERVE_PORT=0)
    logger.headline(f"serve: listening on {bound_host}:{bound_port}")

    try:
        obs_perf.register_build_info()
    except (ImportError, TypeError, ValueError):
        pass  # build-info gauge is best-effort decoration
    metrics_server = obs_metrics.serve_from_settings()
    if metrics_server is not None:
        logger.info(
            "serve: metrics at "
            f"http://127.0.0.1:{metrics_server.server_address[1]}/metrics"
        )

    # elastic capacity (fleet/autoscaler.py): only meaningful with a
    # fleet engine — the control loop drives the coordinator's runtime
    # membership off this app's admission/SLO signals. Starts after the
    # listener opens (the floor fleet is already warm) and stops before
    # drain so no membership change races the shutdown.
    autoscaler = None
    autoscale_on = (
        cfg.autoscale if getattr(cfg, "autoscale", None) is not None
        else settings.get_bool("FISHNET_TPU_AUTOSCALE")
    )
    if autoscale_on and getattr(cfg, "fleet", False):
        from ..fleet.autoscaler import AutoscaleConfig, Autoscaler

        as_cfg = AutoscaleConfig.from_settings()
        if getattr(cfg, "autoscale_min", None) is not None or \
                getattr(cfg, "autoscale_max", None) is not None:
            from dataclasses import replace as _dc_replace

            kw = {}
            if getattr(cfg, "autoscale_min", None) is not None:
                kw["min_members"] = cfg.autoscale_min
            if getattr(cfg, "autoscale_max", None) is not None:
                kw["max_members"] = cfg.autoscale_max
            as_cfg = _dc_replace(as_cfg, **kw)
        autoscaler = Autoscaler(
            engine, app.admission, config=as_cfg, logger=logger,
        )
        autoscaler.start()
        logger.info(
            f"serve: autoscaler on (members {as_cfg.min_members}.."
            f"{as_cfg.max_members}, tick {as_cfg.interval_s:g}s)"
        )
    elif autoscale_on:
        logger.info("serve: autoscale requested without --fleet; off.")

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    try:
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        loop.add_signal_handler(signal.SIGINT, stop.set)
    except NotImplementedError:
        pass  # non-unix
    await stop.wait()  # fishnet-lint: disable=conc-no-timeout
    if autoscaler is not None:
        await autoscaler.stop()
    await app.drain_and_stop()
    await session.close()
    await engine.close()
    rec = obs_trace.RECORDER
    trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
    if rec is not None and trace_dir:
        # the serve ring holds the merged timeline (supervised members'
        # events were absorbed as they streamed); one dump at drain is
        # the whole request waterfall, edge to lane
        path = rec.flight_dump(trace_dir, "serve-final")
        logger.info(f"serve: trace dumped to {path}")
    logger.headline("serve: bye.")
    return 0
