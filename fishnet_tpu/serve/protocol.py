"""Request/response serde for the serving endpoint.

One JSON object per HTTP request. The two endpoints share a body shape:

    POST /analyse   {"id": "...", "tenant": "team-a",
                     "variant": "standard",
                     "positions": [{"fen": "...", "moves": ["e2e4", ...]},
                                   ...],
                     "depth": 8, "multipv": 1, "nodes": 400000,
                     "priority": "batch", "timeout_ms": 6000}
    POST /bestmove  {"id": "...", "tenant": "bot-x",
                     "positions": [{"fen": "...", "moves": [...]}],
                     "level": 6, "priority": "interactive"}

and a response shape mirroring the pipe-wire PositionResponse form
(client/ipc.py response_to_wire — scores/pvs matrices, best_move, depth,
nodes, time_s, nps), one result per position in request order:

    {"id": "...", "results": [{...}, ...], "latency_ms": 12.3}

The echoed "id" is the exactly-once handle smoke clients assert on.
Backpressure replies are JSON too: {"error": "...", "retry_after": N}
with HTTP 429 and a Retry-After header.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..client.ipc import PositionResponse, response_to_wire
from ..engine.session import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PositionRequest,
)
from ..obs import trace as obs_trace

MAX_POSITIONS_PER_REQUEST = 64
MAX_MOVES_PER_POSITION = 1024

_PRIORITY_NAMES = {
    "interactive": PRIORITY_INTERACTIVE,
    "batch": PRIORITY_BATCH,
}
_PRIORITY_VALUES = {v: k for k, v in _PRIORITY_NAMES.items()}


class ProtocolError(ValueError):
    """Malformed request body; the server answers HTTP 400 with this
    message."""


@dataclass(frozen=True)
class ServeRequest:
    """One parsed request body (either endpoint).

    trace_id carries an upstream trace across the HTTP hop (the
    X-Fishnet-Trace body field / header; the fleet's remote members use
    it to keep one causal chain when a chunk is re-dispatched to a
    `fishnet-tpu serve` endpoint). position_ctx is the per-position
    request context in the same order as positions — per-position
    because a re-dispatched sub-chunk can mix positions from different
    upstream requests. Both default to "absent" and never influence the
    search; they are frozen tuples so the dataclass stays hashable.
    """

    kind: str  # "analysis" | "bestmove"
    positions: Tuple[Tuple[str, Tuple[str, ...]], ...]  # (fen, moves)
    id: str = ""
    tenant: str = "default"
    variant: str = "standard"
    depth: Optional[int] = None
    multipv: Optional[int] = None
    nodes: Optional[int] = None
    level: int = 8
    priority: int = PRIORITY_BATCH
    timeout_ms: Optional[int] = None
    trace_id: str = ""
    position_ctx: Tuple[Optional[Tuple[Tuple[str, object], ...]], ...] = ()


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ProtocolError(msg)


def _parse_positions(obj: dict):
    raw = obj.get("positions")
    _require(isinstance(raw, list) and raw, "positions must be a non-empty list")
    _require(
        len(raw) <= MAX_POSITIONS_PER_REQUEST,
        f"at most {MAX_POSITIONS_PER_REQUEST} positions per request",
    )
    out = []
    ctxs = []
    for p in raw:
        _require(isinstance(p, dict), "each position must be an object")
        fen = p.get("fen")
        _require(isinstance(fen, str) and bool(fen.strip()), "position.fen required")
        moves = p.get("moves", [])
        _require(
            isinstance(moves, list) and all(isinstance(m, str) for m in moves),
            "position.moves must be a list of UCI strings",
        )
        _require(
            len(moves) <= MAX_MOVES_PER_POSITION,
            f"at most {MAX_MOVES_PER_POSITION} moves per position",
        )
        out.append((fen, tuple(moves)))
        # foreign/garbage ctx degrades to None, never a 400: the context
        # is observability metadata, not part of the request contract
        ctx = obs_trace.ctx_from_wire(p.get("ctx"))
        ctxs.append(PositionRequest.freeze_ctx(ctx))
    if not any(c is not None for c in ctxs):
        ctxs = []
    return tuple(out), tuple(ctxs)


def _opt_int(obj: dict, key: str, lo: int, hi: int) -> Optional[int]:
    v = obj.get(key)
    if v is None:
        return None
    _require(isinstance(v, int) and not isinstance(v, bool), f"{key} must be an integer")
    _require(lo <= v <= hi, f"{key} out of range [{lo}, {hi}]")
    return v


def parse_request(kind: str, obj: object) -> ServeRequest:
    """Validate one JSON body for /analyse or /bestmove."""
    _require(kind in ("analysis", "bestmove"), f"unknown request kind {kind!r}")
    _require(isinstance(obj, dict), "request body must be a JSON object")
    assert isinstance(obj, dict)
    rid = obj.get("id", "")
    _require(isinstance(rid, str) and len(rid) <= 64, "id must be a string <= 64 chars")
    tenant = obj.get("tenant", "default")
    _require(
        isinstance(tenant, str) and 0 < len(tenant) <= 32,
        "tenant must be a non-empty string <= 32 chars",
    )
    variant = obj.get("variant", "standard")
    _require(isinstance(variant, str) and bool(variant), "variant must be a string")
    priority_name = obj.get(
        "priority", "interactive" if kind == "bestmove" else "batch"
    )
    _require(
        priority_name in _PRIORITY_NAMES,
        f"priority must be one of {sorted(_PRIORITY_NAMES)}",
    )
    level = obj.get("level", 8)
    _require(
        isinstance(level, int) and not isinstance(level, bool) and 1 <= level <= 8,
        "level must be an integer in 1..8",
    )
    trace_id = obj.get("trace_id", "")
    _require(
        isinstance(trace_id, str) and len(trace_id) <= 32,
        "trace_id must be a string <= 32 chars",
    )
    positions, position_ctx = _parse_positions(obj)
    return ServeRequest(
        kind=kind,
        positions=positions,
        id=rid,
        tenant=tenant,
        variant=variant,
        depth=_opt_int(obj, "depth", 1, 64),
        multipv=_opt_int(obj, "multipv", 1, 5),
        nodes=_opt_int(obj, "nodes", 1, 1_000_000_000),
        level=level,
        priority=_PRIORITY_NAMES[priority_name],
        timeout_ms=_opt_int(obj, "timeout_ms", 1, 600_000),
        trace_id=trace_id,
        position_ctx=position_ctx,
    )


def request_to_json(req: ServeRequest) -> dict:
    """Inverse of parse_request (round-trip tested; the smoke client and
    bench build bodies through this so the two sides can't drift)."""
    out: dict = {
        "positions": [
            {"fen": fen, "moves": list(moves)} for fen, moves in req.positions
        ],
        "priority": _PRIORITY_VALUES[req.priority],
    }
    if req.position_ctx:
        for slot, frozen in enumerate(req.position_ctx):
            if frozen is not None:
                out["positions"][slot]["ctx"] = dict(frozen)
    if req.trace_id:
        out["trace_id"] = req.trace_id
    if req.id:
        out["id"] = req.id
    if req.tenant != "default":
        out["tenant"] = req.tenant
    if req.variant != "standard":
        out["variant"] = req.variant
    if req.kind == "bestmove":
        out["level"] = req.level
    for key in ("depth", "multipv", "nodes", "timeout_ms"):
        v = getattr(req, key)
        if v is not None:
            out[key] = v
    return out


def to_position_requests(
    req: ServeRequest, deadline: float, ctx: Optional[dict] = None
) -> List[PositionRequest]:
    """Expand one admitted request into PositionRequests sharing the
    deadline the admission controller stamped on it.

    ctx is the request context the HTTP edge stamped (obs/trace.py
    make_ctx); positions that arrived with their OWN wire context — a
    fleet re-dispatch forwarding someone else's positions — keep it,
    so the original edge's trace_id survives the extra HTTP hop."""
    out = []
    for slot, (fen, moves) in enumerate(req.positions):
        own = (req.position_ctx[slot]
               if slot < len(req.position_ctx) else None)
        out.append(PositionRequest(
            fen=fen,
            moves=moves,
            variant=req.variant,
            kind=req.kind,
            depth=req.depth,
            multipv=req.multipv,
            nodes=req.nodes,
            level=req.level,
            deadline=deadline,
            priority=req.priority,
            trace_ctx=own if own is not None
            else PositionRequest.freeze_ctx(ctx),
        ))
    return out


def results_to_json(
    req: ServeRequest, responses: List[PositionResponse], latency_s: float
) -> dict:
    results = []
    for res in responses:
        wire = response_to_wire(res)
        # position_index/url are chunk-protocol bookkeeping; the HTTP
        # answer is ordered by the request's own positions list
        wire.pop("position_index", None)
        wire.pop("url", None)
        results.append(wire)
    out = {"results": results, "latency_ms": round(latency_s * 1000.0, 3)}
    if req.id:
        out["id"] = req.id
    return out


def shed_to_json(retry_after: int, reason: str) -> dict:
    return {"error": reason, "retry_after": int(retry_after)}
