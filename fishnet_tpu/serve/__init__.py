"""fishnet-tpu serve: the multi-tenant analysis-serving subsystem.

The reference is a long-poll *client* of lichess; this package inverts
it (ROADMAP.md "New directions" #1): `python -m fishnet_tpu serve` runs
an asyncio HTTP/JSON endpoint that many concurrent callers multiplex
into. Requests become `PositionRequest`s (engine/session.py) with a
per-request deadline and priority, pass an admission controller with a
bounded waiting room (admission.py), and feed the same lane pool the
lichess client and bench feed — against the TPU engine, every tenant's
positions land in the LaneScheduler's hardest-deadline-first pending
queue.

Stdlib only: asyncio.start_server plus a minimal HTTP/1.1 layer
(server.py); serde in protocol.py. docs/serving.md is the protocol and
operations reference.
"""
from .admission import AdmissionController, Shed
from .protocol import ProtocolError, ServeRequest, parse_request, request_to_json
from .server import ServeApp, run_serve

__all__ = [
    "AdmissionController",
    "ProtocolError",
    "ServeApp",
    "ServeRequest",
    "Shed",
    "parse_request",
    "request_to_json",
    "run_serve",
]
