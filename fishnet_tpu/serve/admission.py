"""Admission control: bounded in-flight depth, backpressure, shedding.

The LaneScheduler absorbs any number of pending positions, but an HTTP
front-end must not convert overload into an unbounded queue of doomed
requests — the reference client's own 429 handling (client/api.py)
assumes servers shed. Policy:

- at most `max_inflight` positions are inside the engine at once (sized
  to the lane pool: beyond it, extra admissions only deepen the
  scheduler's pending queue and every deadline slips together);
- up to `max_queue` further positions may wait in an ordered waiting
  room. Admission order is (priority tier, deadline): interactive
  bestmove outranks batch analysis, and within a tier the hardest
  deadline goes first — the same key the LaneScheduler uses, so the
  waiting room never inverts the device-side order;
- past that, requests are shed immediately with `Shed` → HTTP 429 and a
  Retry-After derived from the measured drain rate: an EWMA of completed
  positions/second, divided into the current backlog. Saturation sheds
  in microseconds instead of holding sockets open;
- a waiter whose own deadline expires before a slot frees is shed too
  (it could only miss).

Per-tenant counters land in the obs/metrics registry
(`fishnet_serve_*`): requests/positions/sheds per tenant plus a request
latency histogram — the occupancy gauges from the scheduler next to the
shed rate are the autoscaling signal (docs/serving.md).

Event-loop native: admit() is async and the state is only touched from
the server's loop, so no lock is needed; metrics objects carry their
own locks.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools
import time
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics

RETRY_AFTER_MIN_S = 1
RETRY_AFTER_MAX_S = 60

# EWMA horizon for the drain rate: ~30 completed requests of memory.
_DRAIN_ALPHA = 1.0 / 30.0


class Shed(Exception):
    """Request refused at admission; carries the Retry-After hint."""

    def __init__(self, retry_after: int, reason: str):
        super().__init__(reason)
        self.retry_after = retry_after
        self.reason = reason


class Ticket:
    """One admitted request's claim on in-flight capacity."""

    __slots__ = ("tenant", "n_positions", "admitted_at")

    def __init__(self, tenant: str, n_positions: int, admitted_at: float):
        self.tenant = tenant
        self.n_positions = n_positions
        self.admitted_at = admitted_at


class AdmissionController:
    def __init__(
        self,
        max_inflight: int,
        max_queue: int,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ):
        assert max_inflight >= 1
        self.max_inflight = max_inflight
        self.max_queue = max_queue
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self._inflight = 0  # positions inside the engine
        self._queued = 0  # positions waiting for a slot
        # waiting room: (priority, deadline, seq) → hardest first
        self._waiters: List[Tuple[int, float, int, dict]] = []
        self._seq = itertools.count()
        # measured drain rate (positions/s), EWMA over completions
        self._drain_rate = 0.0
        self._last_release = time.monotonic()
        self._g_inflight = self.registry.gauge(
            "fishnet_serve_inflight",
            "positions currently admitted into the engine",
        )
        self._g_queued = self.registry.gauge(
            "fishnet_serve_queued",
            "positions waiting for an in-flight slot",
        )

    # ------------------------------------------------------------ metrics

    def _tenant_counter(self, what: str, tenant: str) -> obs_metrics.Counter:
        return self.registry.counter(
            f"fishnet_serve_{what}_total_{tenant}",
            f"served {what} for tenant {tenant}",
        )

    def _latency_histogram(self, tenant: str) -> obs_metrics.Histogram:
        return self.registry.histogram(
            f"fishnet_serve_latency_ms_{tenant}",
            f"request latency (ms) for tenant {tenant}",
        )

    # ------------------------------------------------------- admission

    def occupancy(self) -> Tuple[int, int]:
        return self._inflight, self._queued

    def drain_rate(self) -> float:
        return self._drain_rate

    def retry_after(self, extra_positions: int = 0) -> int:
        """Seconds until the current backlog plausibly drains: backlog
        over the measured drain rate, clamped to [1, 60]. With no drain
        history yet, the cap — a cold saturated server can only guess
        pessimistically."""
        backlog = self._inflight + self._queued + extra_positions
        if self._drain_rate <= 0.0:
            return RETRY_AFTER_MAX_S
        est = backlog / self._drain_rate
        return max(RETRY_AFTER_MIN_S, min(RETRY_AFTER_MAX_S, int(est) + 1))

    def _shed(self, tenant: str, n: int, reason: str) -> Shed:
        self._tenant_counter("shed", tenant).inc()
        return Shed(self.retry_after(extra_positions=n), reason)

    async def admit(
        self, tenant: str, n_positions: int, deadline: float, priority: int
    ) -> Ticket:
        """Claim n_positions of in-flight capacity, waiting in the
        bounded room if full; raises Shed when the room overflows or the
        deadline can't be met."""
        self._tenant_counter("requests", tenant).inc()
        now = time.monotonic()
        if deadline <= now:
            raise self._shed(tenant, n_positions, "deadline already expired")
        if self._inflight + n_positions <= self.max_inflight and not self._waiters:
            return self._grant(tenant, n_positions)
        if self._queued + n_positions > self.max_queue:
            raise self._shed(tenant, n_positions, "server saturated")
        slot = {
            "future": asyncio.get_running_loop().create_future(),
            "tenant": tenant,
            "n": n_positions,
        }
        heapq.heappush(
            self._waiters, (priority, deadline, next(self._seq), slot)
        )
        self._queued += n_positions
        self._g_queued.set(self._queued)
        try:
            timeout = deadline - time.monotonic()
            return await asyncio.wait_for(slot["future"], timeout=timeout)
        except asyncio.TimeoutError:
            raise self._shed(
                tenant, 0, "deadline expired waiting for capacity"
            ) from None
        finally:
            if not slot["future"].done():
                slot["future"].cancel()
            self._evict(slot)

    def _grant(self, tenant: str, n_positions: int) -> Ticket:
        self._inflight += n_positions
        self._g_inflight.set(self._inflight)
        self._tenant_counter("positions", tenant).inc(n_positions)
        return Ticket(tenant, n_positions, time.monotonic())

    def _evict(self, slot: dict) -> None:
        """Drop a cancelled/timed-out waiter from the room accounting (the
        heap entry is lazily skipped by _pump once its future is done)."""
        if slot.get("evicted"):
            return
        slot["evicted"] = True
        self._queued -= slot["n"]
        self._g_queued.set(self._queued)

    def _pump(self) -> None:
        """Admit waiters while capacity allows — hardest (priority,
        deadline) first."""
        while self._waiters:
            _prio, _dl, _seq, slot = self._waiters[0]
            fut = slot["future"]
            if fut.done():  # timed out / cancelled; already evicted
                heapq.heappop(self._waiters)
                continue
            if self._inflight + slot["n"] > self.max_inflight:
                return
            heapq.heappop(self._waiters)
            self._evict(slot)
            fut.set_result(self._grant(slot["tenant"], slot["n"]))

    def release(self, ticket: Ticket, ok: bool = True) -> None:
        """Return capacity; feeds the drain-rate EWMA and the per-tenant
        latency histogram, then admits eligible waiters."""
        now = time.monotonic()
        self._inflight -= ticket.n_positions
        self._g_inflight.set(self._inflight)
        if ok:
            dt = max(now - ticket.admitted_at, 1e-6)
            inst = ticket.n_positions / dt
            if self._drain_rate <= 0.0:
                self._drain_rate = inst
            else:
                self._drain_rate += _DRAIN_ALPHA * (inst - self._drain_rate)
            self._latency_histogram(ticket.tenant).observe(dt * 1000.0)
            self._tenant_counter("completed", ticket.tenant).inc()
        else:
            self._tenant_counter("failed", ticket.tenant).inc()
        self._pump()
