"""Fleet-wide analysis memoization (docs/caching.md).

Real traffic is heavily Zipf-skewed — opening positions, famous games
and puzzle boards repeat across millions of users — yet a search is
deterministic given (position, search shape, net): the same request
always earns the same answer. This package never searches the same
position twice:

* `keys.py` — the ONE canonical cache-key builder: a content-only
  position fingerprint (no chunk slot index) plus the normalized search
  shape (kind, variant, multipv, effective node budget, level) and the
  engine identity fingerprint (net + search-visible settings). The
  depth axis stays OUT of the key: it is the satisfaction axis — a
  cached depth-20 result answers a depth-12 request of the same shape,
  never the reverse.
* `store.py` — `AnalysisCache`: bounded in-memory LRU over wire-form
  results, sqlite index + per-entry payload files via the
  StatsRecorder plumbing (client/stats.py) so hits survive restarts,
  sha256 integrity checks with an aot-registry-style quarantine
  (`.bad` rename, one warning, fall back to a real search), and
  in-flight coalescing so concurrent identical requests produce one
  search and N deliveries.
* `ttwarm.py` — hot transposition-table slices keyed by opening-prefix
  fingerprint, spliced into the engine's shared TT when a chunk is
  submitted, so even cache *misses* near known theory start warm.

Consulted at two layers: serve admission (fishnet_tpu/serve/server.py —
hits cost microseconds and shed no capacity) and the fleet coordinator
(fishnet_tpu/fleet/coordinator.py — N members share one hit set).
"""
from .keys import (  # noqa: F401
    DEPTH_DEFAULT,
    CacheKey,
    content_fingerprint,
    engine_identity,
    key_for_chunk_position,
    key_for_request,
    keys_for_requests,
    satisfies,
)
from .store import (  # noqa: F401
    AnalysisCache,
    CacheStats,
    attach_engine,
    attach_ttwarm,
    cache_from_settings,
)
from .ttwarm import TTWarmStore, prefix_fingerprint  # noqa: F401
