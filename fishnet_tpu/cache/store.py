"""The analysis-result cache: bounded LRU, sqlite-indexed persistence,
integrity quarantine, and in-flight coalescing.

Tiering (docs/caching.md):

* **memory** — an OrderedDict LRU of wire-form results
  (client/ipc.py response_to_wire dicts), bounded by entry count AND
  byte size. A hit is a dict copy: microseconds, no search, no
  admission capacity.
* **disk** — when built with a cache directory: one payload file per
  entry under `entries/`, indexed by the StatsRecorder sqlite sink
  (client/stats.py `analysis_cache` table) with the payload's sha256.
  Memory misses fall through to the index; a verified load promotes
  the entry back into the LRU. Corruption quarantines EXACTLY that
  entry — `.bad` rename, one warning, index row dropped — and the
  request falls back to a real search (the same trust ladder as
  aot/registry.py bundle loading).

**Invalidation**: the engine identity fingerprint (keys.engine_identity)
is pinned in the sqlite meta table. Opening a store persisted under a
different net/settings fingerprint drops every entry with an explicit
log line — a stale hit is never possible, because the fingerprint is
also inside every key.

**Exactly-once fill**: `store()` is idempotent — re-inserting a key at
the same or shallower depth keeps the existing entry and counts
`dup_fills`, so replayed, speculative and re-dispatched deliveries of
the same result populate the cache once no matter how many paths race.

**Coalescing**: `lease()` lets the serve layer attach a second
identical request to the first's pending search (one search, N
deliveries) — leaders settle an asyncio.Future the followers await.

Thread-safety: lookups/fills arrive from the serve event loop, the
fleet coordinator, and engine executor threads (the LaneScheduler
delivery hook), so every mutation holds one lock. Raw writes outside
this module are flagged by `cache-unkeyed-store` (lint/cache_rules.py).
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import os
import threading
from collections import OrderedDict
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Dict, Optional

from ..client.ipc import PositionResponse, responses_from_wire
from ..client.logger import Logger
from ..obs import metrics as obs_metrics
from .keys import CacheKey, satisfies

# per-tenant hit-ratio histogram buckets: a ratio in [0, 1], not the
# registry's default millisecond scale
RATIO_BUCKETS = (0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0)


@dataclass
class CacheStats:
    """Plain counters; folded into the metrics registry by
    export_metrics (same shape-contract as FleetStats)."""

    hits: int = 0  # memory or verified-disk satisfaction
    misses: int = 0
    disk_hits: int = 0  # subset of hits that came off the index
    fills: int = 0  # new entries (or deepened replacements)
    dup_fills: int = 0  # idempotent re-inserts, kept existing
    evictions: int = 0  # LRU evictions (memory tier)
    coalesced: int = 0  # requests that joined a pending search
    quarantined: int = 0  # corrupt payloads renamed .bad
    invalidated: int = 0  # entries dropped on identity mismatch


@dataclass
class _Entry:
    key: CacheKey
    depth: int
    wire: dict
    nbytes: int


@dataclass
class _DiskRef:
    row_id: str
    depth: int
    sha256: str
    nbytes: int
    filename: str


class _Lease:
    """Leader token for one pending search (see AnalysisCache.lease)."""

    def __init__(self, cache: "AnalysisCache", key: CacheKey, depth: int):
        self.cache = cache
        self.key = key
        self.depth = depth
        self.future: "asyncio.Future[Optional[dict]]" = (
            asyncio.get_running_loop().create_future()
        )

    def settle(self, wire: Optional[dict]) -> None:
        """Resolve followers (None: the search failed; followers fall
        back to their own search) and release the pending slot."""
        self.cache._release_lease(self)
        if not self.future.done():
            self.future.set_result(wire)


class AnalysisCache:
    """One shared hit set for serve admission, the fleet coordinator
    and the engine delivery hook."""

    def __init__(
        self,
        net: str,
        *,
        max_entries: int = 4096,
        max_bytes: int = 32 * 1024 * 1024,
        directory: Optional[str] = None,
        disk_max_entries: int = 65536,
        recorder=None,
        logger: Optional[Logger] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
    ) -> None:
        self.net = net
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.disk_max_entries = int(disk_max_entries)
        self.logger = logger or Logger()
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self.stats = CacheStats()
        self._lock = threading.Lock()
        self._mem: "OrderedDict[CacheKey, _Entry]" = OrderedDict()
        self._mem_bytes = 0
        self._pending: Dict[CacheKey, Dict[int, _Lease]] = {}
        self._dir: Optional[Path] = None
        self._disk: Dict[CacheKey, _DiskRef] = {}
        self.recorder = recorder
        if directory is not None:
            self._dir = Path(directory)
            (self._dir / "entries").mkdir(parents=True, exist_ok=True)
            if self.recorder is None:
                from ..client.stats import StatsRecorder

                self.recorder = StatsRecorder(
                    stats_file=self._dir / "cache-stats.json",
                    db_file=self._dir / "cache.db",
                )
        if self.recorder is not None and self.recorder.ensure_cache_tables():
            self._open_persisted()
        else:
            self.recorder = None

    # ----------------------------------------------------------- persistence

    def _open_persisted(self) -> None:
        """Load the sqlite index (payloads stay on disk until a miss
        wants them), after the identity fingerprint gate."""
        assert self.recorder is not None
        persisted = self.recorder.cache_identity()
        if persisted is not None and persisted != self.net:
            dropped = self.recorder.cache_clear()
            stale = (
                (self._dir / "entries").glob("*.json") if self._dir else ()
            )
            for f in stale:
                try:
                    f.unlink()
                except OSError:
                    pass  # a locked/raced file only wastes disk, never serves
            self.stats.invalidated += dropped
            self.logger.warn(
                f"cache: identity fingerprint changed "
                f"({persisted} -> {self.net}); invalidated {dropped} "
                f"persisted entr{'y' if dropped == 1 else 'ies'}"
            )
        self.recorder.set_cache_identity(self.net)
        for row_id, key_json, depth, sha, nbytes, filename in \
                self.recorder.cache_rows():
            try:
                key = CacheKey(*json.loads(key_json))
            except (ValueError, TypeError):
                self.recorder.cache_delete(row_id)
                continue
            if key.net != self.net:
                # defense in depth: identity is in every key too
                self.recorder.cache_delete(row_id)
                continue
            self._disk[key] = _DiskRef(row_id, int(depth), sha,
                                       int(nbytes), filename)

    def _payload_path(self, filename: str) -> Optional[Path]:
        return (self._dir / "entries" / filename) if self._dir else None

    def _load_disk(self, key: CacheKey, ref: _DiskRef) -> Optional[dict]:
        """Verified payload load; corruption quarantines exactly this
        entry (`.bad` rename, one warning) and reads as a miss."""
        path = self._payload_path(ref.filename)
        if path is None:
            return None
        try:
            blob = path.read_bytes()
        except OSError:
            self._drop_disk(key, ref, why="payload file missing")
            return None
        if hashlib.sha256(blob).hexdigest() != ref.sha256:
            self._quarantine(key, ref, path)
            return None
        try:
            wire = json.loads(blob)
        except ValueError:
            self._quarantine(key, ref, path)
            return None
        return wire

    def _quarantine(self, key: CacheKey, ref: _DiskRef, path: Path) -> None:
        try:
            os.replace(path, str(path) + ".bad")
        except OSError:
            pass  # rename raced a cleanup; the index row still goes
        self._disk.pop(key, None)
        if self.recorder is not None:
            self.recorder.cache_delete(ref.row_id)
        self.stats.quarantined += 1
        self.logger.warn(
            f"cache: integrity check failed for {ref.filename} "
            f"(fp {key.fp}); quarantined to {ref.filename}.bad, "
            "falling back to a real search"
        )

    def _drop_disk(self, key: CacheKey, ref: _DiskRef, why: str) -> None:
        self._disk.pop(key, None)
        if self.recorder is not None:
            self.recorder.cache_delete(ref.row_id)
        self.logger.debug(f"cache: dropped index row {ref.row_id}: {why}")

    def _persist(self, entry: _Entry, blob: bytes) -> None:
        if self.recorder is None or self._dir is None:
            return
        row_id = entry.key.row_id()
        filename = f"{row_id}.json"
        path = self._payload_path(filename)
        assert path is not None
        try:
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(blob)
            os.replace(tmp, path)
        except OSError as e:
            self.logger.warn(f"cache: persist failed for {filename}: {e}")
            return
        sha = hashlib.sha256(blob).hexdigest()
        self.recorder.cache_put(
            row_id, json.dumps(list(entry.key)), entry.depth, sha,
            entry.nbytes, filename,
        )
        self._disk[entry.key] = _DiskRef(row_id, entry.depth, sha,
                                         entry.nbytes, filename)
        dropped = set(self.recorder.cache_trim(self.disk_max_entries))
        for name in dropped:
            p = self._payload_path(name)
            if p is not None:
                try:
                    p.unlink()
                except OSError:
                    pass  # already gone; the index row was the bound
        if dropped:
            for k in [k for k, r in self._disk.items()
                      if r.filename in dropped]:
                del self._disk[k]

    # ---------------------------------------------------------------- lookup

    def lookup(self, key: CacheKey, depth: int) -> Optional[dict]:
        """The satisfaction-gated read: a copy of the stored wire dict
        when (same shape key) AND (cached depth satisfies the wanted
        depth), else None. Counts one hit or one miss."""
        with self._lock:
            return self._lookup_locked(key, depth)

    def _lookup_locked(self, key: CacheKey, depth: int) -> Optional[dict]:
        entry = self._mem.get(key)
        if entry is not None and satisfies(entry.depth, depth):
            self._mem.move_to_end(key)
            self.stats.hits += 1
            return dict(entry.wire)
        ref = self._disk.get(key)
        if ref is not None and satisfies(ref.depth, depth):
            wire = self._load_disk(key, ref)
            if wire is not None:
                self._insert_mem(key, ref.depth, wire)
                self.stats.hits += 1
                self.stats.disk_hits += 1
                return dict(wire)
        self.stats.misses += 1
        return None

    def peek(self, key: CacheKey, depth: int) -> bool:
        """lookup() without counters or promotion (bench/debug)."""
        with self._lock:
            if key in self._mem and satisfies(self._mem[key].depth, depth):
                return True
            return key in self._disk and satisfies(
                self._disk[key].depth, depth
            )

    # ------------------------------------------------------------------ fill

    def store(self, key: CacheKey, depth: int, wire: dict) -> str:
        """Idempotent fill from a delivered result. Returns "inserted"
        (new entry), "deepened" (replaced a shallower one) or "kept"
        (an at-least-as-deep entry already exists — the re-dispatch /
        replay / speculation dedup case)."""
        if key.net != self.net:
            # a foreign-identity result can never be served by this
            # store; refuse rather than poison (docs/caching.md trust)
            return "kept"
        with self._lock:
            existing = self._mem[key] if key in self._mem else None
            ref = self._disk[key] if key in self._disk else None
            if (existing is not None and satisfies(existing.depth, depth)) \
                    or (ref is not None and satisfies(ref.depth, depth)):
                self.stats.dup_fills += 1
                return "kept"
            status = (
                "inserted" if existing is None and ref is None else "deepened"
            )
            entry = self._insert_mem(key, depth, dict(wire))
            blob = json.dumps(entry.wire, sort_keys=True).encode("utf-8")
            self._persist(entry, blob)
            self.stats.fills += 1
            return status

    def _insert_mem(self, key: CacheKey, depth: int, wire: dict) -> _Entry:
        nbytes = len(json.dumps(wire, sort_keys=True))
        old = self._mem.pop(key, None)
        if old is not None:
            self._mem_bytes -= old.nbytes
        entry = _Entry(key, depth, wire, nbytes)
        self._mem[key] = entry
        self._mem_bytes += nbytes
        while self._mem and (
            len(self._mem) > self.max_entries
            or self._mem_bytes > self.max_bytes
        ):
            _, evicted = self._mem.popitem(last=False)
            self._mem_bytes -= evicted.nbytes
            self.stats.evictions += 1
        return entry

    # ------------------------------------------------------------ coalescing

    def lease(self, key: CacheKey, depth: int):
        """Attach-or-lead for one cold position. Returns
        ("hit", wire) | ("join", future) | ("lead", lease):

        * hit — a fill raced ahead; serve it.
        * join — an at-least-as-deep search for this key is already in
          flight; await the future for its wire result (None if the
          leader's search failed — fall back to searching).
        * lead — this request runs the search and MUST call
          lease.settle(wire_or_None) when it resolves.

        Must be called on the event loop (creates/returns futures).
        A join counts as a miss PLUS a coalesced consult: capacity-wise
        it behaves like a miss (the leader is doing a real search), it
        just doesn't pay for its own."""
        with self._lock:
            wire = self._lookup_locked(key, depth)
            if wire is not None:
                return "hit", wire
            by_depth = self._pending[key] if key in self._pending else None
            if by_depth:
                for pend_depth, lease in by_depth.items():
                    if satisfies(pend_depth, depth):
                        self.stats.coalesced += 1
                        return "join", lease.future
            lease = _Lease(self, key, depth)
            self._pending.setdefault(key, {})[depth] = lease
            return "lead", lease

    def _release_lease(self, lease: _Lease) -> None:
        with self._lock:
            if lease.key in self._pending:
                by_depth = self._pending[lease.key]
                if lease.depth in by_depth and \
                        by_depth[lease.depth] is lease:
                    del by_depth[lease.depth]
                if not by_depth:
                    del self._pending[lease.key]

    # ------------------------------------------------------------- reporting

    def counters(self) -> dict:
        """Flat snapshot for /healthz and the bench RESULT rows."""
        with self._lock:
            total = self.stats.hits + self.stats.misses
            return {
                **asdict(self.stats),
                "entries": len(self._mem),
                "bytes": self._mem_bytes,
                "disk_entries": len(self._disk),
                "hit_ratio": round(self.stats.hits / total, 4) if total else 0.0,
            }

    def export_metrics(self) -> None:
        """Mirror the counters into the metrics registry (hit/miss/
        byte/evict gauges per the serving contract)."""
        reg = self.registry
        reg.absorb_totals("fishnet_cache", asdict(self.stats))
        with self._lock:
            entries, nbytes, disk = (
                len(self._mem), self._mem_bytes, len(self._disk)
            )
        reg.gauge(
            "fishnet_cache_entries", "Analysis-cache entries in memory"
        ).set(entries)
        reg.gauge(
            "fishnet_cache_bytes", "Analysis-cache bytes in memory"
        ).set(nbytes)
        reg.gauge(
            "fishnet_cache_disk_entries",
            "Analysis-cache entries in the persisted index",
        ).set(disk)

    def observe_request(self, tenant: str, hits: int, total: int) -> None:
        """Per-tenant hit-ratio histogram: one observation per served
        request (0.0 all-cold .. 1.0 all-hit)."""
        if total <= 0:
            return
        self.registry.histogram(
            f"fishnet_cache_hit_ratio_{tenant}",
            "Per-request analysis-cache hit ratio for this tenant",
            buckets=RATIO_BUCKETS,
        ).observe(hits / total)

    # -------------------------------------------------------------- hydration

    @staticmethod
    def hydrate(
        wire: dict,
        position_index: Optional[int],
        url: Optional[str] = None,
        work=None,
    ) -> PositionResponse:
        """Stored wire dict → PositionResponse for THIS requester: the
        payload's chunk-protocol bookkeeping (slot index, acme url)
        belongs to whoever searched it first and is rewritten."""
        out = dict(wire)
        out["position_index"] = position_index
        out["url"] = url
        return responses_from_wire(work, [out])[0]


# ----------------------------------------------------------------- wiring


def cache_from_settings(
    engine,
    flavor,
    *,
    directory: Optional[str] = None,
    logger: Optional[Logger] = None,
    registry: Optional[obs_metrics.MetricsRegistry] = None,
) -> Optional[AnalysisCache]:
    """Build the AnalysisCache per FISHNET_TPU_CACHE* settings, keyed to
    this engine's identity fingerprint; None when the cache is off.
    An explicit `directory` (the --cache-dir flag) wins over the
    FISHNET_TPU_CACHE_DIR / FISHNET_TPU_CACHE_PERSIST pair."""
    from ..utils import settings as settings_mod
    from .keys import engine_identity

    if not settings_mod.get_bool("FISHNET_TPU_CACHE"):
        return None
    if directory is None and settings_mod.get_bool(
        "FISHNET_TPU_CACHE_PERSIST"
    ):
        directory = settings_mod.get_str("FISHNET_TPU_CACHE_DIR") or str(
            Path.home() / ".cache" / "fishnet-tpu" / "cache"
        )
    return AnalysisCache(
        engine_identity(engine, flavor),
        max_entries=settings_mod.get_int("FISHNET_TPU_CACHE_MAX_ENTRIES"),
        max_bytes=settings_mod.get_int("FISHNET_TPU_CACHE_MAX_MB")
        * 1024 * 1024,
        directory=directory,
        disk_max_entries=settings_mod.get_int(
            "FISHNET_TPU_CACHE_DISK_MAX_ENTRIES"
        ),
        logger=logger,
        registry=registry,
    )


def attach_engine(engine, cache: AnalysisCache) -> bool:
    """Wire the exactly-once fill onto an engine's delivery path.

    The hook rides LaneScheduler `_deliver` (engine/tpu.py) — the single
    point every finalized response passes through exactly once, whether
    it was searched, speculated, replayed or re-dispatched — so a result
    populates the cache once no matter how it arrived. Chains any
    previously installed hook. Returns False for engines without the
    delivery hook (PyEngine subprocess path fills at the coordinator /
    serve layer instead)."""
    if not hasattr(engine, "on_deliver"):
        return False
    from ..client.ipc import response_to_wire
    from .keys import key_for_chunk_position

    prev = engine.on_deliver

    def fill(chunk, wp, response) -> None:
        if prev is not None:
            prev(chunk, wp, response)
        key, depth = key_for_chunk_position(chunk, wp, cache.net)
        cache.store(key, depth, response_to_wire(response))

    engine.on_deliver = fill
    return True


def attach_ttwarm(engine, *, logger: Optional[Logger] = None):
    """Enable opening-prefix TT warm slices (cache/ttwarm.py) on an
    engine per FISHNET_TPU_CACHE_TT*; returns the TTWarmStore or None
    (off, or the engine has no shared table to warm)."""
    from ..utils import settings as settings_mod
    from .ttwarm import TTWarmStore

    if not settings_mod.get_bool("FISHNET_TPU_CACHE_TT"):
        return None
    if not hasattr(engine, "tt_warm") or getattr(engine, "tt", None) is None:
        return None
    directory: Optional[str] = None
    if settings_mod.get_bool("FISHNET_TPU_CACHE_PERSIST"):
        directory = settings_mod.get_str("FISHNET_TPU_CACHE_DIR") or str(
            Path.home() / ".cache" / "fishnet-tpu" / "cache"
        )
    store = TTWarmStore(directory=directory, logger=logger)
    engine.tt_warm = store
    engine.tt_warm_prefix = settings_mod.get_int(
        "FISHNET_TPU_CACHE_TT_PREFIX"
    )
    return store
