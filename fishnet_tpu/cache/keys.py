"""Canonical analysis-cache keys and the satisfaction rule.

Every cache write and every lookup goes through the builders here —
`cache-unkeyed-store` (lint/cache_rules.py) flags raw store calls
anywhere else. The key must capture EVERYTHING that changes the answer
and NOTHING that doesn't:

* **fp** — content-only position fingerprint: sha256 over root FEN and
  the move list. Deliberately NOT client/ipc.py `position_fingerprint`,
  which folds in the chunk slot index (exactly-once bookkeeping): the
  same board reached in slot 0 of one request and slot 5 of another is
  the same position.
* **kind / variant / level** — the request class. level only shapes
  bestmove searches (SkillLevel table), so analysis keys pin it to 0.
* **multipv** — kept raw (None stays -1): multipv=None and multipv=1
  run the same search but answer with different matrix shapes
  (AnalysisWork.matrix_wanted), and a hit must be bit-identical to the
  search it replaces.
* **nodes** — the EFFECTIVE per-position budget the engine sees
  (NodeLimit.get after the chunk-overlap scaling), not the raw request
  field: an explicit budget and a default budget that resolve to the
  same number run the same search and must share an entry.
* **net** — the engine identity fingerprint: net weights + search
  depth cap + the search-visible settings (aot/keys.py
  AOT_KEY_SETTINGS). A netswap or settings flip changes every answer,
  so it changes every key; `AnalysisCache` additionally persists it
  and invalidates the store on mismatch (docs/caching.md).

The **depth axis rides beside the key, not inside it**: a cached
depth-20 result satisfies a depth-12 request of the same shape (deeper
analysis strictly dominates), never the reverse, and the default-depth
marker (-1) only matches itself — what "default" resolves to lives in
the engine, not here. `satisfies` is the whole rule.
"""
from __future__ import annotations

import hashlib
import json
from typing import List, NamedTuple, Optional, Sequence, Tuple

from ..client.ipc import Chunk, WorkPosition
from ..client.wire import AnalysisWork, EngineFlavor, MoveWork

# depth axis value for "engine default depth" requests: matches only
# itself (the resolved default depends on the engine's max_depth, which
# is folded into the identity fingerprint instead)
DEPTH_DEFAULT = -1


class CacheKey(NamedTuple):
    """The exact-match shape key (depth is the satisfaction axis)."""

    fp: str  # content-only position fingerprint
    kind: str  # "analysis" | "bestmove"
    variant: str
    multipv: int  # raw request multipv; -1 for None
    nodes: int  # effective per-position budget; -1 for bestmove
    level: int  # skill level for bestmove; 0 for analysis
    net: str  # engine identity fingerprint

    def row_id(self) -> str:
        """Stable filename/sqlite identity for this key."""
        return hashlib.sha256(
            "\x00".join(str(f) for f in self).encode("utf-8")
        ).hexdigest()[:24]


def content_fingerprint(fen: str, moves: Sequence[str]) -> str:
    """Position identity by content alone (no chunk slot index)."""
    h = hashlib.sha256()
    h.update(fen.encode("utf-8"))
    h.update(b"\x00")
    h.update(" ".join(moves).encode("utf-8"))
    return h.hexdigest()[:16]


def satisfies(cached_depth: int, wanted_depth: int) -> bool:
    """The at-least-as-deep rule, on the normalized depth axis: a
    deeper (or equal) cached search answers a shallower request;
    default-depth only matches default-depth."""
    if wanted_depth == DEPTH_DEFAULT or cached_depth == DEPTH_DEFAULT:
        return cached_depth == wanted_depth
    return cached_depth >= wanted_depth


def key_for_chunk_position(
    chunk: Chunk, wp: WorkPosition, net: str
) -> Tuple[CacheKey, int]:
    """(shape key, depth axis) for one chunk slot — the primitive
    builder; the serve-side helper routes through it so the two layers
    can never disagree on normalization."""
    work = chunk.work
    fp = content_fingerprint(wp.root_fen, wp.moves)
    if isinstance(work, MoveWork):
        key = CacheKey(
            fp=fp, kind="bestmove", variant=chunk.variant,
            multipv=-1, nodes=-1, level=work.level.level, net=net,
        )
        return key, DEPTH_DEFAULT
    assert isinstance(work, AnalysisWork)
    key = CacheKey(
        fp=fp, kind="analysis", variant=chunk.variant,
        multipv=work.multipv if work.multipv is not None else -1,
        nodes=work.nodes.get(chunk.flavor.eval_flavor()),
        level=0, net=net,
    )
    depth = work.depth if work.depth is not None else DEPTH_DEFAULT
    return key, depth


def keys_for_requests(
    requests: Sequence, net: str,
    flavor: EngineFlavor = EngineFlavor.TPU,
) -> List[Tuple[CacheKey, int]]:
    """(shape key, depth axis) per PositionRequest, in request order.

    Normalization by construction: the requests run through the SAME
    requests_to_chunks grouping the session uses, and each resulting
    chunk slot goes through key_for_chunk_position — so a serve-layer
    consult and a coordinator-layer fill of the same request literally
    cannot produce different keys."""
    from ..engine.session import requests_to_chunks

    out: List[Optional[Tuple[CacheKey, int]]] = [None] * len(requests)
    for chunk, indices in requests_to_chunks(
        list(requests), flavor=flavor, id_prefix="cachekey"
    ):
        for wp, idx in zip(chunk.positions, indices):
            out[idx] = key_for_chunk_position(chunk, wp, net)
    assert all(k is not None for k in out)
    return out  # type: ignore[return-value]


def key_for_request(
    request, net: str, flavor: EngineFlavor = EngineFlavor.TPU
) -> Tuple[CacheKey, int]:
    """Single-request convenience over keys_for_requests."""
    return keys_for_requests([request], net, flavor=flavor)[0]


def engine_identity(engine, flavor: EngineFlavor = EngineFlavor.TPU) -> str:
    """The net/settings fingerprint folded into every key.

    Captures what changes answers without re-keying per request: the
    net weights identity, the engine's depth cap (resolves default-
    depth requests), the engine class, the eval flavor, and the
    search-visible settings (the same registry slice that keys AOT
    bundles — aot/keys.py AOT_KEY_SETTINGS)."""
    from ..aot.keys import AOT_KEY_SETTINGS
    from ..utils import settings

    ident = {
        "class": type(engine).__name__,
        "net": (
            getattr(engine, "net_id", None)
            or getattr(engine, "weights_path", None)
            or "builtin"
        ),
        "max_depth": getattr(engine, "max_depth", None),
        "flavor": flavor.value,
        "settings": {
            name: settings.raw(name) or "" for name in AOT_KEY_SETTINGS
        },
    }
    blob = json.dumps(ident, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]
