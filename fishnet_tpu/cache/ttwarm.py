"""Hot transposition-table slices keyed by opening-prefix fingerprint.

The result cache (store.py) only helps when the exact position repeats.
Near known theory the *neighborhood* repeats: millions of games share
the first N plies, then diverge. This module persists the TT rows the
search earned around a position — the search root's slot plus the
slots of its direct children (every depth-1 node of the subtree holds a
near-root-depth entry) — keyed by the fingerprint of the opening
prefix, and splices them back into the engine's shared table when a
later chunk starts on the same prefix. A cache *miss* one novelty away
from theory then begins with deep bounds and a best move already in the
table instead of an empty slot.

Safe by construction:

* the zobrist tables (ops/tt.py Z1/Z2) come from a SEEDED PRNG, so a
  slot index and check word computed in one process are valid in every
  process with the same table size — slices survive restarts.
* every TT entry is self-validating (`check = hash2 ^ meta ^ move`), so
  a row spliced at the wrong slot — or a corrupt payload that slipped
  past the sha256 gate — simply fails probe validation and costs a
  re-search, never a wrong score. That is the same torn-write tolerance
  the table already needs for lock-free batched scatters.
* splicing only fills EMPTY slots (check == 0): a live deeper entry is
  never clobbered by a persisted shallower one.

Because warm-started searches may legitimately return different
(better-informed) answers than cold ones, the feature is opt-in
(FISHNET_TPU_CACHE_TT=0 by default) and sits outside the bit-identity
guarantee of the result cache (docs/caching.md).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

from ..client.logger import Logger
from ..utils import sanitize

# rows per slice: the root + up to this many child slots
MAX_SLICE_ROWS = 48


def prefix_fingerprint(root_fen: str, moves: Sequence[str],
                       plies: int) -> str:
    """Opening-prefix identity: the root FEN plus the first `plies`
    moves. Positions reached through the same prefix share a slice even
    after they diverge (the shared slots still validate; the divergent
    ones read as misses)."""
    h = hashlib.sha256()
    h.update(root_fen.encode("utf-8"))
    h.update(b"\x00")
    h.update(" ".join(list(moves)[:plies]).encode("utf-8"))
    return h.hexdigest()[:16]


def extract_rows(slot_rows, slots: Sequence[int]) -> List[List[int]]:
    """Non-empty TT rows from a gathered (len(slots), 4) row block —
    the caller gathers `table.data[slots]` so only the slice crosses
    from the device: [[slot, check, meta, move, gen], ...]."""
    rows: List[List[int]] = []
    seen = set()
    for s, row in zip(slots, np.asarray(slot_rows)):
        s = int(s)
        if s in seen:
            continue
        seen.add(s)
        if int(row[0]) != 0:
            rows.append([s] + [int(v) for v in row])
        if len(rows) >= MAX_SLICE_ROWS:
            break
    return rows


def splice_rows(data, rows: Sequence[Sequence[int]]):
    """Set persisted rows into a table, empty slots only; returns the
    (possibly new) array and how many slots were written. Works on
    jax arrays (functional .at[] update) — the engine swaps its TTable
    for the result."""
    if not rows:
        return data, 0
    n = data.shape[0]
    slots = np.asarray([r[0] for r in rows], dtype=np.int64)
    vals = np.asarray([r[1:] for r in rows], dtype=np.int32)
    ok = (slots >= 0) & (slots < n)
    slots, vals = slots[ok], vals[ok]
    if slots.size == 0:
        return data, 0
    current = np.asarray(data[slots, 0])
    empty = current == 0
    slots, vals = slots[empty], vals[empty]
    if slots.size == 0:
        return data, 0
    return data.at[slots].set(vals), int(slots.size)


class TTWarmStore:
    """Bounded LRU of TT slices + file persistence with the same
    sha256-then-quarantine integrity ladder as the result store."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_entries: int = 512,
        logger: Optional[Logger] = None,
    ) -> None:
        self.max_entries = int(max_entries)
        self.logger = logger or Logger()
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, List[List[int]]]" = OrderedDict()
        self.splices = 0
        self.warm_slots = 0
        self.exports = 0
        self.quarantined = 0
        # FISHNET_TPU_SANITIZE, captured once: verify that rows entering
        # and leaving the store decode to STORABLE entries (flag != 3,
        # |score| within the store clamp). The sha256 gate catches bit
        # rot; this catches a writer exporting garbage that hashes fine.
        self._sanitize = sanitize.enabled()
        self._dir: Optional[Path] = None
        if directory is not None:
            self._dir = Path(directory) / "tt"
            self._dir.mkdir(parents=True, exist_ok=True)

    def _mem_key(self, size_log2: int, key: str) -> str:
        # slot indices are only meaningful at one table size
        return f"{key}-{int(size_log2)}"

    def _path(self, mem_key: str) -> Optional[Path]:
        return (self._dir / f"{mem_key}.json") if self._dir else None

    def lookup(self, size_log2: int, key: str) -> List[List[int]]:
        mk = self._mem_key(size_log2, key)
        with self._lock:
            rows = self._mem[mk] if mk in self._mem else None
            if rows is not None:
                self._mem.move_to_end(mk)
                return [list(r) for r in rows]
            rows = self._load(mk)
            if rows is None:
                return []
            if self._sanitize:
                sanitize.check_tt_rows(
                    rows, "cache/ttwarm.py::TTWarmStore.lookup")
            self._insert(mk, rows)
            return [list(r) for r in rows]

    def record(self, size_log2: int, key: str,
               rows: List[List[int]]) -> None:
        """Persist a slice; merges with an existing one (new rows win
        per slot — they come from a fresher search)."""
        if not rows:
            return
        if self._sanitize:
            sanitize.check_tt_rows(
                rows, "cache/ttwarm.py::TTWarmStore.record")
        mk = self._mem_key(size_log2, key)
        with self._lock:
            merged = {
                int(r[0]): list(r)
                for r in (self._mem[mk] if mk in self._mem else [])
            }
            for r in rows:
                merged[int(r[0])] = [int(v) for v in r]
            out = list(merged.values())[:MAX_SLICE_ROWS]
            self._insert(mk, out)
            self.exports += 1
            path = self._path(mk)
            if path is not None:
                blob = json.dumps(out, sort_keys=True).encode("utf-8")
                payload = json.dumps({
                    "sha256": hashlib.sha256(blob).hexdigest(),
                    "rows": out,
                }).encode("utf-8")
                try:
                    tmp = path.with_suffix(".tmp")
                    tmp.write_bytes(payload)
                    os.replace(tmp, path)
                except OSError as e:
                    self.logger.warn(f"cache: tt slice persist failed: {e}")

    def _insert(self, mem_key: str, rows: List[List[int]]) -> None:
        self._mem[mem_key] = rows
        self._mem.move_to_end(mem_key)
        while len(self._mem) > self.max_entries:
            self._mem.popitem(last=False)

    def _load(self, mem_key: str) -> Optional[List[List[int]]]:
        path = self._path(mem_key)
        if path is None or not path.exists():
            return None
        try:
            payload = json.loads(path.read_bytes())
            rows = payload["rows"]
            blob = json.dumps(rows, sort_keys=True).encode("utf-8")
            if hashlib.sha256(blob).hexdigest() != payload["sha256"]:
                raise ValueError("sha mismatch")
            return [[int(v) for v in r] for r in rows]
        except (OSError, ValueError, TypeError, KeyError):
            try:
                os.replace(path, str(path) + ".bad")
            except OSError:
                pass  # rename raced a cleanup; treated as a miss either way
            self.quarantined += 1
            self.logger.warn(
                f"cache: tt slice {path.name} failed integrity check; "
                f"quarantined to {path.name}.bad"
            )
            return None

    def counters(self) -> dict:
        with self._lock:
            return {
                "tt_slices": len(self._mem),
                "tt_splices": self.splices,
                "tt_warm_slots": self.warm_slots,
                "tt_exports": self.exports,
                "tt_quarantined": self.quarantined,
            }
